"""HTTP proxy: the ingress that turns HTTP requests into handle calls.

Reference analog: ``serve/_private/http_proxy.py:935`` (``HTTPProxy`` on
uvicorn/ASGI). Here the proxy is one actor running an aiohttp server on the
worker's event loop. Routing: longest-matching ``route_prefix`` from the
controller's routing table (refreshed on a short TTL), then a
``DeploymentHandle`` call on the app's ingress deployment — so the proxy
shares the power-of-two replica routing and backpressure path with every
other caller.

The request crosses process boundaries, so the replica receives a picklable
``ServeRequest`` (method/path/headers/body), not an ASGI scope.
"""

from __future__ import annotations

import asyncio
import json as _json
import os
import time
import uuid
from typing import Any, Dict, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.serve import obs
from ray_tpu.serve.asgi import ASGIResponse, ASGIResponseStart
from ray_tpu.serve.handle import DeploymentHandle, DeploymentResponseGenerator
from ray_tpu.serve.replica import REJECTED as REJECTED_STATUS
from ray_tpu.util import metrics

# aiohttp is the serve-ingress dependency; the module must stay importable
# without it (start() raises the actionable error), but the web/multidict
# lookups must not run per request — PR 10 hot-path rule
try:
    from aiohttp import WSMsgType, web
    from multidict import CIMultiDict
except ImportError:  # surfaced at start(); handlers never run without it
    WSMsgType = web = CIMultiDict = None

_ROUTE_TTL_S = 1.0


class ServeRequest:
    """Picklable HTTP request surface handed to ingress deployments.

    ``query``/``headers`` are convenience dicts (last value wins for
    repeats); ``raw_query`` and ``raw_headers`` preserve the wire form —
    repeated query params (``?tag=a&tag=b``) and duplicate headers — which
    the ASGI adapter needs to hand FastAPI/Starlette an unmodified scope.
    """

    def __init__(self, method: str, path: str, query: Dict[str, str],
                 headers: Dict[str, str], body: bytes,
                 raw_query: Optional[str] = None,
                 raw_headers: Optional[list] = None):
        self.method = method
        self.path = path  # path with the app's route_prefix stripped
        self.query = query
        self.headers = headers
        self.body = body
        self.raw_query = raw_query
        self.raw_headers = raw_headers  # [(name, value), ...] with repeats

    def json(self) -> Any:
        return _json.loads(self.body or b"null")

    def text(self) -> str:
        return (self.body or b"").decode()


def _to_response(result: Any):
    """Map a deployment's return value onto (status, content_type, bytes)."""
    status = 200
    if (isinstance(result, tuple) and len(result) == 2
            and isinstance(result[0], int)):
        status, result = result
    if result is None:
        return status if status != 200 else 204, "text/plain", b""
    if isinstance(result, bytes):
        return status, "application/octet-stream", result
    if isinstance(result, str):
        return status, "text/plain; charset=utf-8", result.encode()
    try:
        if isinstance(result, np.ndarray):
            result = result.tolist()
        payload = _json.dumps(result, default=_np_default).encode()
        return status, "application/json", payload
    except TypeError:
        return status, "text/plain; charset=utf-8", str(result).encode()


def _np_default(o):
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    raise TypeError(f"not JSON serializable: {type(o)}")


@ray_tpu.remote
class ProxyActor:
    def __init__(self):
        self._routes: Dict[str, Tuple[str, str]] = {}
        self._routes_version = -1
        self._routes_fetched = 0.0
        self._handles: Dict[Tuple[str, str], Any] = {}
        self._runner = None
        self._site = None
        self._port: Optional[int] = None
        self._requests_served = 0
        self._proxy_id = "proxy-0"
        self._poller_started = False
        self._stopped = False
        # healthz honesty: a load balancer must see a proxy whose route
        # table went stale (controller unreachable) as unhealthy
        self._started_at = time.time()
        self._last_route_ok = 0.0   # last successful routing-table fetch
        self._poll_ok = True        # did the last fetch attempt succeed?
        self._route_stale_s = float(
            os.environ.get("RT_SERVE_ROUTE_STALE_S", "30"))

    async def start(self, host: str, port: int,
                    proxy_id: str = "proxy-0") -> int:
        self._proxy_id = proxy_id
        if web is None:
            raise ImportError("aiohttp is required for the serve HTTP "
                              "proxy (pip install aiohttp)")
        app = web.Application(client_max_size=64 * 1024 * 1024)
        app.router.add_route("*", "/{tail:.*}", self._handle)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        self._site = web.TCPSite(self._runner, host, port)
        await self._site.start()
        self._port = self._site._server.sockets[0].getsockname()[1]
        return self._port

    async def stop(self) -> None:
        self._stopped = True
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    def _controller(self):
        # rt: lint-allow(hot-path) import-cycle break (serve.api imports
        # this module); resolved once then cached on self
        from ray_tpu.serve.api import _get_controller

        return _get_controller()

    async def _refresh_routes(self) -> None:
        # long-poll push (reference: LongPollClient in the proxy): one
        # blocked executor thread tracks the table; requests read the cache
        if not self._poller_started:
            self._poller_started = True
            loop = asyncio.get_running_loop()
            loop.run_in_executor(None, self._route_poll_loop)
        if self._routes_fetched == 0.0:
            # first request: fetch synchronously so routing is never empty
            loop = asyncio.get_running_loop()
            table = await loop.run_in_executor(
                None, self._fetch_routes_blocking, False)
            self._apply_routes(table)

    def _apply_routes(self, table: Dict[str, Any]) -> None:
        self._routes = table["routes"]
        self._routes_version = table["version"]
        self._routes_fetched = time.time()
        self._last_route_ok = self._routes_fetched
        self._poll_ok = True

    def _route_poll_loop(self) -> None:
        while not self._stopped:
            try:
                self._apply_routes(self._fetch_routes_blocking(True))
            except Exception:
                self._poll_ok = False
                time.sleep(1.0)

    def _fetch_routes_blocking(self, wait: bool) -> Dict[str, Any]:
        return ray_tpu.get(self._controller().get_routing_table.remote(
            self._routes_version if wait else -1, wait, 10.0))

    def _match(self, path: str) -> Optional[Tuple[str, str, str, str]]:
        """Longest-prefix route match ->
        (app, ingress, stripped_path, route_prefix)."""
        best = None
        for prefix, (app, ingress) in self._routes.items():
            norm = prefix.rstrip("/") or ""
            if path == norm or path.startswith(norm + "/") or norm == "":
                if best is None or len(norm) > len(best[0]):
                    best = (norm, app, ingress)
        if best is None:
            return None
        stripped = path[len(best[0]):] or "/"
        return best[1], best[2], stripped, best[0] or "/"

    async def _healthz(self, request):
        """Honest health: include route-table age and controller
        reachability; 503 past the staleness threshold so a load balancer
        drains a proxy whose controller went away. ``?verbose=1`` returns
        the JSON body on 200 too; ``?stale_after=`` overrides the
        threshold (tests / per-LB tuning)."""
        # probe on demand: an idle proxy must not go stale merely because
        # no request has started the poller yet
        if self._last_route_ok == 0.0:
            try:
                await self._refresh_routes()
            except Exception:  # noqa: BLE001 — controller unreachable
                self._poll_ok = False
        now = time.time()
        age = now - (self._last_route_ok or self._started_at)
        try:
            stale_after = float(request.rel_url.query.get(
                "stale_after", self._route_stale_s))
        except (TypeError, ValueError):
            stale_after = self._route_stale_s
        degraded = age > stale_after
        payload = {"status": "degraded" if degraded else "ok",
                   "route_table_age_s": round(age, 3),
                   "stale_after_s": stale_after,
                   "controller_reachable": self._poll_ok,
                   "routes_version": self._routes_version}
        if degraded:
            return web.json_response(payload, status=503)
        if request.rel_url.query.get("verbose"):
            return web.json_response(payload)
        return web.Response(text="ok")

    def _observe_request(self, app: str, deployment: str, route: str,
                         code: int, seconds: float) -> None:
        obs.request_seconds().observe(seconds, tags={
            "app": app, "deployment": deployment, "route": route,
            "code": str(code)})
        obs.requests_total().inc(tags={"app": app, "code": str(code)})
        # per-process spread check for multi-proxy front doors
        obs.proxy_requests_total().inc(tags={"proxy": self._proxy_id})
        if code >= 500:
            obs.errors_total().inc(tags={
                "app": app, "deployment": deployment, "kind": "http_5xx"})

    async def _handle(self, request):
        path = "/" + request.match_info["tail"]
        if path == "/-/healthz":
            return await self._healthz(request)
        if path == "/-/routes":
            await self._refresh_routes()
            return web.json_response(
                {p: f"{a}:{i}" for p, (a, i) in self._routes.items()})
        t_epoch, t0 = time.time(), time.perf_counter()
        # ingress: mint (or adopt a well-formed upstream's) request id — it
        # is the TRACE id every downstream hop joins
        upstream_rid = request.headers.get(obs.REQUEST_ID_HEADER, "")
        request_id = (upstream_rid if obs.valid_request_id(upstream_rid)
                      else obs.mint_request_id())
        rid_hdr = {obs.REQUEST_ID_HEADER: request_id}
        await self._refresh_routes()
        m = self._match(path)
        if m is None:
            self._observe_request("", "", "_unmatched", 404,
                                  time.perf_counter() - t0)
            return web.Response(status=404, text=f"no app at {path}",
                                headers=rid_hdr)
        app_name, ingress, stripped, route = m
        key = (app_name, ingress)
        handle = self._handles.get(key)
        if handle is None:
            handle = DeploymentHandle(app_name, ingress)
            self._handles[key] = handle
        req_ctx = {"request_id": request_id, "app": app_name,
                   "deployment": ingress, "route": route,
                   "span_id": obs.new_span_id()}
        if (request.headers.get("Upgrade", "").lower() == "websocket"
                and request.method == "GET"):
            # websockets are ingress traffic too: count the connection and
            # give the trace its root span (101 = a completed WS session;
            # error paths return plain responses with their own codes)
            try:
                resp = await self._handle_websocket(request, handle,
                                                    stripped, req_ctx)
                ws_code = getattr(resp, "status", 200)
            except Exception:
                ws_code = 500
                raise
            finally:
                t_end = time.perf_counter()
                self._observe_request(app_name, ingress, route, ws_code,
                                      t_end - t0)
                obs.emit_span(
                    f"serve:{request_id}:p:{req_ctx['span_id'][:8]}",
                    f"proxy:WS {route}",
                    request_id=request_id, span_id=req_ctx["span_id"],
                    parent_span_id=None, t_start=t_epoch,
                    t_end=t_epoch + (t_end - t0),
                    phases={"stream": t_end - t0})
            try:
                resp.headers.setdefault(obs.REQUEST_ID_HEADER, request_id)
            except Exception:  # noqa: BLE001 — headers already sent
                pass
            return resp
        sreq = ServeRequest(
            method=request.method, path=stripped,
            query=dict(request.rel_url.query),
            headers=dict(request.headers), body=await request.read(),
            raw_query=request.rel_url.raw_query_string,
            raw_headers=[(k, v) for k, v in request.headers.items()])
        t_route = time.perf_counter()

        def finish(code: int, t_handle: float,
                   extra_phases: Optional[Dict[str, float]] = None) -> None:
            t_end = time.perf_counter()
            phases = {"proxy_route": t_route - t0,
                      "handle": t_handle - t_route}
            phases.update(extra_phases or
                          {"respond": t_end - t_handle})
            self._observe_request(app_name, ingress, route, code,
                                  t_end - t0)
            obs.emit_span(
                # unique store key per ATTEMPT: a client retrying with the
                # same adopted request id must not clobber the first
                # attempt's proxy span (rt trace joins on trace_id)
                f"serve:{request_id}:p:{req_ctx['span_id'][:8]}",
                f"proxy:{request.method} {route}",
                request_id=request_id, span_id=req_ctx["span_id"],
                parent_span_id=None, t_start=t_epoch,
                t_end=t_epoch + (t_end - t0), phases=phases)

        # activate while SUBMITTING: handle.remote captures the ambient
        # request context synchronously; the await happens outside it
        token = obs.activate_request(req_ctx)
        try:
            pending = handle.remote(sreq)
        finally:
            obs.deactivate_request(token)
        try:
            result = await pending
        except TimeoutError as e:
            finish(503, time.perf_counter())
            return web.Response(status=503, text=f"overloaded: {e}",
                                headers=rid_hdr)
        except Exception as e:  # noqa: BLE001 — user code raised
            finish(500, time.perf_counter())
            return web.Response(status=500, text=f"{type(e).__name__}: {e}",
                                headers=rid_hdr)
        t_handle = time.perf_counter()
        self._requests_served += 1
        if isinstance(result, DeploymentResponseGenerator):
            return await self._stream_response(
                request, result, req_ctx=req_ctx, t0=t0,
                t_handle=t_handle, finish=finish)
        if isinstance(result, ASGIResponse):
            # ASGI deployments control the full response surface; a
            # multidict preserves duplicate headers (Set-Cookie x2)
            headers = CIMultiDict(result.headers)
            headers.setdefault(obs.REQUEST_ID_HEADER, request_id)
            finish(result.status, t_handle)
            return web.Response(status=result.status, headers=headers,
                                body=result.body)
        status, ctype, payload = _to_response(result)
        finish(status, t_handle)
        return web.Response(status=status, content_type=ctype.split(";")[0],
                            body=payload, headers=rid_hdr)

    async def _handle_websocket(self, request, handle, stripped: str,
                                req_ctx: Optional[Dict[str, str]] = None):
        """Bridge an aiohttp websocket to an ASGI deployment (reference:
        the uvicorn proxy's native WS path, ``serve/_private/http_proxy.py``).

        Outbound: one streaming actor call (``__ws_connect__``) yields
        accept/text/bytes/close events. Inbound: each client frame is an
        ordered ``__ws_push__`` call PINNED to the same replica (the
        generator's actor), so the per-caller actor FIFO preserves frame
        order. The 101 handshake is deferred until the app accepts; a
        close-before-accept surfaces as HTTP 403 (ASGI denial semantics)."""
        conn_id = uuid.uuid4().hex
        sreq = ServeRequest(
            method="GET", path=stripped,
            query=dict(request.rel_url.query),
            headers=dict(request.headers), body=b"",
            raw_query=request.rel_url.raw_query_string,
            raw_headers=[(k, v) for k, v in request.headers.items()])
        token = obs.activate_request(req_ctx)
        try:
            pending = handle.options(
                method_name="__ws_connect__").remote(sreq, conn_id)
        finally:
            obs.deactivate_request(token)
        try:
            gen = await pending
        except TimeoutError as e:
            return web.Response(status=503, text=f"overloaded: {e}")
        except Exception as e:  # noqa: BLE001
            return web.Response(status=500,
                                text=f"{type(e).__name__}: {e}")
        if not isinstance(gen, DeploymentResponseGenerator):
            return web.Response(
                status=426, text="deployment is not websocket-capable "
                                 "(no ASGI app bound)")
        actor = gen._actor
        it = gen.__aiter__()
        loop = asyncio.get_running_loop()

        async def push(kind: str, data=None, code: int = 1005) -> None:
            # ordered, awaited pushes: per-caller FIFO on the pinned
            # replica keeps frame order. __ws_push__ bypasses admission
            # control on the replica (the connection's stream holds the
            # slot); a REJECTED here is therefore unexpected — fail loudly
            # rather than silently dropping a frame
            ref = actor.handle_request.remote(
                "__ws_push__", (conn_id, kind, data, code), {}, None)
            reply = await loop.run_in_executor(None, ray_tpu.get, ref)
            if reply[0] == REJECTED_STATUS:
                raise RuntimeError("websocket frame rejected by replica")

        try:
            first = await it.__anext__()
        except (StopAsyncIteration, Exception) as e:  # noqa: B014
            gen.cancel()
            return web.Response(status=500,
                                text=f"websocket app failed: {e}")
        if first.get("kind") == "close":
            gen.cancel()
            if first.get("code") == 1011:
                # app CRASHED before accepting (asgi.py translates app
                # errors to a 1011 close) — that's a server error, not an
                # auth-style denial
                return web.Response(
                    status=500,
                    text=f"websocket app failed: {first.get('reason', '')}")
            return web.Response(status=403, text="websocket rejected")
        ws = web.WebSocketResponse(
            protocols=[first["subprotocol"]] if first.get("subprotocol")
            else ())
        await ws.prepare(request)
        self._requests_served += 1

        async def inbound():
            try:
                async for msg in ws:
                    if msg.type == WSMsgType.TEXT:
                        await push("text", msg.data)
                    elif msg.type == WSMsgType.BINARY:
                        await push("bytes", msg.data)
                    elif msg.type == WSMsgType.ERROR:
                        break
            finally:
                await push("disconnect",
                           code=ws.close_code or 1005)

        in_task = asyncio.ensure_future(inbound())
        try:
            async for ev in it:
                kind = ev.get("kind")
                if kind == "text":
                    await ws.send_str(ev["data"])
                elif kind == "bytes":
                    await ws.send_bytes(ev["data"])
                elif kind == "close":
                    await ws.close(code=ev.get("code", 1000),
                                   message=ev.get("reason", "").encode())
                    break
        except Exception:  # noqa: BLE001 — replica died mid-connection
            pass
        finally:
            gen.cancel()
            if not ws.closed:
                await ws.close(code=1011)
            in_task.cancel()
            try:
                await in_task
            except (asyncio.CancelledError, Exception):  # noqa: B014
                pass
        return ws

    async def _stream_response(self, request, gen, req_ctx=None, t0=None,
                               t_handle=None, finish=None):
        """Chunked transfer of a streaming deployment response (reference:
        ``serve/_private/replica.py:346`` streamed ASGI messages). str/bytes
        chunks pass through; other values are JSON-encoded, one per line.
        An ASGI deployment's stream leads with ``ASGIResponseStart``, which
        sets the response status/headers before the first body byte.

        Token-streaming telemetry (the series continuous batching and
        spec-decode are judged against): TTFT is request receipt to the
        first body chunk, every inter-chunk gap lands in the TPOT
        histogram, and chunks count into ``rt_serve_tokens_total``."""
        tok_tags = ({"app": req_ctx["app"],
                     "deployment": req_ctx["deployment"]}
                    if req_ctx else None)
        it = gen.__aiter__()
        status = 200
        headers = CIMultiDict({"Content-Type": "application/octet-stream"})
        if req_ctx:
            headers.setdefault(obs.REQUEST_ID_HEADER, req_ctx["request_id"])
        _NO_CHUNK = object()  # a literal None chunk is a valid stream item
        pending_first = _NO_CHUNK
        try:
            first = await it.__anext__()
            if isinstance(first, ASGIResponseStart):
                status, headers = first.status, CIMultiDict(first.headers)
                if req_ctx:
                    headers.setdefault(obs.REQUEST_ID_HEADER,
                                       req_ctx["request_id"])
            else:
                pending_first = first
        except StopAsyncIteration:
            pass
        except Exception:  # noqa: BLE001 — failed before first chunk
            gen.cancel()
            if finish is not None:
                finish(500, time.perf_counter())
            return web.Response(status=500, text="stream failed")
        resp = web.StreamResponse(status=status, headers=headers)
        try:
            await resp.prepare(request)
        except Exception:
            # client gone before the first byte: release the replica
            # stream and the router's in-flight slot, and account the
            # aborted request (499: client closed) before propagating
            gen.cancel()
            if finish is not None:
                finish(499, time.perf_counter())
            raise

        def encode(chunk):
            if isinstance(chunk, str):
                return chunk.encode()
            if not isinstance(chunk, (bytes, bytearray)):
                return _json.dumps(chunk, default=_np_default).encode() + b"\n"
            return chunk

        n_chunks = 0
        t_prev: Optional[float] = None

        def note_chunk() -> None:
            nonlocal n_chunks, t_prev
            now = time.perf_counter()
            if tok_tags is not None:
                if n_chunks == 0 and t0 is not None:
                    obs.ttft_seconds().observe(now - t0, tags=tok_tags)
                elif t_prev is not None:
                    obs.inter_token_seconds().observe(now - t_prev,
                                                      tags=tok_tags)
                obs.tokens_total().inc(tags=tok_tags)
            n_chunks += 1
            t_prev = now

        drain = getattr(it, "drain_buffered", None)
        try:
            if pending_first is not _NO_CHUNK:
                await resp.write(encode(pending_first))
                note_chunk()
            async for chunk in it:
                payload = encode(chunk)
                note_chunk()
                if drain is not None:
                    # write coalescing: a continuous-batching engine
                    # emits token BURSTS (one per fused decode tick) —
                    # ship what is already buffered in ONE write instead
                    # of a chunked-transfer frame + syscall per token
                    for extra in drain():
                        payload += encode(extra)
                        note_chunk()
                await resp.write(payload)
        except Exception:  # noqa: BLE001 — mid-stream failure: cut the body
            gen.cancel()
        finally:
            try:
                await resp.write_eof()
            except Exception:  # noqa: BLE001 — client gone mid-stream;
                pass           # the aborted stream still gets accounted
            if finish is not None:
                t_end = time.perf_counter()
                finish(status, t_handle if t_handle is not None else t_end,
                       {"stream": t_end - (t_handle or t_end)})
        return resp

    def flush_metrics(self) -> None:
        """Push this proxy's metric registry + buffered serve spans now
        (tests/ops — the background pushers run on an interval)."""
        obs.flush_spans()
        metrics.flush_now()

    def stats(self) -> Dict[str, Any]:
        return {"port": self._port, "proxy_id": self._proxy_id,
                "requests_served": self._requests_served,
                "route_table_age_s": time.time() - (self._last_route_ok
                                                    or self._started_at),
                "controller_reachable": self._poll_ok}
