"""HTTP proxy: the ingress that turns HTTP requests into handle calls.

Reference analog: ``serve/_private/http_proxy.py:935`` (``HTTPProxy`` on
uvicorn/ASGI). Here the proxy is one actor running an aiohttp server on the
worker's event loop. Routing: longest-matching ``route_prefix`` from the
controller's routing table (refreshed on a short TTL), then a
``DeploymentHandle`` call on the app's ingress deployment — so the proxy
shares the power-of-two replica routing and backpressure path with every
other caller.

The request crosses process boundaries, so the replica receives a picklable
``ServeRequest`` (method/path/headers/body), not an ASGI scope.
"""

from __future__ import annotations

import asyncio
import json as _json
import time
from typing import Any, Dict, Optional, Tuple

import ray_tpu

_ROUTE_TTL_S = 1.0


class ServeRequest:
    """Picklable HTTP request surface handed to ingress deployments.

    ``query``/``headers`` are convenience dicts (last value wins for
    repeats); ``raw_query`` and ``raw_headers`` preserve the wire form —
    repeated query params (``?tag=a&tag=b``) and duplicate headers — which
    the ASGI adapter needs to hand FastAPI/Starlette an unmodified scope.
    """

    def __init__(self, method: str, path: str, query: Dict[str, str],
                 headers: Dict[str, str], body: bytes,
                 raw_query: Optional[str] = None,
                 raw_headers: Optional[list] = None):
        self.method = method
        self.path = path  # path with the app's route_prefix stripped
        self.query = query
        self.headers = headers
        self.body = body
        self.raw_query = raw_query
        self.raw_headers = raw_headers  # [(name, value), ...] with repeats

    def json(self) -> Any:
        return _json.loads(self.body or b"null")

    def text(self) -> str:
        return (self.body or b"").decode()


def _to_response(result: Any):
    """Map a deployment's return value onto (status, content_type, bytes)."""
    status = 200
    if (isinstance(result, tuple) and len(result) == 2
            and isinstance(result[0], int)):
        status, result = result
    if result is None:
        return status if status != 200 else 204, "text/plain", b""
    if isinstance(result, bytes):
        return status, "application/octet-stream", result
    if isinstance(result, str):
        return status, "text/plain; charset=utf-8", result.encode()
    try:
        import numpy as np

        if isinstance(result, np.ndarray):
            result = result.tolist()
        payload = _json.dumps(result, default=_np_default).encode()
        return status, "application/json", payload
    except TypeError:
        return status, "text/plain; charset=utf-8", str(result).encode()


def _np_default(o):
    import numpy as np

    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    raise TypeError(f"not JSON serializable: {type(o)}")


@ray_tpu.remote
class ProxyActor:
    def __init__(self):
        self._routes: Dict[str, Tuple[str, str]] = {}
        self._routes_version = -1
        self._routes_fetched = 0.0
        self._handles: Dict[Tuple[str, str], Any] = {}
        self._runner = None
        self._site = None
        self._port: Optional[int] = None
        self._requests_served = 0
        self._poller_started = False
        self._stopped = False

    async def start(self, host: str, port: int) -> int:
        from aiohttp import web

        app = web.Application(client_max_size=64 * 1024 * 1024)
        app.router.add_route("*", "/{tail:.*}", self._handle)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        self._site = web.TCPSite(self._runner, host, port)
        await self._site.start()
        self._port = self._site._server.sockets[0].getsockname()[1]
        return self._port

    async def stop(self) -> None:
        self._stopped = True
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    def _controller(self):
        from ray_tpu.serve.api import _get_controller

        return _get_controller()

    async def _refresh_routes(self) -> None:
        # long-poll push (reference: LongPollClient in the proxy): one
        # blocked executor thread tracks the table; requests read the cache
        if not self._poller_started:
            self._poller_started = True
            loop = asyncio.get_running_loop()
            loop.run_in_executor(None, self._route_poll_loop)
        if self._routes_fetched == 0.0:
            # first request: fetch synchronously so routing is never empty
            loop = asyncio.get_running_loop()
            table = await loop.run_in_executor(
                None, self._fetch_routes_blocking, False)
            self._apply_routes(table)

    def _apply_routes(self, table: Dict[str, Any]) -> None:
        self._routes = table["routes"]
        self._routes_version = table["version"]
        self._routes_fetched = time.time()

    def _route_poll_loop(self) -> None:
        while not self._stopped:
            try:
                self._apply_routes(self._fetch_routes_blocking(True))
            except Exception:
                time.sleep(1.0)

    def _fetch_routes_blocking(self, wait: bool) -> Dict[str, Any]:
        return ray_tpu.get(self._controller().get_routing_table.remote(
            self._routes_version if wait else -1, wait, 10.0))

    def _match(self, path: str) -> Optional[Tuple[str, str, str]]:
        """Longest-prefix route match -> (app, ingress, stripped_path)."""
        best = None
        for prefix, (app, ingress) in self._routes.items():
            norm = prefix.rstrip("/") or ""
            if path == norm or path.startswith(norm + "/") or norm == "":
                if best is None or len(norm) > len(best[0]):
                    best = (norm, app, ingress)
        if best is None:
            return None
        stripped = path[len(best[0]):] or "/"
        return best[1], best[2], stripped

    async def _handle(self, request):
        from aiohttp import web

        path = "/" + request.match_info["tail"]
        if path == "/-/healthz":
            return web.Response(text="ok")
        if path == "/-/routes":
            await self._refresh_routes()
            return web.json_response(
                {p: f"{a}:{i}" for p, (a, i) in self._routes.items()})
        await self._refresh_routes()
        m = self._match(path)
        if m is None:
            return web.Response(status=404, text=f"no app at {path}")
        app_name, ingress, stripped = m
        key = (app_name, ingress)
        handle = self._handles.get(key)
        if handle is None:
            from ray_tpu.serve.handle import DeploymentHandle

            handle = DeploymentHandle(app_name, ingress)
            self._handles[key] = handle
        if (request.headers.get("Upgrade", "").lower() == "websocket"
                and request.method == "GET"):
            return await self._handle_websocket(request, handle, stripped)
        sreq = ServeRequest(
            method=request.method, path=stripped,
            query=dict(request.rel_url.query),
            headers=dict(request.headers), body=await request.read(),
            raw_query=request.rel_url.raw_query_string,
            raw_headers=[(k, v) for k, v in request.headers.items()])
        try:
            result = await handle.remote(sreq)
        except TimeoutError as e:
            return web.Response(status=503, text=f"overloaded: {e}")
        except Exception as e:  # noqa: BLE001 — user code raised
            return web.Response(status=500, text=f"{type(e).__name__}: {e}")
        self._requests_served += 1
        from ray_tpu.serve.asgi import ASGIResponse
        from ray_tpu.serve.handle import DeploymentResponseGenerator

        if isinstance(result, DeploymentResponseGenerator):
            return await self._stream_response(request, result)
        if isinstance(result, ASGIResponse):
            # ASGI deployments control the full response surface; a
            # multidict preserves duplicate headers (Set-Cookie x2)
            from multidict import CIMultiDict

            return web.Response(status=result.status,
                                headers=CIMultiDict(result.headers),
                                body=result.body)
        status, ctype, payload = _to_response(result)
        return web.Response(status=status, content_type=ctype.split(";")[0],
                            body=payload)

    async def _handle_websocket(self, request, handle, stripped: str):
        """Bridge an aiohttp websocket to an ASGI deployment (reference:
        the uvicorn proxy's native WS path, ``serve/_private/http_proxy.py``).

        Outbound: one streaming actor call (``__ws_connect__``) yields
        accept/text/bytes/close events. Inbound: each client frame is an
        ordered ``__ws_push__`` call PINNED to the same replica (the
        generator's actor), so the per-caller actor FIFO preserves frame
        order. The 101 handshake is deferred until the app accepts; a
        close-before-accept surfaces as HTTP 403 (ASGI denial semantics)."""
        import uuid

        from aiohttp import WSMsgType, web

        from ray_tpu.serve.handle import DeploymentResponseGenerator
        from ray_tpu.serve.replica import REJECTED as REJECTED_STATUS

        conn_id = uuid.uuid4().hex
        sreq = ServeRequest(
            method="GET", path=stripped,
            query=dict(request.rel_url.query),
            headers=dict(request.headers), body=b"",
            raw_query=request.rel_url.raw_query_string,
            raw_headers=[(k, v) for k, v in request.headers.items()])
        try:
            gen = await handle.options(
                method_name="__ws_connect__").remote(sreq, conn_id)
        except TimeoutError as e:
            return web.Response(status=503, text=f"overloaded: {e}")
        except Exception as e:  # noqa: BLE001
            return web.Response(status=500,
                                text=f"{type(e).__name__}: {e}")
        if not isinstance(gen, DeploymentResponseGenerator):
            return web.Response(
                status=426, text="deployment is not websocket-capable "
                                 "(no ASGI app bound)")
        actor = gen._actor
        it = gen.__aiter__()
        loop = asyncio.get_running_loop()

        async def push(kind: str, data=None, code: int = 1005) -> None:
            # ordered, awaited pushes: per-caller FIFO on the pinned
            # replica keeps frame order. __ws_push__ bypasses admission
            # control on the replica (the connection's stream holds the
            # slot); a REJECTED here is therefore unexpected — fail loudly
            # rather than silently dropping a frame
            ref = actor.handle_request.remote(
                "__ws_push__", (conn_id, kind, data, code), {}, None)
            reply = await loop.run_in_executor(None, ray_tpu.get, ref)
            if reply[0] == REJECTED_STATUS:
                raise RuntimeError("websocket frame rejected by replica")

        try:
            first = await it.__anext__()
        except (StopAsyncIteration, Exception) as e:  # noqa: B014
            gen.cancel()
            return web.Response(status=500,
                                text=f"websocket app failed: {e}")
        if first.get("kind") == "close":
            gen.cancel()
            if first.get("code") == 1011:
                # app CRASHED before accepting (asgi.py translates app
                # errors to a 1011 close) — that's a server error, not an
                # auth-style denial
                return web.Response(
                    status=500,
                    text=f"websocket app failed: {first.get('reason', '')}")
            return web.Response(status=403, text="websocket rejected")
        ws = web.WebSocketResponse(
            protocols=[first["subprotocol"]] if first.get("subprotocol")
            else ())
        await ws.prepare(request)
        self._requests_served += 1

        async def inbound():
            try:
                async for msg in ws:
                    if msg.type == WSMsgType.TEXT:
                        await push("text", msg.data)
                    elif msg.type == WSMsgType.BINARY:
                        await push("bytes", msg.data)
                    elif msg.type == WSMsgType.ERROR:
                        break
            finally:
                await push("disconnect",
                           code=ws.close_code or 1005)

        in_task = asyncio.ensure_future(inbound())
        try:
            async for ev in it:
                kind = ev.get("kind")
                if kind == "text":
                    await ws.send_str(ev["data"])
                elif kind == "bytes":
                    await ws.send_bytes(ev["data"])
                elif kind == "close":
                    await ws.close(code=ev.get("code", 1000),
                                   message=ev.get("reason", "").encode())
                    break
        except Exception:  # noqa: BLE001 — replica died mid-connection
            pass
        finally:
            gen.cancel()
            if not ws.closed:
                await ws.close(code=1011)
            in_task.cancel()
            try:
                await in_task
            except (asyncio.CancelledError, Exception):  # noqa: B014
                pass
        return ws

    async def _stream_response(self, request, gen):
        """Chunked transfer of a streaming deployment response (reference:
        ``serve/_private/replica.py:346`` streamed ASGI messages). str/bytes
        chunks pass through; other values are JSON-encoded, one per line.
        An ASGI deployment's stream leads with ``ASGIResponseStart``, which
        sets the response status/headers before the first body byte."""
        from aiohttp import web

        from multidict import CIMultiDict

        from ray_tpu.serve.asgi import ASGIResponseStart

        it = gen.__aiter__()
        status = 200
        headers = CIMultiDict({"Content-Type": "application/octet-stream"})
        _NO_CHUNK = object()  # a literal None chunk is a valid stream item
        pending_first = _NO_CHUNK
        try:
            first = await it.__anext__()
            if isinstance(first, ASGIResponseStart):
                status, headers = first.status, CIMultiDict(first.headers)
            else:
                pending_first = first
        except StopAsyncIteration:
            pass
        except Exception:  # noqa: BLE001 — failed before first chunk
            gen.cancel()
            return web.Response(status=500, text="stream failed")
        resp = web.StreamResponse(status=status, headers=headers)
        await resp.prepare(request)

        def encode(chunk):
            if isinstance(chunk, str):
                return chunk.encode()
            if not isinstance(chunk, (bytes, bytearray)):
                return _json.dumps(chunk, default=_np_default).encode() + b"\n"
            return chunk

        try:
            if pending_first is not _NO_CHUNK:
                await resp.write(encode(pending_first))
            async for chunk in it:
                await resp.write(encode(chunk))
        except Exception:  # noqa: BLE001 — mid-stream failure: cut the body
            gen.cancel()
        finally:
            await resp.write_eof()
        return resp

    def stats(self) -> Dict[str, Any]:
        return {"port": self._port, "requests_served": self._requests_served}
