"""Replica: the actor that hosts one copy of a deployment's user callable.

Reference analog: ``serve/_private/replica.py:497`` (``RayServeReplica``,
``handle_request :235``). Each replica tracks its ongoing-request count and
REJECTS requests over ``max_ongoing_requests`` — the router treats a
rejection as backpressure and retries elsewhere (the reference's
power-of-two scheduler does the same with queue-length probing).

TPU note: a replica is where chips live (``num_tpus`` in
``ray_actor_options`` pins whole chips via the raylet's
``TPU_VISIBLE_CHIPS`` isolation), so replica count == chip-group count and
the autoscaler is effectively provisioning TPU slices.
"""

from __future__ import annotations

import asyncio
import inspect
import time
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu

REJECTED = "__rt_serve_rejected__"


class _FunctionWrapper:
    """Adapts a plain function deployment to the class-callable protocol.

    Deliberately a plain (sync) __call__: handle_request runs it in the
    replica executor, so a blocking function body occupies an executor
    thread, NOT the worker's event loop. Async fns return a coroutine here,
    which handle_request awaits on the loop."""

    def __init__(self, fn):
        self._fn = fn

    def __call__(self, *args, **kwargs):
        return self._fn(*args, **kwargs)


@ray_tpu.remote
class ReplicaActor:
    """One replica. Created by the controller with the deployment's body
    (class or function), init args (deployment-handle markers already
    substituted by the controller), and config."""

    def __init__(self, deployment_name: str, app_name: str, replica_id: str,
                 body_ref, init_args: Tuple, init_kwargs: Dict,
                 max_ongoing_requests: int,
                 user_config: Optional[Dict] = None):
        from ray_tpu.serve.handle import _resolve_handle_markers

        self._deployment = deployment_name
        self._app = app_name
        self._replica_id = replica_id
        self._max_ongoing = max_ongoing_requests
        self._ongoing = 0
        self._total_served = 0
        self._started_at = time.time()
        # sync user callables run here, NOT on the worker's event loop — a
        # blocking body (the common case: a jitted forward pass) must not
        # stall the RPC server or sibling requests
        from concurrent.futures import ThreadPoolExecutor

        self._exec = ThreadPoolExecutor(
            max_workers=max(1, max_ongoing_requests),
            thread_name_prefix="rt-replica")
        self._streams: Dict[str, Any] = {}  # response streams being consumed
        self._next_stream_id = 0

        body = body_ref
        init_args = _resolve_handle_markers(init_args)
        init_kwargs = _resolve_handle_markers(init_kwargs)
        if isinstance(body, type):
            self._instance = body(*init_args, **init_kwargs)
        else:
            self._instance = _FunctionWrapper(body)
        if user_config is not None:
            self._reconfigure_sync(user_config)

    def _reconfigure_sync(self, user_config: Dict) -> None:
        fn = getattr(self._instance, "reconfigure", None)
        if fn is not None:
            fn(user_config)

    async def handle_request(self, method_name: str, args: Tuple,
                             kwargs: Dict,
                             meta: Optional[Dict] = None) -> Tuple:
        """Returns ("ok", result, loaded_model_ids),
        ("stream", stream_id, loaded_model_ids) for generator results, or
        (REJECTED, ongoing_count)."""
        if self._ongoing >= self._max_ongoing:
            return (REJECTED, self._ongoing)
        self._ongoing += 1
        try:
            import contextvars
            import functools

            from ray_tpu.serve.multiplex import (
                _current_model_id,
                loaded_model_ids,
            )

            target = self._instance
            if method_name != "__call__":
                target = getattr(self._instance, method_name, None)
                if target is None:
                    raise AttributeError(
                        f"deployment {self._deployment} has no method "
                        f"{method_name!r}")
            token = _current_model_id.set((meta or {}).get("model_id", ""))
            try:
                # copy AFTER setting so the executor thread sees the model id
                ctx = contextvars.copy_context()
                loop = asyncio.get_running_loop()
                result = await loop.run_in_executor(
                    self._exec,
                    functools.partial(ctx.run, target, *args, **kwargs))
                if inspect.isawaitable(result):
                    result = await result
            finally:
                _current_model_id.reset(token)
            self._total_served += 1
            models = loaded_model_ids(self._instance)
            if inspect.isgenerator(result) or inspect.isasyncgen(result):
                sid = f"s{self._next_stream_id}"
                self._next_stream_id += 1
                self._streams[sid] = result
                # the stream HOLDS the in-flight slot until exhausted or
                # cancelled: +1 here cancels the finally's -1, so ongoing
                # counts active streams (admission control, autoscaler
                # metrics, and prepare_shutdown draining all depend on it)
                self._ongoing += 1
                return ("stream", sid, models)
            return ("ok", result, models)
        finally:
            self._ongoing -= 1

    async def next_chunks(self, stream_id: str, max_items: int = 10) -> Tuple:
        """Pull up to max_items from a response stream: (items, done).
        A mid-stream exception travels as the last pull's error."""
        import functools

        it = self._streams.get(stream_id)
        if it is None:
            return ([], True)
        items: List[Any] = []
        loop = asyncio.get_running_loop()
        try:
            if inspect.isasyncgen(it):
                for _ in range(max_items):
                    try:
                        items.append(await it.__anext__())
                    except StopAsyncIteration:
                        self._finish_stream(stream_id)
                        return (items, True)
            else:
                def pull():
                    out = []
                    for _ in range(max_items):
                        try:
                            out.append(next(it))
                        except StopIteration:
                            return out, True
                    return out, False

                items, done = await loop.run_in_executor(
                    self._exec, pull)
                if done:
                    self._finish_stream(stream_id)
                    return (items, True)
        except Exception:
            self._finish_stream(stream_id)
            raise
        return (items, False)

    def _finish_stream(self, stream_id: str) -> None:
        if self._streams.pop(stream_id, None) is not None:
            self._ongoing -= 1  # release the slot the stream was holding

    def cancel_stream(self, stream_id: str) -> None:
        it = self._streams.get(stream_id)
        self._finish_stream(stream_id)
        closer = getattr(it, "close", None)
        if closer is not None:
            try:
                closer()
            except Exception:  # noqa: BLE001
                pass

    # -- controller-facing ----------------------------------------------------
    def ongoing_count(self) -> int:
        return self._ongoing

    def stats(self) -> Dict[str, Any]:
        from ray_tpu.serve.multiplex import loaded_model_ids

        return {"replica_id": self._replica_id, "ongoing": self._ongoing,
                "total_served": self._total_served,
                "uptime_s": time.time() - self._started_at,
                "model_ids": loaded_model_ids(self._instance)}

    async def check_health(self) -> str:
        fn = getattr(self._instance, "check_health", None)
        if fn is not None:
            result = fn()
            if inspect.isawaitable(result):
                await result
        return "ok"

    def reconfigure(self, user_config: Dict) -> None:
        self._reconfigure_sync(user_config)

    async def prepare_shutdown(self, timeout_s: float) -> int:
        """Drain: wait for ongoing requests to finish (bounded)."""
        deadline = time.time() + timeout_s
        while self._ongoing > 0 and time.time() < deadline:
            await asyncio.sleep(0.05)
        return self._ongoing
