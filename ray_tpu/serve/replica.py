"""Replica: the actor that hosts one copy of a deployment's user callable.

Reference analog: ``serve/_private/replica.py:497`` (``RayServeReplica``,
``handle_request :235``). Each replica tracks its ongoing-request count and
REJECTS requests over ``max_ongoing_requests`` — the router treats a
rejection as backpressure and retries elsewhere (the reference's
power-of-two scheduler does the same with queue-length probing).

TPU note: a replica is where chips live (``num_tpus`` in
``ray_actor_options`` pins whole chips via the raylet's
``TPU_VISIBLE_CHIPS`` isolation), so replica count == chip-group count and
the autoscaler is effectively provisioning TPU slices.
"""

from __future__ import annotations

import asyncio
import contextvars
import functools
import inspect
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu.cluster import stream as rt_stream
from ray_tpu.serve import obs
from ray_tpu.serve.multiplex import loaded_model_ids
from ray_tpu.util import metrics, step_profiler

REJECTED = "__rt_serve_rejected__"


class _AsyncStreamPump:
    """Drains an async generator into a bounded queue from a background
    task, so ``next_chunks`` can return items AS PRODUCED instead of
    awaiting the generator ``max_items`` times per pull (which would hold
    back SSE tokens and websocket frames until a batch filled). The bound
    gives a fast producer backpressure when the consumer lags."""

    _DONE = object()

    def __init__(self, agen, maxsize: int = 256):
        self._agen = agen
        self._queue: asyncio.Queue = asyncio.Queue(maxsize=maxsize)
        self._error: Optional[BaseException] = None
        self._loop = asyncio.get_running_loop()
        self._task = asyncio.ensure_future(self._pump())

    async def _pump(self) -> None:
        try:
            async for item in self._agen:
                await self._queue.put(item)
        except asyncio.CancelledError:
            # close() tearing us down: the consumer is gone, so an awaited
            # put on a full queue would pend forever (a fast producer fills
            # the bound, nothing drains it). Never block — and RE-RAISE so
            # cancellation stays cancellation instead of becoming the
            # stream's "error".
            self._put_done_nowait()
            raise
        except BaseException as e:  # noqa: BLE001 — delivered to consumer
            self._error = e
        # completion/error: an awaited put keeps backpressure honest (a
        # lagging-but-live consumer will drain the queue), but close()
        # cancelling us AT this await must still land the marker
        try:
            await self._queue.put(self._DONE)
        except asyncio.CancelledError:
            self._put_done_nowait()
            raise

    def _put_done_nowait(self) -> None:
        """Enqueue the DONE marker without ever blocking: on a full queue
        drop buffered items (teardown path — nobody will consume them)
        until the marker fits."""
        while True:
            try:
                self._queue.put_nowait(self._DONE)
                return
            except asyncio.QueueFull:
                try:
                    self._queue.get_nowait()
                except asyncio.QueueEmpty:
                    pass

    async def take(self, max_items: int) -> Tuple[List[Any], bool]:
        """Block for one item, then drain opportunistically."""
        items: List[Any] = []
        first = await self._queue.get()
        if first is self._DONE:
            if self._error is not None:
                raise self._error
            return (items, True)
        items.append(first)
        while len(items) < max_items and not self._queue.empty():
            nxt = self._queue.get_nowait()
            if nxt is self._DONE:
                if self._error is not None:
                    # deliver the collected items now; the error travels
                    # as the NEXT pull's failure (contract above)
                    self._queue.put_nowait(self._DONE)
                    return (items, False)
                return (items, True)
            items.append(nxt)
        return (items, False)

    def close(self) -> None:
        """Thread-safe teardown (cancel_stream may run off-loop)."""
        def _do():
            self._task.cancel()
            closer = getattr(self._agen, "aclose", None)
            if closer is not None:
                asyncio.ensure_future(closer())

        self._loop.call_soon_threadsafe(_do)


class _SyncStreamPump:
    """Gives a plain (sync) generator the pump interface (``async take``)
    so the push transport and the pull path share one stream surface;
    pulls run on the replica executor, so a blocking user generator never
    stalls the event loop (same economics as the old next_chunks sync
    branch: items batch up to ``max_items`` per take)."""

    def __init__(self, gen, executor):
        self._gen = gen
        self._exec = executor

    async def take(self, max_items: int) -> Tuple[List[Any], bool]:
        loop = asyncio.get_running_loop()

        def pull():
            out: List[Any] = []
            for _ in range(max_items):
                try:
                    out.append(next(self._gen))
                except StopIteration:
                    return out, True
            return out, False

        return await loop.run_in_executor(self._exec, pull)

    def close(self) -> None:
        closer = getattr(self._gen, "close", None)
        if closer is not None:
            closer()


class _FunctionWrapper:
    """Adapts a plain function deployment to the class-callable protocol.

    Deliberately a plain (sync) __call__: handle_request runs it in the
    replica executor, so a blocking function body occupies an executor
    thread, NOT the worker's event loop. Async fns return a coroutine here,
    which handle_request awaits on the loop."""

    def __init__(self, fn):
        self._fn = fn

    def __call__(self, *args, **kwargs):
        return self._fn(*args, **kwargs)


@ray_tpu.remote
class ReplicaActor:
    """One replica. Created by the controller with the deployment's body
    (class or function), init args (deployment-handle markers already
    substituted by the controller), and config."""

    def __init__(self, deployment_name: str, app_name: str, replica_id: str,
                 body_ref, init_args: Tuple, init_kwargs: Dict,
                 max_ongoing_requests: int,
                 user_config: Optional[Dict] = None):
        # rt: lint-allow(hot-path) import-cycle break (handle.py imports
        # REJECTED from this module); one lookup per replica boot
        from ray_tpu.serve.handle import _resolve_handle_markers

        self._deployment = deployment_name
        self._app = app_name
        self._replica_id = replica_id
        self._max_ongoing = max_ongoing_requests
        self._ongoing = 0
        self._total_served = 0
        self._started_at = time.time()
        # request observability (serve/obs.py): admitted-but-not-executing
        # count and a bounded window of completed-request latencies — the
        # controller's stats_window poll aggregates these into the
        # per-deployment p50/p99 + QPS the autoscaler and `rt serve
        # status` report
        self._executing = 0
        # executor threads and the event loop both move the counter — a
        # drifted count would misreport queue depth forever
        self._exec_lock = threading.Lock()
        self._lat_window: "deque" = deque(maxlen=512)  # (t_end, wall_s)
        # sync user callables run here, NOT on the worker's event loop — a
        # blocking body (the common case: a jitted forward pass) must not
        # stall the RPC server or sibling requests
        self._exec = ThreadPoolExecutor(
            max_workers=max(1, max_ongoing_requests),
            thread_name_prefix="rt-replica")
        self._streams: Dict[str, Any] = {}  # response streams being consumed
        # pull-fallback error handoff: a pushed stream that failed after a
        # broken channel parks its error here for the next pull to raise
        self._stream_errors: Dict[str, BaseException] = {}
        self._next_stream_id = 0

        body = body_ref
        init_args = _resolve_handle_markers(init_args)
        init_kwargs = _resolve_handle_markers(init_kwargs)
        if isinstance(body, type):
            self._instance = body(*init_args, **init_kwargs)
        else:
            self._instance = _FunctionWrapper(body)
        if user_config is not None:
            self._reconfigure_sync(user_config)

    def _reconfigure_sync(self, user_config: Dict) -> None:
        fn = getattr(self._instance, "reconfigure", None)
        if fn is not None:
            fn(user_config)

    async def handle_request(self, method_name: str, args: Tuple,
                             kwargs: Dict,
                             meta: Optional[Dict] = None) -> Tuple:
        """Returns ("ok", result, loaded_model_ids, kv_residency),
        ("stream", stream_id, loaded_model_ids, kv_residency) for
        generator results, or (REJECTED, ongoing_count)."""
        # websocket inbound frames bypass admission control: the
        # connection's __ws_connect__ stream already holds a slot, and
        # rejecting its own frames would wedge every connection on a
        # replica running at max_ongoing (e.g. max_ongoing_requests=1)
        if (self._ongoing >= self._max_ongoing
                and method_name != "__ws_push__"):
            return (REJECTED, self._ongoing)
        self._ongoing += 1
        try:
            # rt: lint-allow(hot-path) must stay function-local: a
            # module-global ContextVar would ride cloudpickle's by-value
            # capture of this actor class, and ContextVars don't pickle
            from ray_tpu.serve.multiplex import _current_model_id

            target = self._instance
            if method_name != "__call__":
                target = getattr(self._instance, method_name, None)
                if target is None:
                    raise AttributeError(
                        f"deployment {self._deployment} has no method "
                        f"{method_name!r}")
            req = (meta or {}).get("request")
            replica_span = obs.new_span_id() if req else ""
            req_token = None
            if req:
                # nested handle calls made by the user callable must join
                # THIS request's trace: make the context ambient before the
                # contextvars copy below snapshots it
                req_token = obs.activate_request({
                    "request_id": req["request_id"],
                    "app": req.get("app", self._app),
                    "deployment": self._deployment,
                    "route": req.get("route", ""),
                    "span_id": replica_span})
            token = _current_model_id.set((meta or {}).get("model_id", ""))
            t_epoch, t0 = time.time(), time.perf_counter()
            exec_mark = [t0]  # executor thread stamps user-code start
            failed = False
            try:
                # copy AFTER setting so the executor thread sees the model id
                ctx = contextvars.copy_context()
                loop = asyncio.get_running_loop()

                def invoke():
                    # queue-wait ends HERE: the request held an admission
                    # slot but waited for an executor thread (and the
                    # loop's handoff) before user code ran
                    exec_mark[0] = time.perf_counter()
                    with self._exec_lock:
                        self._executing += 1
                    try:
                        return target(*args, **kwargs)
                    finally:
                        with self._exec_lock:
                            self._executing -= 1

                result = await loop.run_in_executor(
                    self._exec, functools.partial(ctx.run, invoke))
                if inspect.isawaitable(result):
                    with self._exec_lock:
                        self._executing += 1
                    try:
                        result = await result
                    finally:
                        with self._exec_lock:
                            self._executing -= 1
            except BaseException:
                failed = True
                raise
            finally:
                _current_model_id.reset(token)
                obs.deactivate_request(req_token)
                # telemetry runs for FAILING requests too: a deployment
                # erroring after a slow forward pass must still feed the
                # latency window (p50/p99/QPS, doctor's p99 warn), the
                # queue/execute histograms and its trace span
                t1 = time.perf_counter()
                if method_name not in ("__ws_push__",):
                    queue_wait_s = max(0.0, exec_mark[0] - t0)
                    execute_s = max(0.0, t1 - exec_mark[0])
                    tags = {"app": self._app,
                            "deployment": self._deployment}
                    obs.queue_wait_seconds().observe(queue_wait_s,
                                                     tags=tags)
                    obs.execute_seconds().observe(execute_s, tags=tags)
                    with self._exec_lock:  # stats_window reads off-loop
                        self._lat_window.append((time.time(), t1 - t0))
                    if req:
                        obs.emit_span(
                            f"serve:{req['request_id']}:x:"
                            f"{replica_span[:8]}",
                            f"replica:{self._deployment}.{method_name}",
                            request_id=req["request_id"],
                            span_id=replica_span,
                            parent_span_id=req.get("span_id"),
                            t_start=t_epoch, t_end=t_epoch + (t1 - t0),
                            phases={"queue_wait": queue_wait_s,
                                    "execute": execute_s},
                            state="FAILED" if failed else "FINISHED")
            if step_profiler.is_enabled():
                # serve is a profiler hot path too: per-request wall time
                # (the user callable's execution — a returned stream's
                # drain is accounted by the generate/decode records it
                # produces, not here)
                step_profiler.record(
                    "serve", name=self._deployment, t_start=t_epoch,
                    wall_s=time.perf_counter() - t0,
                    meta={"method": method_name,
                          "replica_id": self._replica_id})
            self._total_served += 1
            models = loaded_model_ids(self._instance)
            kv = None
            kv_fn = getattr(self._instance, "kv_residency", None)
            if kv_fn is not None:
                # duck-typed like loaded_model_ids: a cache-aware engine
                # reports its warm prefix digests on every reply, so the
                # router's residency view is as fresh as its last call
                # to this replica (no extra RPC, no controller round)
                try:
                    kv = kv_fn()
                except Exception:  # noqa: BLE001 — residency is advisory
                    pass
            if inspect.isgenerator(result) or inspect.isasyncgen(result):
                sid = f"s{self._next_stream_id}"
                self._next_stream_id += 1
                if inspect.isasyncgen(result):
                    # async gens are drained by a pump task into a queue so
                    # take() returns each item AS IT IS PRODUCED — a
                    # batched pull that awaited __anext__ max_items times
                    # would hold back SSE tokens / websocket frames until
                    # the batch filled
                    pump: Any = _AsyncStreamPump(result)
                else:
                    pump = _SyncStreamPump(result, self._exec)
                self._streams[sid] = pump
                # push transport (cluster/stream.py): the consumer's ONE
                # stream_subscribe RPC binds this pump to a push channel;
                # every subsequent token burst is a one-way frame. The
                # pull path below stays as the fallback.
                rt_stream.register_source(
                    sid, pump,
                    on_done=functools.partial(self._finish_stream, sid))
                # the stream HOLDS the in-flight slot until exhausted or
                # cancelled: +1 here cancels the finally's -1, so ongoing
                # counts active streams (admission control, autoscaler
                # metrics, and prepare_shutdown draining all depend on it)
                self._ongoing += 1
                return ("stream", sid, models, kv)
            return ("ok", result, models, kv)
        finally:
            self._ongoing -= 1

    async def next_chunks(self, stream_id: str, max_items: int = 10) -> Tuple:
        """Pull up to max_items from a response stream: (items, done).
        A mid-stream exception travels as the last pull's error.

        Async-gen streams block only for the FIRST item of a pull; the rest
        are taken opportunistically (whatever the pump already produced) —
        incremental streams (SSE, websocket frames) flow with per-item
        latency while bursty producers still batch."""
        err = self._stream_errors.pop(stream_id, None)
        if err is not None:
            self._finish_stream(stream_id)
            raise err
        it = self._streams.get(stream_id)
        if it is None:
            return ([], True)
        try:
            items, done = await it.take(max_items)
        except Exception:
            self._finish_stream(stream_id)
            raise
        rt_stream.count_pull_frames(len(items))
        if done:
            self._finish_stream(stream_id)
        return (items, done)

    async def resume_pull(self, stream_id: str, delivered: int) -> Tuple:
        """Pull-fallback handoff after a broken push channel: detach the
        push binding and return the replayed tail past the consumer's
        ``delivered`` count — token-exact across the transport switch.
        The consumer continues on ``next_chunks`` from here. Async so it
        runs on the event loop the push binding lives on."""
        items, source_done, err = await rt_stream.reclaim(
            stream_id, delivered)
        if err is not None:
            if items:
                # pull-path contract: collected items now, the error as
                # the next pull's failure
                self._stream_errors[stream_id] = err
                return (items, False)
            self._finish_stream(stream_id)
            raise err
        if source_done:
            self._finish_stream(stream_id)
            return (items, True)
        return (items, False)

    def _finish_stream(self, stream_id: str) -> None:
        if self._streams.pop(stream_id, None) is not None:
            self._ongoing -= 1  # release the slot the stream was holding
            self._stream_errors.pop(stream_id, None)
            rt_stream.unregister_source(stream_id)

    def cancel_stream(self, stream_id: str) -> None:
        it = self._streams.get(stream_id)
        self._finish_stream(stream_id)
        closer = getattr(it, "close", None)
        if closer is not None:
            try:
                closer()
            except Exception:  # noqa: BLE001
                pass

    # -- controller-facing ----------------------------------------------------
    def ongoing_count(self) -> int:
        return self._ongoing

    def stats_window(self, window_s: float = 30.0) -> Dict[str, Any]:
        """Windowed request stats for the controller's autoscaler poll:
        ongoing count, executor queue depth, and the recent completed-
        request latencies (the controller merges replicas and computes the
        per-deployment p50/p99 + QPS the decision log records)."""
        now = time.time()
        with self._exec_lock:  # the event loop appends concurrently
            window = list(self._lat_window)
            saturated = len(window) == self._lat_window.maxlen
        lats = [w for t, w in window if now - t <= window_s]
        # a saturated ring evicted completions that were still inside the
        # nominal window: report the span the retained samples actually
        # cover, or the controller's completed/window_s rate math caps at
        # maxlen/window_s qps under exactly the heavy traffic this plane
        # is for
        eff_window_s = window_s
        if saturated and window:
            eff_window_s = min(window_s, max(1e-3, now - window[0][0]))
        out = {"replica_id": self._replica_id,
               "ongoing": self._ongoing,
               "queue_depth": max(0, self._ongoing - self._executing
                                  - len(self._streams)),
               "completed": len(lats),
               "window_s": eff_window_s,
               "latencies": lats[-200:]}
        # duck-typed engine surface (serve/llm.py ContinuousLLM): a
        # continuous-batching instance reports slot occupancy, which the
        # controller aggregates into win_stats / `rt serve status`
        eng_fn = getattr(self._instance, "engine_stats", None)
        if eng_fn is not None:
            try:
                out["engine"] = eng_fn()
            except Exception:  # noqa: BLE001 — stats are advisory
                pass
        return out

    def flush_metrics(self) -> None:
        """Push this replica's metric registry + buffered serve spans now
        (tests/ops — the background pushers run on an interval)."""
        obs.flush_spans()
        metrics.flush_now()

    def stats(self) -> Dict[str, Any]:
        return {"replica_id": self._replica_id, "ongoing": self._ongoing,
                "total_served": self._total_served,
                "uptime_s": time.time() - self._started_at,
                "model_ids": loaded_model_ids(self._instance)}

    async def check_health(self) -> str:
        fn = getattr(self._instance, "check_health", None)
        if fn is not None:
            result = fn()
            if inspect.isawaitable(result):
                await result
        return "ok"

    def reconfigure(self, user_config: Dict) -> None:
        self._reconfigure_sync(user_config)

    async def prepare_shutdown(self, timeout_s: float) -> int:
        """Drain: wait for ongoing requests to finish (bounded)."""
        deadline = time.time() + timeout_s
        while self._ongoing > 0 and time.time() < deadline:
            await asyncio.sleep(0.05)
        return self._ongoing
