"""Serve request observability: request ids, serve spans, rt_serve_* series.

Reference analogs: the request-context plumbing in
``serve/_private/request_router`` + ``ray.serve.context`` (request id
minted at the proxy, carried on every hop) and the autoscaler metrics
pipeline (``serve/_private/metrics_utils.py``). Redesign for this repo:

  - every ingress (HTTP proxy, gRPC proxy, direct ``DeploymentHandle``
    call) mints a request id; the id doubles as the TRACE id of the PR 3
    tracing plane, so the proxy-, handle- and replica-level serve spans
    and the real actor-call task spans all join one tree and
    ``rt trace <request_id>`` prints the full proxy -> route ->
    replica-queue -> execute -> stream path;
  - serve spans are ordinary GCS task events with ``task_id``
    ``serve:<request_id>...`` — they land in their own bounded store
    (``cluster/gcs.py``) via the batched drainer below, so heavy traffic
    cannot evict real task history;
  - the ``rt_serve_*`` Prometheus series are registered lazily in
    whichever process observes them (proxy, replica, controller) and ride
    the standard per-process KV push (``util/metrics.py``).

The ambient request context propagates caller -> pool thread -> replica ->
nested handle calls explicitly (thread pools do not inherit contextvars),
so composition chains keep one request id end to end.
"""

from __future__ import annotations

import collections
import contextvars
import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from ray_tpu.util import metrics as M

REQUEST_ID_HEADER = "x-rt-request-id"

# request context: {"request_id", "app", "deployment", "route", "span_id"}
_request_ctx: "contextvars.ContextVar[Optional[Dict[str, str]]]" = \
    contextvars.ContextVar("rt_serve_request_ctx", default=None)


def mint_request_id() -> str:
    return uuid.uuid4().hex


_RID_CHARS = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789-_")


def valid_request_id(rid: str) -> bool:
    """Gate for ADOPTING an upstream ``x-rt-request-id``: bounded length,
    URL/metric-safe charset — the id becomes a GCS span key, a trace id
    and an echoed header, so arbitrary client bytes don't belong."""
    return bool(rid) and len(rid) <= 128 and set(rid) <= _RID_CHARS


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


def current_request_context() -> Optional[Dict[str, str]]:
    """The ambient serve request context (None outside a request)."""
    return _request_ctx.get()


def get_serve_request_id() -> Optional[str]:
    """Inside a serve request: the request id every hop shares (user code
    can log it; ``rt trace <id>`` joins it with the span tree)."""
    ctx = _request_ctx.get()
    return ctx.get("request_id") if ctx else None


def activate_request(ctx: Optional[Dict[str, str]]):
    """Make ``ctx`` ambient; returns a token for :func:`deactivate_request`.

    Also activates the matching tracing span context so task/actor calls
    made under this request become children of ``ctx['span_id']`` in the
    trace whose id IS the request id.
    """
    if ctx is None:
        return None
    from ray_tpu.util import tracing

    req_token = _request_ctx.set(ctx)
    trace_token = tracing.activate({"trace_id": ctx["request_id"],
                                    "span_id": ctx["span_id"]})
    return (req_token, trace_token)


def deactivate_request(token) -> None:
    if token is None:
        return
    from ray_tpu.util import tracing

    req_token, trace_token = token
    _request_ctx.reset(req_token)
    tracing.deactivate(trace_token)


# ---------------------------------------------------------------------------
# Metrics (lazy: registered in whichever process first observes them)
# ---------------------------------------------------------------------------

_metrics_lock = threading.Lock()
_metrics: Dict[str, Any] = {}

_REQUEST_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                    1.0, 2.5, 5.0, 10.0, 30.0)
_TOKEN_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                  0.5, 1.0, 2.5)
_BATCH_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256)
_OCCUPANCY_BUCKETS = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)


def _metric(key: str, factory) -> Any:
    m = _metrics.get(key)
    if m is None:
        with _metrics_lock:
            m = _metrics.get(key)
            if m is None:
                m = factory()
                _metrics[key] = m
    return m


def request_seconds() -> M.Histogram:
    return _metric("request_seconds", lambda: M.get_or_create(
        M.Histogram, "rt_serve_request_seconds",
        "End-to-end serve request latency at the ingress "
        "(streamed requests close at last byte)",
        boundaries=_REQUEST_BUCKETS,
        tag_keys=("app", "deployment", "route", "code")))


def requests_total() -> M.Counter:
    return _metric("requests_total", lambda: M.get_or_create(
        M.Counter, "rt_serve_requests_total",
        "Serve requests by response code at the ingress",
        tag_keys=("app", "code")))


def errors_total() -> M.Counter:
    return _metric("errors_total", lambda: M.get_or_create(
        M.Counter, "rt_serve_errors_total",
        "Serve request errors by kind (replica_died / rejected_timeout / "
        "app_error / http_5xx)",
        tag_keys=("app", "deployment", "kind")))


def queue_wait_seconds() -> M.Histogram:
    return _metric("queue_wait_seconds", lambda: M.get_or_create(
        M.Histogram, "rt_serve_queue_wait_seconds",
        "Replica-side wait between request admission and user-code start",
        boundaries=_TOKEN_BUCKETS,
        tag_keys=("app", "deployment")))


def execute_seconds() -> M.Histogram:
    return _metric("execute_seconds", lambda: M.get_or_create(
        M.Histogram, "rt_serve_execute_seconds",
        "Replica-side user-callable execution time",
        boundaries=_REQUEST_BUCKETS,
        tag_keys=("app", "deployment")))


def ongoing_gauge() -> M.Gauge:
    return _metric("ongoing", lambda: M.get_or_create(
        M.Gauge, "rt_serve_ongoing",
        "In-flight requests per deployment (controller-polled)",
        tag_keys=("app", "deployment")))


def queue_depth_gauge() -> M.Gauge:
    return _metric("queue_depth", lambda: M.get_or_create(
        M.Gauge, "rt_serve_queue_depth",
        "Admitted requests waiting for a replica executor thread, "
        "per deployment (controller-polled)",
        tag_keys=("app", "deployment")))


def ttft_seconds() -> M.Histogram:
    return _metric("ttft", lambda: M.get_or_create(
        M.Histogram, "rt_serve_ttft_seconds",
        "Time to first streamed chunk, request receipt to first byte",
        boundaries=_REQUEST_BUCKETS,
        tag_keys=("app", "deployment")))


def inter_token_seconds() -> M.Histogram:
    return _metric("inter_token", lambda: M.get_or_create(
        M.Histogram, "rt_serve_inter_token_seconds",
        "Gap between consecutive streamed chunks (TPOT)",
        boundaries=_TOKEN_BUCKETS,
        tag_keys=("app", "deployment")))


def tokens_total() -> M.Counter:
    return _metric("tokens_total", lambda: M.get_or_create(
        M.Counter, "rt_serve_tokens_total",
        "Streamed chunks delivered through the serve ingress",
        tag_keys=("app", "deployment")))


def batch_size_hist() -> M.Histogram:
    return _metric("batch_size", lambda: M.get_or_create(
        M.Histogram, "rt_serve_batch_size",
        "@serve.batch fused batch size per flush",
        boundaries=_BATCH_BUCKETS,
        tag_keys=("fn",)))


def batch_occupancy_hist() -> M.Histogram:
    return _metric("batch_occupancy", lambda: M.get_or_create(
        M.Histogram, "rt_serve_batch_occupancy",
        "@serve.batch batch size as a fraction of max_batch_size",
        boundaries=_OCCUPANCY_BUCKETS,
        tag_keys=("fn",)))


def cb_slots_gauge() -> M.Gauge:
    return _metric("cb_slots", lambda: M.get_or_create(
        M.Gauge, "rt_serve_cb_slots_active",
        "Continuous-batching decode slots occupied per engine tick "
        "(serve/llm.py ContinuousLLM)",
        tag_keys=("deployment",)))


def kv_cache_hits() -> M.Counter:
    return _metric("kv_hits", lambda: M.get_or_create(
        M.Counter, "rt_serve_kv_cache_hits",
        "Prefix/KV-cache admission hits (prefill ran only on the "
        "uncached suffix)",
        tag_keys=("deployment",)))


def kv_cache_misses() -> M.Counter:
    return _metric("kv_misses", lambda: M.get_or_create(
        M.Counter, "rt_serve_kv_cache_misses",
        "Prefix/KV-cache admission misses (full cold prefill)",
        tag_keys=("deployment",)))


def kv_cache_evictions() -> M.Counter:
    return _metric("kv_evictions", lambda: M.get_or_create(
        M.Counter, "rt_serve_kv_cache_evictions",
        "Prefix/KV-cache pages evicted by the bytes-budget LRU",
        tag_keys=("deployment",)))


def kv_cache_bytes() -> M.Gauge:
    return _metric("kv_bytes", lambda: M.get_or_create(
        M.Gauge, "rt_serve_kv_cache_bytes",
        "Retained prefix/KV-cache page bytes per engine (LRU budget "
        "from RT_KV_CACHE_BYTES / kv_cache_bytes)",
        tag_keys=("deployment",)))


def proxy_requests_total() -> M.Counter:
    return _metric("proxy_requests", lambda: M.get_or_create(
        M.Counter, "rt_proxy_requests_total",
        "Requests handled per HTTP proxy process (multi-proxy spread)",
        tag_keys=("proxy",)))


def mux_requests_total() -> M.Counter:
    return _metric("mux_requests", lambda: M.get_or_create(
        M.Counter, "rt_serve_mux_requests_total",
        "Multiplexed model lookups by model id and cache outcome "
        "(hit / load)",
        tag_keys=("model_id", "outcome")))


def autoscale_decisions_total() -> M.Counter:
    return _metric("autoscale_decisions", lambda: M.get_or_create(
        M.Counter, "rt_serve_autoscale_decisions_total",
        "Controller scaling decisions applied, by direction "
        "(up / down / deploy)",
        tag_keys=("app", "deployment", "direction")))


# ---------------------------------------------------------------------------
# Serve span emission (batched drain into the GCS serve-event store)
# ---------------------------------------------------------------------------

_SPAN_FLUSH_S = float(os.environ.get("RT_SERVE_SPAN_FLUSH_S", "1.0"))
_SPAN_BUFFER_CAP = 4096

_span_lock = threading.Lock()
# deque: O(1) drop-oldest on overflow — emit_span sits on the request hot
# path, and a GCS outage must not turn every span append into an O(cap)
# list shift inside the lock
_span_buf: "collections.deque[Dict[str, Any]]" = collections.deque(
    maxlen=_SPAN_BUFFER_CAP)
_span_drainer: Optional[threading.Thread] = None
_dropped_spans = 0


def spans_enabled() -> bool:
    return os.environ.get("RT_SERVE_TRACE", "1") not in ("0", "false")


def emit_span(task_id: str, name: str, *, request_id: str, span_id: str,
              parent_span_id: Optional[str], t_start: float, t_end: float,
              phases: Optional[Dict[str, float]] = None,
              state: str = "FINISHED") -> None:
    """Buffer one serve span for the background drain. ``task_id`` must
    start with ``serve:`` so the GCS routes it into the serve store."""
    if not spans_enabled():
        return
    global _dropped_spans
    ev = {
        "task_id": task_id, "name": name, "state": state,
        "node_id": os.uname().nodename,
        "trace": {"trace_id": request_id, "span_id": span_id,
                  "parent_span_id": parent_span_id},
        "times": {"RUNNING": t_start, "FINISHED": t_end},
    }
    if phases:
        ev["phases"] = {k: max(0.0, v) for k, v in phases.items()}
    with _span_lock:
        if len(_span_buf) >= _SPAN_BUFFER_CAP:
            _dropped_spans += 1  # maxlen evicts the oldest on append
        _span_buf.append(ev)
    _ensure_drainer()


def _ensure_drainer() -> None:
    global _span_drainer
    if _span_drainer is not None and _span_drainer.is_alive():
        return
    with _span_lock:
        if _span_drainer is not None and _span_drainer.is_alive():
            return
        _span_drainer = threading.Thread(
            target=_drain_loop, daemon=True, name="rt-serve-span-drain")
        _span_drainer.start()


def _drain_loop() -> None:
    while True:
        time.sleep(_SPAN_FLUSH_S)
        try:
            flush_spans()
        except Exception:  # noqa: BLE001 — observability must never
            pass  # take the serve path down


def flush_spans() -> int:
    """Push buffered serve spans to the GCS in one batched RPC (tests and
    shutdown hooks call this directly). Returns the number shipped."""
    try:
        import ray_tpu

        if not ray_tpu.is_initialized():
            return 0
        backend = ray_tpu.global_worker()._require_backend()
        if not hasattr(backend, "_gcs"):
            return 0  # local_mode: no event store
    except Exception:  # noqa: BLE001
        return 0
    with _span_lock:
        if not _span_buf:
            return 0
        pending = list(_span_buf)
        _span_buf.clear()
    try:
        backend.io.run(backend._gcs.call(
            "task_events", {"events": pending}))
    except Exception:  # noqa: BLE001 — requeue for the next interval
        with _span_lock:
            # prepend so ordering holds; extendleft walks reversed input.
            # On overlap the maxlen deque evicts from the right (the
            # newest spans) — only reachable when a full buffer ALSO
            # failed to flush, where dropping some is already the deal
            _span_buf.extendleft(reversed(pending))
        return 0
    return len(pending)
