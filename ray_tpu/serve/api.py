"""Public serve API: ``@serve.deployment``, ``serve.run``, handles.

Reference analogs: ``serve/api.py`` (``deployment :320``, ``run :480``),
``serve/deployment.py`` (``Deployment``, ``Application``). An app is a DAG
of deployments composed by ``.bind()``: binding an ``Application`` as an
init arg gives the parent a ``DeploymentHandle`` to the child at replica
construction time (the reference's model-composition pattern).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Union

import ray_tpu
from ray_tpu.serve.config import (AutoscalingConfig, DeploymentConfig,
                                  HTTPOptions)
from ray_tpu.serve.controller import CONTROLLER_NAME, ServeController
from ray_tpu.serve.handle import DeploymentHandle, _HandleMarker

_controller_lock = threading.Lock()
_controller = None


def _get_controller(create: bool = False):
    """The singleton controller actor (named, discovered via get_actor).

    RPCs run OUTSIDE _controller_lock: a caller blocked in get_actor (e.g.
    a stale router poller racing a shutdown) must never wedge every other
    serve call behind the lock."""
    global _controller
    with _controller_lock:
        if _controller is not None:
            return _controller
    try:
        found = ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:  # noqa: BLE001 — not created yet
        if not create:
            raise RuntimeError(
                "serve is not running (no controller); call serve.run() "
                "or serve.start() first") from None
        # long-poll calls (get_replicas/get_routing_table wait=True)
        # each hold an actor thread — size the pool for many routers
        found = ServeController.options(
            name=CONTROLLER_NAME, max_concurrency=256,
            num_cpus=0, get_if_exists=True).remote()
    with _controller_lock:
        if _controller is None:
            _controller = found
        return _controller


def _forget_controller() -> None:
    global _controller
    with _controller_lock:
        _controller = None
    from ray_tpu.serve.handle import _reset_pool

    _reset_pool()


class Application:
    """A deployment bound with init args — the unit passed to serve.run."""

    def __init__(self, deployment: "Deployment", args: tuple, kwargs: dict):
        self._deployment = deployment
        self._args = args
        self._kwargs = kwargs


class Deployment:
    """The product of ``@serve.deployment`` — immutable; ``options`` copies."""

    def __init__(self, body: Union[type, Callable], name: str,
                 config: DeploymentConfig):
        self._body = body
        self.name = name
        self._config = config

    def options(self, *, name: Optional[str] = None,
                num_replicas: Optional[Union[int, str]] = None,
                max_ongoing_requests: Optional[int] = None,
                autoscaling_config: Optional[Union[Dict, AutoscalingConfig]] = None,
                user_config: Optional[Dict] = None,
                ray_actor_options: Optional[Dict] = None,
                health_check_period_s: Optional[float] = None,
                graceful_shutdown_timeout_s: Optional[float] = None,
                ) -> "Deployment":
        import dataclasses

        cfg = dataclasses.replace(self._config)
        if num_replicas == "auto":
            autoscaling_config = autoscaling_config or AutoscalingConfig()
            num_replicas = None
        if num_replicas is not None:
            cfg.num_replicas = num_replicas
        if max_ongoing_requests is not None:
            cfg.max_ongoing_requests = max_ongoing_requests
        if autoscaling_config is not None:
            if isinstance(autoscaling_config, dict):
                autoscaling_config = AutoscalingConfig(**autoscaling_config)
            cfg.autoscaling_config = autoscaling_config
        if user_config is not None:
            cfg.user_config = user_config
        if ray_actor_options is not None:
            cfg.ray_actor_options = ray_actor_options
        if health_check_period_s is not None:
            cfg.health_check_period_s = health_check_period_s
        if graceful_shutdown_timeout_s is not None:
            cfg.graceful_shutdown_timeout_s = graceful_shutdown_timeout_s
        return Deployment(self._body, name or self.name, cfg)

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)

    def __repr__(self) -> str:
        return f"Deployment({self.name})"


def deployment(_body=None, *, name: Optional[str] = None,
               num_replicas: Union[int, str, None] = None,
               max_ongoing_requests: Optional[int] = None,
               autoscaling_config: Optional[Union[Dict, AutoscalingConfig]] = None,
               user_config: Optional[Dict] = None,
               ray_actor_options: Optional[Dict] = None,
               health_check_period_s: Optional[float] = None,
               graceful_shutdown_timeout_s: Optional[float] = None):
    """``@serve.deployment`` on a class (or function) makes it deployable::

        @serve.deployment(num_replicas=2, ray_actor_options={"num_tpus": 1})
        class Model:
            def __call__(self, request): ...
    """

    def make(body):
        base = Deployment(body, getattr(body, "__name__", "deployment"),
                          DeploymentConfig())
        return base.options(
            name=name, num_replicas=num_replicas,
            max_ongoing_requests=max_ongoing_requests,
            autoscaling_config=autoscaling_config, user_config=user_config,
            ray_actor_options=ray_actor_options,
            health_check_period_s=health_check_period_s,
            graceful_shutdown_timeout_s=graceful_shutdown_timeout_s)

    if _body is not None:
        return make(_body)
    return make


def _collect_graph(app: Application, app_name: str,
                   out: List[Dict]) -> str:
    """DFS the bind graph; child Applications in args become handle markers.
    Returns this app node's deployment name."""

    def convert(obj):
        if isinstance(obj, Application):
            child = _collect_graph(obj, app_name, out)
            return _HandleMarker(app_name, child)
        if isinstance(obj, tuple):
            return tuple(convert(x) for x in obj)
        if isinstance(obj, list):
            return [convert(x) for x in obj]
        if isinstance(obj, dict):
            return {k: convert(v) for k, v in obj.items()}
        return obj

    dep = app._deployment
    entry = {"name": dep.name, "body": dep._body,
             "init_args": convert(app._args),
             "init_kwargs": convert(app._kwargs),
             "config": dep._config}
    existing = next((d for d in out if d["name"] == dep.name), None)
    if existing is None:
        out.append(entry)
    elif (existing["body"] is not dep._body
          or existing["init_args"] != entry["init_args"]
          or existing["init_kwargs"] != entry["init_kwargs"]
          or existing["config"] != dep._config):
        raise ValueError(
            f"deployment name {dep.name!r} bound twice with different "
            f"code/args/config — rename one with "
            f".options(name=...) (each name maps to ONE replica set)")
    return dep.name


def run(app: Application, *, name: str = "default",
        route_prefix: Optional[str] = "/",
        _blocking: bool = True,
        http_options: Optional[HTTPOptions] = None) -> DeploymentHandle:
    """Deploy an application; returns a handle to its ingress deployment."""
    if not isinstance(app, Application):
        raise TypeError("serve.run() takes an Application "
                        "(deployment.bind(...))")
    controller = _get_controller(create=True)
    deployments: List[Dict] = []
    ingress = _collect_graph(app, name, deployments)
    ray_tpu.get(controller.deploy_application.remote(
        name, route_prefix or "/", ingress, deployments))
    if route_prefix is not None:
        opts = http_options or HTTPOptions()
        ray_tpu.get(controller.ensure_proxy.remote(
            opts.host, opts.port, opts.num_proxies))
    if _blocking:
        ray_tpu.get(controller.wait_healthy.remote(name), timeout=120)
    return DeploymentHandle(name, ingress)


def start(http_options: Optional[HTTPOptions] = None) -> None:
    """Start the controller (and proxy fleet) without deploying anything."""
    controller = _get_controller(create=True)
    opts = http_options or HTTPOptions()
    ray_tpu.get(controller.ensure_proxy.remote(
        opts.host, opts.port, opts.num_proxies))


def start_grpc(host: str = "127.0.0.1", port: int = 0) -> int:
    """Start the gRPC ingress (reference: ``gRPCProxy``); returns the bound
    port. Callers hit ``/rt.serve/<app>[.<method>]`` with cloudpickled
    (args, kwargs) — see ``serve.grpc_proxy.grpc_request``."""
    controller = _get_controller(create=True)
    return ray_tpu.get(controller.ensure_grpc_proxy.remote(host, port))


def http_port() -> int:
    """The bound port of the (first) HTTP proxy (after serve.run/start)."""
    controller = _get_controller()
    return ray_tpu.get(controller.ensure_proxy.remote("127.0.0.1", 0))


def proxy_ports() -> List[int]:
    """Every bound HTTP proxy port, registry order (multi-proxy front
    doors — point a load balancer at all of them)."""
    controller = _get_controller()
    return ray_tpu.get(controller.proxy_ports.remote())


def get_app_handle(name: str = "default") -> DeploymentHandle:
    controller = _get_controller()
    apps = ray_tpu.get(controller.list_applications.remote())
    if name not in apps:
        raise KeyError(f"no application named {name!r}")
    return DeploymentHandle(name, apps[name]["ingress"])


def get_deployment_handle(deployment_name: str,
                          app_name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(app_name, deployment_name)


def status() -> Dict[str, Any]:
    controller = _get_controller()
    return ray_tpu.get(controller.list_applications.remote())


def detailed_status(decision_limit: int = 50) -> Dict[str, Any]:
    """Applications + per-deployment windowed stats (p50/p99/QPS/queue
    depth) + the autoscaler decision-log tail — what `rt serve status
    --verbose` and the dashboard Serve tab render."""
    controller = _get_controller()
    return ray_tpu.get(controller.serve_status.remote(decision_limit))


def delete(name: str) -> None:
    controller = _get_controller()
    ray_tpu.get(controller.delete_application.remote(name))


def shutdown() -> None:
    global _controller
    try:
        controller = _get_controller()
    except RuntimeError:
        return
    try:
        ray_tpu.get(controller.shutdown.remote(), timeout=30)
        ray_tpu.kill(controller)
    except Exception:  # noqa: BLE001 — already gone
        pass
    _forget_controller()
