"""ASGI adapter: deploy any ASGI3 app (FastAPI, Starlette, Quart, raw
callables) as a serve deployment, unchanged.

Reference analog: ``serve/_private/http_proxy.py:935`` (``HTTPProxy`` speaks
ASGI natively on uvicorn, and ``@serve.ingress(fastapi_app)`` mounts an app
on a deployment class). This framework's proxy hands replicas a picklable
``ServeRequest`` instead of a live ASGI connection, so the adapter runs the
ASGI protocol *inside the replica*: scope/receive/send are synthesized from
the request, and the app's send events are translated back into either a
buffered :class:`ASGIResponse` or a streamed response (first item an
:class:`ASGIResponseStart`, then body chunks) riding the existing response
stream machinery.

Two ways in:

- ``serve.asgi_app(app_or_factory)`` — wraps a bare ASGI app (or a
  zero-arg factory, for apps that aren't picklable) into a deployment body.
- ``@serve.ingress(app)`` on a deployment class — the class keeps its own
  ``__init__``/methods; HTTP traffic is routed through the app. The app can
  reach the live deployment instance as ``scope["extensions"]
  ["ray_tpu.deployment"]`` (FastAPI: ``request.scope[...]``) — a redesign
  of the reference's class-based-view binding, which rewrites FastAPI
  dependencies; here the instance is surfaced through the scope instead.

Lifespan: ``lifespan.startup`` runs once before the first request in the
replica; ``lifespan.shutdown`` is best-effort (replica teardown is process
teardown).

WebSockets: the proxy bridges an accepted aiohttp websocket to the replica
over two legs (reference: the uvicorn proxy speaks WS natively,
``serve/_private/http_proxy.py``): outbound app frames ride a streaming
actor call (``__ws_connect__`` yields accept/text/bytes/close events);
inbound client frames are pushed with per-connection-ordered
``__ws_push__`` calls onto the SAME replica. The ASGI websocket protocol
(connect/receive/disconnect in, accept/send/close out) runs inside the
replica, like the HTTP path.
"""

from __future__ import annotations

import asyncio
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = ["ASGIResponse", "ASGIResponseStart", "asgi_app", "ingress"]


class ASGIResponse:
    """Picklable buffered HTTP response produced by the ASGI adapter; the
    proxy maps it 1:1 onto the wire (status/headers/body)."""

    def __init__(self, status: int, headers: List[Tuple[str, str]],
                 body: bytes):
        self.status = status
        self.headers = headers
        self.body = body


class ASGIResponseStart:
    """First item of a streamed ASGI response: status + headers; the
    remaining stream items are body chunks."""

    def __init__(self, status: int, headers: List[Tuple[str, str]]):
        self.status = status
        self.headers = headers


def _build_scope(request, instance,
                 scope_type: str = "http") -> Dict[str, Any]:
    """ServeRequest -> ASGI HTTP/websocket scope. The path is the route-
    prefix-stripped path the proxy computed, so an app mounted at /api
    sees /."""
    from urllib.parse import urlencode

    # raw forms preserve repeated params/headers (?tag=a&tag=b, duplicate
    # Set-Cookie) that the convenience dicts collapse
    raw_headers = getattr(request, "raw_headers", None)
    header_items = raw_headers if raw_headers is not None \
        else (request.headers or {}).items()
    headers = [(k.lower().encode(), v.encode()) for k, v in header_items]
    raw_query = getattr(request, "raw_query", None)
    query_string = raw_query.encode() if raw_query is not None \
        else urlencode(request.query or {}).encode()
    scope = {
        "type": scope_type,
        "asgi": {"version": "3.0", "spec_version": "2.3"},
        "http_version": "1.1",
        "scheme": "http" if scope_type == "http" else "ws",
        "path": request.path,
        "raw_path": request.path.encode(),
        "query_string": query_string,
        "root_path": "",
        "headers": headers,
        "client": ("127.0.0.1", 0),
        "server": ("127.0.0.1", 0),
        "extensions": {"ray_tpu.deployment": instance},
    }
    if scope_type == "http":
        scope["method"] = request.method
    else:
        protos = (request.headers or {}).get("Sec-WebSocket-Protocol", "")
        scope["subprotocols"] = [p.strip() for p in protos.split(",")
                                 if p.strip()]
    return scope


async def _run_lifespan_startup(app) -> None:
    """Drive lifespan.startup once. Apps that don't implement lifespan
    (raise on the unknown scope type) are fine — ASGI allows that."""
    startup_done = asyncio.Event()
    failed: List[str] = []
    delivered = False

    async def receive():
        # startup exactly once; then park — the standard lifespan loop
        # calls receive() again waiting for lifespan.shutdown, which never
        # comes (replica teardown is process teardown)
        nonlocal delivered
        if not delivered:
            delivered = True
            return {"type": "lifespan.startup"}
        await asyncio.Event().wait()

    async def send(message):
        if message["type"] == "lifespan.startup.complete":
            startup_done.set()
        elif message["type"] == "lifespan.startup.failed":
            failed.append(message.get("message", ""))
            startup_done.set()

    async def run():
        try:
            await app({"type": "lifespan", "asgi": {"version": "3.0"}},
                      receive, send)
        except BaseException:  # noqa: BLE001 — app opted out of lifespan
            pass
        finally:
            # apps may RETURN from the lifespan scope without sending
            # startup.complete (e.g. `if scope["type"] != "http": return`)
            # — that must not park the first request forever
            startup_done.set()

    task = asyncio.ensure_future(run())
    await startup_done.wait()
    # keep the lifespan task alive for apps that hold state in it; replica
    # teardown is process teardown, so shutdown is implicit
    _lifespan_tasks.append(task)
    if failed:
        raise RuntimeError(f"ASGI lifespan startup failed: {failed[0]}")


_lifespan_tasks: List[asyncio.Task] = []


async def _call_asgi(app, request, instance):
    """Run one HTTP request through the app.

    Returns an :class:`ASGIResponse` when the app finished the body in its
    first write, else an async generator (``ASGIResponseStart`` then body
    chunks) so chunked/SSE/token streams flow incrementally through the
    replica's response-stream machinery.
    """
    scope = _build_scope(request, instance)
    body = request.body or b""
    sent_body = False
    events: asyncio.Queue = asyncio.Queue()
    # Set once the response has been fully delivered; a later receive()
    # then reports http.disconnect so apps polling is_disconnected() (SSE,
    # long-poll) unwind instead of parking their task forever.
    response_complete = asyncio.Event()

    async def receive():
        nonlocal sent_body
        if not sent_body:
            sent_body = True
            return {"type": "http.request", "body": body,
                    "more_body": False}
        await response_complete.wait()
        return {"type": "http.disconnect"}

    async def send(message):
        await events.put(message)

    app_task = asyncio.ensure_future(app(scope, receive, send))
    # retrieve the exception of an app that fails AFTER its response was
    # returned — an unobserved task exception warns at GC otherwise
    app_task.add_done_callback(
        lambda t: t.cancelled() or t.exception())

    async def next_event():
        # drain queued events before consulting the app task: the app may
        # have finished AFTER putting its final body messages
        if not events.empty():
            return events.get_nowait()
        if app_task.done():
            exc = app_task.exception()
            if exc is not None:
                raise exc
            return None  # app returned without completing the response
        getter = asyncio.ensure_future(events.get())
        await asyncio.wait({getter, app_task},
                           return_when=asyncio.FIRST_COMPLETED)
        if getter.done():
            return getter.result()
        getter.cancel()
        if not events.empty():
            return events.get_nowait()
        exc = app_task.exception()
        if exc is not None:
            raise exc
        return None

    try:
        start: Optional[Dict] = None
        while start is None:
            msg = await next_event()
            if msg is None:
                raise RuntimeError("ASGI app returned before response.start")
            if msg["type"] == "http.response.start":
                start = msg
        status = start["status"]
        headers = [(k.decode(), v.decode())
                   for k, v in start.get("headers", [])]

        first = await next_event()
        if first is None or first["type"] != "http.response.body":
            response_complete.set()
            return ASGIResponse(status, headers, b"")
        if not first.get("more_body"):
            if app_task.done() and app_task.exception():
                raise app_task.exception()
            response_complete.set()
            return ASGIResponse(status, headers,
                                bytes(first.get("body", b"")))
    except BaseException:
        response_complete.set()
        app_task.cancel()
        raise

    async def stream():
        try:
            yield ASGIResponseStart(status, headers)
            if first.get("body"):
                yield bytes(first["body"])
            while True:
                msg = await next_event()
                if msg is None:
                    return
                if msg["type"] != "http.response.body":
                    continue
                if msg.get("body"):
                    yield bytes(msg["body"])
                if not msg.get("more_body"):
                    return
        finally:
            # normal end, consumer cancel (GeneratorExit), or app error:
            # unblock the app's next receive() so its task exits
            response_complete.set()

    return stream()


# Per-connection inbound queues for websocket bridging; keyed by the
# proxy-generated connection id. Lives at module level: __ws_push__ actor
# calls and the __ws_connect__ stream land on the same replica process.
_WS_INBOX: Dict[str, asyncio.Queue] = {}


async def _run_ws_asgi(app, request, conn_id: str, instance):
    """Drive one websocket connection through the app; an async generator
    of outbound events for the proxy:

      {"kind": "accept", "subprotocol": ..., "headers": [...]}
      {"kind": "text", "data": str} / {"kind": "bytes", "data": bytes}
      {"kind": "close", "code": int, "reason": str}   (always last)

    Inbound client frames arrive via ``_WS_INBOX[conn_id]`` (pushed by
    ``__ws_push__``) and surface to the app as websocket.receive /
    websocket.disconnect messages."""
    scope = _build_scope(request, instance, scope_type="websocket")
    inbox: asyncio.Queue = asyncio.Queue()
    _WS_INBOX[conn_id] = inbox
    events: asyncio.Queue = asyncio.Queue()
    delivered_connect = False

    async def receive():
        nonlocal delivered_connect
        if not delivered_connect:
            delivered_connect = True
            return {"type": "websocket.connect"}
        msg = await inbox.get()
        kind = msg["kind"]
        if kind == "text":
            return {"type": "websocket.receive", "text": msg["data"]}
        if kind == "bytes":
            return {"type": "websocket.receive", "bytes": msg["data"]}
        return {"type": "websocket.disconnect",
                "code": msg.get("code", 1005)}

    async def send(message):
        await events.put(message)

    app_task = asyncio.ensure_future(app(scope, receive, send))
    app_task.add_done_callback(lambda t: t.cancelled() or t.exception())

    async def next_event():
        if not events.empty():
            return events.get_nowait()
        if app_task.done():
            exc = app_task.exception()
            if exc is not None:
                raise exc
            return None
        getter = asyncio.ensure_future(events.get())
        await asyncio.wait({getter, app_task},
                           return_when=asyncio.FIRST_COMPLETED)
        if getter.done():
            return getter.result()
        getter.cancel()
        if not events.empty():
            return events.get_nowait()
        exc = app_task.exception()
        if exc is not None:
            raise exc
        return None

    try:
        while True:
            msg = await next_event()
            if msg is None:
                # app returned without an explicit close
                yield {"kind": "close", "code": 1000, "reason": ""}
                return
            t = msg["type"]
            if t == "websocket.accept":
                yield {"kind": "accept",
                       "subprotocol": msg.get("subprotocol"),
                       "headers": [(k.decode(), v.decode()) for k, v in
                                   msg.get("headers", [])]}
            elif t == "websocket.send":
                if msg.get("text") is not None:
                    yield {"kind": "text", "data": msg["text"]}
                else:
                    yield {"kind": "bytes",
                           "data": bytes(msg.get("bytes", b""))}
            elif t == "websocket.close":
                yield {"kind": "close", "code": msg.get("code", 1000),
                       "reason": msg.get("reason", "")}
                return
    except (asyncio.CancelledError, GeneratorExit):
        # consumer torn down mid-stream: yielding a close frame from here
        # would raise "async generator ignored GeneratorExit" — cleanup
        # happens in finally, cancellation stays cancellation
        raise
    except BaseException as e:  # noqa: BLE001 — app error -> 1011 close
        yield {"kind": "close", "code": 1011, "reason": str(e)[:120]}
        return
    finally:
        _WS_INBOX.pop(conn_id, None)
        if not app_task.done():
            # unblock a receive()-parked app so its task can unwind
            inbox.put_nowait({"kind": "disconnect", "code": 1001})
            await asyncio.sleep(0)
            app_task.cancel()


class _ASGIAdapter:
    """Mixin driving requests through ``self._asgi_app``."""

    _asgi_app = None
    _asgi_startup: Optional[asyncio.Future] = None

    def _resolve_asgi_app(self):
        app = self._asgi_app
        if app is None:
            raise RuntimeError("no ASGI app bound")
        return app

    async def _ensure_startup(self):
        app = self._resolve_asgi_app()
        # one shared startup task: concurrent first requests all await the
        # SAME lifespan completion (not run the app pre-startup), and a
        # failed startup re-raises for every subsequent request
        if self._asgi_startup is None:
            self._asgi_startup = asyncio.ensure_future(
                _run_lifespan_startup(app))
        await asyncio.shield(self._asgi_startup)
        return app

    async def __call__(self, request):
        app = await self._ensure_startup()
        return await _call_asgi(app, request, self)

    async def __ws_connect__(self, request, conn_id: str):
        """Streaming entry for one websocket connection (called by the
        proxy); yields outbound events."""
        app = await self._ensure_startup()
        async for ev in _run_ws_asgi(app, request, conn_id, self):
            yield ev

    async def __ws_push__(self, conn_id: str, kind: str, data=None,
                          code: int = 1005) -> bool:
        """Inbound client frame (or disconnect) for a live connection.
        Returns False when the connection is already gone."""
        q = _WS_INBOX.get(conn_id)
        if q is None:
            return False
        q.put_nowait({"kind": kind, "data": data, "code": code})
        return True


def asgi_app(app_or_factory: Any) -> type:
    """Wrap an ASGI3 app — or a zero-arg factory returning one, for apps
    that don't cloudpickle — into a deployment body class.

    >>> serve.run(serve.deployment(serve.asgi_app(fastapi_app)))
    """

    class ASGIDeployment(_ASGIAdapter):
        def __init__(self):
            app = app_or_factory
            # a factory is a callable that is NOT itself an ASGI app; ASGI
            # apps take 3 args (scope, receive, send)
            if callable(app) and not _looks_like_asgi(app):
                app = app()
            self._asgi_app = app

    ASGIDeployment.__name__ = getattr(
        app_or_factory, "__name__", type(app_or_factory).__name__)
    return ASGIDeployment


def ingress(app: Any) -> Callable[[type], type]:
    """Class decorator mounting an ASGI app on a deployment class
    (reference: ``serve.ingress(fastapi_app)``). The class's ``__init__``
    and methods are untouched; HTTP requests route through ``app``, which
    can reach the instance via ``scope["extensions"]["ray_tpu.deployment"]``.
    """

    def wrap(cls: type) -> type:
        ns = {"_asgi_app_static": app}

        class Ingress(cls, _ASGIAdapter):  # type: ignore[misc, valid-type]
            def _resolve_asgi_app(self):
                return ns["_asgi_app_static"]

            async def __call__(self, request):
                return await _ASGIAdapter.__call__(self, request)

        Ingress.__name__ = cls.__name__
        Ingress.__qualname__ = cls.__qualname__
        return Ingress

    return wrap


def _looks_like_asgi(obj: Any) -> bool:
    """ASGI apps are callables taking (scope, receive, send); factories
    take zero args. Class instances (FastAPI, Starlette) are ASGI."""
    import inspect

    if not inspect.isfunction(obj) and not inspect.ismethod(obj):
        return True  # app objects (FastAPI etc.) — callable instances
    try:
        params = [
            p for p in inspect.signature(obj).parameters.values()
            if p.default is p.empty
            and p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
        ]
        return len(params) >= 3
    except (TypeError, ValueError):
        return True
