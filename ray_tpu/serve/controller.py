"""ServeController: the control plane actor for the serve layer.

Reference analogs: ``serve/controller.py:82`` (``ServeController``),
``_private/application_state.py:669`` (``ApplicationStateManager``),
``_private/deployment_state.py:1156`` (``DeploymentState`` reconciler) and
``_private/autoscaling_policy.py:12`` (``calculate_desired_num_replicas``).

One actor owns all desired/actual state:
  - ``deploy_application`` records the desired app graph;
  - a reconcile thread starts missing replicas, removes dead ones, and
    applies autoscaling decisions computed from polled per-replica
    ongoing-request counts with upscale/downscale hysteresis;
  - routers/proxies read versioned replica sets from ``get_replicas`` /
    ``get_routing_table``.

Methods are sync on purpose: they run on the actor's thread pool where
blocking ``ray_tpu.get`` is legal (async actor methods run on the worker's
io loop, which blocking calls would deadlock).

Scale-to-zero: a deployment with ``min_replicas=0`` drops to zero when idle;
a handle's ``wake`` RPC records demand, which the next reconcile tick serves
by starting a replica.
"""

from __future__ import annotations

import threading
import time
import traceback
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig
from ray_tpu.serve.replica import ReplicaActor

CONTROLLER_NAME = "RT_SERVE_CONTROLLER"
RECONCILE_PERIOD_S = 0.25
_METRICS_WINDOW_CAP = 512   # samples per deployment (one per reconcile tick)
_DECISION_LOG_CAP = 256
_STATUS_KV_KEY = "@serve/status"
_STATUS_PUSH_PERIOD_S = 1.0
_STATS_POLL_PERIOD_S = 1.0


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[idx]


class _ReplicaInfo:
    def __init__(self, replica_id: str, handle):
        self.replica_id = replica_id
        self.handle = handle
        self.last_health_check = time.time()
        self.last_ongoing = 0


class _DeploymentState:
    def __init__(self, app_name: str, name: str, config: DeploymentConfig,
                 body, init_args, init_kwargs):
        self.app_name = app_name
        self.name = name
        self.config = config
        self.body = body
        self.init_args = init_args
        self.init_kwargs = init_kwargs
        self.replicas: Dict[str, _ReplicaInfo] = {}
        # Replica-set version: assigned from the controller's GLOBAL counter
        # so versions stay monotonic across redeploys of the same name — a
        # long-polling router must never see a fresh state reuse a version
        # it already knows.
        self.version = 0
        self.next_replica_idx = 0
        # autoscaling bookkeeping: a bounded ring pruned in place — the
        # old list rebuild ran on every poll AND every target_replicas
        # call. Sized to cover the configured look-back at the per-tick
        # sample rate, or a long look_back_period_s would silently
        # average over a truncated window.
        cap = _METRICS_WINDOW_CAP
        ac = self.config.autoscaling_config
        if ac is not None:
            cap = max(cap, int(ac.look_back_period_s
                               / RECONCILE_PERIOD_S) + 16)
        self.metrics: "deque[Tuple[float, float]]" = deque(
            maxlen=cap)  # (t, total_ongoing)
        self.wake_requested_at: Optional[float] = None
        self.scale_candidate: Optional[int] = None
        self.scale_candidate_since: float = 0.0
        self.last_target: int = 0
        self.starting: Dict[str, Any] = {}  # replica_id -> (handle, ready ref)
        # windowed request stats from the last replica poll (the numbers
        # the decision log records and `rt serve status` prints)
        self.win_stats: Dict[str, Any] = {}
        self.last_stats_poll: float = 0.0
        # why the last target_replicas() returned what it did
        self.last_trigger: Dict[str, Any] = {}

    @property
    def autoscaling(self) -> Optional[AutoscalingConfig]:
        return self.config.autoscaling_config

    def _prune_metrics(self, now: float, keep_s: float) -> None:
        while self.metrics and now - self.metrics[0][0] > keep_s:
            self.metrics.popleft()

    def target_replicas(self, now: float) -> int:
        """Fixed num_replicas, or the autoscaler's desired count
        (reference ``calculate_desired_num_replicas``), extended with the
        optional queue-depth / p99 / QPS signals computed from the
        windowed stats poll — desired is the MAX across enabled signals
        and the trigger records which one drove it."""
        ac = self.autoscaling
        if ac is None:
            self.last_trigger = {"reason": "fixed",
                                 "num_replicas": self.config.num_replicas}
            return self.config.num_replicas
        current = len(self.replicas) + len(self.starting)
        self._prune_metrics(now, ac.look_back_period_s)
        total_ongoing = (sum(m[1] for m in self.metrics) / len(self.metrics)
                         if self.metrics else 0.0)
        desired = int(-(-total_ongoing // ac.target_ongoing_requests))  # ceil
        signal = "ongoing"
        # continuous-batching replicas queue INSIDE the engine (every
        # request is a stream, so the replica-level executor queue stays
        # ~0) — the engine's pending count must feed the queue signal or
        # the signal is blind on exactly the deployments it exists for
        queue_depth = (self.win_stats.get("queue_depth", 0)
                       + self.win_stats.get("cb_pending", 0))
        p99_s = self.win_stats.get("p99_s", 0.0)
        qps = self.win_stats.get("qps", 0.0)
        if ac.target_queue_depth is not None and queue_depth:
            by_queue = int(-(-queue_depth // ac.target_queue_depth))
            if by_queue > desired:
                desired, signal = by_queue, "queue_depth"
        if ac.target_qps_per_replica is not None and qps:
            by_qps = int(-(-qps // ac.target_qps_per_replica))
            if by_qps > desired:
                desired, signal = by_qps, "qps"
        if (ac.max_p99_s is not None and qps > 0 and p99_s > ac.max_p99_s
                and current + 1 > desired):
            # latency backstop: ask for one more than we have; the
            # hysteresis delay keeps a single slow window from thrashing
            desired, signal = current + 1, "p99"
        woke = (self.wake_requested_at is not None
                and now - self.wake_requested_at < 30.0)
        if woke:
            # cold-start demand: guarantee capacity even before metrics move
            desired = max(desired, 1)
        desired = max(ac.min_replicas, min(ac.max_replicas, desired))
        self.last_trigger = {
            "reason": "wake" if (woke and total_ongoing == 0) else "ongoing",
            "signal": signal,
            "ongoing_avg": round(total_ongoing, 3),
            "target_ongoing_requests": ac.target_ongoing_requests,
            "look_back_period_s": ac.look_back_period_s,
            "queue_depth": queue_depth,
            "p50_s": self.win_stats.get("p50_s", 0.0),
            "p99_s": p99_s,
            "qps": qps,
        }
        if desired == current:
            self.scale_candidate = None
            return current
        # hysteresis: hold the new value for the delay before acting
        if self.scale_candidate != desired:
            self.scale_candidate = desired
            self.scale_candidate_since = now
        delay = (ac.upscale_delay_s if desired > current
                 else ac.downscale_delay_s)
        self.last_trigger["hysteresis"] = {
            "candidate": desired, "held_s": round(
                now - self.scale_candidate_since, 3),
            "delay_s": delay}
        if now - self.scale_candidate_since >= delay:
            return desired
        return current


@ray_tpu.remote
class ServeController:
    def __init__(self):
        self._lock = threading.RLock()
        self._update_cond = threading.Condition(self._lock)
        self._apps: Dict[str, Dict[str, Any]] = {}
        self._deployments: Dict[Tuple[str, str], _DeploymentState] = {}
        self._routing_version = 0
        self._version_counter = 0
        self._proxy = None
        self._grpc_proxy = None
        self._grpc_port = None
        self._proxy_port: Optional[int] = None
        # multi-proxy scale-out: [(proxy_id, handle, port)]; entry 0 is
        # the back-compat RT_SERVE_PROXY on the requested port
        self._proxies: List[Tuple[str, Any, int]] = []  # rt: guarded-by(_lock)
        # serializes proxy *boots* only: actor creation + ready round-trips
        # take seconds and must never run under self._lock, which every
        # cheap status/routing getter shares (rt lint: lock-discipline)
        self._proxy_boot_lock = threading.Lock()
        self._shutdown = False
        # autoscaler decision log: every applied target change, with the
        # metric values that produced it (bounded; `rt serve status
        # --verbose`, /api/serve and the timeline serve lane read it)
        self._decisions: "deque" = deque(maxlen=_DECISION_LOG_CAP)
        self._last_status_push = 0.0
        self._reconciler = threading.Thread(
            target=self._reconcile_loop, daemon=True, name="rt-serve-rec")
        self._reconciler.start()

    # -- deploy ---------------------------------------------------------------
    def deploy_application(self, app_name: str, route_prefix: str,
                           ingress: str, deployments: List[Dict]) -> None:
        """deployments: [{name, body, init_args, init_kwargs, config}]"""
        with self._lock:
            new_names = {d["name"] for d in deployments}
            for key in [k for k in self._deployments
                        if k[0] == app_name and k[1] not in new_names]:
                self._stop_deployment(self._deployments.pop(key))
            self._apps[app_name] = {"route_prefix": route_prefix,
                                    "ingress": ingress}
            for d in deployments:
                cfg: DeploymentConfig = d["config"]
                cfg.validate()
                key = (app_name, d["name"])
                existing = self._deployments.get(key)
                if existing is not None:
                    # redeploy: new code/config — restart replicas
                    self._stop_deployment(existing)
                self._deployments[key] = _DeploymentState(
                    app_name, d["name"], cfg, d["body"], d["init_args"],
                    d["init_kwargs"])
            self._bump_routing()

    def delete_application(self, app_name: str) -> None:
        with self._lock:
            for key in [k for k in self._deployments if k[0] == app_name]:
                self._stop_deployment(self._deployments.pop(key))
            self._apps.pop(app_name, None)
            self._bump_routing()

    def wait_healthy(self, app_name: str, timeout_s: float = 60.0) -> bool:
        """Block until every deployment of the app has its minimum replica
        count running (autoscaling min may be 0 — then 'healthy' is free)."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            with self._lock:
                states = [s for (a, _), s in self._deployments.items()
                          if a == app_name]
                ok = states and all(
                    len(s.replicas) >= self._min_required(s) for s in states)
            if ok:
                return True
            time.sleep(0.05)
        raise TimeoutError(f"app {app_name!r} not healthy in {timeout_s}s")

    def _min_required(self, s: _DeploymentState) -> int:
        if s.autoscaling is not None:
            return s.autoscaling.min_replicas
        return s.config.num_replicas

    # -- routing --------------------------------------------------------------
    def _bump_routing(self) -> None:
        self._routing_version += 1
        self._update_cond.notify_all()

    def _next_version(self) -> int:
        self._version_counter += 1
        self._update_cond.notify_all()
        return self._version_counter

    def get_replicas(self, app_name: str, deployment: str,
                     known_version: int, wait: bool = False,
                     timeout: float = 10.0) -> Dict[str, Any]:
        """``wait=True`` long-polls: block until the replica set's version
        moves past ``known_version`` or the timeout lapses (reference:
        ``LongPollHost``, ``serve/_private/long_poll.py`` — handles hold ONE
        blocked call instead of TTL-polling). Runs on the controller's actor
        thread pool, so blocking here is legal and local; version bumps
        ``notify_all`` the condition, so waiters wake immediately."""
        deadline = time.time() + timeout
        with self._update_cond:
            while True:
                s = self._deployments.get((app_name, deployment))
                version = s.version if s is not None else known_version
                remaining = deadline - time.time()
                if (not wait or version != known_version
                        or remaining <= 0 or self._shutdown):
                    if s is None:
                        return {"version": known_version, "replicas": []}
                    return {"version": s.version,
                            "replicas": [(r.replica_id, r.handle)
                                         for r in s.replicas.values()]}
                self._update_cond.wait(remaining)

    def get_routing_table(self, known_version: int = -1, wait: bool = False,
                          timeout: float = 10.0) -> Dict[str, Any]:
        """For proxies: route_prefix -> (app, ingress deployment); long-polls
        like ``get_replicas`` when ``wait=True``."""
        deadline = time.time() + timeout
        with self._update_cond:
            while True:
                remaining = deadline - time.time()
                if (not wait or self._routing_version != known_version
                        or remaining <= 0 or self._shutdown):
                    return {
                        "version": self._routing_version,
                        "routes": {meta["route_prefix"]: (app, meta["ingress"])
                                   for app, meta in self._apps.items()}}
                self._update_cond.wait(remaining)

    def wake(self, app_name: str, deployment: str) -> None:
        with self._lock:
            s = self._deployments.get((app_name, deployment))
            if s is not None:
                s.wake_requested_at = time.time()

    def list_applications(self) -> Dict[str, Any]:
        with self._lock:
            out = {}
            for app, meta in self._apps.items():
                deps = {}
                for (a, name), s in self._deployments.items():
                    if a != app:
                        continue
                    deps[name] = {
                        "replicas": len(s.replicas),
                        "starting": len(s.starting),
                        "target": s.last_target,
                        "autoscaling": s.autoscaling is not None,
                        "stats": dict(s.win_stats),
                    }
                out[app] = {"route_prefix": meta["route_prefix"],
                            "ingress": meta["ingress"], "deployments": deps}
            return out

    def get_decisions(self, limit: int = 50) -> List[Dict[str, Any]]:
        """Tail of the autoscaler decision log, oldest first."""
        with self._lock:
            return list(self._decisions)[-limit:]

    def serve_status(self, decision_limit: int = 50) -> Dict[str, Any]:
        """Everything `rt serve status` / the dashboard Serve tab renders:
        applications with per-deployment windowed stats, plus the
        decision-log tail."""
        return {"applications": self.list_applications(),
                "decisions": self.get_decisions(decision_limit),
                "proxies": self._proxy_rows(),
                "t": time.time()}

    def flush_metrics(self) -> None:
        """Push the controller's metric registry to the KV now (tests)."""
        from ray_tpu.util import metrics

        metrics.flush_now()

    def get_ingress(self, app_name: str):
        """Ingress deployment name of one application (gRPC proxy lookup)."""
        with self._lock:
            meta = self._apps.get(app_name)
            return meta["ingress"] if meta else None

    def ensure_grpc_proxy(self, host: str, port: int) -> int:
        """gRPC ingress (reference: ``gRPCProxy``); idempotent like the
        HTTP proxy."""
        from ray_tpu.serve.grpc_proxy import GrpcProxyActor

        with self._proxy_boot_lock:
            with self._lock:
                if self._grpc_proxy is not None:
                    return self._grpc_port
            if self._shutdown:
                # shutdown held the boot lock first and already tore the
                # proxies down — booting now would leak past teardown
                raise RuntimeError("serve controller is shut down")
            # boot OUTSIDE self._lock: the ready round-trip takes seconds
            # and would convoy every status/routing getter behind it
            handle = GrpcProxyActor.options(
                name="RT_SERVE_GRPC_PROXY", max_concurrency=256,
                num_cpus=0).remote(host, port)
            try:
                # rt: lint-allow(lock-discipline) the boot lock's whole
                # job is to serialize this slow boot; nothing latency-
                # sensitive contends on it (self._lock must stay free)
                got = ray_tpu.get(handle.ready.remote())
            except BaseException:
                # a half-booted NAMED actor left alive would block every
                # retry with "actor name taken" and escape shutdown
                try:
                    ray_tpu.kill(handle)
                except Exception:  # noqa: BLE001 — best-effort reap
                    pass
                raise
            with self._lock:
                self._grpc_proxy, self._grpc_port = handle, got
                return self._grpc_port

    # -- http proxy -----------------------------------------------------------
    def ensure_proxy(self, host: str, port: int, count: int = 1) -> int:
        """Start (up to) ``count`` HTTP proxy processes; idempotent and
        grow-only — a later call with a larger ``count`` adds proxies,
        a smaller one never tears running ones down (requests may be in
        flight). The first proxy keeps the RT_SERVE_PROXY name and the
        requested port; the rest bind ephemeral ports and register in
        the GCS proxy registry so an external load balancer (or
        ``serve.proxy_ports()``) can fan traffic across every event
        loop instead of queueing behind one aiohttp process."""
        from ray_tpu.serve.proxy import ProxyActor

        want = max(1, int(count))
        # the boot lock (not self._lock) serializes concurrent growers:
        # each actor boot + start round-trip takes seconds, and holding
        # self._lock across it used to freeze every status/routing getter
        with self._proxy_boot_lock:
            while True:
                with self._lock:
                    idx = len(self._proxies)
                    if idx >= want:
                        return self._proxy_port
                if self._shutdown:
                    # shutdown held the boot lock first and already tore
                    # the proxies down — booting now would leak past it
                    raise RuntimeError("serve controller is shut down")
                proxy_id = "proxy-0" if idx == 0 else f"proxy-{idx}"
                name = ("RT_SERVE_PROXY" if idx == 0
                        else f"RT_SERVE_PROXY_{idx}")
                handle = ProxyActor.options(
                    name=name, max_concurrency=256, num_cpus=0).remote()
                bind_port = port if idx == 0 else 0
                try:
                    # rt: lint-allow(lock-discipline) boot lock again:
                    # held across the boot on purpose, cheap getters use
                    # self._lock
                    got = ray_tpu.get(handle.start.remote(host, bind_port,
                                                          proxy_id))
                except BaseException:
                    # reap the half-booted named actor or its name blocks
                    # every retry and it escapes shutdown teardown
                    try:
                        ray_tpu.kill(handle)
                    except Exception:  # noqa: BLE001 — best-effort reap
                        pass
                    raise
                with self._lock:
                    self._proxies.append((proxy_id, handle, got))
                    if idx == 0:
                        self._proxy, self._proxy_port = handle, got
                self._register_proxy(proxy_id, host, got)

    def proxy_ports(self) -> List[int]:
        with self._lock:
            return [p for _, _, p in self._proxies]

    def _proxy_rows(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [{"proxy": pid, "port": port}
                    for pid, _, port in self._proxies]

    def _register_proxy(self, proxy_id: str, host: str, port: int) -> None:
        """Best-effort row in the GCS proxy registry (``rt serve status``
        and external LB config readers see every front door)."""
        try:
            backend = ray_tpu.global_worker()._require_backend()
            if hasattr(backend, "_gcs"):
                backend.io.run(backend._gcs.call(
                    "serve_proxy_register",
                    {"proxy_id": proxy_id, "host": host, "port": port,
                     "pid": None}))
        except Exception:  # noqa: BLE001 — registry is advisory
            pass

    def _deregister_proxies(self) -> None:
        try:
            backend = ray_tpu.global_worker()._require_backend()
            if hasattr(backend, "_gcs"):
                backend.io.run(backend._gcs.call(
                    "serve_proxy_deregister", {"proxy_id": "*"}))
        except Exception:  # noqa: BLE001
            pass

    # -- reconcile ------------------------------------------------------------
    def _reconcile_loop(self) -> None:
        while not self._shutdown:
            try:
                self._reconcile_once()
            except Exception:  # noqa: BLE001 — keep the loop alive
                traceback.print_exc()
            time.sleep(RECONCILE_PERIOD_S)

    def _reconcile_once(self) -> None:
        now = time.time()
        with self._lock:
            states = list(self._deployments.values())
        for s in states:
            self._adopt_started(s)
            self._poll_metrics(s, now)
            rec = None
            with self._lock:
                old_target = s.last_target
                target = s.target_replicas(now)
                s.last_target = target
                current = len(s.replicas) + len(s.starting)
                if target != old_target:
                    rec = self._record_decision(s, old_target, target, now)
                if current < target:
                    for _ in range(target - current):
                        self._start_replica(s)
                elif current > target:
                    self._remove_replicas(s, current - target)
            if rec is not None:
                # best-effort mirror into the GCS serve-event feed (the
                # timeline serve lane joins decisions against the request
                # spans) — a blocking RPC, so OUTSIDE the lock that
                # routers' long-polls contend on
                try:
                    backend = ray_tpu.global_worker()._require_backend()
                    if hasattr(backend, "_gcs"):
                        backend.io.run(
                            backend._gcs.call("serve_event", dict(rec)))
                except Exception:  # noqa: BLE001
                    pass
            self._health_check(s, now)
        self._push_status_snapshot(now)

    def _record_decision(self, s: _DeploymentState, old_target: int,
                         new_target: int, now: float) -> Dict[str, Any]:
        """Stamp one scaling decision (caller holds the lock): old->new
        target, the triggering metric values, and the hysteresis state —
        so "why did it scale?" is answerable after the fact."""
        direction = ("deploy" if old_target == 0 and s.next_replica_idx == 0
                     else "up" if new_target > old_target else "down")
        rec = {"t": now, "kind": "autoscale_decision",
               "app": s.app_name, "deployment": s.name,
               "old_target": old_target, "new_target": new_target,
               "direction": direction,
               "trigger": dict(s.last_trigger),
               "replicas": len(s.replicas), "starting": len(s.starting)}
        self._decisions.append(rec)
        try:
            from ray_tpu.serve import obs

            obs.autoscale_decisions_total().inc(tags={
                "app": s.app_name, "deployment": s.name,
                "direction": direction})
        except Exception:  # noqa: BLE001 — telemetry best-effort
            pass
        return rec

    def _push_status_snapshot(self, now: float) -> None:
        """Throttled compact status snapshot into the GCS KV, so `rt
        doctor` can grade serve health without attaching a driver."""
        if now - self._last_status_push < _STATUS_PUSH_PERIOD_S:
            return
        self._last_status_push = now
        try:
            import json

            backend = ray_tpu.global_worker()._require_backend()
            if not hasattr(backend, "kv_put"):
                return
            with self._lock:
                deployments = [
                    {"app": s.app_name, "name": s.name,
                     "replicas": len(s.replicas),
                     "starting": len(s.starting),
                     "target": s.last_target,
                     "autoscaling": s.autoscaling is not None,
                     **{k: s.win_stats.get(k, 0) for k in
                        ("ongoing", "queue_depth", "p50_s", "p99_s",
                         "qps")}}
                    for s in self._deployments.values()]
            backend.kv_put(_STATUS_KV_KEY, json.dumps(
                {"t": now, "deployments": deployments}).encode())
        except Exception:  # noqa: BLE001 — snapshot best-effort
            pass

    def _start_replica(self, s: _DeploymentState) -> None:
        rid = f"{s.app_name}#{s.name}#{s.next_replica_idx}"
        s.next_replica_idx += 1
        opts = dict(s.config.ray_actor_options or {})
        opts.setdefault("num_cpus", 0.1)
        # replicas spread across nodes by default (reference:
        # SpreadDeploymentSchedulingPolicy) — one node dying must not take a
        # whole deployment's replica set with it
        if "scheduling_strategy" not in opts:
            from ray_tpu.core.task_spec import SpreadStrategy

            opts["scheduling_strategy"] = SpreadStrategy()
        opts["max_concurrency"] = max(16, s.config.max_ongoing_requests + 4)
        opts["name"] = f"RT_SERVE:{rid}"
        handle = ReplicaActor.options(**opts).remote(
            s.name, s.app_name, rid, s.body, s.init_args, s.init_kwargs,
            s.config.max_ongoing_requests, s.config.user_config)
        # readiness probe: the first health check resolving means __init__ ran
        s.starting[rid] = (handle, handle.check_health.remote())

    def _adopt_started(self, s: _DeploymentState) -> None:
        with self._lock:
            pending = list(s.starting.items())
        for rid, (handle, ready_ref) in pending:
            done, _ = ray_tpu.wait([ready_ref], num_returns=1, timeout=0)
            if not done:
                continue
            with self._lock:
                s.starting.pop(rid, None)
            try:
                ray_tpu.get(done[0])
            except Exception:  # init failed — drop; next tick restarts
                traceback.print_exc()
                continue
            with self._lock:
                s.replicas[rid] = _ReplicaInfo(rid, handle)
                s.version = self._next_version()
                self._bump_routing()

    def _poll_metrics(self, s: _DeploymentState, now: float) -> None:
        """Windowed stats poll: every replica reports ongoing, executor
        queue depth and its recent request latencies in ONE RPC; the merge
        feeds the autoscaler, the decision log, the `rt_serve_ongoing` /
        `rt_serve_queue_depth` gauges and `rt serve status`.

        The stats poll re-ships up to 200 latency floats per replica, so
        it runs at the 1 s status cadence (its consumers — snapshot,
        gauges, decision log — are 1 s-grained), not per reconcile tick;
        autoscaled deployments keep the cheap per-tick ``ongoing_count``
        sample in between so the look-back average keeps its resolution."""
        with self._lock:
            reps = list(s.replicas.values())
        if now - s.last_stats_poll < _STATS_POLL_PERIOD_S:
            if s.autoscaling is None:
                return
            total = 0
            if reps:
                refs = [r.handle.ongoing_count.remote() for r in reps]
                ready, _ = ray_tpu.wait(refs, num_returns=len(refs),
                                        timeout=2.0)
                for r, ref in zip(reps, refs):
                    if ref in ready:
                        try:
                            r.last_ongoing = ray_tpu.get(ref)
                            total += r.last_ongoing
                        except Exception:  # noqa: BLE001
                            pass
            with self._lock:
                s.metrics.append((now, total))
            return
        s.last_stats_poll = now
        total_ongoing = 0
        total_queue = 0
        completed = 0
        qps = 0.0
        window_s = 30.0
        lats: List[float] = []
        cb = {"active": 0, "max_slots": 0, "pending": 0,
              "tokens_generated": 0, "requests_completed": 0}
        cb_seen = False
        kv = {"hits": 0, "misses": 0, "evictions": 0, "bytes": 0,
              "pages": 0, "hit_tokens": 0}
        kv_seen = False
        # engine flight-recorder rollup (attainment/goodput averaged,
        # gap p99 worst-of-fleet): the replica's engine_stats() carries
        # its recorder summary, and `rt serve status` shows the fleet
        # SLO picture without a second RPC
        eng_roll = {"ttft_att": 0.0, "tpot_att": 0.0, "goodput": 0.0,
                    "gap_p99": 0.0, "n": 0}
        if reps:
            refs = [r.handle.stats_window.remote(window_s) for r in reps]
            ready, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=2.0)
            for r, ref in zip(reps, refs):
                if ref in ready:
                    try:
                        st = ray_tpu.get(ref)
                        r.last_ongoing = st.get("ongoing", 0)
                        total_ongoing += r.last_ongoing
                        total_queue += st.get("queue_depth", 0)
                        completed += st.get("completed", 0)
                        # per-replica effective window: a saturated latency
                        # ring reports the shorter span it actually covers
                        qps += (st.get("completed", 0)
                                / max(1e-3, st.get("window_s", window_s)))
                        lats.extend(st.get("latencies") or ())
                        eng = st.get("engine")
                        if eng:
                            # continuous-batching engines report slot
                            # occupancy; the sum is the deployment's
                            # live decode capacity picture
                            cb_seen = True
                            for k in cb:
                                cb[k] += eng.get(k, 0)
                            ekv = eng.get("kv")
                            if ekv:
                                # prefix/KV-cache plane: summed over the
                                # replica fleet (monotonic counters +
                                # live bytes/pages)
                                kv_seen = True
                                for k in kv:
                                    kv[k] += ekv.get(k, 0)
                            rec = eng.get("recorder")
                            if rec and rec.get("window_completed"):
                                eng_roll["n"] += 1
                                eng_roll["ttft_att"] += rec.get(
                                    "ttft_attainment", 0.0)
                                eng_roll["tpot_att"] += rec.get(
                                    "tpot_attainment", 0.0)
                                eng_roll["goodput"] += rec.get(
                                    "goodput_tok_s", 0.0)
                                eng_roll["gap_p99"] = max(
                                    eng_roll["gap_p99"],
                                    rec.get("tick_gap_p99_s", 0.0))
                    except Exception:  # noqa: BLE001 — health check handles it
                        pass
        lats.sort()
        win = {"ongoing": total_ongoing, "queue_depth": total_queue,
               "completed": completed, "window_s": window_s,
               "qps": round(qps, 3),
               "p50_s": round(_percentile(lats, 0.50), 6),
               "p99_s": round(_percentile(lats, 0.99), 6)}
        if cb_seen:
            win["cb_active"] = cb["active"]
            win["cb_slots"] = cb["max_slots"]
            win["cb_pending"] = cb["pending"]
            # monotonic engine counters (summed over replicas): `rt
            # serve status` and pollers difference these across windows
            # instead of inferring load from instantaneous occupancy
            win["cb_tokens_generated"] = cb["tokens_generated"]
            win["cb_requests_completed"] = cb["requests_completed"]
        if eng_roll["n"]:
            n = eng_roll["n"]
            win["eng_ttft_att"] = round(eng_roll["ttft_att"] / n, 4)
            win["eng_tpot_att"] = round(eng_roll["tpot_att"] / n, 4)
            win["eng_goodput_tok_s"] = round(eng_roll["goodput"], 1)
            win["eng_gap_p99_s"] = round(eng_roll["gap_p99"], 6)
        if kv_seen:
            win["kv_hits"] = kv["hits"]
            win["kv_misses"] = kv["misses"]
            win["kv_evictions"] = kv["evictions"]
            win["kv_bytes"] = kv["bytes"]
            win["kv_pages"] = kv["pages"]
            win["kv_hit_tokens"] = kv["hit_tokens"]
            lookups = kv["hits"] + kv["misses"]
            # lifetime hit rate: `rt serve status` / the dashboard show
            # this as the hit-rate column; pollers wanting a windowed
            # rate difference the monotonic hits/misses across polls
            win["kv_hit_rate"] = round(kv["hits"] / lookups, 4) \
                if lookups else 0.0
        with self._lock:
            s.win_stats = win
            s.metrics.append((now, total_ongoing))
        try:
            from ray_tpu.serve import obs

            tags = {"app": s.app_name, "deployment": s.name}
            obs.ongoing_gauge().set(total_ongoing, tags=tags)
            obs.queue_depth_gauge().set(total_queue, tags=tags)
        except Exception:  # noqa: BLE001 — telemetry best-effort
            pass

    def _health_check(self, s: _DeploymentState, now: float) -> None:
        with self._lock:
            due = [r for r in s.replicas.values()
                   if now - r.last_health_check >= s.config.health_check_period_s]
            for r in due:
                r.last_health_check = now
        for r in due:
            ref = r.handle.check_health.remote()
            ready, _ = ray_tpu.wait([ref], num_returns=1,
                                    timeout=s.config.health_check_timeout_s)
            ok = False
            if ready:
                try:
                    ray_tpu.get(ready[0])
                    ok = True
                except Exception:  # noqa: BLE001
                    pass
            if not ok:
                with self._lock:
                    s.replicas.pop(r.replica_id, None)
                    s.version = self._next_version()
                    self._bump_routing()
                try:
                    ray_tpu.kill(r.handle)
                except Exception:  # noqa: BLE001
                    pass

    def _remove_replicas(self, s: _DeploymentState, n: int) -> None:
        # caller holds the lock; prefer tearing down still-starting replicas
        for rid in list(s.starting)[:n]:
            handle, _ = s.starting.pop(rid)
            n -= 1
            try:
                ray_tpu.kill(handle)
            except Exception:  # noqa: BLE001
                pass
        if n <= 0:
            return
        victims = sorted(s.replicas.values(),
                         key=lambda r: r.last_ongoing)[:n]
        for r in victims:
            del s.replicas[r.replica_id]
            s.version = self._next_version()
            self._bump_routing()
            threading.Thread(
                target=self._drain_and_kill,
                args=(r.handle, s.config.graceful_shutdown_timeout_s),
                daemon=True).start()

    def _drain_and_kill(self, handle, timeout_s: float) -> None:
        try:
            ref = handle.prepare_shutdown.remote(timeout_s)
            ray_tpu.wait([ref], num_returns=1, timeout=timeout_s + 5.0)
        except Exception:  # noqa: BLE001
            pass
        try:
            ray_tpu.kill(handle)
        except Exception:  # noqa: BLE001
            pass

    def _stop_deployment(self, s: _DeploymentState) -> None:
        # caller holds the lock
        for rid in list(s.starting):
            handle, _ = s.starting.pop(rid)
            try:
                ray_tpu.kill(handle)
            except Exception:  # noqa: BLE001
                pass
        for r in list(s.replicas.values()):
            try:
                ray_tpu.kill(r.handle)
            except Exception:  # noqa: BLE001
                pass
        s.replicas.clear()
        s.version = self._next_version()
        self._bump_routing()
        # stale-label removal: a deleted deployment's gauges must not
        # linger on the Prometheus page forever
        try:
            from ray_tpu.serve import obs

            tags = {"app": s.app_name, "deployment": s.name}
            obs.ongoing_gauge().remove(tags=tags)
            obs.queue_depth_gauge().remove(tags=tags)
        except Exception:  # noqa: BLE001
            pass

    def shutdown(self) -> None:
        self._shutdown = True
        try:
            # drop the status snapshot: doctor must not grade a dead
            # serve instance's numbers (it also skips stale stamps)
            backend = ray_tpu.global_worker()._require_backend()
            if hasattr(backend, "kv_del"):
                backend.kv_del(_STATUS_KV_KEY)
        except Exception:  # noqa: BLE001
            pass
        with self._update_cond:
            self._update_cond.notify_all()  # release blocked long-polls
        # the boot lock serializes against an in-flight ensure_proxy /
        # ensure_grpc_proxy on another controller thread: without it, a
        # proxy mid-boot would be appended+registered AFTER the teardown
        # below swapped the list, leaking a live actor past shutdown
        # rt: lint-allow(lock-discipline) boot lock: held across the
        # proxy stop RPCs on purpose (see ensure_proxy)
        with self._proxy_boot_lock:
            self._deregister_proxies()
            with self._lock:
                for key in list(self._deployments):
                    self._stop_deployment(self._deployments.pop(key))
                self._apps.clear()
                proxies, self._proxies = list(self._proxies), []
                self._proxy = None
                gproxy, self._grpc_proxy = self._grpc_proxy, None
            for _, proxy, _ in proxies:
                try:
                    # rt: lint-allow(lock-discipline) shutdown stop RPC:
                    # the boot lock is held on purpose (header comment)
                    ray_tpu.get(proxy.stop.remote())
                    ray_tpu.kill(proxy)
                except Exception:  # noqa: BLE001
                    pass
            if gproxy is not None:
                try:
                    # rt: lint-allow(lock-discipline) same as above
                    ray_tpu.get(gproxy.shutdown.remote())
                    ray_tpu.kill(gproxy)
                except Exception:  # noqa: BLE001
                    pass
