"""Live stack capture: the py-spy-equivalent observability surface.

Reference analog: ``dashboard/modules/reporter/profile_manager.py`` shells
out to py-spy for stack/flamegraph captures of worker processes. Redesign:
workers are cooperating Python processes with RPC servers already, so the
dashboard asks each worker to snapshot ``sys._current_frames()`` in-process
— no ptrace, no external binary, works in containers that forbid
SYS_PTRACE. The trade-off vs py-spy: a worker wedged in a C extension
without releasing the GIL can't respond; its entry reports unreachable
(the signal that you need SIGUSR1/faulthandler — which workers also
register — or a real profiler).
"""

from __future__ import annotations

import sys
import threading
import traceback
from typing import Dict


def format_current_stacks() -> str:
    """All threads of THIS process, python-traceback formatted."""
    names: Dict[int, str] = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in sorted(sys._current_frames().items()):
        out.append(f"--- thread {tid} ({names.get(tid, '?')}) ---")
        out.extend(line.rstrip()
                   for line in traceback.format_stack(frame))
    return "\n".join(out)
