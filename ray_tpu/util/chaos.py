"""Chaos plane: seeded, deterministic fault injection for the whole runtime.

Reference analogs: the ``NodeKiller``/``WorkerKillerActor`` fault injectors
behind Ray's chaos suites (``_private/test_utils.py:1401``) and the
``RAY_testing_*`` fault-injection flags — except first-class: a
:class:`ChaosPlan` is a *seeded, replayable* set of faults armed against
**named injection sites** threaded through the raylet, GCS, worker-core, RPC
layer and object store. The same plan + seed produces the same fire
sequence, so a chaos test is an assertion, not a dice roll.

Sites (see README "Chaos & recovery" for the effects table):

  ======================  ====================================================
  site                    fires in / effect
  ======================  ====================================================
  ``worker.kill``         worker process, at task/actor-method entry:
                          ``os._exit(137)`` mid-execution
  ``raylet.kill_worker``  raylet ``_run_task``: SIGKILL the acquired worker
                          just before the push (counters live in the
                          long-lived raylet — use this for kill-once plans)
  ``raylet.heartbeat_drop``  raylet heartbeat loop: skip the beat
                          (partition raylet from the GCS -> node death)
  ``gcs.kill``            GCS heartbeat handler: ``os._exit(137)`` when the
                          GCS runs as a standalone daemon (``rt start``);
                          suppressed (stamped only) for an in-process GCS
  ``rpc.delay``           RpcClient.call: sleep ``delay_s`` before sending
                          (``target`` matches the method name)
  ``rpc.drop``            RpcClient.call: raise ConnectionLost instead of
                          sending (simulated partition)
  ``object.lose``         raylet seal path: the object's store copy (and any
                          spill file) is deleted right after its location
                          registers — every later get must reconstruct
  ``spill.slow``          raylet spill executor: sleep ``delay_s`` per
                          spilled object (slow disk)
  ``oom.pressure``        raylet memory monitor: report fake node memory at
                          ``value`` (fraction, default 0.99) -> OOM kill
  ======================  ====================================================

Fault spec fields (all optional except ``site``): ``at`` (fire exactly on
hit #N of the site, 1-based), ``after`` (fire on every hit > N), ``prob``
(fire with seeded probability), ``max_fires`` (stop after M fires),
``target`` (substring match against the site's target, e.g. a method or
function name), ``delay_s`` / ``value`` (effect parameters). Counters are
**per process**: a killed worker's replacement starts fresh, so kill-once
plans belong on the raylet-side sites.

Distribution: ``rt chaos arm`` ships the plan to the GCS
(``rpc_chaos_arm``), which stores it in the KV under ``@chaos/plan`` and
bumps a revision that rides every heartbeat reply; raylets see the new rev,
fetch the plan, arm their own process, forward it to live workers
(``chaos_arm`` worker RPC) and inject ``RT_CHAOS_PLAN_JSON`` into every new
worker's env. Every fired fault stamps a FailureEvent with
``origin="chaos"`` into the PR 5 feed — injected and organic failures stay
distinguishable (``rt errors --origin chaos`` / ``--origin organic``) — and
ticks ``rt_chaos_injections_total{site=}``.
"""

from __future__ import annotations

import json
import random
import threading
import time
from typing import Any, Dict, List, Optional

ORIGIN_CHAOS = "chaos"

# site -> the failure category its injection event is stamped with
# (core/failure.py taxonomy; values must stay inside F.CATEGORIES)
SITE_CATEGORIES: Dict[str, str] = {
    "worker.kill": "worker_crash",
    "raylet.kill_worker": "worker_crash",
    "raylet.heartbeat_drop": "node_death",
    "gcs.kill": "node_death",
    "rpc.delay": "unknown",
    "rpc.drop": "unknown",
    "object.lose": "object_lost",
    "spill.slow": "unknown",
    "oom.pressure": "oom_kill",
}
SITES = tuple(SITE_CATEGORIES)

_FAULT_FIELDS = ("site", "at", "after", "prob", "max_fires", "target",
                 "delay_s", "value")


class ChaosPlan:
    """A seeded list of fault specs. Validates eagerly so a typo'd site
    fails at arm time, not silently never-fires. ``nonce`` is stamped by
    the GCS per explicit ``rt chaos arm``: a DELIBERATE re-arm of the
    same faults gets a fresh nonce (counters reset, the experiment
    repeats), while re-announcements of one stored plan (head restart,
    worker forwards) carry the same nonce and stay idempotent."""

    def __init__(self, seed: int = 0,
                 faults: Optional[List[Dict[str, Any]]] = None,
                 nonce: int = 0):
        self.seed = int(seed)
        self.nonce = int(nonce)
        self.faults: List[Dict[str, Any]] = []
        for f in faults or ():
            if not isinstance(f, dict) or "site" not in f:
                raise ValueError(f"fault {f!r} needs a 'site'")
            if f["site"] not in SITE_CATEGORIES:
                raise ValueError(
                    f"unknown injection site {f['site']!r}; valid sites: "
                    f"{', '.join(SITES)}")
            unknown = set(f) - set(_FAULT_FIELDS)
            if unknown:
                raise ValueError(
                    f"fault {f['site']!r} has unknown field(s) "
                    f"{sorted(unknown)}; valid: {_FAULT_FIELDS}")
            f = dict(f)
            # eager numeric coercion: a malformed value (e.g. "at": null
            # from a JSON plan file) must fail HERE, not silently disable
            # evaluation inside maybe_fire's never-raise guard
            try:
                for key in ("at", "after", "max_fires"):
                    if key in f:
                        f[key] = int(f[key])
                for key in ("prob", "delay_s", "value"):
                    if key in f:
                        f[key] = float(f[key])
            except (TypeError, ValueError):
                raise ValueError(
                    f"fault {f['site']!r}: non-numeric value for a "
                    f"numeric field in {f!r}") from None
            if "prob" in f and not 0.0 <= f["prob"] <= 1.0:
                raise ValueError(f"prob must be in [0, 1], got {f['prob']}")
            self.faults.append(f)
        if not self.faults:
            raise ValueError("a ChaosPlan needs at least one fault")

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"seed": self.seed,
                             "faults": [dict(f) for f in self.faults]}
        if self.nonce:
            d["nonce"] = self.nonce
        return d

    def to_json(self) -> str:
        return json.dumps(self.to_dict())

    @classmethod
    def from_value(cls, value: Any) -> "ChaosPlan":
        if isinstance(value, ChaosPlan):
            return value
        if isinstance(value, str):
            value = json.loads(value)
        if not isinstance(value, dict):
            raise ValueError(f"cannot build a ChaosPlan from {type(value)}")
        return cls(value.get("seed", 0), value.get("faults"),
                   nonce=value.get("nonce", 0))


class _ArmedState:
    """Process-local armed plan + deterministic per-fault decision streams."""

    def __init__(self, plan: ChaosPlan, rev: int):
        self.plan = plan
        self.rev = rev
        self.hits: Dict[str, int] = {}        # per-site (status/debug)
        self.fault_hits: Dict[int, int] = {}  # per-fault, target-filtered
        self.fires: Dict[int, int] = {}
        # one seeded stream per fault: the Nth probability draw of fault i
        # is identical across arm() calls with the same plan — determinism
        self.rngs: Dict[int, random.Random] = {
            i: random.Random(f"{plan.seed}:{f['site']}:{i}")
            for i, f in enumerate(plan.faults)}
        # rpc.* fires have no GCS handle at the site (and a dropped GCS rpc
        # cannot report itself) — they buffer here and the host process's
        # maintenance loop (raylet heartbeat, worker raylet-watch) drains
        # them to the failure feed
        from collections import deque

        self.pending_events: "deque" = deque(maxlen=256)
        self.lock = threading.Lock()


_STATE: Optional[_ArmedState] = None


def arm(plan: Any, rev: int = 0) -> ChaosPlan:
    """Arm this process. ``plan`` is a ChaosPlan, dict, or JSON string.

    Distributed arms (rev > 0, from the GCS heartbeat sync or a raylet's
    worker forward) are idempotent whenever the PLAN is unchanged:
    re-arms of the same plan — several in-process raylets syncing one
    rev, a worker armed from its spawn env then re-armed by the
    worker_ready forward, a head restart re-announcing the persisted
    plan under a drifted rev — must not reset hit/fire counters (a
    kill-once plan would fire once per reset, breaking seeded
    determinism). Direct arms (rev == 0, tests/tools) always reset."""
    global _STATE
    p = ChaosPlan.from_value(plan)
    st = _STATE
    if (st is not None and rev > 0
            and st.plan.to_json() == p.to_json()):
        st.rev = rev
        return st.plan
    _STATE = _ArmedState(p, rev)
    return p


def disarm() -> None:
    global _STATE
    _STATE = None


def armed() -> bool:
    return _STATE is not None


def current_rev() -> int:
    st = _STATE
    return st.rev if st is not None else -1


def plan_json() -> Optional[str]:
    st = _STATE
    return st.plan.to_json() if st is not None else None


def status() -> Dict[str, Any]:
    """This process's armed state + hit/fire counters (rt chaos status)."""
    st = _STATE
    if st is None:
        return {"armed": False}
    with st.lock:
        fires: Dict[str, int] = {}
        for i, n in st.fires.items():  # sum per site: a plan may hold
            site = st.plan.faults[i]["site"]  # several faults on one site
            fires[site] = fires.get(site, 0) + n
        return {"armed": True, "rev": st.rev, "seed": st.plan.seed,
                "hits": dict(st.hits), "fires": fires}


def maybe_fire(site: str, target: Optional[str] = None
               ) -> Optional[Dict[str, Any]]:
    """The one hook every injection site calls. Unarmed: two loads and out.
    Armed: bump the site's hit counter and evaluate each matching fault
    deterministically; returns the fault spec on fire, else None. Never
    raises — chaos must not add failure modes of its own."""
    st = _STATE
    if st is None:
        return None
    try:
        with st.lock:
            st.hits[site] = st.hits.get(site, 0) + 1
            for i, f in enumerate(st.plan.faults):
                if f["site"] != site:
                    continue
                if f.get("target") and (target is None
                                        or f["target"] not in str(target)):
                    continue
                # at/after count THIS fault's target-matched hits — a
                # busy site (every rpc, every seal) doesn't skew the plan
                n = st.fault_hits.get(i, 0) + 1
                st.fault_hits[i] = n
                fired = st.fires.get(i, 0)
                if f.get("max_fires") is not None \
                        and fired >= int(f["max_fires"]):
                    continue
                if "at" in f:
                    if n != int(f["at"]):
                        continue
                elif "after" in f and n <= int(f["after"]):
                    continue
                if "prob" in f:
                    # draw even when at/after gated us in, so the stream
                    # index depends only on how often this check ran
                    if st.rngs[i].random() >= float(f["prob"]):
                        continue
                st.fires[i] = fired + 1
                _observe_injection(site)
                if site in ("rpc.delay", "rpc.drop"):
                    st.pending_events.append(
                        event_payload(site, f, target=target))
                return dict(f)
    except Exception:  # noqa: BLE001 — injection must never break the host
        return None
    return None


def drain_events() -> List[Dict[str, Any]]:
    """Pop buffered injection events (rpc.* sites) for shipping to the GCS
    failure store. Called from the raylet heartbeat loop and the worker's
    raylet-watch loop."""
    st = _STATE
    if st is None or not st.pending_events:
        return []
    out: List[Dict[str, Any]] = []
    with st.lock:
        while st.pending_events:
            out.append(st.pending_events.popleft())
    return out


def event_payload(site: str, fault: Dict[str, Any],
                  **fields: Any) -> Dict[str, Any]:
    """The FailureEvent wire dict an injection stamps into the GCS feed:
    categorized per site, tagged ``origin="chaos"`` so `rt errors` and
    `rt doctor` can tell injected failures from organic ones."""
    msg: Dict[str, Any] = {
        "category": SITE_CATEGORIES.get(site, "unknown"),
        "message": f"chaos: injected {site}",
        "origin": ORIGIN_CHAOS, "site": site, "t": time.time(),
    }
    if fault.get("target"):
        msg["message"] += f" (target {fault['target']!r})"
    msg.update({k: v for k, v in fields.items() if v is not None})
    return msg


# ---- Prometheus twin --------------------------------------------------------

_injections_counter = None


def _observe_injection(site: str) -> None:
    """``rt_chaos_injections_total{site=}``: one tick per fired fault in
    the firing process's registry. Never raises."""
    global _injections_counter
    try:
        from ray_tpu.util import metrics as M

        if _injections_counter is None:
            _injections_counter = M.get_or_create(
                M.Counter, "rt_chaos_injections_total",
                "Chaos faults fired, by injection site",
                tag_keys=("site",))
        _injections_counter.inc(1.0, {"site": site})
    except Exception:  # noqa: BLE001
        pass
