"""Cluster memory introspection: ``memory_summary()`` / ``rt memory``.

Reference analog: ``ray memory`` / ``ray.internal.memory_summary`` — the
aggregation that answers "where did the bytes go". Three sources join here:

  1. per-node raylet ``memory_report`` RPCs (store usage by state, the
     per-object table with spill/pin state, cumulative spill/restore/OOM/
     pin-purge counters, live worker RSS),
  2. per-process ownership ledgers (``core/object_ledger.py``): owner,
     ref kinds (live local refs / task-arg uses / gets), and — under
     ``RT_RECORD_REF_CREATION_SITES=1`` — the creating call site. Remote
     processes' ledgers ride the GCS KV under ``@memobj/``; this process's
     ledger is read live,
  3. device HBM stats via ``jax.local_devices()[i].memory_stats()``
     (graceful fallback when the backend lacks it), also published as
     ``rt_hbm_used_bytes`` gauges.

Works against both backends: the cluster backend fans out over the node
table; the local (threaded) backend reports its in-process object table as
one synthetic node. OOM post-mortems (``rt memory --oom``) replay the GCS
``oom_kill`` mem-events stamped by the raylet memory monitor.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional

from ray_tpu.core import object_ledger

_LEDGER_KV_PREFIX = "@memobj/"
_KVCACHE_KV_PREFIX = "@memkv/"


def _fmt_bytes(n: Optional[float]) -> str:
    if n is None or n < 0:
        return "?"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return f"{n:.1f} TiB"


# ---------------------------------------------------------------------------
# HBM accounting
# ---------------------------------------------------------------------------

def device_memory_stats() -> List[Dict[str, Any]]:
    """Per-device live/peak HBM bytes. ``available=False`` entries mean the
    backend exposes no ``memory_stats`` (e.g. CPU) — callers must treat the
    numbers as absent, not zero."""
    try:
        import jax

        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 — no jax in this process
        return []
    out = []
    for d in devices:
        stats: Dict[str, Any] = {}
        try:
            s = d.memory_stats()
            if s:
                stats = dict(s)
        except Exception:  # noqa: BLE001 — backend without memory_stats
            pass
        out.append({
            "id": getattr(d, "id", 0),
            "platform": getattr(d, "platform", "?"),
            "kind": getattr(d, "device_kind", "?"),
            "bytes_in_use": stats.get("bytes_in_use"),
            "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
            "bytes_limit": stats.get("bytes_limit"),
            "available": bool(stats),
        })
    return out


def publish_hbm_gauges(stats: Optional[List[Dict[str, Any]]] = None
                       ) -> None:
    """Set ``rt_hbm_used_bytes{device=}`` from device stats (no-op when the
    backend has no memory accounting)."""
    try:
        from ray_tpu.util import metrics as M

        stats = device_memory_stats() if stats is None else stats
        gauge = None
        for d in stats:
            if d.get("bytes_in_use") is None:
                continue
            if gauge is None:
                gauge = M.get_or_create(
                    M.Gauge, "rt_hbm_used_bytes",
                    "Live device (HBM) bytes in use per local device",
                    tag_keys=("device",))
            gauge.set(d["bytes_in_use"],
                      {"device": f"{d['platform']}:{d['id']}"})
    except Exception:  # noqa: BLE001 — observability never fails the caller
        pass


# ---------------------------------------------------------------------------
# Aggregation
# ---------------------------------------------------------------------------

def _cluster_reports(backend, limit: int) -> List[Dict[str, Any]]:
    async def _go():
        import asyncio

        nodes = await backend._gcs.call("list_nodes", {})

        async def one(n):
            try:
                client = await backend._pool.get(n["address"])
                return await asyncio.wait_for(
                    client.call("memory_report", {"limit": limit}), 15.0)
            except Exception as e:  # noqa: BLE001 — partial view is fine
                return {"node_id": n["node_id"], "address": n["address"],
                        "error": f"{type(e).__name__}: {e}"}

        return list(await asyncio.gather(
            *(one(n) for n in nodes if n.get("alive"))))

    return backend.io.run(_go())


# Snapshots older than this are treated as dead-process remnants (live
# pushers refresh every ~5s; shutdown retracts the key, but a worker
# killed outright — OOM, crash — leaves its last push behind).
_LEDGER_STALE_S = 30.0


def _kv_ledgers(backend) -> List[Dict[str, Any]]:
    """Every live process's pushed ownership-ledger snapshot, this
    process's live ledger folded in last (it is fresher than its last
    push). Stale snapshots (dead processes) are dropped."""
    out: List[Dict[str, Any]] = []
    now = time.time()
    try:
        for key in backend.kv_keys(_LEDGER_KV_PREFIX):
            raw = backend.kv_get(key)
            if not raw:
                continue
            try:
                led = json.loads(raw)
            except (ValueError, KeyError):
                continue
            if now - led.get("t", 0.0) <= _LEDGER_STALE_S:
                out.append(led)
    except Exception:  # noqa: BLE001 — KV unavailable (local backend)
        pass
    own = object_ledger.get_ledger().snapshot()
    out = [l for l in out
           if l.get("owner") != getattr(backend, "address", "local")]
    out.append({"t": now,
                "owner": getattr(backend, "address", "local"),
                "objects": own})
    return out


def publish_kv_snapshot(backend) -> None:
    """Push this process's live prefix/KV-cache stats to the GCS KV
    (``@memkv/<address>``) — serve replicas call this on a throttle so
    ``rt memory`` run from ANY process sees the serving plane's retained
    KV pages next to the object ledgers."""
    try:
        from ray_tpu.models import serving

        caches = serving.live_kv_cache_stats()
    except Exception:  # noqa: BLE001 — no jax/serving in this process
        return
    if not caches:
        return
    owner = getattr(backend, "address", "local")
    try:
        backend.kv_put(f"{_KVCACHE_KV_PREFIX}{owner}",
                       json.dumps({"t": time.time(), "owner": owner,
                                   "caches": caches}))
    except Exception:  # noqa: BLE001 — KV unavailable (local backend)
        pass


def _kv_cache_snapshots(backend) -> List[Dict[str, Any]]:
    """Every live process's pushed KV-cache snapshot plus this process's
    live registry (fresher than its last push), stale entries dropped —
    the ledger pattern, applied to serving KV pages."""
    out: List[Dict[str, Any]] = []
    now = time.time()
    try:
        for key in backend.kv_keys(_KVCACHE_KV_PREFIX):
            raw = backend.kv_get(key)
            if not raw:
                continue
            try:
                snap = json.loads(raw)
            except (ValueError, KeyError):
                continue
            if now - snap.get("t", 0.0) <= _LEDGER_STALE_S:
                out.append(snap)
    except Exception:  # noqa: BLE001 — KV unavailable (local backend)
        pass
    own = getattr(backend, "address", "local")
    out = [s for s in out if s.get("owner") != own]
    try:
        from ray_tpu.models import serving

        caches = serving.live_kv_cache_stats()
        if caches:
            out.append({"t": now, "owner": own, "caches": caches})
    except Exception:  # noqa: BLE001 — serving not imported here
        pass
    return out


def _merge_owner_info(ledgers: List[Dict[str, Any]]
                      ) -> Dict[str, Dict[str, Any]]:
    """oid -> best-known ledger entry across processes. The OWNER's entry
    (real size, creation call site) must win over a borrower's info-poor
    one (a worker that only received the ref as a task arg)."""
    info: Dict[str, Dict[str, Any]] = {}
    for led in ledgers:
        for obj in led.get("objects", ()):
            obj = dict(obj)
            obj.setdefault("owner", led.get("owner"))
            cur = info.get(obj["oid"])
            if cur is None:
                info[obj["oid"]] = obj
                continue
            richer = ((bool(obj.get("call_site"))
                       and not cur.get("call_site"))
                      or obj.get("size", 0) > cur.get("size", 0))
            if richer:
                # keep the union of ref counts: they are per-process views
                for k in ("local_refs", "task_arg_uses", "get_count"):
                    obj[k] = obj.get(k, 0) + cur.get(k, 0)
                obj["last_get_at"] = max(obj.get("last_get_at", 0.0),
                                         cur.get("last_get_at", 0.0))
                info[obj["oid"]] = obj
            else:
                for k in ("local_refs", "task_arg_uses", "get_count"):
                    cur[k] = cur.get(k, 0) + obj.get(k, 0)
                cur["last_get_at"] = max(cur.get("last_get_at", 0.0),
                                         obj.get("last_get_at", 0.0))
    return info


def _suspects_from_ledgers(owner_info: Dict[str, Dict[str, Any]],
                           age_s: Optional[float]) -> List[Dict[str, Any]]:
    """Leak suspects computed over the AGGREGATED ledgers, so `rt memory`
    (a fresh attached driver) and the dashboard see the leaking driver's
    refs, not just their own empty ledger: objects past the age threshold
    whose only references are local refs somewhere, never consumed by a
    task and not recently read."""
    if age_s is None:
        from ray_tpu._private.config import get_config

        age_s = get_config().memory_leak_age_s
    now = time.time()
    out = []
    for o in owner_info.values():
        if o.get("state") == "freed" or o.get("local_refs", 0) <= 0:
            continue
        age = now - o.get("created_at", now)
        if age < age_s:
            continue
        if o.get("task_arg_uses", 0) == 0 and (
                o.get("last_get_at", 0.0) == 0.0
                or now - o["last_get_at"] >= age_s):
            d = dict(o)
            d["age_s"] = age
            out.append(d)
    out.sort(key=lambda d: -d.get("size", 0))
    return out


def memory_snapshot(limit: int = 200,
                    leak_age_s: Optional[float] = None,
                    include_devices: bool = True) -> Dict[str, Any]:
    """The structured form behind ``memory_summary()`` and the dashboard's
    ``/api/memory``."""
    import ray_tpu

    backend = ray_tpu.global_worker()._require_backend()
    if hasattr(backend, "_gcs"):
        nodes = _cluster_reports(backend, limit)
    else:
        nodes = [backend.memory_report()]
    ledgers = _kv_ledgers(backend)
    owner_info = _merge_owner_info(ledgers)
    # annotate the store objects with ownership where known
    for n in nodes:
        for obj in n.get("objects", ()):
            info = owner_info.get(obj["oid"])
            if info:
                obj["owner"] = info.get("owner")
                obj["call_site"] = info.get("call_site", "")
                obj["local_refs"] = info.get("local_refs", 0)
    suspects = _suspects_from_ledgers(owner_info, leak_age_s)
    snap = {
        "t": time.time(),
        "nodes": nodes,
        "ledgers": ledgers,
        "kv_caches": _kv_cache_snapshots(backend),
        "leak_suspects": suspects,
    }
    if include_devices:
        devs = device_memory_stats()
        publish_hbm_gauges(devs)
        snap["devices"] = devs
    return snap


def oom_reports(limit: int = 20) -> List[Dict[str, Any]]:
    """The most recent ``oom_kill`` post-mortem events from the GCS."""
    import ray_tpu

    backend = ray_tpu.global_worker()._require_backend()
    if not hasattr(backend, "_gcs"):
        return []
    return backend.io.run(backend._gcs.call(
        "list_mem_events", {"kind": "oom_kill", "limit": limit}))


# ---------------------------------------------------------------------------
# Formatting
# ---------------------------------------------------------------------------

def _short_oid(oid: str) -> str:
    """Head..tail form: a put/return oid's distinguishing bits (the index)
    live at the END of the 48-char hex, so a plain prefix is ambiguous."""
    return oid if len(oid) <= 18 else f"{oid[:8]}..{oid[-8:]}"


def _object_row(o: Dict[str, Any]) -> str:
    site = o.get("call_site") or ""
    refs = (f"{o.get('local_refs', '?')}/"
            f"{o.get('task_arg_uses', '?')}/{o.get('get_count', '?')}")
    return (f"  {_short_oid(o['oid']):<18} {_fmt_bytes(o.get('size')):>12} "
            f"{o.get('state', '?'):<10} {refs:>8} "
            f"{o.get('age_s', 0.0):>8.1f}s  {site}")


def memory_summary(limit: int = 200, top_n: int = 10,
                   leak_age_s: Optional[float] = None,
                   include_devices: bool = False,
                   group_by: str = "owner") -> str:
    """Human-readable memory plane report (what ``rt memory`` prints)."""
    snap = memory_snapshot(limit=limit, leak_age_s=leak_age_s,
                           include_devices=include_devices)
    lines: List[str] = []
    lines.append("=== Per-node object store usage ===")
    head = (f"{'node':<10} {'shm used':>12} {'capacity':>12} "
            f"{'in-mem':>12} {'spilled':>12} {'pinned':>10} "
            f"{'objs':>6} {'spills':>7} {'restores':>9} "
            f"{'pin-purges':>11} {'oom-kills':>10}")
    lines.append(head)
    for n in snap["nodes"]:
        if n.get("error"):
            lines.append(f"{n['node_id'][:8]:<10} unreachable: {n['error']}")
            continue
        s = n.get("store", {})
        spilled = (f"{_fmt_bytes(s.get('spilled_bytes'))} "
                   f"({s.get('spilled_count', 0)})")
        lines.append(
            f"{n['node_id'][:8]:<10} {_fmt_bytes(s.get('used_bytes')):>12} "
            f"{_fmt_bytes(s.get('capacity_bytes')):>12} "
            f"{_fmt_bytes(s.get('in_mem_bytes')):>12} "
            f"{spilled:>12} {s.get('pinned_count', 0):>10} "
            f"{s.get('num_objects', 0):>6} {int(s.get('spills', 0)):>7} "
            f"{int(s.get('restores', 0)):>9} "
            f"{int(s.get('pin_purges', 0)):>11} "
            f"{int(s.get('oom_kills', 0)):>10}")

    lines.append("")
    lines.append("=== Objects by owner "
                 "(refs = local/task-arg/gets) ===")
    for led in snap["ledgers"]:
        objs = led.get("objects") or []
        if not objs:
            continue
        total = sum(o.get("size", 0) for o in objs)
        lines.append(f"owner {led.get('owner', '?')} — {len(objs)} "
                     f"object(s), {_fmt_bytes(total)}")
        for o in objs[:limit]:
            o = dict(o)
            now = time.time()
            o.setdefault("age_s", max(0.0, now - o.get("created_at", now)))
            lines.append(_object_row(o))

    all_store_objs = [dict(o, node=n["node_id"][:8])
                      for n in snap["nodes"] if not n.get("error")
                      for o in n.get("objects", ())]
    all_store_objs.sort(key=lambda o: -o.get("size", 0))
    lines.append("")
    lines.append(f"=== Top {top_n} largest store objects ===")
    if not all_store_objs:
        lines.append("  (store empty)")
    for o in all_store_objs[:top_n]:
        lines.append(
            f"  {o['node']:<10} {_short_oid(o['oid']):<18} "
            f"{_fmt_bytes(o['size']):>12} {o.get('state', '?'):<10} "
            f"{o.get('age_s', 0.0):>8.1f}s  "
            f"owner={o.get('owner', '?')} {o.get('call_site', '')}")

    kv_snaps = snap.get("kv_caches") or []
    if any(s.get("caches") for s in kv_snaps):
        lines.append("")
        lines.append("=== Serving prefix/KV-cache pages ===")
        lines.append(f"{'owner':<28} {'engine':<16} {'pages':>6} "
                     f"{'bytes':>12} {'budget':>12} {'hits':>8} "
                     f"{'misses':>8} {'evict':>6}")
        for s in kv_snaps:
            for c in s.get("caches", ()):
                lines.append(
                    f"{str(s.get('owner', '?')):<28} "
                    f"{str(c.get('label') or '?'):<16} "
                    f"{c.get('pages', 0):>6} "
                    f"{_fmt_bytes(c.get('bytes')):>12} "
                    f"{_fmt_bytes(c.get('max_bytes')):>12} "
                    f"{c.get('hits', 0):>8} {c.get('misses', 0):>8} "
                    f"{c.get('evictions', 0):>6}")

    lines.append("")
    suspects = snap["leak_suspects"]
    if suspects:
        lines.append(f"=== Leak suspects ({len(suspects)}): driver-local "
                     f"refs only, past the age threshold ===")
        for o in suspects[:top_n]:
            lines.append(_object_row(o))
    else:
        lines.append("=== Leak suspects: none ===")

    if include_devices:
        lines.append("")
        lines.append("=== Devices (HBM) ===")
        devs = snap.get("devices") or []
        if not devs:
            lines.append("  (no jax devices visible in this process)")
        for d in devs:
            if d["available"]:
                lines.append(
                    f"  {d['platform']}:{d['id']} {d['kind']:<16} "
                    f"in use {_fmt_bytes(d['bytes_in_use']):>12}  "
                    f"peak {_fmt_bytes(d['peak_bytes_in_use']):>12}  "
                    f"limit {_fmt_bytes(d['bytes_limit']):>12}")
            else:
                lines.append(f"  {d['platform']}:{d['id']} {d['kind']:<16} "
                             f"(no memory_stats on this backend)")
    return "\n".join(lines)


def format_oom_reports(events: List[Dict[str, Any]]) -> str:
    """Render ``oom_kill`` post-mortems (newest last)."""
    if not events:
        return "(no oom_kill events recorded)"
    lines: List[str] = []
    for ev in events:
        when = time.strftime("%Y-%m-%d %H:%M:%S",
                             time.localtime(ev.get("t", 0)))
        mem = ev.get("node_memory", {})
        v = ev.get("victim", {})
        lines.append(f"--- oom_kill @ {when} node={ev.get('node_id', '?')[:8]}"
                     f" ---")
        lines.append(
            f"  node memory: {_fmt_bytes(mem.get('used'))} / "
            f"{_fmt_bytes(mem.get('total'))}")
        task = v.get("task") or (f"actor {v.get('actor_id')}"
                                 if v.get("actor_id") else "(idle)")
        lines.append(
            f"  victim: {v.get('role', 'worker')} "
            f"{str(v.get('worker_id'))[:8]} pid={v.get('pid')} "
            f"rss={_fmt_bytes(v.get('rss'))} running {task}")
        top = ev.get("top_objects") or []
        if top:
            lines.append("  largest live store objects at kill time:")
            for o in top:
                lines.append(f"    {_short_oid(o['oid']):<18} "
                             f"{_fmt_bytes(o['size']):>12} {o['state']}")
    return "\n".join(lines)
