"""Distributed FIFO queue backed by an actor.

Reference analog: ``python/ray/util/queue.py`` (``Queue`` wrapping an async
``_QueueActor``) — same surface: put/get with block/timeout, qsize/empty/
full, put_nowait/get_nowait, batch variants.
"""

from __future__ import annotations

import asyncio
from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        self._q: asyncio.Queue = asyncio.Queue(maxsize=maxsize)

    async def put(self, item, timeout: Optional[float] = None) -> bool:
        try:
            if timeout is None:
                await self._q.put(item)
            else:
                await asyncio.wait_for(self._q.put(item), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def get(self, timeout: Optional[float] = None):
        try:
            if timeout is None:
                return True, await self._q.get()
            return True, await asyncio.wait_for(self._q.get(), timeout)
        except asyncio.TimeoutError:
            return False, None

    async def put_nowait(self, item) -> bool:
        try:
            self._q.put_nowait(item)
            return True
        except asyncio.QueueFull:
            return False

    async def get_nowait(self):
        try:
            return True, self._q.get_nowait()
        except asyncio.QueueEmpty:
            return False, None

    async def qsize(self) -> int:
        return self._q.qsize()

    async def maxsize(self) -> int:
        return self._q.maxsize


class Queue:
    """Create in a driver/task/actor; pass by value — all holders share the
    same queue actor."""

    def __init__(self, maxsize: int = 0, *, actor_options: Optional[dict] = None):
        opts = {"num_cpus": 0, "max_concurrency": 64}
        opts.update(actor_options or {})
        self._actor = ray_tpu.remote(_QueueActor).options(**opts).remote(maxsize)

    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None) -> None:
        if not block:
            if not ray_tpu.get(self._actor.put_nowait.remote(item)):
                raise Full("queue is full")
            return
        if not ray_tpu.get(self._actor.put.remote(item, timeout)):
            raise Full("queue put timed out")

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        if not block:
            ok, item = ray_tpu.get(self._actor.get_nowait.remote())
            if not ok:
                raise Empty("queue is empty")
            return item
        ok, item = ray_tpu.get(self._actor.get.remote(timeout))
        if not ok:
            raise Empty("queue get timed out")
        return item

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def put_nowait_batch(self, items: List[Any]) -> None:
        for item in items:
            self.put_nowait(item)

    def get_nowait_batch(self, num_items: int) -> List[Any]:
        return [self.get_nowait() for _ in range(num_items)]

    def qsize(self) -> int:
        return ray_tpu.get(self._actor.qsize.remote())

    def empty(self) -> bool:
        return self.qsize() == 0

    def full(self) -> bool:
        maxsize = ray_tpu.get(self._actor.maxsize.remote())
        return maxsize > 0 and self.qsize() >= maxsize

    def shutdown(self) -> None:
        ray_tpu.kill(self._actor)
