"""Step profiler: per-step device-time breakdown over the JAX hot paths.

VERDICT's standing directive is "attack the MFU gap with a profile, not a
guess" — this is the instrument. The cluster plane already has metrics,
tracing, timeline, and stack capture; what was missing is a STEP-level lens
over the code that actually burns the chips (train steps, decode loops,
serve requests). Podracer (arXiv:2104.06272) shows TPU-side step accounting
— device vs host time, tokens/s, FLOP utilization — is what makes
throughput work tractable.

What one record holds, and how it is measured around ONE dispatched step
(``profiled_call``):

  wall_s      total host wall time for the step
  compile_s   first-call trace+compile time for this step's ``key`` (jit
              compiles synchronously inside the first call, so the first
              dispatch IS the compile; later calls record it as dispatch)
  dispatch_s  host time to enqueue the compiled program (launch overhead —
              the per-step cost ``make_multi_step`` amortizes)
  execute_s   host-sync stall: time blocked in the device fence after
              dispatch returned — the device-execution tail the host had
              to wait for
  launches    device dispatches this record covers (1 for a fused step,
              ``max_new_tokens`` for a streamed decode)
  tokens/flops  analytic accounting from ``util/flops.py`` → tokens_per_s
              and MFU against the platform's peak

The fence is ``jax.block_until_ready`` PLUS a small host read: on the axon
tunnel backend block_until_ready can return without draining the execution
queue (bench.py's sweep exists because of this), so only a device->host
copy proves the step finished.

Records land in a bounded per-process ring buffer. ``drain()`` pushes them
into the GCS task-event store (the table ``ray_tpu.timeline()`` exports and
the dashboard lists), where each step becomes a span with ``step`` /
``compile`` / ``sync`` Perfetto lanes; a daemon drainer also ships them on
an interval, so serve replicas and remote workers need no explicit call. Every record also observes the
auto-registered ``rt_step_*`` histograms, which ride the existing
Prometheus push (``util/metrics.py``).

Enable with ``enable()`` or ``RT_STEP_PROFILER=1``; when disabled the hot
paths pay one predicate check per step and nothing else.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

_enabled = os.environ.get("RT_STEP_PROFILER", "") not in ("", "0", "false")
_CAP = int(os.environ.get("RT_STEP_PROFILER_CAP", "4096"))

_lock = threading.Lock()
_records: "deque[StepRecord]" = deque(maxlen=_CAP)
_seen_keys: set = set()
_seq = 0
_drained_seq = 0
_epoch = 0
_per_kind_step: Dict[str, int] = {}
# per-kind authoritative launch/step counters (PR 20 reconciliation):
# when a flight recorder owns the instrumentation point it registers a
# source here and summary(kind) reads ITS join, so `rt profile`'s st/ln
# column and `rt train stats` can never drift apart
_launch_sources: Dict[str, Any] = {}  # rt: guarded-by(_lock)


def register_launch_source(kind: str, fn: Any) -> None:
    """Register ``fn() -> Optional[{"launches": int, "steps": int}]`` as
    the authoritative launch/step counter for ``kind``. Idempotent; a
    source returning None (nothing recorded yet) defers back to the
    profiler's own records."""
    with _lock:
        _launch_sources[kind] = fn


def is_enabled() -> bool:
    return _enabled


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def reset() -> None:
    """Drop buffered records and compile-key memory (tests; fresh runs).
    Bumps the drain epoch so a post-reset run's records get fresh event-
    store ids instead of overwriting the previous run's (seq restarts)."""
    global _seq, _drained_seq, _epoch
    with _lock:
        _records.clear()
        _seen_keys.clear()
        _per_kind_step.clear()
        _seq = 0
        _drained_seq = 0
        _epoch += 1


@dataclasses.dataclass
class StepRecord:
    kind: str            # "train" | "generate" | "speculative" | "decode" |
    #                      "prefill" | "serve" | caller-defined
    name: str            # preset / deployment / caller label
    step: int            # per-(process, kind) sequence number
    seq: int             # process-global sequence (drain watermark)
    t_start: float       # epoch seconds (timeline lane placement)
    wall_s: float
    compile_s: float
    dispatch_s: float
    execute_s: float
    launches: int
    tokens: int
    flops: float
    tokens_per_s: float
    mfu: float
    first_call: bool
    meta: Dict[str, Any]
    hbm_peak_bytes: int = 0  # max per-device peak HBM (0 = no accounting)
    # optimizer/model steps this record covers: a fused-K train launch has
    # launches=1, steps=K — the per-launch vs per-step attribution the
    # launch-amortization summary divides by
    steps: int = 1

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


# ---- measurement ------------------------------------------------------------

def _fence(out: Any) -> None:
    """Prove the step finished on-device: block, then read (part of) the
    smallest output leaf back to the host (block_until_ready alone does
    not drain the axon tunnel's execution queue — see bench.py)."""
    import jax
    import numpy as np

    jax.block_until_ready(out)
    leaves = [x for x in jax.tree.leaves(out)
              if hasattr(x, "size") and x.size > 0]
    if not leaves:
        return
    smallest = min(leaves, key=lambda x: x.size)
    if smallest.size <= 1024:
        np.asarray(smallest)
    else:  # big outputs: a one-element read still drains the queue
        np.asarray(smallest.reshape(-1)[:1])


def _peak_total() -> float:
    """Aggregate peak FLOP/s of this process's local devices."""
    import jax

    from ray_tpu.util import flops as F

    return F.peak_flops_per_chip(jax.default_backend()) \
        * max(1, jax.local_device_count())


def _hbm_peak_bytes() -> int:
    """Max per-device ``peak_bytes_in_use`` across local devices (the
    step record's peak-HBM column), refreshing the ``rt_hbm_used_bytes``
    live gauges on the way. One implementation — util/memory.py — owns
    device probing and gauge registration; backends without
    ``memory_stats`` (CPU) report 0."""
    try:
        from ray_tpu.util.memory import (
            device_memory_stats,
            publish_hbm_gauges,
        )

        stats = device_memory_stats()
        publish_hbm_gauges(stats)
        return max((d.get("peak_bytes_in_use") or d.get("bytes_in_use")
                    or 0 for d in stats), default=0)
    except Exception:  # noqa: BLE001 — profiling must never fail the step
        return 0


def record(kind: str, *, name: str = "", t_start: Optional[float] = None,
           wall_s: float, compile_s: float = 0.0, dispatch_s: float = 0.0,
           execute_s: float = 0.0, launches: int = 1, tokens: int = 0,
           flops: float = 0.0, first_call: bool = False,
           steps: int = 1,
           meta: Optional[Dict[str, Any]] = None) -> "StepRecord":
    """Append one step record (hot paths that time themselves — the serve
    replica — call this directly; JAX steps go through ``profiled_call``)."""
    global _seq
    tok_s = tokens / wall_s if wall_s > 0 and tokens else 0.0
    if flops > 0 and wall_s > 0:
        try:
            from ray_tpu.util import flops as F

            mfu = F.mfu(flops, wall_s, 1, _peak_total())
        except Exception:  # noqa: BLE001 — no jax in this process
            mfu = 0.0
    else:
        mfu = 0.0
    hbm_peak = _hbm_peak_bytes()
    with _lock:
        _seq += 1
        step = _per_kind_step.get(kind, 0)
        _per_kind_step[kind] = step + 1
        rec = StepRecord(
            kind=kind, name=name, step=step, seq=_seq,
            t_start=time.time() - wall_s if t_start is None else t_start,
            wall_s=wall_s, compile_s=compile_s, dispatch_s=dispatch_s,
            execute_s=execute_s, launches=launches, tokens=tokens,
            flops=flops, tokens_per_s=tok_s, mfu=mfu,
            first_call=first_call, meta=dict(meta or {}),
            hbm_peak_bytes=hbm_peak, steps=max(1, steps))
        _records.append(rec)
    _observe_metrics(rec)
    _ensure_drainer()
    return rec


def profiled_call(kind: str, fn, args: Tuple = (), kwargs=None, *,
                  key: Any = None, name: str = "", tokens: int = 0,
                  flops: float = 0.0, launches: int = 1, steps: int = 1,
                  meta: Optional[Dict[str, Any]] = None):
    """Run ``fn(*args, **kwargs)`` as one profiled step.

    ``key`` identifies the compiled program: its first call through here
    books the host-side call time as ``compile_s`` (jit compiles
    synchronously inside that call), later calls book it as ``dispatch_s``.
    Keys must be STABLE program identities (config/shape tuples, or a
    counter minted when the program is built) — never ``id()`` of a
    collectable object, which CPython reuses. Caveat: a program evicted
    from an lru cache and recompiled under the same key books its
    recompile as dispatch; the outlier is visible in the records.
    Disabled ⇒ straight call, no fence, no record.
    """
    kwargs = kwargs or {}
    if not _enabled:
        return fn(*args, **kwargs)
    first = False
    if key is not None:
        with _lock:
            first = key not in _seen_keys
    t_epoch = time.time()
    t0 = time.perf_counter()
    out = fn(*args, **kwargs)
    t1 = time.perf_counter()
    if first:
        # book the key only on success: a failed first call (OOM, shape
        # error) must not make the retry's real compile look like dispatch
        with _lock:
            _seen_keys.add(key)
    try:
        _fence(out)
    except Exception:  # noqa: BLE001 — non-array outputs: wall==dispatch
        pass
    t2 = time.perf_counter()
    record(kind, name=name, t_start=t_epoch, wall_s=t2 - t0,
           compile_s=(t1 - t0) if first else 0.0,
           dispatch_s=0.0 if first else (t1 - t0),
           execute_s=t2 - t1, launches=launches, tokens=tokens,
           flops=flops, first_call=first, steps=steps, meta=meta)
    return out


# ---- access -----------------------------------------------------------------

def records(kind: Optional[str] = None) -> List[StepRecord]:
    with _lock:
        out = list(_records)
    return [r for r in out if kind is None or r.kind == kind]


def summary(kind: Optional[str] = None) -> Dict[str, Any]:
    """Aggregates for the ``rt profile`` table: steady-state means exclude
    first-call (compile) steps so one compile doesn't drown N executes."""
    rs = records(kind)
    if not rs:
        return {}
    steady = [r for r in rs if not r.first_call] or rs
    n = len(steady)
    wall = sum(r.wall_s for r in steady)
    launches = sum(r.launches for r in rs)
    steps = sum(getattr(r, "steps", 1) for r in rs)
    launch_source = None
    if kind is not None:
        with _lock:
            src = _launch_sources.get(kind)
        if src is not None:
            try:
                joined = src()
            except Exception:  # noqa: BLE001 — a broken source must not
                joined = None  # take the profile table down
            if joined and joined.get("launches"):
                launches = int(joined["launches"])
                steps = int(joined.get("steps", steps))
                launch_source = "recorder"
    return {
        **({"launch_source": launch_source} if launch_source else {}),
        "records": len(rs),
        "compile_s": sum(r.compile_s for r in rs),
        "mean_wall_s": wall / n,
        "mean_dispatch_s": sum(r.dispatch_s for r in steady) / n,
        "mean_execute_s": sum(r.execute_s for r in steady) / n,
        "launches": launches,
        "steps": steps,
        # fused-K attribution: how many optimizer steps each device launch
        # amortizes, and the true per-STEP wall once fused (mean_wall_s is
        # per RECORD — one launch — so divide by the fusion factor)
        "mean_steps_per_launch": steps / max(1, launches),
        "per_step_wall_s": (wall / sum(getattr(r, "steps", 1)
                                       for r in steady)) if n else 0.0,
        "tokens": sum(r.tokens for r in rs),
        "tokens_per_s": (sum(r.tokens for r in steady) / wall
                         if wall > 0 else 0.0),
        "mean_mfu": sum(r.mfu for r in steady) / n,
        "peak_hbm_bytes": max((r.hbm_peak_bytes for r in rs), default=0),
    }


# ---- metrics ----------------------------------------------------------------

_MFU_BUCKETS = (0.01, 0.02, 0.05, 0.1, 0.15, 0.2, 0.3, 0.4, 0.6, 0.8, 1.0)
_TOKS_BUCKETS = (10.0, 100.0, 1e3, 1e4, 1e5, 1e6, 1e7)
_hists: Optional[Dict[str, Any]] = None


def _observe_metrics(rec: StepRecord) -> None:
    global _hists
    try:
        from ray_tpu.util import metrics as M

        if _hists is None:
            _hists = {
                "wall": M.get_or_create(
                    M.Histogram, "rt_step_time_seconds",
                    "Step wall time", tag_keys=("kind",)),
                "device": M.get_or_create(
                    M.Histogram, "rt_step_device_time_seconds",
                    "Step device-execution stall (post-dispatch fence)",
                    tag_keys=("kind",)),
                "mfu": M.get_or_create(
                    M.Histogram, "rt_step_mfu",
                    "Analytic model-FLOPs utilization per step",
                    boundaries=_MFU_BUCKETS, tag_keys=("kind",)),
                "toks": M.get_or_create(
                    M.Histogram, "rt_step_tokens_per_s",
                    "Tokens per second per step",
                    boundaries=_TOKS_BUCKETS, tag_keys=("kind",)),
                "launches": M.get_or_create(
                    M.Counter, "rt_step_launches_total",
                    "Device dispatches recorded by the step profiler",
                    tag_keys=("kind",)),
            }
        tags = {"kind": rec.kind}
        _hists["wall"].observe(rec.wall_s, tags)
        _hists["device"].observe(rec.execute_s, tags)
        if rec.flops > 0:
            _hists["mfu"].observe(rec.mfu, tags)
        if rec.tokens > 0:
            _hists["toks"].observe(rec.tokens_per_s, tags)
        _hists["launches"].inc(float(rec.launches), tags)
    except Exception:  # noqa: BLE001 — metrics must never break the step
        pass


# ---- structured event log drain ---------------------------------------------

_DRAIN_INTERVAL_S = 5.0
_drainer: Optional[threading.Thread] = None


def _ensure_drainer() -> None:
    """A daemon thread that drains the ring buffer on an interval — the
    path that gets SERVE/worker-process records into the event store
    (nothing in a replica ever calls drain() explicitly; same pattern as
    the metrics pusher)."""
    global _drainer
    if _drainer is not None and _drainer.is_alive():
        return
    _drainer = threading.Thread(target=_drain_loop, daemon=True,
                                name="rt-step-drain")
    _drainer.start()


def _drain_loop() -> None:
    while True:
        time.sleep(_DRAIN_INTERVAL_S)
        if not _enabled:
            continue
        try:
            drain()
        except Exception:  # noqa: BLE001 — observability must never
            pass  # take the workload down


def drain() -> int:
    """Push not-yet-drained records into the GCS task-event store (the
    table ``ray_tpu.timeline()`` exports). Best-effort and idempotent per
    record: each carries a process-global ``seq`` watermark. Returns the
    number of records shipped."""
    global _drained_seq
    try:
        import ray_tpu

        if not ray_tpu.is_initialized():
            return 0
        backend = ray_tpu.global_worker()._require_backend()
        if not hasattr(backend, "_gcs"):
            return 0  # local_mode: no event store
    except Exception:  # noqa: BLE001
        return 0
    with _lock:
        pending = [r for r in _records if r.seq > _drained_seq]
        epoch = _epoch
    if not pending:
        return 0
    node = os.uname().nodename
    pid = os.getpid()
    events = [{
        "task_id": f"step:{node}:{pid}:{epoch}:{r.seq}",
        "name": f"{r.kind}:{r.name}" if r.name else r.kind,
        "state": "FINISHED", "node_id": node,
        "times": {"RUNNING": r.t_start,
                  "FINISHED": r.t_start + r.wall_s},
        "profile": r.to_dict()} for r in pending]

    try:
        # one batched RPC for the whole ring — a streamed decode can have
        # thousands of pending records, and a round-trip each would pin
        # the drainer (and the GCS) for seconds
        backend.io.run(backend._gcs.call("task_events", {"events": events}))
    except Exception:  # noqa: BLE001 — observability must not take
        return 0  # the workload down
    with _lock:
        if _epoch == epoch:  # a reset() mid-push restarted the seq space;
            # advancing the watermark then would orphan the new records
            _drained_seq = max(_drained_seq, pending[-1].seq)
    return len(pending)
