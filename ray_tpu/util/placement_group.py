"""Placement groups: atomic gang reservation of resources across nodes.

Reference analog: ``python/ray/util/placement_group.py`` +
``GcsPlacementGroupManager``/``GcsPlacementGroupScheduler`` (2-phase commit
of bundles across raylets, ``gcs_placement_group_scheduler.h:137-222``).

TPU-first extension: ``slice_group()`` builds the PG shape for a TPU pod
slice — one bundle per host, STRICT_SPREAD, each bundle holding the host's
chips — the primitive under multi-host meshes (SURVEY.md §7 "SliceGroup").
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ray_tpu._private.ids import PlacementGroupID
from ray_tpu.core.resources import CPU, TPU
from ray_tpu.core.task_spec import PlacementGroupStrategy

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: List[Dict[str, float]],
                 strategy: str):
        self.id = pg_id
        self.bundles = bundles
        self.strategy = strategy

    @property
    def bundle_count(self) -> int:
        return len(self.bundles)

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until all bundles are committed (2PC done); True on success.

        (The reference returns an ObjectRef from ``pg.ready()``; here readiness
        is a control-plane long-poll — same blocking semantics via ``wait``.)
        """
        from ray_tpu.core.worker import global_worker

        backend = global_worker()._require_backend()
        if not hasattr(backend, "_gcs"):
            return True  # local mode: reservation is trivially satisfied
        reply = backend.io.run(backend._gcs.call("wait_placement_group", {
            "pg_id": self.id.hex(), "timeout": timeout if timeout is not None else 3600.0}))
        return reply.get("state") == "CREATED"

    def ready(self) -> "PlacementGroup":
        if not self.wait():
            raise TimeoutError(f"placement group {self.id} not ready")
        return self

    def bundle_specs(self) -> List[Dict[str, float]]:
        return list(self.bundles)

    def __reduce__(self):
        return (PlacementGroup, (self.id, self.bundles, self.strategy))


def placement_group(bundles: List[Dict[str, float]], strategy: str = "PACK",
                    name: str = "", lifetime: Optional[str] = None) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}")
    if not bundles:
        raise ValueError("placement group needs at least one bundle")
    for b in bundles:
        if not b or all(v == 0 for v in b.values()):
            raise ValueError(f"empty bundle {b!r}")
        if any(v < 0 for v in b.values()):
            raise ValueError(f"negative resource in bundle {b!r}")
    from ray_tpu.core.worker import global_worker

    backend = global_worker()._require_backend()
    pg_id = PlacementGroupID.from_random()
    if not hasattr(backend, "_gcs"):
        return PlacementGroup(pg_id, bundles, strategy)  # local mode no-op
    reply = backend.io.run(backend._gcs.call("create_placement_group", {
        "pg_id": pg_id.hex(), "bundles": bundles, "strategy": strategy,
        "name": name, "lifetime": lifetime}))
    if reply.get("error"):
        raise ValueError(reply["error"])
    return PlacementGroup(pg_id, bundles, strategy)


def remove_placement_group(pg: PlacementGroup) -> None:
    from ray_tpu.core.worker import global_worker

    backend = global_worker()._require_backend()
    if not hasattr(backend, "_gcs"):
        return
    backend.io.run(backend._gcs.call("remove_placement_group",
                                     {"pg_id": pg.id.hex()}))


def placement_group_table() -> List[Dict]:
    from ray_tpu.core.worker import global_worker

    backend = global_worker()._require_backend()
    if not hasattr(backend, "_gcs"):
        return []
    return backend.io.run(backend._gcs.call("list_placement_groups", {}))


def slice_group(num_hosts: int, chips_per_host: int = 4,
                cpus_per_host: float = 1, strategy: str = "STRICT_SPREAD",
                name: str = "") -> PlacementGroup:
    """A PG shaped like a TPU pod slice: one bundle per host, all-or-nothing.

    STRICT_SPREAD pins each bundle to a distinct host so the gang maps 1:1
    onto the slice's hosts; chips within a bundle are a contiguous block on
    that host (per-instance accounting in the raylet).
    """
    bundle = {TPU: float(chips_per_host), CPU: float(cpus_per_host)}
    return placement_group([dict(bundle) for _ in range(num_hosts)],
                           strategy=strategy, name=name)


class PlacementGroupSchedulingStrategy:
    """Option value for ``.options(scheduling_strategy=...)``."""

    def __init__(self, placement_group: PlacementGroup,
                 placement_group_bundle_index: int = -1,
                 placement_group_capture_child_tasks: bool = False):
        idx = placement_group_bundle_index
        if idx < -1 or idx >= placement_group.bundle_count:
            raise ValueError(
                f"bundle index {idx} out of range for a "
                f"{placement_group.bundle_count}-bundle placement group")
        self.placement_group = placement_group
        self.bundle_index = idx
        self.capture_child_tasks = placement_group_capture_child_tasks

    def to_spec(self) -> PlacementGroupStrategy:
        return PlacementGroupStrategy(
            placement_group_id_hex=self.placement_group.id.hex(),
            bundle_index=self.bundle_index,
            capture_child_tasks=self.capture_child_tasks)
