"""Chunk-aligned prompt-prefix digests — the shared vocabulary of the
KV-reuse plane.

Three layers must agree on what "the same prefix" means for cache keys to
line up end to end:

  - the engine's :class:`~ray_tpu.models.serving.PrefixKVCache` keys its
    retained KV pages by chunk-aligned token prefixes,
  - the replica reports its resident prefixes as short digests through
    ``stats_window`` / the ``handle_request`` reply,
  - the handle router hashes an incoming request's prompt the same way
    and biases power-of-two routing toward replicas already holding the
    longest matching prefix.

This module is that vocabulary: pure-python (no jax/numpy imports — it is
imported by ``serve/handle.py``, a hot module on the proxy path), one
digest function, one chunk-size knob (``RT_KV_CHUNK``, tokens per chunk;
both the engine and the router read it so the two sides cannot drift).
"""

from __future__ import annotations

import hashlib
import os
import struct
from typing import Any, Dict, List, Optional, Sequence, Tuple

_DEFAULT_CHUNK = 16
#: residency reports and request-side probes are bounded to this many
#: chunk digests — a router decision needs the longest few matches, not
#: the whole prompt
MAX_PROBE_CHUNKS = 32
#: bytes per token in a packed key (int32 little-endian) — key prefixes
#: slice at TOKEN_WIDTH * n_tokens
TOKEN_WIDTH = 4


def chunk_size() -> int:
    """Tokens per prefix chunk (``RT_KV_CHUNK``): prefixes are cached and
    matched at multiples of this."""
    try:
        return max(1, int(os.environ.get("RT_KV_CHUNK", _DEFAULT_CHUNK)))
    except ValueError:
        return _DEFAULT_CHUNK


def aligned_len(n: int, chunk: Optional[int] = None) -> int:
    """Largest chunk multiple <= n."""
    c = chunk or chunk_size()
    return (n // c) * c


def token_key(tokens: Sequence[int], n: int) -> bytes:
    """Exact byte key of ``tokens[:n]`` (int32 little-endian) — collision-
    free equality, used as the engine cache's index key."""
    return struct.pack(f"<{n}i", *[int(t) for t in tokens[:n]])


def prefix_digest(tokens: Sequence[int], n: int) -> str:
    """Short stable digest of ``tokens[:n]`` for residency reports (16
    hex chars of sha1 — a report row, not a security boundary)."""
    return hashlib.sha1(token_key(tokens, n)).hexdigest()[:16]


def chunked_digests(key: bytes, chunk: int) -> List[str]:
    """Digests of every chunk-aligned prefix of an already-packed token
    key, SHORTEST first — ONE incremental sha1 pass over the buffer
    instead of re-hashing each prefix from scratch (O(n) not O(n^2))."""
    w = TOKEN_WIDTH * chunk
    h = hashlib.sha1()
    out: List[str] = []
    for off in range(0, len(key) - len(key) % w, w):
        h.update(key[off:off + w])
        out.append(h.copy().hexdigest()[:16])
    return out


def prompt_digests(tokens: Sequence[int],
                   chunk: Optional[int] = None,
                   max_chunks: int = MAX_PROBE_CHUNKS) -> List[str]:
    """Digests of chunk-aligned prefixes of ``tokens``, LONGEST FIRST
    (the router scores a replica by the first — longest — digest it
    holds). At most ``max_chunks`` entries; when the prompt has more
    aligned prefixes than that, the probe keeps BOTH ends — the longest
    (session-replay residency) and the shortest (a short shared system
    prompt under a long unique tail; truncating longest-only would
    silently zero affinity for exactly that trace). One packed buffer,
    one incremental sha1 pass."""
    c = chunk or chunk_size()
    n = aligned_len(len(tokens), c)
    nchunks = n // c
    if nchunks <= 0:
        return []
    keep = None
    if nchunks > max_chunks:
        head = max_chunks // 2
        keep = set(range(1, head + 1)) | set(
            range(nchunks - (max_chunks - head) + 1, nchunks + 1))
    buf = token_key(tokens, n)
    w = TOKEN_WIDTH * c
    h = hashlib.sha1()
    out: List[str] = []
    for i in range(1, nchunks + 1):
        h.update(buf[(i - 1) * w:i * w])
        if keep is None or i in keep:
            out.append(h.copy().hexdigest()[:16])
    return out[::-1]


def request_prefix_digests(args: Tuple, kwargs: Dict[str, Any]
                           ) -> Optional[List[str]]:
    """Best-effort prefix probe for a handle call: when the request body
    follows the LLM protocol (a dict with a ``tokens`` list — serve/llm.py
    ``_parse_request``), return its prompt's chunk digests longest-first;
    None for any other call shape (the router then routes load-only).

    Deliberately shallow: one isinstance walk over the top-level args, no
    JSON parsing — this runs on the routing hot path for EVERY handle
    call, LLM or not."""
    for v in list(args) + list(kwargs.values()):
        if isinstance(v, dict):
            toks = v.get("tokens")
        else:
            toks = getattr(v, "_rt_prefix_tokens", None)
        if (isinstance(toks, (list, tuple)) and toks
                and all(isinstance(t, int) for t in toks[:4])):
            try:
                digests = prompt_digests(toks)
            except Exception:  # noqa: BLE001 — non-conforming payload
                # (mixed types past the probe, ints outside int32): this
                # is a ROUTING probe — never fail the request, route
                # load-only instead
                return None
            return digests or None
    return None
