"""Chrome-trace timeline export from the GCS task-event store.

Reference analog: ``ray.timeline()`` (``_private/state.py:865``) — dump task
execution spans as a Chrome ``chrome://tracing`` / Perfetto JSON file. Spans
come from the per-state transition times the raylets report to the GCS task
store (PENDING -> RUNNING -> FINISHED/FAILED).

Step-profiler records (``util/step_profiler.py``) live in the same store
and export as their own lanes in the same file: each step is a ``step``
category span on a ``step:<kind>`` track, with ``compile`` and ``sync``
sub-spans marking the first-call compile time and the post-dispatch
host-sync stall — so the train/decode breakdown lines up against the task
lanes in one Perfetto view.

Traced tasks additionally carry a per-phase breakdown (``util/tracing.py``
``PHASE_ORDER``): each phase becomes its own span on a ``<task>:phases``
track, laid out consecutively from the task's enqueue time — queue-wait,
worker-acquire (spawn vs warm), arg-fetch, execute, result-store line up
under the task's main lane.

Engine flight-recorder records (``util/engine_recorder.py``) export as
``engine:<name>:*`` lanes: the tick-phase lane (admission / kv_restore /
prefill / decode_step / token_delivery / swap_barrier partition per
tick, with decode tick-gap stalls as their own spans) and per-slot
request lanes (queued + decode span per lifecycle) — a prefill burst
starving decode is visible as a widening gap between decode launches.

RLHF flight-recorder records (``util/pipeline_recorder.py``) export as
``rlhf:<name>:*`` lanes: one PER-ROLE lane (generator / reference /
reward / learner) carrying each role's actor-side phase intervals, plus
an iteration lane with the driver's full-round span — the strict-phase
bubble is literally visible as the white space on three role lanes while
the fourth works, and an interrupted iteration (chaos kill) lands as an
instant marker at the phase it died in.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

import ray_tpu


def timeline(filename: Optional[str] = None) -> List[Dict[str, Any]]:
    """Build (and optionally write) Chrome trace events for recent tasks."""
    backend = ray_tpu.global_worker()._require_backend()
    events = backend.io.run(backend._gcs.call(
        "list_tasks", {"limit": 10000, "profile": "include",
                       "serve": "include"}))
    trace: List[Dict[str, Any]] = []
    for ev in events:
        prof = ev.get("profile")
        if prof:
            trace.extend(_step_lanes(ev, prof))
            continue
        etick = ev.get("engine_tick")
        if etick:
            trace.extend(_engine_tick_lanes(ev, etick))
            continue
        ereq = ev.get("engine_request")
        if ereq:
            trace.extend(_engine_request_lanes(ev, ereq))
            continue
        rit = ev.get("rlhf_iter")
        if rit:
            trace.extend(_rlhf_iter_lanes(ev, rit))
            continue
        tl = ev.get("train_launch")
        if tl:
            trace.extend(_train_launch_lanes(ev, tl))
            continue
        is_serve = str(ev.get("task_id", "")).startswith("serve:")
        times = ev.get("times", {})
        start = times.get("RUNNING") or times.get("PENDING")
        end = times.get("FINISHED") or times.get("FAILED")
        if start is None:
            continue
        if end is None:
            end = start  # still running: zero-length marker
        trace.append({
            "name": ev.get("name") or "task",
            "cat": "serve" if is_serve else "task",
            "ph": "X",
            "ts": start * 1e6,
            "dur": max(0.0, (end - start) * 1e6),
            "pid": ev.get("node_id") or "node",
            # serve request spans share one lane so the proxy/route/
            # replica hops of all requests line up against task lanes
            "tid": "serve" if is_serve else ev["task_id"][:8],
            "args": {"task_id": ev["task_id"], "state": ev.get("state")},
        })
        pend = times.get("PENDING")
        if pend is not None and times.get("RUNNING"):
            trace.append({
                "name": f"{ev.get('name') or 'task'}:queued",
                "cat": "scheduling", "ph": "X",
                "ts": pend * 1e6,
                "dur": max(0.0, (times["RUNNING"] - pend) * 1e6),
                "pid": ev.get("node_id") or "node",
                "tid": ev["task_id"][:8],
            })
        if ev.get("phases"):
            trace.extend(_phase_lanes(ev))
    trace.extend(_memory_instants(backend))
    trace.extend(_failure_instants(backend))
    trace.extend(_serve_decision_instants(backend))
    trace.extend(_placement_instants(backend))
    if filename:
        with open(filename, "w") as f:
            json.dump(trace, f)
    return trace


def _memory_instants(backend) -> List[Dict[str, Any]]:
    """Spill / restore / oom_kill instant markers on a per-node ``memory``
    track, merged from the GCS mem-event store (cluster/raylet.py stamps
    them; `rt memory --oom` replays the oom_kill payloads)."""
    try:
        events = backend.io.run(backend._gcs.call(
            "list_mem_events", {"limit": 2000}))
    except Exception:  # noqa: BLE001 — older GCS / local backend
        return []
    out: List[Dict[str, Any]] = []
    for ev in events or ():
        kind = ev.get("kind", "mem")
        name = kind
        args: Dict[str, Any] = {}
        if kind in ("spill", "restore"):
            name = f"{kind} {str(ev.get('oid', ''))[:8]}"
            args = {"oid": ev.get("oid"), "size": ev.get("size"),
                    "seconds": ev.get("seconds")}
        elif kind == "oom_kill":
            victim = ev.get("victim", {})
            name = f"oom_kill {str(victim.get('worker_id', ''))[:8]}"
            args = {"victim": victim, "node_memory": ev.get("node_memory")}
        out.append({
            "name": name, "cat": "memory", "ph": "i", "s": "t",
            "ts": ev.get("t", 0.0) * 1e6,
            "pid": ev.get("node_id") or "node", "tid": "memory",
            "args": args,
        })
    return out


def _failure_instants(backend) -> List[Dict[str, Any]]:
    """Categorized FailureEvents as instant markers on a per-node
    ``errors`` track (cluster/gcs.py ``failure_events`` store — the same
    feed behind `rt errors` and `/api/errors`), so deaths line up against
    the task lanes they interrupted."""
    try:
        events = backend.io.run(backend._gcs.call(
            "list_failure_events", {"limit": 2000}))
    except Exception:  # noqa: BLE001 — older GCS / local backend
        return []
    out: List[Dict[str, Any]] = []
    for ev in events or ():
        cat = ev.get("category", "unknown")
        who = (ev.get("name") or ev.get("task_id") or ev.get("actor_id")
               or ev.get("worker_id") or "")
        name = f"{cat} {str(who)[:12]}".strip()
        count = ev.get("count", 1)
        if count > 1:
            name += f" x{count}"
        out.append({
            "name": name, "cat": "error", "ph": "i", "s": "t",
            "ts": ev.get("t", 0.0) * 1e6,
            "pid": ev.get("node_id") or "node", "tid": "errors",
            "args": {k: v for k, v in ev.items() if k != "t"},
        })
    return out


def _serve_decision_instants(backend) -> List[Dict[str, Any]]:
    """Autoscaler decision records as instant markers on the ``serve``
    lane (GCS ``serve_decisions`` store — the same records behind
    ``rt serve status --verbose``), so "why did it scale?" lines up
    against the request spans that produced the load."""
    try:
        events = backend.io.run(backend._gcs.call(
            "list_serve_events", {"limit": 500}))
    except Exception:  # noqa: BLE001 — older GCS / local backend
        return []
    out: List[Dict[str, Any]] = []
    for ev in events or ():
        out.append({
            "name": (f"scale {ev.get('deployment')} "
                     f"{ev.get('old_target')}->{ev.get('new_target')}"),
            "cat": "serve", "ph": "i", "s": "t",
            "ts": ev.get("t", 0.0) * 1e6,
            "pid": "serve", "tid": "autoscaler",
            "args": {k: v for k, v in ev.items() if k != "t"},
        })
    return out


def _placement_instants(backend) -> List[Dict[str, Any]]:
    """Placement decision receipts as instant markers on a per-node
    ``placement`` lane (GCS ``placement_events`` store — the same records
    behind ``rt sched decisions`` and ``/api/sched``), so "why did this
    task land here / hop there?" lines up against the task lanes."""
    try:
        events = backend.io.run(backend._gcs.call(
            "list_placement_events", {"limit": 500}))
    except Exception:  # noqa: BLE001 — older GCS / local backend
        return []
    out: List[Dict[str, Any]] = []
    for ev in events or ():
        kind = ev.get("kind", "place")
        who = (ev.get("name") or ev.get("task_id") or ev.get("actor_id")
               or ev.get("pg_id") or "")
        name = f"{kind} {str(who)[:12]}".strip()
        if ev.get("kind") == "spillback":
            name += (f" {str(ev.get('from_node', ''))[:8]}"
                     f"→{str(ev.get('node_id', ''))[:8]}")
        count = ev.get("count", 1)
        if count > 1:
            name += f" x{count}"
        out.append({
            "name": name, "cat": "placement", "ph": "i", "s": "t",
            "ts": ev.get("t", 0.0) * 1e6,
            "pid": ev.get("node_id") or "node", "tid": "placement",
            "args": {k: v for k, v in ev.items() if k != "t"},
        })
    return out


def _engine_tick_lanes(ev: Dict[str, Any], tick: Dict[str, Any]
                       ) -> List[Dict[str, Any]]:
    """One engine tick (util/engine_recorder.py) -> the tick-phase lane:
    the full tick span on ``engine:<name>:ticks`` with its phase
    partition laid out consecutively underneath on ``...:phases``, plus
    a ``gap`` span BEFORE the tick when the decode tick-gap was nonzero —
    a prefill-burst starvation stall is visible as a widening gap span
    between decode launches."""
    pid = ev.get("node_id") or "node"
    name = tick.get("engine", "engine")
    ts = tick["t"] * 1e6
    out = [{
        "name": f"tick k={tick.get('k', 0)}",
        "cat": "engine", "ph": "X", "ts": ts,
        "dur": max(0.0, tick.get("wall_s", 0.0)) * 1e6,
        "pid": pid, "tid": f"engine:{name}:ticks",
        "args": {"active": tick.get("active"),
                 "pending": tick.get("pending"),
                 "bucket": tick.get("bucket"), "k": tick.get("k"),
                 "tokens": tick.get("tokens"),
                 "admitted": tick.get("admitted"),
                 "gap_s": tick.get("gap_s")},
    }]
    gap = tick.get("gap_s") or 0.0
    if gap > 0:
        out.append({"name": "gap", "cat": "engine", "ph": "X",
                    "ts": ts - gap * 1e6, "dur": gap * 1e6,
                    "pid": pid, "tid": f"engine:{name}:gap"})
    from ray_tpu.util.tracing import sorted_phases

    t = ts
    for pname, secs in sorted_phases(tick.get("phases") or {}):
        dur = max(0.0, secs) * 1e6
        out.append({"name": pname, "cat": "engine_phase", "ph": "X",
                    "ts": t, "dur": dur, "pid": pid,
                    "tid": f"engine:{name}:phases",
                    "args": {"seconds": secs}})
        t += dur
    return out


def _engine_request_lanes(ev: Dict[str, Any], req: Dict[str, Any]
                          ) -> List[Dict[str, Any]]:
    """One engine request lifecycle -> its slot's lane: a ``queued``
    span (submit -> admission) followed by the decode span on
    ``engine:<name>:slot<N>`` — per-slot occupancy reads directly off
    the lane, and a starved slot shows its queued span stretching."""
    pid = ev.get("node_id") or "node"
    name = req.get("engine", "engine")
    slot = req.get("slot", -1)
    tid = f"engine:{name}:slot{slot}" if slot >= 0 \
        else f"engine:{name}:requests"
    t_submit = req.get("t_submit")
    t_admit = req.get("t_admit")
    t_done = req.get("t_done") or req.get("t_first") or t_admit
    if t_admit is None:
        return []
    out = []
    if t_submit is not None and t_admit > t_submit:
        out.append({"name": f"req {req.get('rid')}:queued",
                    "cat": "engine", "ph": "X", "ts": t_submit * 1e6,
                    "dur": (t_admit - t_submit) * 1e6,
                    "pid": pid, "tid": tid})
    out.append({
        "name": f"req {req.get('rid')} [{req.get('state', '?')}]",
        "cat": "engine", "ph": "X", "ts": t_admit * 1e6,
        "dur": max(0.0, (t_done - t_admit)) * 1e6,
        "pid": pid, "tid": tid,
        "args": {"rid": req.get("rid"), "state": req.get("state"),
                 "prompt_tokens": req.get("prompt_tokens"),
                 "cached_tokens": req.get("cached_tokens"),
                 "tokens": req.get("tokens"),
                 "decode_ticks": req.get("decode_ticks"),
                 "ttft_s": req.get("ttft_s"),
                 "tpot_s": req.get("tpot_s"),
                 "request_id": req.get("request_id")},
    })
    return out


def _rlhf_iter_lanes(ev: Dict[str, Any], rit: Dict[str, Any]
                     ) -> List[Dict[str, Any]]:
    """One RLHF pipeline iteration (util/pipeline_recorder.py) -> its
    per-role lanes: each actor-side interval becomes a phase span on
    ``rlhf:<name>:<role>``, the driver's full round lands on
    ``rlhf:<name>:iters``, and an interrupted record becomes an instant
    marker naming the phase it died in. Three idle role lanes under one
    busy one IS the strict-phase bubble, visually."""
    pid = ev.get("node_id") or "node"
    name = rit.get("pipeline", "rlhf")
    if rit.get("state") == "interrupted":
        return [{"name": f"interrupt:{rit.get('phase', '?')}",
                 "cat": "rlhf", "ph": "i", "s": "t",
                 "ts": rit.get("t", 0.0) * 1e6,
                 "pid": pid, "tid": f"rlhf:{name}:iters",
                 "args": {"phase": rit.get("phase"),
                          "error": rit.get("error")}}]
    out = [{
        "name": f"iter {rit.get('iteration')}",
        "cat": "rlhf", "ph": "X", "ts": rit.get("t", 0.0) * 1e6,
        "dur": max(0.0, rit.get("wall_s", 0.0)) * 1e6,
        "pid": pid, "tid": f"rlhf:{name}:iters",
        "args": {"iteration": rit.get("iteration"),
                 "bubble_fraction": rit.get("bubble_fraction"),
                 "coverage": rit.get("coverage"),
                 "staleness": rit.get("staleness"),
                 "tokens": rit.get("tokens"),
                 "restart_gap_s": rit.get("restart_gap_s")},
    }]
    for iv in rit.get("intervals") or ():
        t0, t1 = iv.get("t0"), iv.get("t1")
        if t0 is None or t1 is None:
            continue
        out.append({"name": iv.get("phase", "phase"), "cat": "rlhf",
                    "ph": "X", "ts": t0 * 1e6,
                    "dur": max(0.0, t1 - t0) * 1e6, "pid": pid,
                    "tid": f"rlhf:{name}:{iv.get('role', 'role')}",
                    "args": {"seconds": round(max(0.0, t1 - t0), 6)}})
    return out


def _train_launch_lanes(ev: Dict[str, Any], tl: Dict[str, Any]
                        ) -> List[Dict[str, Any]]:
    """One fused-K train launch (util/train_recorder.py) -> its lanes:
    the full launch span on ``train:<name>:launches`` with the phase
    partition laid out consecutively on ``...:phases`` (launch order:
    data_wait -> h2d -> dispatch/compile -> device_compute), plus a
    ``gap`` span BEFORE the launch when dispatch starvation was stamped —
    a data-starved run reads as wide data_wait spans, a host-bound run
    as gap spans between back-to-back launches."""
    pid = ev.get("node_id") or "node"
    name = tl.get("driver", "train")
    ts = tl.get("t", 0.0) * 1e6
    phases = tl.get("phases") or {}
    out = [{
        "name": f"launch k={tl.get('k', 0)}",
        "cat": "train", "ph": "X", "ts": ts,
        "dur": max(0.0, tl.get("wall_s", 0.0)) * 1e6,
        "pid": pid, "tid": f"train:{name}:launches",
        "args": {"seq": tl.get("seq"), "k": tl.get("k"),
                 "tokens": tl.get("tokens"),
                 "batch_shape": tl.get("batch_shape"),
                 "flops": tl.get("flops"), "gap_s": tl.get("gap_s")},
    }]
    gap = tl.get("gap_s") or 0.0
    if gap > 0:
        # the devices idled for `gap` before this dispatch with a stacked
        # batch in hand — anchor the span at dispatch start, minus gap
        disp_t = ts + (phases.get("data_wait", 0.0)
                       + phases.get("h2d", 0.0)) * 1e6
        out.append({"name": "gap", "cat": "train", "ph": "X",
                    "ts": disp_t - gap * 1e6, "dur": gap * 1e6,
                    "pid": pid, "tid": f"train:{name}:gap"})
    from ray_tpu.util.train_recorder import LAUNCH_PHASES

    t = ts
    for pname in LAUNCH_PHASES:
        if pname == "host_tax":
            continue  # overlaps device_compute — not part of the chain
        secs = phases.get(pname) or 0.0
        if secs <= 0.0:
            continue
        dur = secs * 1e6
        out.append({"name": pname, "cat": "train_phase", "ph": "X",
                    "ts": t, "dur": dur, "pid": pid,
                    "tid": f"train:{name}:phases",
                    "args": {"seconds": secs}})
        t += dur
    tax = phases.get("host_tax") or 0.0
    if tax > 0:
        # host_tax runs concurrently with device_compute (the callback
        # fires after dispatch returns) — its own lane, not the chain
        disp_end = ts + sum((phases.get(p) or 0.0) * 1e6
                            for p in ("data_wait", "h2d", "dispatch",
                                      "compile"))
        out.append({"name": "host_tax", "cat": "train_phase", "ph": "X",
                    "ts": disp_end, "dur": tax * 1e6, "pid": pid,
                    "tid": f"train:{name}:host_tax",
                    "args": {"seconds": tax}})
    return out


def _phase_lanes(ev: Dict[str, Any]) -> List[Dict[str, Any]]:
    """One traced task's phase breakdown -> consecutive Perfetto sub-spans
    on a ``<task>:phases`` track, anchored at the task's enqueue time.
    ``driver_get`` trails the reply, so it lays out after the partition."""
    from ray_tpu.util.tracing import sorted_phases

    times = ev.get("times", {})
    start = times.get("PENDING") or times.get("RUNNING")
    if start is None:
        return []
    pid = ev.get("node_id") or "node"
    tid = f"{ev['task_id'][:8]}:phases"
    out: List[Dict[str, Any]] = []
    # PENDING is stamped at raylet enqueue — the submit phase precedes it
    t = (start - max(0.0, ev["phases"].get("submit", 0.0))) * 1e6
    for name, secs in sorted_phases(ev["phases"]):
        dur = max(0.0, secs) * 1e6
        args = {"seconds": secs}
        if name == "worker_acquire" and ev.get("worker_source"):
            args["worker_source"] = ev["worker_source"]
        out.append({"name": name, "cat": "phase", "ph": "X",
                    "ts": t, "dur": dur, "pid": pid, "tid": tid,
                    "args": args})
        t += dur
    return out


def _step_lanes(ev: Dict[str, Any], prof: Dict[str, Any]
                ) -> List[Dict[str, Any]]:
    """One step record -> its Perfetto lanes: the full step span plus
    compile (front of the span) and sync (tail: the post-dispatch device
    stall) sub-spans where nonzero."""
    pid = ev.get("node_id") or "node"
    tid = f"step:{prof.get('kind', 'step')}"
    ts = prof["t_start"] * 1e6
    wall = max(0.0, prof.get("wall_s", 0.0)) * 1e6
    out = [{
        "name": ev.get("name") or prof.get("kind", "step"),
        "cat": "step", "ph": "X", "ts": ts, "dur": wall,
        "pid": pid, "tid": tid,
        "args": {"step": prof.get("step"), "tokens": prof.get("tokens"),
                 "tokens_per_s": prof.get("tokens_per_s"),
                 "mfu": prof.get("mfu"),
                 "launches": prof.get("launches")},
    }]
    compile_s = prof.get("compile_s") or 0.0
    if compile_s > 0:
        out.append({"name": "compile", "cat": "compile", "ph": "X",
                    "ts": ts, "dur": compile_s * 1e6,
                    "pid": pid, "tid": tid})
    sync_s = prof.get("execute_s") or 0.0
    if sync_s > 0:
        out.append({"name": "sync", "cat": "sync", "ph": "X",
                    "ts": ts + wall - sync_s * 1e6, "dur": sync_s * 1e6,
                    "pid": pid, "tid": tid})
    return out
