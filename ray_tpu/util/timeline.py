"""Chrome-trace timeline export from the GCS task-event store.

Reference analog: ``ray.timeline()`` (``_private/state.py:865``) — dump task
execution spans as a Chrome ``chrome://tracing`` / Perfetto JSON file. Spans
come from the per-state transition times the raylets report to the GCS task
store (PENDING -> RUNNING -> FINISHED/FAILED).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

import ray_tpu


def timeline(filename: Optional[str] = None) -> List[Dict[str, Any]]:
    """Build (and optionally write) Chrome trace events for recent tasks."""
    backend = ray_tpu.global_worker()._require_backend()
    events = backend.io.run(backend._gcs.call("list_tasks", {"limit": 10000}))
    trace: List[Dict[str, Any]] = []
    for ev in events:
        times = ev.get("times", {})
        start = times.get("RUNNING") or times.get("PENDING")
        end = times.get("FINISHED") or times.get("FAILED")
        if start is None:
            continue
        if end is None:
            end = start  # still running: zero-length marker
        trace.append({
            "name": ev.get("name") or "task",
            "cat": "task",
            "ph": "X",
            "ts": start * 1e6,
            "dur": max(0.0, (end - start) * 1e6),
            "pid": ev.get("node_id") or "node",
            "tid": ev["task_id"][:8],
            "args": {"task_id": ev["task_id"], "state": ev.get("state")},
        })
        pend = times.get("PENDING")
        if pend is not None and times.get("RUNNING"):
            trace.append({
                "name": f"{ev.get('name') or 'task'}:queued",
                "cat": "scheduling", "ph": "X",
                "ts": pend * 1e6,
                "dur": max(0.0, (times["RUNNING"] - pend) * 1e6),
                "pid": ev.get("node_id") or "node",
                "tid": ev["task_id"][:8],
            })
    if filename:
        with open(filename, "w") as f:
            json.dump(trace, f)
    return trace
