"""RLHF dataflow flight recorder: per-role bubble attribution,
weight-plane transfer receipts, and staleness accounting for
``RLHFPipeline``.

The strict-phase RLHF pipeline (generate → score → update → sync) is the
one open ROADMAP item with no measurement substrate: `rt_rlhf_phase_seconds`
is stamped driver-side only, so nothing says how much ROLE time is wasted
while one role works and three idle — the scaling waste the adaptive-
placement RLHF paper (arxiv 2312.11819) and MindSpeed RL's disaggregated
dataflow analysis (arxiv 2507.19017) both identify. This module is the
lens the interleave arc will be judged against.

What one ITERATION record holds:

  intervals   per-role phase intervals stamped ACTOR-SIDE inside each
              role's method (generate / score_ref / score_reward /
              update / ship / sync_swap), joined to the driver's record
  driver_s    the driver-observed wall per driver phase (generate /
              score / update / ship / sync_swap)
  tax_s       orchestration tax per phase: driver wall minus actor wall
              (RPC submit/get, serialization, scheduling — what
              `rt_rlhf_phase_seconds` used to silently conflate)
  bubble      role-seconds idle while ANY other role works ÷ total
              role-seconds over the busy span (interval sweep — the
              strict-phase pipeline's headline waste number)
  staleness   learner weights-version minus the version the generate
              batch decoded under (strict phases measure 0; the
              interleave arc trades bounded staleness for throughput)
  receipt     the joined weight-plane transfer record:
              ship→fetch→barrier→swap in one dict (bytes, leaves,
              inline-vs-oid frames, transport push/fallback, pump wall,
              fetch/drain wall, drain-barrier wall, swap apply wall)

Discipline (the engine recorder's, verbatim): the driver path ONLY
appends to bounded in-process deques under a microsecond lock — metrics
observation, the ``@rlhf/`` KV snapshot and the timeline event push all
happen on a separate drain thread. The ring-buffer + watermark-drain +
self-timing substrate lives in ``util/recorder_core.py`` (shared with
the engine and train recorders); this module owns only the RLHF
vocabulary and the bubble/tax/staleness accounting. The recorder times
itself: ``overhead_s`` accumulates wall spent inside recorder calls and
``summary()`` reports it as a fraction of recorded iteration wall (the
bench gate holds it ≤ 2%).

Disable with ``RT_RLHF_RECORDER=0`` — every hook then costs one
predicate check per iteration.
"""

from __future__ import annotations

import os
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ray_tpu.util.recorder_core import (RecorderCore, RecorderRegistry,
                                        pct as _pct)

_ENABLED_DEFAULT = os.environ.get("RT_RLHF_RECORDER", "1") \
    not in ("", "0", "false")
_CAP = int(os.environ.get("RT_RLHF_RECORDER_CAP", "1024"))
_DRAIN_S = float(os.environ.get("RT_RLHF_DRAIN_S", "2.0"))
_KV_PREFIX = "@rlhf/"

#: canonical actor-side phase vocabulary, in strict-phase order (the
#: timeline role lanes and ``rt rlhf stats`` render phases in this order)
PIPE_PHASES = ("generate", "score_ref", "score_reward", "update",
               "ship", "sync_swap")

#: which role executes each phase (one lane per role in the timeline)
PHASE_ROLE = {"generate": "generator", "score_ref": "reference",
              "score_reward": "reward", "update": "learner",
              "ship": "learner", "sync_swap": "generator"}

#: the driver's phase vocabulary and which actor phases each one covers
#: (the driver's "score" wall spans BOTH parallel scoring roles, so its
#: orchestration tax is measured against their union span)
DRIVER_PHASES = ("generate", "score", "update", "ship", "sync_swap")
DRIVER_PHASE_ACTORS = {"generate": ("generate",),
                       "score": ("score_ref", "score_reward"),
                       "update": ("update",),
                       "ship": ("ship",),
                       "sync_swap": ("sync_swap",)}

ROLES = ("generator", "reference", "reward", "learner")

_REGISTRY = RecorderRegistry()


def live_recorders() -> List["PipelineRecorder"]:
    """Every recorder constructed in this process and not yet closed."""
    return _REGISTRY.live()


def bubble_attribution(intervals: List[Dict[str, Any]],
                       roles: Optional[List[str]] = None) -> Dict[str, Any]:
    """Interval-sweep bubble accounting over one iteration's role
    intervals (``{"role", "phase", "t0", "t1"}`` each).

    Over every elementary segment where AT LEAST one role is busy, a
    role not busy in that segment contributes idle role-seconds — the
    pipeline bubble. ``bubble_fraction`` = idle role-seconds ÷ total
    role-seconds over the busy span. A perfectly overlapped pipeline
    scores 0.0; a 4-role strict-phase pipeline whose score phase runs
    two roles concurrently lands around 0.7.
    """
    role_list = list(roles) if roles else sorted(
        {iv["role"] for iv in intervals}) or list(ROLES)
    n_roles = max(1, len(role_list))
    by_role: Dict[str, List] = {r: [] for r in role_list}
    points: List[float] = []
    for iv in intervals:
        t0, t1 = float(iv["t0"]), float(iv["t1"])
        if t1 <= t0 or iv["role"] not in by_role:
            continue
        by_role[iv["role"]].append((t0, t1))
        points.append(t0)
        points.append(t1)
    points = sorted(set(points))
    busy_s = {r: 0.0 for r in role_list}
    idle_s = {r: 0.0 for r in role_list}
    span_busy = 0.0
    bubble = 0.0
    for a, b in zip(points, points[1:]):
        seg = b - a
        if seg <= 0:
            continue
        busy = [r for r in role_list
                if any(t0 <= a and b <= t1 for t0, t1 in by_role[r])]
        if not busy:
            continue
        span_busy += seg
        for r in role_list:
            if r in busy:
                busy_s[r] += seg
            else:
                idle_s[r] += seg
                bubble += seg
    total_role_s = n_roles * span_busy
    return {
        "bubble_fraction": round(bubble / total_role_s, 4)
        if total_role_s > 0 else 0.0,
        "bubble_role_s": round(bubble, 6),
        "total_role_s": round(total_role_s, 6),
        "span_busy_s": round(span_busy, 6),
        "role_busy_s": {r: round(v, 6) for r, v in busy_s.items()},
        "role_idle_s": {r: round(v, 6) for r, v in idle_s.items()},
    }


class PipelineRecorder(RecorderCore):
    """Bounded flight recorder for one ``RLHFPipeline``.

    The DRIVER THREAD is the only writer (`record_iteration` /
    `record_interrupt` fire from `run_iteration`); the drain thread only
    reads. All shared state lives behind one lock held for O(1) appends
    plus a ~10-interval sweep — never across an RPC or a metrics
    observation.
    """

    KV_PREFIX = _KV_PREFIX
    DRAIN_S = _DRAIN_S
    THREAD_NAME = "rt-rlhf-rec"
    REGISTRY = _REGISTRY

    def __init__(self, name: str = "rlhf", *, cap: int = _CAP,
                 enabled: Optional[bool] = None):
        self.name = name or "rlhf"
        self.enabled = _ENABLED_DEFAULT if enabled is None else bool(enabled)
        cap = max(64, int(cap))
        self._init_core(self.name)
        self._iters: "deque[Dict[str, Any]]" = deque(maxlen=cap)  # rt: guarded-by(_lock)
        self._seq = 0  # rt: guarded-by(_lock)
        self._interrupted_total = 0  # rt: guarded-by(_lock)
        self._last_interrupt_t: Optional[float] = None  # rt: guarded-by(_lock)
        # drain-side watermarks (drain thread only; the lock still guards
        # the snapshot reads that feed them)
        self._metrics_wm = 0
        self._event_wm = 0

    # -- driver path -------------------------------------------------------

    def record_iteration(self, *, iteration: int, t0: float, wall_s: float,
                         intervals: List[Dict[str, Any]],
                         driver_s: Dict[str, float],
                         tokens: int = 0,
                         learner_version: int = 0,
                         decoded_version: int = 0,
                         receipt: Optional[Dict[str, Any]] = None
                         ) -> Dict[str, Any]:
        """One completed pipeline iteration: the driver's record joined
        with the actor-side intervals every role stamped. Appends to a
        bounded deque plus one O(k log k) sweep over ~10 intervals — no
        metrics, no I/O (drained off-thread). Returns the derived fields
        (bubble / coverage / tax / staleness) so the driver can surface
        them in its own result dict without recomputing."""
        if not self.enabled:
            return {}
        t_in = time.perf_counter()
        actor_s = {p: 0.0 for p in PIPE_PHASES}
        for iv in intervals:
            w = iv.get("wall_s")
            if w is None:
                w = max(0.0, float(iv["t1"]) - float(iv["t0"]))
            actor_s[iv["phase"]] = actor_s.get(iv["phase"], 0.0) + float(w)
        tax_s: Dict[str, float] = {}
        for p, dv in driver_s.items():
            sub = [iv for iv in intervals
                   if iv["phase"] in DRIVER_PHASE_ACTORS.get(p, (p,))]
            span = max(float(iv["t1"]) for iv in sub) \
                - min(float(iv["t0"]) for iv in sub) if sub else 0.0
            tax_s[p] = round(max(0.0, float(dv) - span), 6)
        bub = bubble_attribution(intervals, roles=list(ROLES))
        coverage = round(bub["span_busy_s"] / wall_s, 4) if wall_s > 0 \
            else 0.0
        staleness = max(0, int(learner_version) - int(decoded_version))
        rec = {"t": t0, "t1": t0 + wall_s, "wall_s": round(wall_s, 6),
               "state": "ok", "iteration": int(iteration),
               "tokens": int(tokens),
               "learner_version": int(learner_version),
               "decoded_version": int(decoded_version),
               "staleness": staleness,
               "intervals": [{"role": iv["role"], "phase": iv["phase"],
                              "t0": float(iv["t0"]), "t1": float(iv["t1"])}
                             for iv in intervals],
               "actor_s": {p: round(v, 6) for p, v in actor_s.items()
                           if v > 0.0},
               "driver_s": {p: round(float(v), 6)
                            for p, v in driver_s.items()},
               "tax_s": tax_s,
               "bubble_fraction": bub["bubble_fraction"],
               "coverage": coverage,
               "role_busy_s": bub["role_busy_s"],
               "role_idle_s": bub["role_idle_s"],
               "span_busy_s": bub["span_busy_s"]}
        if receipt:
            rec["receipt"] = dict(receipt)
        with self._lock:
            if self._last_interrupt_t is not None:
                rec["restart_gap_s"] = round(
                    max(0.0, t0 - self._last_interrupt_t), 6)
                self._last_interrupt_t = None
            self._seq += 1
            rec["seq"] = self._seq
            self._iters.append(rec)
            self._wall_total_s += wall_s
            self._overhead_s += time.perf_counter() - t_in
        self._ensure_drainer()
        return {"bubble_fraction": rec["bubble_fraction"],
                "coverage": coverage, "staleness": staleness,
                "tax_s": tax_s,
                "restart_gap_s": rec.get("restart_gap_s")}

    def record_interrupt(self, *, phase: str, t: float,
                         error: str = "") -> None:
        """An iteration died mid-phase (chaos kill, actor crash): stamp
        the interrupted phase so the postmortem snapshot names where the
        pipeline stopped. The next successful iteration stamps its
        ``restart_gap_s`` against this timestamp."""
        if not self.enabled:
            return
        t_in = time.perf_counter()
        rec = {"t": t, "state": "interrupted", "phase": phase,
               "error": str(error)[:200]}
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._iters.append(rec)
            self._interrupted_total += 1
            self._last_interrupt_t = t
            self._overhead_s += time.perf_counter() - t_in
        self._ensure_drainer()

    # -- derived accounting ------------------------------------------------

    def iterations(self, limit: int = 0) -> List[Dict[str, Any]]:
        with self._lock:
            out = list(self._iters)
        return out[-limit:] if limit else out

    def summary(self) -> Dict[str, Any]:
        """The strict-phase waste picture: what ``rt rlhf stats``, the
        doctor bubble finding and the gauges read."""
        with self._lock:
            recs = list(self._iters)
            interrupted = self._interrupted_total
            total = self._seq
        ok = [r for r in recs if r["state"] == "ok"]
        out: Dict[str, Any] = {"name": self.name,
                               "iterations_total": total,
                               "interrupted_total": interrupted,
                               "window_iterations": len(ok)}
        actor_tot = {p: 0.0 for p in PIPE_PHASES}
        driver_tot: Dict[str, float] = {}
        tax_tot: Dict[str, float] = {}
        busy_tot = {r: 0.0 for r in ROLES}
        idle_tot = {r: 0.0 for r in ROLES}
        span_tot = 0.0
        bubbles: List[float] = []
        coverages: List[float] = []
        stalenesses: List[int] = []
        gaps: List[float] = []
        tokens = 0
        receipt_last = None
        for r in ok:
            for p, v in r["actor_s"].items():
                actor_tot[p] = actor_tot.get(p, 0.0) + v
            for p, v in r["driver_s"].items():
                driver_tot[p] = driver_tot.get(p, 0.0) + v
            for p, v in r["tax_s"].items():
                tax_tot[p] = tax_tot.get(p, 0.0) + v
            for role, v in r["role_busy_s"].items():
                busy_tot[role] = busy_tot.get(role, 0.0) + v
            for role, v in r["role_idle_s"].items():
                idle_tot[role] = idle_tot.get(role, 0.0) + v
            span_tot += r["span_busy_s"]
            bubbles.append(r["bubble_fraction"])
            coverages.append(r["coverage"])
            stalenesses.append(r["staleness"])
            tokens += r["tokens"]
            if "restart_gap_s" in r:
                gaps.append(r["restart_gap_s"])
            if "receipt" in r:
                receipt_last = r["receipt"]
        out["tokens"] = tokens
        out["actor_s"] = {p: round(v, 6) for p, v in actor_tot.items()
                          if v > 0.0}
        out["driver_s"] = {p: round(v, 6) for p, v in driver_tot.items()}
        out["tax_s"] = {p: round(v, 6) for p, v in tax_tot.items()}
        if span_tot > 0:
            out["role_busy_frac"] = {r: round(busy_tot[r] / span_tot, 4)
                                     for r in busy_tot}
            out["role_idle_frac"] = {r: round(idle_tot[r] / span_tot, 4)
                                     for r in idle_tot}
        if ok:
            out["bubble_fraction"] = round(sum(bubbles) / len(bubbles), 4)
            out["bubble_last"] = bubbles[-1]
            # the doctor's "sustained" signal: the last few per-iteration
            # bubble fractions, newest last
            out["bubble_recent"] = bubbles[-8:]
            out["coverage"] = round(sum(coverages) / len(coverages), 4)
            srt = sorted(stalenesses)
            out["staleness"] = {"last": stalenesses[-1],
                                "p50": _pct(srt, 0.50),
                                "p99": _pct(srt, 0.99),
                                "max": srt[-1]}
        if gaps:
            out["restart_gaps_s"] = [round(g, 4) for g in gaps[-4:]]
        if receipt_last:
            out["receipt_last"] = receipt_last
        last_int = [r for r in recs if r["state"] == "interrupted"]
        if last_int:
            out["interrupted_last"] = {"phase": last_int[-1]["phase"],
                                       "t": last_int[-1]["t"],
                                       "error": last_int[-1]["error"]}
        self._overhead_fields(out)
        return out

    def snapshot(self, iters_limit: int = 32) -> Dict[str, Any]:
        """The ``@rlhf/`` KV payload: summary + iteration-record tail,
        compact enough to push every couple of seconds."""
        out = self._snapshot_header()
        out["summary"] = self.summary()
        out["iterations"] = [self._compact_iter(r)
                             for r in self.iterations(iters_limit)]
        return out

    @staticmethod
    def _compact_iter(r: Dict[str, Any]) -> Dict[str, Any]:
        if r["state"] == "interrupted":
            return {"seq": r["seq"], "t": round(r["t"], 4),
                    "state": "interrupted", "phase": r["phase"],
                    "error": r["error"]}
        out = {"seq": r["seq"], "t": round(r["t"], 4),
               "state": "ok", "iteration": r["iteration"],
               "wall_ms": round(r["wall_s"] * 1e3, 3),
               "bubble_fraction": r["bubble_fraction"],
               "coverage": r["coverage"], "staleness": r["staleness"],
               "tokens": r["tokens"],
               "actor_ms": {p: round(v * 1e3, 3)
                            for p, v in r["actor_s"].items()},
               "tax_ms": {p: round(v * 1e3, 3)
                          for p, v in r["tax_s"].items()}}
        if "restart_gap_s" in r:
            out["restart_gap_s"] = r["restart_gap_s"]
        if "receipt" in r:
            out["receipt"] = r["receipt"]
        return out

    # -- off-driver drain (template in recorder_core; hooks below) ---------

    def _pending_since(self, wm_attr: str) -> List[Dict]:
        with self._lock:
            wm = getattr(self, wm_attr)
            return [r for r in self._iters if r.get("seq", 0) > wm]

    def _drain_metrics(self) -> int:
        try:
            from ray_tpu.util import metrics as M
        except Exception:  # noqa: BLE001
            return 0
        h = _metric_handles(M)
        tags = {"pipeline": self.name}
        new = self._pending_since("_metrics_wm")
        for r in new:
            if r["state"] != "ok":
                continue
            for p, v in r["tax_s"].items():
                h["tax"].observe(v, tags={"pipeline": self.name,
                                          "phase": p})
            h["staleness"].observe(float(r["staleness"]), tags=tags)
            rcpt = r.get("receipt") or {}
            for stage, key in (("pump", "pump_wall_s"),
                               ("fetch", "fetch_wall_s"),
                               ("barrier", "barrier_drain_s"),
                               ("swap", "swap_apply_s")):
                v = rcpt.get(key)
                if v is not None:
                    h["transfer"].observe(float(v),
                                          tags={"pipeline": self.name,
                                                "stage": stage})
        if new:
            self._metrics_wm = new[-1]["seq"]
        summ = self.summary()
        if summ.get("window_iterations"):
            h["bubble"].set(summ["bubble_last"], tags=tags)
            for role, v in summ.get("role_idle_frac", {}).items():
                h["idle"].set(v, tags={"pipeline": self.name,
                                       "role": role})
            h["overhead"].set(summ["overhead_frac"], tags=tags)
        return len(new)

    def _build_events(self, node: str, pid: int):
        """Iteration records as GCS task events; the advance closure
        runs only after a successful push."""
        events = []
        new = self._pending_since("_event_wm")
        for r in new[-128:]:
            if r["state"] == "interrupted":
                events.append({
                    "task_id": f"rlhfit:{node}:{pid}:{self.name}:"
                               f"{r['seq']}",
                    "name": f"rlhf:{self.name}:interrupt",
                    "state": "FAILED", "node_id": node,
                    "times": {"RUNNING": r["t"], "FAILED": r["t"]},
                    "rlhf_iter": {**r, "pipeline": self.name}})
                continue
            events.append({
                "task_id": f"rlhfit:{node}:{pid}:{self.name}:{r['seq']}",
                "name": f"rlhf:{self.name}:iter{r['iteration']}",
                "state": "FINISHED", "node_id": node,
                "times": {"RUNNING": r["t"], "FINISHED": r["t1"]},
                "rlhf_iter": {**{k: v for k, v in r.items()
                                 if k not in ("role_busy_s",
                                              "role_idle_s")},
                              "pipeline": self.name}})

        def advance() -> None:
            if new:
                self._event_wm = new[-1]["seq"]

        return events, advance


_metric_cache: Optional[Dict[str, Any]] = None
_TAX_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 0.5, 1.0)
_STALE_BUCKETS = (0.0, 1.0, 2.0, 4.0, 8.0, 16.0)
_XFER_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                 0.5, 1.0, 2.5, 5.0)


def _metric_handles(M) -> Dict[str, Any]:
    """Lazily registered ``rt_rlhf_*`` recorder series (drain thread
    only)."""
    global _metric_cache
    if _metric_cache is None:
        _metric_cache = {
            "bubble": M.get_or_create(
                M.Gauge, "rt_rlhf_bubble_fraction",
                "Role-seconds idle while any other role works / total "
                "role-seconds, last iteration (strict phases ~0.7; the "
                "interleave arc drives this down)",
                tag_keys=("pipeline",)),
            "idle": M.get_or_create(
                M.Gauge, "rt_rlhf_role_idle_fraction",
                "Fraction of the pipeline's busy span each role spent "
                "idle while another role worked, role= "
                "(generator / reference / reward / learner)",
                tag_keys=("pipeline", "role")),
            "tax": M.get_or_create(
                M.Histogram, "rt_rlhf_orchestration_tax_seconds",
                "Driver-observed phase wall minus actor-side phase wall "
                "(RPC submit/get + serialization + scheduling), phase=",
                boundaries=_TAX_BUCKETS, tag_keys=("pipeline", "phase")),
            "staleness": M.get_or_create(
                M.Histogram, "rt_rlhf_staleness_versions",
                "Learner weights-version minus the version each generate "
                "batch decoded under (strict phases measure 0)",
                boundaries=_STALE_BUCKETS, tag_keys=("pipeline",)),
            "transfer": M.get_or_create(
                M.Histogram, "rt_rlhf_transfer_seconds",
                "Weight-plane transfer receipt walls, stage= "
                "(pump / fetch / barrier / swap)",
                boundaries=_XFER_BUCKETS, tag_keys=("pipeline", "stage")),
            "overhead": M.get_or_create(
                M.Gauge, "rt_rlhf_recorder_overhead_ratio",
                "Recorder self-time as a fraction of recorded iteration "
                "wall",
                tag_keys=("pipeline",)),
        }
    return _metric_cache
