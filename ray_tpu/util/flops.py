"""Analytic FLOP accounting for the model zoo — MFU and tokens/s in one place.

Every throughput claim in the repo (bench.py's sweep MFU, the step
profiler's per-step MFU, the decode tokens/s legs) needs the same three
ingredients: a parameter count, a per-token FLOP estimate, and a peak-FLOPs
denominator. This module is the single home for those formulas so the
numbers agree everywhere (Podracer, arXiv:2104.06272, makes the same
accounting the basis of TPU throughput work).

Conventions (the standard scaling-book estimates):
  - A matmul touching N parameters costs 2N FLOPs per token forward and
    4N backward, so a train step is ~6N per token plus the attention
    quadratic term (causal halves it): 6*L*S*d per token.
  - Decode costs 2N per token forward plus attention over the live
    context: 4*L*d*ctx per token (no causal halving — one query row).
  - MoE counts ACTIVE parameters (top-k experts), not total.
Embedding/head params are included — at the small-vocab presets they are
a real fraction of the work; callers wanting the non-embedding convention
can pass their own ``params`` count.
"""

from __future__ import annotations

import os
from typing import Optional

# Per-chip peak (bf16 matmul). v5e: 197 TFLOP/s. "cpu" is a rough
# placeholder so CPU smoke runs report a stable (if synthetic) MFU.
PEAK_FLOPS = {"tpu": 197e12, "gpu": 312e12, "cpu": 1e11}


def peak_flops_per_chip(platform: Optional[str] = None) -> float:
    """Peak FLOP/s of one device; RT_PEAK_FLOPS overrides (e.g. for a
    different TPU generation than the v5e default)."""
    env = os.environ.get("RT_PEAK_FLOPS")
    if env:
        return float(env)
    if platform is None:
        import jax

        platform = jax.default_backend()
    return PEAK_FLOPS.get(platform, 1e12)


def _flops_params(cfg) -> int:
    """The FLOPs-relevant parameter count: active params for MoE (top-k
    experts per token), total params otherwise."""
    active = getattr(cfg, "active_params", None)
    return active() if callable(active) else cfg.num_params()


def train_flops_per_token(cfg, seq: int) -> float:
    """Fwd+bwd FLOPs per trained token: 6N + causal attention term."""
    n = _flops_params(cfg)
    attn = 6 * cfg.n_layers * seq * cfg.n_heads * cfg.head_dim
    return 6.0 * n + attn


def train_step_flops(cfg, batch: int, seq: int) -> float:
    """One optimizer step over a [batch, seq] token block."""
    return batch * seq * train_flops_per_token(cfg, seq)


def decode_flops_per_token(cfg, context: int) -> float:
    """One-token forward with a KV cache holding ``context`` positions."""
    n = _flops_params(cfg)
    attn = 4 * cfg.n_layers * cfg.n_heads * cfg.head_dim * context
    return 2.0 * n + attn


def prefill_flops(cfg, batch: int, seq: int) -> float:
    """Batched prompt forward (causal attention over the prompt)."""
    n = _flops_params(cfg)
    attn = 2 * cfg.n_layers * seq * cfg.n_heads * cfg.head_dim
    return batch * seq * (2.0 * n + attn)


def generate_flops(cfg, batch: int, prompt_len: int,
                   new_tokens: int) -> float:
    """Prefill + autoregressive decode of ``new_tokens`` tokens. The decode
    attention term uses the mean live context (prompt + T/2)."""
    ctx = prompt_len + new_tokens / 2.0
    return (prefill_flops(cfg, batch, prompt_len)
            + batch * new_tokens * decode_flops_per_token(cfg, ctx))


def vit_step_flops(cfg, batch: int) -> float:
    """ViT classification train step: 6N per patch token plus the
    NON-causal attention term (every token attends to every token)."""
    tokens = cfg.num_patches + 1  # + cls token
    n = cfg.num_params()
    attn = 12 * cfg.n_layers * tokens * cfg.n_heads * cfg.head_dim
    return batch * tokens * (6.0 * n + attn)


def mfu(flops: float, seconds: float, n_devices: int = 1,
        peak_per_chip: Optional[float] = None) -> float:
    """Model-FLOPs utilization: analytic work / (wall * aggregate peak)."""
    if seconds <= 0 or flops <= 0:
        return 0.0
    peak = peak_per_chip if peak_per_chip is not None \
        else peak_flops_per_chip()
    return flops / (seconds * peak * max(1, n_devices))
