"""Distributed-safe progress bars over the log plumbing.

Reference analog: ``python/ray/experimental/tqdm_ray.py`` — worker-side
``tqdm`` emits structured magic lines instead of terminal control codes
(which would interleave garbage across the worker->driver log echo);
the driver's log pump recognizes them and renders one compact,
rate-limited progress line per bar.

Worker side::

    from ray_tpu.util.tqdm_rt import tqdm
    for row in tqdm(items, desc="ingest", total=len(items)):
        ...

Bars also work in the driver process directly (rendered locally).
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Dict, Iterable, Iterator, Optional

MAGIC = "__rt_tqdm__:"
_UPDATE_INTERVAL_S = 0.5


class tqdm:
    """Minimal tqdm-compatible surface: iteration, ``update``, ``close``,
    ``set_description``. State updates ride as ``MAGIC + json`` lines."""

    _next_uid = [0]

    def __init__(self, iterable: Optional[Iterable] = None, *,
                 desc: str = "", total: Optional[int] = None,
                 file=None):
        self._iterable = iterable
        self.desc = desc
        if total is None and iterable is not None:
            try:
                total = len(iterable)  # type: ignore[arg-type]
            except TypeError:
                total = None
        self.total = total
        self.n = 0
        self._start = time.monotonic()
        self._last_emit = 0.0
        self._file = file or sys.stdout
        self._closed = False
        tqdm._next_uid[0] += 1
        self._uid = tqdm._next_uid[0]

    # -- tqdm surface -----------------------------------------------------

    def __iter__(self) -> Iterator:
        assert self._iterable is not None, "no iterable given"
        completed = False
        try:
            for x in self._iterable:
                yield x
                self.update(1)
            completed = True
        finally:
            # an aborted loop must NOT read as finished in the log stream
            self.close(done=completed)

    def update(self, n: int = 1) -> None:
        self.n += n
        now = time.monotonic()
        if now - self._last_emit >= _UPDATE_INTERVAL_S:
            self._emit(now)

    def set_description(self, desc: str) -> None:
        self.desc = desc

    def close(self, done: bool = True) -> None:
        if not self._closed:
            self._closed = True
            self._emit(time.monotonic(), done=done)

    def __enter__(self) -> "tqdm":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- emission ---------------------------------------------------------

    def _emit(self, now: float, done: bool = False) -> None:
        self._last_emit = now
        state = {"uid": self._uid, "desc": self.desc, "n": self.n,
                 "total": self.total,
                 "rate": round(self.n / max(now - self._start, 1e-9), 1),
                 "done": done}
        if os.environ.get("RT_WORKER_ID"):
            # inside a worker: the magic line rides the log pump to the
            # driver, which renders it compactly
            print(MAGIC + json.dumps(state), file=self._file, flush=True)
        else:
            # driver/standalone process: render directly
            print(render_state(state), file=self._file, flush=True)


def render_state(state: Dict[str, Any]) -> str:
    """One compact text line for a bar state (driver-side display)."""
    desc = state.get("desc") or "progress"
    n, total = state.get("n", 0), state.get("total")
    rate = state.get("rate", 0.0)
    if total:
        pct = 100.0 * n / max(total, 1)
        body = f"{desc}: {n}/{total} ({pct:.0f}%) [{rate}/s]"
    else:
        body = f"{desc}: {n} [{rate}/s]"
    return body + (" done" if state.get("done") else "")


def maybe_render(line: str) -> Optional[str]:
    """If ``line`` is a bar magic line, return its rendered form (None =
    not a progress line; caller prints the raw line as usual)."""
    if not line.startswith(MAGIC):
        return None
    try:
        return render_state(json.loads(line[len(MAGIC):]))
    except ValueError:
        return None
