"""Joblib-on-ray_tpu: run scikit-learn / joblib.Parallel over cluster tasks.

Reference analog: ``python/ray/util/joblib/`` (``register_ray`` +
``RayBackend``). ``register_ray()`` registers a joblib parallel backend
named "ray"; ``with joblib.parallel_backend("ray"):`` then fans each batch
out as a task.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from joblib._parallel_backends import ParallelBackendBase

import ray_tpu


def register_ray() -> None:
    from joblib.parallel import register_parallel_backend

    register_parallel_backend("ray", RayBackend)


class _TaskFuture:
    """joblib expects a multiprocessing-style async result."""

    def __init__(self, ref):
        self._ref = ref

    def get(self, timeout: Optional[float] = None) -> Any:
        return ray_tpu.get(self._ref, timeout=timeout)


@ray_tpu.remote
def _run_batch(batch: Callable) -> Any:
    return batch()


class RayBackend(ParallelBackendBase):
    """joblib backend over cluster tasks: ParallelBackendBase supplies the
    batching/dispatch machinery; submission is a task per batch."""

    supports_timeout = True
    uses_threads = False
    supports_sharedmem = False

    def configure(self, n_jobs: int = 1, parallel=None, prefer=None,
                  require=None, **kwargs) -> int:
        self.parallel = parallel
        self._n_jobs = self.effective_n_jobs(n_jobs)
        return self._n_jobs

    def effective_n_jobs(self, n_jobs: Optional[int]) -> int:
        if n_jobs == 0:
            raise ValueError("n_jobs == 0 has no meaning")
        if n_jobs is None:
            n_jobs = 1
        if n_jobs < 0:  # -1 = cluster CPU capacity
            total = ray_tpu.cluster_resources().get("CPU", 1)
            return max(1, int(total))
        return n_jobs

    def submit(self, func, callback=None):
        return self.apply_async(func, callback)

    def apply_async(self, func: Callable, callback=None) -> _TaskFuture:
        ref = _run_batch.remote(func)
        fut = _TaskFuture(ref)
        if callback is not None:
            # joblib's completion callback drives its dispatch window; ONE
            # waiter thread drains all in-flight batches (a thread per
            # batch would mean thousands of parked OS threads on large
            # Parallel runs)
            self._enqueue_wait(ref, fut, callback)
        return fut

    def _enqueue_wait(self, ref, fut, callback) -> None:
        import queue
        import threading

        if getattr(self, "_waitq", None) is None:
            q: "queue.Queue" = queue.Queue()
            self._waitq = q

            def drain(q=q):  # local ref: terminate() nulls the attribute
                pending = {}
                stopping = False
                while True:
                    block = not pending and not stopping
                    try:
                        item = q.get(block=block, timeout=None
                                     if block else 0)
                        if item is None:
                            stopping = True  # finish pending, then exit
                        else:
                            pending[item[0]] = item
                    except queue.Empty:
                        pass
                    if not pending:
                        if stopping:
                            return
                        continue
                    ready, _ = ray_tpu.wait(list(pending),
                                            num_returns=1, timeout=1.0)
                    for r in ready:
                        _, f, cb = pending.pop(r)
                        try:
                            cb(f)
                        except Exception:  # noqa: BLE001
                            pass

            self._wait_thread = threading.Thread(
                target=drain, daemon=True, name="rt-joblib-wait")
            self._wait_thread.start()
        self._waitq.put((ref, fut, callback))

    def retrieve_result_callback(self, out):
        return out.get() if isinstance(out, _TaskFuture) else out

    def get_nested_backend(self):
        from joblib._parallel_backends import SequentialBackend

        return SequentialBackend(nesting_level=1), None

    def terminate(self) -> None:
        if getattr(self, "_waitq", None) is not None:
            self._waitq.put(None)  # waiter thread exits
            self._waitq = None

    def abort_everything(self, ensure_ready: bool = True) -> None:
        pass

    # joblib calls these around a Parallel run
    def start_call(self) -> None:
        pass

    def stop_call(self) -> None:
        pass
