"""Shared flight-recorder core: the substrate the engine (PR 18), RLHF
(PR 19) and train (PR 20) recorders are built on.

Three hot paths grew the same recorder shape independently — bounded
ring buffers appended under a microsecond lock, a daemon drain thread
shipping derived telemetry off the hot path on seq-watermarks, a compact
KV snapshot pushed every couple of seconds and deleted at close, and
recorder self-timing against a ≤2% overhead bar. This module extracts
that core once so the next plane inherits the discipline instead of
copying it:

  RecorderRegistry  per-module registry of live recorders (bounded at
                    64 — a leaked construct loop must not grow an
                    unbounded dict), backing each module's
                    ``live_recorders()``
  RecorderCore      the drain-side template: ``_ensure_drainer`` /
                    ``_drain_loop`` / ``drain_now`` / ``_drain_gcs`` /
                    ``close``, parameterized by class attrs
                    (``KV_PREFIX`` / ``DRAIN_S`` / ``THREAD_NAME`` /
                    ``REGISTRY``) and subclass hooks (``snapshot`` /
                    ``_drain_metrics`` / ``_build_events``; engine-only
                    ``_drain_spans``)
  cluster_backend   the "initialized runtime or None" probe every
                    drain pass makes
  pct               nearest-rank percentile over a pre-sorted list

The hot-path discipline (the PR 15 ``@memkv/`` lesson, measured: a
blocking GCS push on the tick path froze admission AND decode, warm p99
181 ms → 2.6 s) stays the subclasses' job: record methods ONLY append
to bounded deques under ``_lock`` and accumulate their own wall into
``_overhead_s``; everything with I/O in it runs on the drain thread.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple


def pct(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[idx]


def cluster_backend() -> Optional[Any]:
    """The initialized cluster runtime's backend, or None — every drain
    pass starts with this probe so a recorder outside a cluster (unit
    tests, bare bench legs) costs nothing and raises nothing."""
    try:
        import ray_tpu

        if not ray_tpu.is_initialized():
            return None
        return ray_tpu.global_worker()._require_backend()
    except Exception:  # noqa: BLE001 — no runtime is a normal state
        return None


class RecorderRegistry:
    """Per-module registry of live recorders.

    Bounded: a pathological construct loop (a test fixture, a retrying
    driver) must not grow an unbounded id->recorder dict, so the oldest
    entry is evicted past ``cap``. Eviction only forgets the handle —
    the evicted recorder keeps recording and draining until closed.
    """

    def __init__(self, cap: int = 64):
        self._cap = int(cap)
        self._lock = threading.Lock()
        self._recorders: "OrderedDict[int, Any]" = \
            OrderedDict()  # rt: guarded-by(_lock)

    def register(self, rec: Any) -> None:
        with self._lock:
            self._recorders[id(rec)] = rec
            while len(self._recorders) > self._cap:
                self._recorders.popitem(last=False)

    def unregister(self, rec: Any) -> None:
        with self._lock:
            self._recorders.pop(id(rec), None)

    def live(self) -> List[Any]:
        """Every recorder registered in this process and not yet
        closed — the local stats paths and tests read through this."""
        with self._lock:
            return list(self._recorders.values())


class RecorderCore:
    """Drain-side template shared by every flight recorder.

    Subclasses set the class attrs, call ``_init_core(name)`` from
    ``__init__`` (after their own fields — it registers the recorder,
    which makes it visible to ``live_recorders()``), and implement:

      snapshot() -> dict                  the KV payload
      _drain_metrics() -> int             observe new records into
                                          ``util.metrics`` series
      _build_events(node, pid)            (events, advance_fn): GCS
                                          task-events for new records
                                          plus the watermark advance to
                                          run only on a successful push
      _drain_spans() -> Optional[int]     request-span join (engine
                                          only); None = no span plane,
                                          key omitted from drain counts
    """

    KV_PREFIX = "@rec/"
    DRAIN_S = 2.0
    THREAD_NAME = "rt-rec"
    REGISTRY: RecorderRegistry = RecorderRegistry()

    name: str

    def _init_core(self, name: str) -> None:
        self._lock = threading.Lock()
        self._overhead_s = 0.0  # rt: guarded-by(_lock)
        self._wall_total_s = 0.0  # rt: guarded-by(_lock)
        self._closed = False  # rt: guarded-by(_lock)
        self._drainer: Optional[threading.Thread] = None  # rt: guarded-by(_lock)
        self._kv_key = f"{self.KV_PREFIX}{os.uname().nodename}:" \
                       f"{os.getpid()}:{name}"
        self.REGISTRY.register(self)

    # -- subclass hooks ----------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:  # pragma: no cover - abstract
        raise NotImplementedError

    def _drain_metrics(self) -> int:  # pragma: no cover - abstract
        raise NotImplementedError

    def _build_events(self, node: str, pid: int
                      ) -> Tuple[List[Dict[str, Any]], Callable[[], None]]:
        return [], lambda: None

    def _drain_spans(self) -> Optional[int]:
        return None

    # -- shared helpers ----------------------------------------------------

    def _snapshot_header(self) -> Dict[str, Any]:
        return {"t": time.time(), "name": self.name,
                "node": os.uname().nodename, "pid": os.getpid()}

    def _overhead_fields(self, out: Dict[str, Any]) -> None:
        """Stamp the self-timing triple every summary reports (the
        bench gates hold ``overhead_frac`` ≤ 2%)."""
        with self._lock:
            overhead = self._overhead_s
            wall = self._wall_total_s
        out["overhead_s"] = round(overhead, 6)
        out["recorded_wall_s"] = round(wall, 6)
        out["overhead_frac"] = round(overhead / wall, 6) \
            if wall > 0 else 0.0

    # -- drain side --------------------------------------------------------

    def _ensure_drainer(self) -> None:
        if self._drainer is not None and self._drainer.is_alive():
            return
        with self._lock:
            if self._closed or (self._drainer is not None
                                and self._drainer.is_alive()):
                return
            self._drainer = threading.Thread(
                target=self._drain_loop, daemon=True,
                name=f"{self.THREAD_NAME}:{self.name}")
            self._drainer.start()

    def _drain_loop(self) -> None:
        while True:
            time.sleep(self.DRAIN_S)
            with self._lock:
                if self._closed:
                    return
            try:
                self.drain_now()
            except Exception:  # noqa: BLE001 — observability must never
                pass           # take the instrumented loop down

    def drain_now(self) -> Dict[str, int]:
        """One drain pass (tests call this instead of waiting out the
        interval): metrics observation, span emission where the plane
        has one, the KV snapshot, and record events into the GCS
        task-event store."""
        counts = {"metrics": self._drain_metrics()}
        spans = self._drain_spans()
        if spans is not None:
            counts["spans"] = spans
        counts.update(self._drain_gcs())
        return counts

    def _drain_gcs(self) -> Dict[str, int]:
        """KV snapshot + timeline events; both best-effort, both skipped
        cleanly outside an initialized cluster runtime. Event watermarks
        advance only on a successful push — a flaky GCS re-sends, never
        drops."""
        out = {"kv": 0, "events": 0}
        backend = cluster_backend()
        if backend is None:
            return out
        try:
            if hasattr(backend, "kv_put"):
                backend.kv_put(self._kv_key,
                               json.dumps(self.snapshot()).encode())
                out["kv"] = 1
        except Exception:  # noqa: BLE001
            pass
        if not hasattr(backend, "_gcs"):
            return out
        events, advance = self._build_events(os.uname().nodename,
                                             os.getpid())
        if not events:
            return out
        try:
            backend.io.run(backend._gcs.call("task_events",
                                             {"events": events}))
            advance()
            out["events"] = len(events)
        except Exception:  # noqa: BLE001
            pass
        return out

    def close(self) -> None:
        """Stop the drain thread and drop the KV snapshot (the doctor
        must not grade a dead plane's numbers — same discipline as the
        serve controller's shutdown)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self.REGISTRY.unregister(self)
        try:
            backend = cluster_backend()
            if backend is not None and hasattr(backend, "kv_del"):
                backend.kv_del(self._kv_key)
        except Exception:  # noqa: BLE001
            pass
