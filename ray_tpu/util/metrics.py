"""User metrics API: Counter / Gauge / Histogram + Prometheus text export.

Reference analogs: ``python/ray/util/metrics.py`` (the user API) and the
metrics pipeline ``src/ray/stats/metric_defs.cc`` -> per-node agent ->
Prometheus (``_private/metrics_agent.py``, ``prometheus_exporter.py``).
Redesign: no per-node agent process — every worker/driver process keeps a
local registry and pushes snapshots to the GCS KV on an interval; scrapers
read one aggregated Prometheus text page from ``rt metrics`` (or the
``metrics_text`` helper).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

_PUSH_INTERVAL_S = 5.0
_KV_PREFIX = "@metrics/"

_DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                    5.0, 10.0)


class _Registry:
    def __init__(self):
        self.metrics: Dict[str, "Metric"] = {}
        self.lock = threading.Lock()
        self._pusher: Optional[threading.Thread] = None

    def register(self, metric: "Metric") -> None:
        with self.lock:
            existing = self.metrics.get(metric.name)
            if existing is not None and type(existing) is not type(metric):
                raise ValueError(
                    f"metric {metric.name!r} already registered as "
                    f"{type(existing).__name__}")
            self.metrics[metric.name] = metric
        self._ensure_pusher()

    def snapshot(self) -> List[Dict]:
        with self.lock:
            return [m.to_dict() for m in self.metrics.values()]

    def _ensure_pusher(self) -> None:
        if self._pusher is not None and self._pusher.is_alive():
            return
        self._pusher = threading.Thread(target=self._push_loop, daemon=True,
                                        name="rt-metrics-push")
        self._pusher.start()

    def _push_loop(self) -> None:
        import os

        import ray_tpu

        key = _KV_PREFIX + f"{os.uname().nodename}:{os.getpid()}"
        while True:
            time.sleep(_PUSH_INTERVAL_S)
            try:
                if not ray_tpu.is_initialized():
                    continue
                backend = ray_tpu.global_worker()._require_backend()
                if not hasattr(backend, "kv_put"):
                    continue
                backend.kv_put(key, json.dumps({
                    "t": time.time(), "metrics": self.snapshot()}).encode())
            except Exception:
                pass  # metrics must never take the workload down


_registry = _Registry()


_GET_OR_CREATE_LOCK = threading.Lock()


def get_or_create(kind: type, name: str, description: str = "",
                  **kwargs) -> "Metric":
    """Idempotent registration: return the live metric when one of the same
    type already holds ``name`` (constructing a fresh object would shadow
    the accumulated samples in the registry). Library instrumentation (the
    step profiler's auto-registered histograms) goes through this so
    re-entry — a second ``enable()``, concurrent first observations from
    two threads, a reimport under tests — is safe. The outer lock makes
    check-then-construct atomic; construction takes the registry lock
    nested inside it (never the reverse), so there is no lock cycle."""
    with _GET_OR_CREATE_LOCK:
        with _registry.lock:
            existing = _registry.metrics.get(name)
        if existing is not None and type(existing) is kind:
            return existing
        return kind(name, description, **kwargs)


def _label_key(labels: Optional[Dict[str, str]]) -> Tuple:
    return tuple(sorted((labels or {}).items()))


class Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: Sequence[str] = ()):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: Dict[str, str] = {}
        self._lock = threading.Lock()
        _registry.register(self)

    def set_default_tags(self, tags: Dict[str, str]) -> "Metric":
        self._default_tags = dict(tags)
        return self

    def _tags(self, tags: Optional[Dict[str, str]]) -> Dict[str, str]:
        merged = dict(self._default_tags)
        merged.update(tags or {})
        return merged


class Counter(Metric):
    def __init__(self, name, description="", tag_keys=()):
        self._values: Dict[Tuple, float] = {}
        super().__init__(name, description, tag_keys)

    def inc(self, value: float = 1.0,
            tags: Optional[Dict[str, str]] = None) -> None:
        if value < 0:
            raise ValueError("counters only increase")
        key = _label_key(self._tags(tags))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def to_dict(self) -> Dict:
        with self._lock:
            return {"type": "counter", "name": self.name,
                    "help": self.description,
                    "samples": [[dict(k), v] for k, v in self._values.items()]}


class Gauge(Metric):
    def __init__(self, name, description="", tag_keys=()):
        self._values: Dict[Tuple, float] = {}
        super().__init__(name, description, tag_keys)

    def set(self, value: float, tags: Optional[Dict[str, str]] = None) -> None:
        with self._lock:
            self._values[_label_key(self._tags(tags))] = float(value)

    def inc(self, value: float = 1.0, tags=None) -> None:
        key = _label_key(self._tags(tags))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def dec(self, value: float = 1.0, tags=None) -> None:
        self.inc(-value, tags)

    def remove(self, tags: Optional[Dict[str, str]] = None) -> None:
        """Drop one labeled sample (e.g. a dead worker's RSS gauge) so
        stale series don't linger on the Prometheus page forever."""
        with self._lock:
            self._values.pop(_label_key(self._tags(tags)), None)

    def to_dict(self) -> Dict:
        with self._lock:
            return {"type": "gauge", "name": self.name,
                    "help": self.description,
                    "samples": [[dict(k), v] for k, v in self._values.items()]}


class Histogram(Metric):
    def __init__(self, name, description="", boundaries: Sequence[float] = (),
                 tag_keys=()):
        self.boundaries = tuple(boundaries) or _DEFAULT_BUCKETS
        self._counts: Dict[Tuple, List[int]] = {}
        self._sums: Dict[Tuple, float] = {}
        self._totals: Dict[Tuple, int] = {}
        super().__init__(name, description, tag_keys)

    def observe(self, value: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        key = _label_key(self._tags(tags))
        with self._lock:
            counts = self._counts.setdefault(
                key, [0] * (len(self.boundaries) + 1))
            for i, b in enumerate(self.boundaries):
                if value <= b:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def to_dict(self) -> Dict:
        with self._lock:
            return {"type": "histogram", "name": self.name,
                    "help": self.description,
                    "boundaries": list(self.boundaries),
                    "samples": [[dict(k), {
                        "counts": list(self._counts[k]),
                        "sum": self._sums[k], "count": self._totals[k]}]
                        for k in self._counts]}


# ---- export -----------------------------------------------------------------

def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def prometheus_text(snapshots: List[Dict]) -> str:
    """Render metric snapshots (from one or many processes) as the
    Prometheus text exposition format, merging same-named series."""
    by_name: Dict[str, List[Dict]] = {}
    for m in snapshots:
        by_name.setdefault(m["name"], []).append(m)
    lines: List[str] = []
    for name, metrics in sorted(by_name.items()):
        kind = metrics[0]["type"]
        lines.append(f"# HELP {name} {metrics[0].get('help', '')}")
        lines.append(f"# TYPE {name} {kind}")
        if kind in ("counter", "gauge"):
            merged: Dict[Tuple, float] = {}
            for m in metrics:
                for labels, v in m["samples"]:
                    key = _label_key(labels)
                    if kind == "counter":
                        merged[key] = merged.get(key, 0.0) + v
                    else:
                        merged[key] = v  # last writer wins for gauges
            for key, v in sorted(merged.items()):
                lines.append(f"{name}{_fmt_labels(dict(key))} {v}")
        else:  # histogram
            for m in metrics:
                bounds = m["boundaries"]
                for labels, h in m["samples"]:
                    cum = 0
                    for b, c in zip(bounds, h["counts"]):
                        cum += c
                        lab = dict(labels)
                        lab["le"] = str(b)
                        lines.append(f"{name}_bucket{_fmt_labels(lab)} {cum}")
                    lab = dict(labels)
                    lab["le"] = "+Inf"
                    lines.append(
                        f"{name}_bucket{_fmt_labels(lab)} {h['count']}")
                    lines.append(
                        f"{name}_sum{_fmt_labels(dict(labels))} {h['sum']}")
                    lines.append(
                        f"{name}_count{_fmt_labels(dict(labels))} {h['count']}")
    return "\n".join(lines) + "\n"


def metrics_text() -> str:
    """Aggregate every process's pushed snapshot from the GCS KV into one
    Prometheus page (what ``rt metrics`` prints / an exporter serves)."""
    import ray_tpu

    backend = ray_tpu.global_worker()._require_backend()
    try:
        flush_now()  # fold this process's live registry into its KV slot
    except Exception:  # noqa: BLE001
        pass
    snapshots: List[Dict] = []
    for key in backend.kv_keys(_KV_PREFIX):
        raw = backend.kv_get(key)
        if raw:
            try:
                snapshots.extend(json.loads(raw)["metrics"])
            except (ValueError, KeyError):
                pass
    return prometheus_text(snapshots)


def flush_now() -> None:
    """Push this process's snapshot immediately (tests; shutdown hooks)."""
    import os

    import ray_tpu

    backend = ray_tpu.global_worker()._require_backend()
    key = _KV_PREFIX + f"{os.uname().nodename}:{os.getpid()}"
    backend.kv_put(key, json.dumps(
        {"t": time.time(), "metrics": _registry.snapshot()}).encode())
