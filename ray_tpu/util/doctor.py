"""``rt doctor`` — one-shot cluster health report with a CI-friendly exit
code.

Reads every observability plane this repo has grown (no driver attach —
direct GCS/raylet RPCs, so it works against a wedged cluster too):

  - node / actor / worker liveness (GCS node+actor tables, raylet
    ``node_stats``),
  - the failure plane: recent FailureEvents ranked by category
    (``cluster/gcs.py`` ``failure_events`` store, `rt errors`' feed),
  - the memory plane (PR 4): OOM post-mortems, spill pressure and leak
    suspects (raylet ``memory_report`` + the ``@memobj/`` KV ledgers),
  - scheduler pressure: per-node raylet queue depth,
  - the engine plane: flight-recorder snapshots (``@engine/`` KV,
    ``util/engine_recorder.py``) — sustained decode tick-gap and
    TTFT/TPOT SLO-attainment findings at nonzero load,
  - the RLHF plane: pipeline flight-recorder snapshots (``@rlhf/`` KV,
    ``util/pipeline_recorder.py``) — sustained bubble-fraction findings
    (role-seconds idling while another role works).

Exit codes: 0 healthy, 1 unhealthy (any critical finding), 2 cluster
unreachable. ``collect()`` returns the raw report; ``diagnose()`` turns it
into findings; ``format_report()`` renders the human page.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Any, Dict, List, Optional, Tuple

# categories that indicate breakage (vs. intentional / user-code outcomes)
_CRITICAL_CATEGORIES = ("oom_kill", "worker_crash", "node_death",
                        "actor_restart_exhausted", "owner_died")
_WARN_CATEGORIES = ("task_error", "object_lost", "get_timeout",
                    "scheduling_timeout", "pg_removed",
                    "runtime_env_setup", "unknown")

OK, WARN, CRITICAL = "ok", "warn", "critical"


async def _collect_async(gcs_address: str, window_s: float,
                         limit: int) -> Dict[str, Any]:
    from ray_tpu.cluster.rpc import RpcClient

    gcs = RpcClient(gcs_address, peer_id="rt-doctor")
    await gcs.connect()
    try:
        nodes, actors, failures, ooms = await asyncio.gather(
            gcs.call("list_nodes", {}, timeout=10.0),
            gcs.call("list_actors", {}, timeout=10.0),
            gcs.call("list_failure_events", {"limit": limit}, timeout=10.0),
            gcs.call("list_mem_events",
                     {"kind": "oom_kill", "limit": 50}, timeout=10.0))

        # cross-node balance plane (placement receipts PR): CoV snapshot +
        # recent per-tick history for the sustained-imbalance grading
        sched_balance = None
        try:
            sched_balance = await gcs.call("sched_balance", {"limit": 60},
                                           timeout=10.0)
        except Exception:  # noqa: BLE001 — older GCS
            pass

        async def probe_node(n):
            out = {"node_id": n["node_id"], "alive": n.get("alive", True),
                   "queue_depth": n.get("queue_depth", 0),
                   "sched": n.get("sched"),
                   "address": n.get("address"),
                   "death_t": n.get("death_t"),
                   "death_reason": n.get("death_reason", "")}
            if not out["alive"]:
                return out
            client = None
            try:
                client = RpcClient(n["address"], peer_id="rt-doctor")
                await client.connect()
                stats, mem = await asyncio.gather(
                    client.call("node_stats", {}, timeout=10.0),
                    client.call("memory_report", {"limit": 20},
                                timeout=10.0))
                out["stats"] = stats
                out["memory"] = mem
            except Exception as e:  # noqa: BLE001 — report, don't die
                out["unreachable"] = f"{type(e).__name__}: {e}"
            finally:
                if client is not None:
                    try:
                        await client.close()
                    except Exception:  # noqa: BLE001
                        pass
            return out

        probed = list(await asyncio.gather(*(probe_node(n) for n in nodes)))

        # ownership ledgers via the GCS KV (no driver needed) -> suspects;
        # fetched concurrently — the one-shot report must not serialize
        # 200 round-trips against a loaded GCS
        ledgers: List[Dict] = []
        try:
            keys = (await gcs.call("kv_keys", {"prefix": "@memobj/"},
                                   timeout=10.0))["keys"]
            now = time.time()
            replies = await asyncio.gather(
                *(gcs.call("kv_get", {"key": k}, timeout=10.0)
                  for k in keys[:200]))
            for reply in replies:
                raw = reply.get("value")
                if not raw:
                    continue
                try:
                    led = json.loads(raw)
                except ValueError:
                    continue
                if now - led.get("t", 0.0) <= 30.0:  # live pushers only
                    ledgers.append(led)
        except Exception:  # noqa: BLE001 — ledger plane optional
            pass

        # engine plane: each ContinuousEngine's flight recorder pushes a
        # compact @engine/ snapshot from its drain thread
        # (util/engine_recorder.py) and deletes it at shutdown — stale
        # ones (a crashed pusher) are skipped at diagnose time
        engines: List[Dict] = []
        try:
            keys = (await gcs.call("kv_keys", {"prefix": "@engine/"},
                                   timeout=10.0))["keys"]
            replies = await asyncio.gather(
                *(gcs.call("kv_get", {"key": k}, timeout=10.0)
                  for k in keys[:50]))
            for reply in replies:
                raw = reply.get("value")
                if not raw:
                    continue
                try:
                    engines.append(json.loads(raw))
                except ValueError:
                    continue
        except Exception:  # noqa: BLE001 — engine plane optional
            pass

        # RLHF plane: each pipeline driver's flight recorder pushes a
        # compact @rlhf/ snapshot (util/pipeline_recorder.py); stale
        # ones (finished/crashed driver) are skipped at diagnose time
        rlhf: List[Dict] = []
        try:
            keys = (await gcs.call("kv_keys", {"prefix": "@rlhf/"},
                                   timeout=10.0))["keys"]
            replies = await asyncio.gather(
                *(gcs.call("kv_get", {"key": k}, timeout=10.0)
                  for k in keys[:50]))
            for reply in replies:
                raw = reply.get("value")
                if not raw:
                    continue
                try:
                    rlhf.append(json.loads(raw))
                except ValueError:
                    continue
        except Exception:  # noqa: BLE001 — RLHF plane optional
            pass

        # train plane: each StepDriver's flight recorder pushes a compact
        # @train/ snapshot (util/train_recorder.py); the key SURVIVES the
        # driver (postmortem reads) so staleness is decided at diagnose
        # time, not collection time
        trains: List[Dict] = []
        try:
            keys = (await gcs.call("kv_keys", {"prefix": "@train/"},
                                   timeout=10.0))["keys"]
            replies = await asyncio.gather(
                *(gcs.call("kv_get", {"key": k}, timeout=10.0)
                  for k in keys[:50]))
            for reply in replies:
                raw = reply.get("value")
                if not raw:
                    continue
                try:
                    trains.append(json.loads(raw))
                except ValueError:
                    continue
        except Exception:  # noqa: BLE001 — train plane optional
            pass

        # serve plane: the controller pushes a compact status snapshot to
        # the KV every reconcile tick (serve/controller.py) — readable
        # here without attaching a driver
        serve_status = None
        try:
            raw = (await gcs.call("kv_get", {"key": "@serve/status"},
                                  timeout=10.0)).get("value")
            if raw:
                serve_status = json.loads(raw)
        except Exception:  # noqa: BLE001 — serve plane optional
            pass

        return {"t": time.time(), "gcs_address": gcs_address,
                "window_s": window_s, "nodes": probed, "actors": actors,
                "failures": failures, "oom_kills": ooms,
                "ledgers": ledgers, "serve": serve_status,
                "engines": engines, "rlhf": rlhf, "trains": trains,
                "sched_balance": sched_balance}
    finally:
        try:
            await gcs.close()
        except Exception:  # noqa: BLE001
            pass


def collect(gcs_address: str, window_s: float = 600.0,
            limit: int = 1000) -> Dict[str, Any]:
    """Gather the health report, or raise ConnectionError when the GCS is
    unreachable."""
    return asyncio.run(_collect_async(gcs_address, window_s, limit))


def _recent(events: List[Dict], window_s: float,
            now: Optional[float] = None) -> List[Dict]:
    now = time.time() if now is None else now
    return [e for e in events or ()
            if now - e.get("last_t", e.get("t", 0.0)) <= window_s]


def diagnose(report: Dict[str, Any],
             queue_warn: int = 100,
             queue_wait_warn_s: float = 10.0,
             serve_p99_warn_s: float = 5.0,
             imbalance_warn: float = 0.5,
             tick_gap_warn_s: float = 0.5,
             slo_warn: float = 0.9,
             bubble_warn: float = 0.75,
             launch_gap_warn_s: float = 0.25,
             data_wait_warn: float = 0.25) -> List[Tuple[str, str]]:
    """Turn the raw report into ranked ``(level, message)`` findings.
    Any CRITICAL finding makes the cluster unhealthy (exit 1)."""
    findings: List[Tuple[str, str]] = []
    window_s = report.get("window_s", 600.0)

    # -- liveness ------------------------------------------------------------
    now = time.time()
    nodes = report.get("nodes", [])
    dead = [n for n in nodes if not n.get("alive", True)]
    for n in dead:
        # dead rows persist forever in the GCS node table — window them
        # like actor deaths (a drain from hours ago must not fail today's
        # CI gate), and grade a deliberate drain as a warning, not a page
        died_at = n.get("death_t")
        if died_at is not None and now - died_at > window_s:
            continue
        reason = n.get("death_reason") or ""
        level = WARN if "drain" in reason else CRITICAL
        findings.append((level, f"node {n['node_id'][:8]} is DEAD"
                                + (f" ({reason})" if reason else "")))
    for n in nodes:
        if n.get("alive", True) and n.get("unreachable"):
            findings.append((CRITICAL,
                             f"node {n['node_id'][:8]} is marked alive but "
                             f"unreachable: {n['unreachable']}"))
    if not nodes:
        findings.append((CRITICAL, "no nodes registered with the GCS"))

    # -- actors --------------------------------------------------------------
    for a in report.get("actors", []):
        if a.get("state") != "DEAD":
            continue
        cause = a.get("death_cause") or {}
        cat = cause.get("category", "unknown")
        if cat == "cancelled":
            continue  # deliberate kill() — not a health problem
        # recency window: the actor table keeps DEAD rows for the cluster's
        # lifetime — a death from hours ago must not fail today's CI gate
        # (causes without a stamp are treated as recent, conservatively)
        died_at = cause.get("t")
        if died_at is not None and now - died_at > window_s:
            continue
        level = (CRITICAL if cat in _CRITICAL_CATEGORIES else WARN)
        findings.append((
            level,
            f"actor {str(a.get('actor_id'))[:8]} "
            f"({a.get('class_name')}) died: "
            f"{a.get('death_reason') or cat} "
            f"[category={cat}, restarts={a.get('num_restarts', 0)}]"))

    # -- failure feed, ranked by category ------------------------------------
    # chaos-injected events (util/chaos.py stamps origin="chaos") count
    # separately so a torture run's findings say which failures were
    # deliberate and which the cluster produced on its own
    recent = _recent(report.get("failures"), window_s)
    by_cat: Dict[str, int] = {}
    injected: Dict[str, int] = {}
    for e in recent:
        cat = e.get("category", "unknown")
        n = e.get("count", 1)
        by_cat[cat] = by_cat.get(cat, 0) + n
        if e.get("origin") == "chaos":
            injected[cat] = injected.get(cat, 0) + n
    for cat, count in sorted(by_cat.items(), key=lambda kv: -kv[1]):
        if cat == "cancelled":
            continue
        level = CRITICAL if cat in _CRITICAL_CATEGORIES else WARN
        chaos_note = (f", {injected[cat]} chaos-injected"
                      if injected.get(cat) else "")
        findings.append((level,
                         f"{count} recent failure(s) of category {cat} "
                         f"(last {int(window_s)}s{chaos_note}; see "
                         f"`rt errors --category {cat}`)"))

    # -- OOM post-mortems (memory plane) -------------------------------------
    for ev in _recent(report.get("oom_kills"), window_s):
        v = ev.get("victim", {})
        findings.append((
            CRITICAL,
            f"OOM kill on node {str(ev.get('node_id'))[:8]}: "
            f"{v.get('role', 'worker')} {str(v.get('worker_id'))[:8]} "
            f"running {v.get('task') or v.get('actor_id') or '(idle)'} "
            f"(replay: `rt memory --oom`)"))

    # -- scheduler / spill pressure ------------------------------------------
    for n in nodes:
        if not n.get("alive", True):
            continue
        depth = n.get("queue_depth", 0)
        if depth > queue_warn:
            findings.append((WARN,
                             f"node {n['node_id'][:8]} raylet queue depth "
                             f"{depth} (> {queue_warn}; tasks are waiting "
                             f"on resources)"))
        # per-class starvation: sustained queue-wait p99 (or an oldest
        # waiter aging past the threshold) names the starving class —
        # aggregate depth alone can't tell a busy class from a starved one
        for c in (n.get("sched") or {}).get("classes") or ():
            p99 = c.get("wait_p99_s") or 0.0
            oldest = c.get("oldest_wait_s") or 0.0
            worst = max(p99, oldest)
            if worst > queue_wait_warn_s:
                measure = ("queue-wait p99" if p99 >= oldest
                           else "oldest waiter")
                findings.append((WARN,
                                 f"node {n['node_id'][:8]} scheduling "
                                 f"class {str(c.get('class'))!r} is "
                                 f"starving: {measure} {worst:.1f}s "
                                 f"(> {queue_wait_warn_s:.0f}s, "
                                 f"{c.get('depth', 0)} queued — see "
                                 f"per-class depth in `rt status`)"))
        store = (n.get("memory") or {}).get("store") or {}
        cap = store.get("capacity_bytes") or 0
        in_mem = store.get("in_mem_bytes") or 0
        if cap and in_mem / cap > 0.9:
            findings.append((WARN,
                             f"node {n['node_id'][:8]} object store at "
                             f"{100 * in_mem / cap:.0f}% of capacity "
                             f"(spill imminent)"))
        if store.get("spilled_bytes"):
            findings.append((WARN,
                             f"node {n['node_id'][:8]} holds "
                             f"{store.get('spilled_count', 0)} spilled "
                             f"object(s) on disk "
                             f"({store['spilled_bytes']} bytes) — gets pay "
                             f"restore IO"))

    # -- cross-node balance (placement receipts plane) -----------------------
    # SUSTAINED imbalance only: one skewed tick is normal scheduling churn,
    # three consecutive ticks above the threshold names a hot node the
    # spillback path isn't draining (see `rt sched balance`)
    balance = report.get("sched_balance") or {}
    hist = balance.get("history") or []
    recent_cov = [h.get("cov", 0.0) for h in hist[-3:]]
    if (len(balance.get("nodes") or ()) >= 2 and len(recent_cov) >= 3
            and all(c > imbalance_warn for c in recent_cov)):
        hot = max(balance["nodes"], key=lambda r: r.get("load", 0))
        findings.append((WARN,
                         f"cross-node load imbalance sustained: CoV "
                         f"{balance.get('cov', recent_cov[-1]):.2f} over "
                         f"{len(recent_cov)} ticks (> {imbalance_warn:.2f}"
                         f"); hot node {str(hot.get('node_id'))[:8]} holds "
                         f"{hot.get('load', 0)} queued+running task(s) — "
                         f"see `rt sched balance`"))

    # -- serve plane (controller status snapshot) ----------------------------
    serve = report.get("serve") or {}
    # stale snapshots describe a controller that's gone — skip rather
    # than grade yesterday's numbers
    if serve and now - serve.get("t", 0.0) <= 30.0:
        for d in serve.get("deployments") or ():
            name = f"{d.get('app')}/{d.get('name')}"
            replicas, target = d.get("replicas", 0), d.get("target", 0)
            if replicas < target:
                findings.append((WARN,
                                 f"serve deployment {name} has "
                                 f"{replicas}/{target} replicas "
                                 f"({d.get('starting', 0)} starting — "
                                 f"unhealthy or missing; see "
                                 f"`rt serve status`)"))
            p99 = d.get("p99_s") or 0.0
            if p99 > serve_p99_warn_s and (d.get("qps") or 0) > 0:
                findings.append((WARN,
                                 f"serve deployment {name} request p99 "
                                 f"{p99:.2f}s (> {serve_p99_warn_s:.1f}s "
                                 f"at {d.get('qps')} qps — sustained "
                                 f"latency degradation)"))

    # -- engine flight recorder (@engine/ snapshots) -------------------------
    # SUSTAINED starvation only: one wide decode tick-gap is a normal
    # admission prefill; the last three gaps all above the threshold means
    # decode is being starved tick after tick. SLO findings need nonzero
    # load (completed requests in the rolling window) — an idle engine
    # attains nothing and that's fine. Stale snapshots (dead pusher)
    # are skipped, like the serve findings.
    for snap in report.get("engines") or ():
        if now - snap.get("t", 0.0) > 30.0:
            continue
        s = snap.get("summary") or {}
        label = (f"{str(snap.get('node', '?'))[:12]}:"
                 f"{snap.get('name', 'engine')}")
        gaps = (s.get("gap_recent") or [])[-3:]
        if len(gaps) >= 3 and all(g > tick_gap_warn_s for g in gaps):
            findings.append((WARN,
                             f"engine {label} decode tick-gap sustained at "
                             f"{max(gaps):.3f}s (> {tick_gap_warn_s:.3f}s "
                             f"over {len(gaps)} launches — prefill or swap "
                             f"work is starving decode; see `rt engine "
                             f"ticks`)"))
        if (s.get("window_completed") or 0) > 0:
            for slo in ("ttft", "tpot"):
                att = s.get(f"{slo}_attainment")
                if att is not None and att < slo_warn:
                    target = s.get(f"{slo}_slo_s", 0.0)
                    findings.append((WARN,
                                     f"engine {label} {slo.upper()} SLO "
                                     f"attainment {att:.2f} (< "
                                     f"{slo_warn:.2f} against a "
                                     f"{target * 1e3:.0f}ms target over "
                                     f"{s['window_completed']} completed "
                                     f"request(s); see `rt engine stats`)"))

    # -- RLHF flight recorder (@rlhf/ snapshots) -----------------------------
    # SUSTAINED bubble only: one bubbly iteration is warm-up noise; the
    # last three per-iteration bubble fractions all above the threshold
    # means the dataflow is running phase-serialized waste iteration
    # after iteration. Stale snapshots (finished driver) are skipped.
    for snap in report.get("rlhf") or ():
        if now - snap.get("t", 0.0) > 30.0:
            continue
        s = snap.get("summary") or {}
        label = (f"{str(snap.get('node', '?'))[:12]}:"
                 f"{snap.get('name', 'rlhf')}")
        recent_b = (s.get("bubble_recent") or [])[-3:]
        if len(recent_b) >= 3 and all(b > bubble_warn for b in recent_b):
            idle = s.get("role_idle_frac") or {}
            worst = max(idle, key=idle.get) if idle else "?"
            findings.append((WARN,
                             f"rlhf pipeline {label} bubble fraction "
                             f"sustained at {max(recent_b):.2f} (> "
                             f"{bubble_warn:.2f} over {len(recent_b)} "
                             f"iterations — roles idle while others "
                             f"work; idlest role: {worst}; see "
                             f"`rt rlhf stats`)"))
        if (s.get("interrupted_total") or 0) > 0 \
                and s.get("interrupted_last") \
                and now - s["interrupted_last"].get("t", 0.0) <= window_s \
                and not s.get("restart_gaps_s"):
            # an interrupt with NO later successful iteration = the
            # pipeline died mid-phase and never recovered
            intr = s["interrupted_last"]
            findings.append((WARN,
                             f"rlhf pipeline {label} interrupted in "
                             f"phase {intr.get('phase')!r} with no "
                             f"completed iteration since (see `rt rlhf "
                             f"stats`)"))

    # -- train flight recorder (@train/ snapshots) ---------------------------
    # SUSTAINED signals only, same discipline as the engine findings: one
    # wide launch gap is a checkpoint fence; the last three gaps all above
    # the threshold means the devices idle launch after launch with a
    # stacked batch in hand. data_wait grading needs a nonzero window —
    # an idle driver (no launches in the ring) trains nothing and that's
    # fine. Stale snapshots are skipped, NOT failed: the @train/ key
    # deliberately survives the driver for postmortem reads.
    for snap in report.get("trains") or ():
        if now - snap.get("t", 0.0) > 30.0:
            continue
        s = snap.get("summary") or {}
        if not (s.get("window_launches") or 0):
            continue  # idle driver — nothing to grade
        label = (f"{str(snap.get('node', '?'))[:12]}:"
                 f"{snap.get('name', 'train')}")
        lgaps = (s.get("gap_recent") or [])[-3:]
        if len(lgaps) >= 3 and all(g > launch_gap_warn_s for g in lgaps):
            findings.append((WARN,
                             f"train driver {label} launch-gap sustained "
                             f"at {max(lgaps):.3f}s (> "
                             f"{launch_gap_warn_s:.3f}s over {len(lgaps)} "
                             f"launches — devices idle between launches "
                             f"with a stacked batch available; see `rt "
                             f"train stats`)"))
        dw = s.get("data_wait_frac")
        if dw is not None and dw > data_wait_warn:
            wf = (s.get("waterfall") or {}).get("mfu_cost") or {}
            cost = wf.get("data_wait")
            cost_note = (f", costing {cost:.3f} MFU"
                         if cost is not None else "")
            findings.append((WARN,
                             f"train driver {label} data-starved: "
                             f"data_wait is {dw:.0%} of the window wall "
                             f"(> {data_wait_warn:.0%}{cost_note} — the "
                             f"loader, not the devices, bounds "
                             f"throughput; see `rt train stats`)"))

    # -- leak suspects (memory plane) ----------------------------------------
    try:
        from ray_tpu.util.memory import (_merge_owner_info,
                                         _suspects_from_ledgers)

        owner_info = _merge_owner_info(report.get("ledgers") or [])
        suspects = _suspects_from_ledgers(owner_info, None)
        if suspects:
            top = suspects[0]
            findings.append((WARN,
                             f"{len(suspects)} leak suspect(s) — oldest-"
                             f"held driver-local refs (largest: "
                             f"{top.get('size', 0)} bytes, see "
                             f"`rt memory`)"))
    except Exception:  # noqa: BLE001 — ledger plane optional
        pass

    if not findings:
        findings.append((OK, "no dead nodes/actors, no recent failures, "
                             "no memory pressure"))
    order = {CRITICAL: 0, WARN: 1, OK: 2}
    findings.sort(key=lambda f: order.get(f[0], 3))
    return findings


def exit_code(findings: List[Tuple[str, str]]) -> int:
    return 1 if any(level == CRITICAL for level, _ in findings) else 0


def format_report(report: Dict[str, Any],
                  findings: List[Tuple[str, str]]) -> str:
    nodes = report.get("nodes", [])
    actors = report.get("actors", [])
    alive_n = sum(1 for n in nodes if n.get("alive", True))
    alive_a = sum(1 for a in actors if a.get("state") == "ALIVE")
    recent = _recent(report.get("failures"), report.get("window_s", 600.0))
    lines = [
        f"=== rt doctor @ {time.strftime('%Y-%m-%d %H:%M:%S')} "
        f"(gcs {report.get('gcs_address')}) ===",
        f"nodes:  {alive_n}/{len(nodes)} alive   "
        f"actors: {alive_a}/{len(actors)} alive   "
        f"recent failures: {sum(e.get('count', 1) for e in recent)} "
        f"(last {int(report.get('window_s', 600))}s)",
        "",
    ]
    marks = {CRITICAL: "[CRIT]", WARN: "[warn]", OK: "[ ok ]"}
    for level, msg in findings:
        lines.append(f"{marks.get(level, '[ ?? ]')} {msg}")
    workers = sum((n.get("stats") or {}).get("workers", 0) for n in nodes)
    queued = sum((n.get("stats") or {}).get("queued", 0) for n in nodes)
    lines.append("")
    lines.append(f"workers: {workers} live   queued tasks: {queued}")
    verdict = ("UNHEALTHY" if exit_code(findings) else "healthy")
    lines.append(f"verdict: {verdict}")
    return "\n".join(lines)


def run(gcs_address: str, window_s: float = 600.0, queue_warn: int = 100,
        queue_wait_warn_s: float = 10.0, serve_p99_warn_s: float = 5.0,
        imbalance_warn: float = 0.5, tick_gap_warn_s: float = 0.5,
        slo_warn: float = 0.9, bubble_warn: float = 0.75,
        launch_gap_warn_s: float = 0.25, data_wait_warn: float = 0.25,
        as_json: bool = False
        ) -> Tuple[str, int]:
    """Collect + diagnose + render; returns (text, exit_code). Exit 2 when
    the GCS itself is unreachable."""
    try:
        report = collect(gcs_address, window_s=window_s)
    except Exception as e:  # noqa: BLE001 — the cluster is the patient
        return (f"rt doctor: cannot reach GCS at {gcs_address}: "
                f"{type(e).__name__}: {e}", 2)
    findings = diagnose(report, queue_warn=queue_warn,
                        queue_wait_warn_s=queue_wait_warn_s,
                        serve_p99_warn_s=serve_p99_warn_s,
                        imbalance_warn=imbalance_warn,
                        tick_gap_warn_s=tick_gap_warn_s,
                        slo_warn=slo_warn, bubble_warn=bubble_warn,
                        launch_gap_warn_s=launch_gap_warn_s,
                        data_wait_warn=data_wait_warn)
    if as_json:
        rc = exit_code(findings)
        payload = dict(report,
                       findings=[{"level": lv, "message": m}
                                 for lv, m in findings],
                       healthy=rc == 0, exit_code=rc)
        return json.dumps(payload, indent=2, default=str), rc
    return format_report(report, findings), exit_code(findings)
