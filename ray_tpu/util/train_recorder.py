"""Training flight recorder: per-launch phase attribution,
data-starvation accounting, and the MFU-gap waterfall for ``StepDriver``.

TRAIN_r09 proved the fused-K fast path holds 1.06× through/raw — one
end-to-end number with no attribution of where the remaining MFU gap
lives. This module is the training plane's flight recorder, in the
PR 18/19 shape: the STEP-DRIVER THREAD stamps one bounded record per
fused-K launch, a watcher thread turns the launch's output buffers into
an async device-done stamp, and the shared drain substrate
(``util/recorder_core.py``) ships ``@train/`` KV snapshots, ``rt_train_*``
series and timeline launch lanes off the step path.

What one LAUNCH record holds — a partition of the launch's wall
(first batch fetch → device done) into the phases the loop actually runs:

  data_wait        host wall blocked in ``next(it)`` + the K-batch
                   ``np.stack`` (the loader's share of the gap)
  h2d              batch placement onto the plan's NamedShardings
  dispatch         host wall inside the compiled call (enqueue only —
                   the per-launch cost fused-K amortizes)
  device_compute   dispatch-return → output-buffers-ready, measured by
                   an ASYNC done-hook (a watcher thread blocks on the
                   launch's metrics leaves; never ``block_until_ready``
                   on the step path — the PR 19 lesson that unforced
                   dispatch books real compute as orchestration tax,
                   inverted)
  host_tax         ``on_launch`` callback wall merged in late (report
                   drain handoff + checkpoint fence)
  compile          a first call's trace+compile (booked instead of
                   dispatch, step-profiler convention)

plus K, tokens, the [K, B, S] batch shape, analytic FLOPs from
``util/flops.py``, and the LAUNCH-GAP: launch N's dispatch start minus
launch N−1's device-done while a stacked batch was already available —
the dispatch-starvation analogue of the engine recorder's decode
tick-gap. When the loader was genuinely dry (the batch became ready
only after the previous launch finished) the gap is NOT stamped and
``dry_resets`` counts the reset, so starvation is never blamed on the
devices.

Joining launches to analytic FLOPs yields the marginal-MFU series and
the MFU-GAP WATERFALL at summary time: ``raw_mfu`` (FLOPs over
device-busy seconds — what the chips sustain while actually running)
down to ``achieved_mfu`` (FLOPs over the window's wall), the difference
attributed bucket by bucket to data_wait / launch_gap / host_tax /
compile (scaled onto the measured lost wall, with an ``uncovered``
residual — the waterfall never invents more loss than the clock saw).
``window_summary(t0, t1)`` carves bench legs out of one run.

Discipline (the PR 15 ``@memkv/`` lesson): the step path ONLY appends
to bounded deques under a microsecond lock and enqueues the done-hook;
metrics, KV snapshots and timeline events all happen on the drain
thread. The recorder times itself; ``summary()`` reports overhead as a
fraction of recorded launch wall (the bench gate holds it ≤ 2%).

Disable with ``RT_TRAIN_RECORDER=0`` — every hook then costs one
predicate check per launch.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.util.recorder_core import (RecorderCore, RecorderRegistry,
                                        pct as _pct)

_ENABLED_DEFAULT = os.environ.get("RT_TRAIN_RECORDER", "1") \
    not in ("", "0", "false")
_CAP = int(os.environ.get("RT_TRAIN_RECORDER_CAP", "2048"))
_DRAIN_S = float(os.environ.get("RT_TRAIN_DRAIN_S", "2.0"))
_KV_PREFIX = "@train/"

#: canonical launch-phase vocabulary, in launch order (the timeline
#: launch lane and ``rt train stats`` render phases in this order)
LAUNCH_PHASES = ("data_wait", "h2d", "dispatch", "device_compute",
                 "host_tax", "compile")

#: the waterfall's loss buckets, in render order (device_compute and
#: dispatch are the device-busy numerator, not losses)
WATERFALL_BUCKETS = ("data_wait", "launch_gap", "host_tax", "compile")

_REGISTRY = RecorderRegistry()


def live_recorders() -> List["TrainRecorder"]:
    """Every recorder constructed in this process and not yet closed."""
    return _REGISTRY.live()


def _profiler_launch_join() -> Optional[Dict[str, int]]:
    """The step-profiler's registered launch source: launch/step counts
    from THIS instrumentation point, so ``rt profile``'s st/ln column
    and ``rt train stats`` can never drift apart. Returns None when no
    fused launch has been recorded (the profiler falls back to its own
    records)."""
    launches = steps = 0
    for r in live_recorders():
        with r._lock:
            launches += r._launches_total
            steps += r._steps_total
    if launches == 0:
        return None
    return {"launches": launches, "steps": steps}


class TrainRecorder(RecorderCore):
    """Bounded flight recorder for one ``StepDriver``.

    The STEP-DRIVER THREAD is the only caller of ``record_launch`` /
    ``add_host_tax`` / ``watch_outputs``; ``finalize_launch`` fires from
    the watcher thread (or directly from tests feeding synthetic
    records). All shared state lives behind one lock held for O(1)
    appends — never across a device call, an RPC, or a metrics
    observation.
    """

    KV_PREFIX = _KV_PREFIX
    DRAIN_S = _DRAIN_S
    THREAD_NAME = "rt-train-rec"
    REGISTRY = _REGISTRY

    def __init__(self, name: str = "train", *, cap: int = _CAP,
                 n_devices: int = 0, peak_flops: Optional[float] = None,
                 enabled: Optional[bool] = None):
        self.name = name or "train"
        self.enabled = _ENABLED_DEFAULT if enabled is None else bool(enabled)
        self.n_devices = int(n_devices)  # 0 = resolve from jax lazily
        self.peak_flops = peak_flops     # None = platform peak, lazily
        cap = max(64, int(cap))
        self._init_core(self.name)
        self._launches: "deque[Dict[str, Any]]" = deque(maxlen=cap)  # rt: guarded-by(_lock)
        self._open: Dict[int, Dict[str, Any]] = {}  # rt: guarded-by(_lock)
        self._seq = 0  # rt: guarded-by(_lock)
        self._launches_total = 0  # rt: guarded-by(_lock)
        self._steps_total = 0  # rt: guarded-by(_lock)
        self._prev_done_t: Optional[float] = None  # rt: guarded-by(_lock)
        self._dry_resets = 0  # rt: guarded-by(_lock)
        self._compiles = 0  # rt: guarded-by(_lock)
        self._peak_total_cached: Optional[float] = None
        # done-hook plumbing: the step path enqueues, one watcher thread
        # blocks on output buffers FIFO (launch order), so finalize order
        # is monotone and _prev_done_t never runs backwards
        self._watch_q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._watcher: Optional[threading.Thread] = None  # rt: guarded-by(_lock)
        # drain-side watermarks (drain thread only)
        self._metrics_wm = 0
        self._event_wm = 0
        try:
            from ray_tpu.util import step_profiler as SP

            SP.register_launch_source("train", _profiler_launch_join)
        except Exception:  # noqa: BLE001 — profiler plane optional
            pass

    # -- step path (driver thread) -----------------------------------------

    def record_launch(self, *, t_start: float, data_wait_s: float,
                      h2d_s: float, dispatch_s: float,
                      compile_s: float = 0.0,
                      data_ready_t: Optional[float] = None,
                      t_dispatch_end: Optional[float] = None,
                      k: int = 1, tokens: int = 0,
                      batch_shape: Tuple[int, ...] = (),
                      flops: float = 0.0) -> int:
        """One fused-K launch, stamped right after the compiled call
        returned (the device is still computing — ``watch_outputs``
        finishes the record). Appends to a bounded deque, decides the
        launch-gap, nothing else. Returns the record's seq for the
        done-hook and the host-tax merge.

        ``t_dispatch_end`` is the epoch stamp of the dispatch call's
        RETURN — pass it when you have it (the driver does): deriving it
        from the phase sums undercounts untimed loop wall and that error
        lands in device_compute."""
        if not self.enabled:
            return 0
        t_in = time.perf_counter()
        t_dispatch_start = t_start + data_wait_s + h2d_s
        if t_dispatch_end is None:
            t_dispatch_end = t_dispatch_start + dispatch_s \
                + max(0.0, compile_s)
        rec = {"t": t_start, "k": int(k), "tokens": int(tokens),
               "batch_shape": list(batch_shape),
               "flops": float(flops),
               "phases": {"data_wait": max(0.0, data_wait_s),
                          "h2d": max(0.0, h2d_s),
                          "dispatch": max(0.0, dispatch_s),
                          "host_tax": 0.0,
                          "compile": max(0.0, compile_s)},
               "t_dispatch_end": t_dispatch_end}
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._launches_total += 1
            self._steps_total += max(1, int(k))
            if compile_s > 0.0:
                self._compiles += 1
            prev_done = self._prev_done_t
            if prev_done is not None:
                if data_ready_t is not None and data_ready_t > prev_done:
                    # loader genuinely dry: the stacked batch only became
                    # ready after the devices went idle — that wall is
                    # data_wait's to answer for, not a launch gap
                    self._dry_resets += 1
                else:
                    rec["gap_s"] = max(0.0, t_dispatch_start - prev_done)
            self._launches.append(rec)
            self._open[rec["seq"]] = rec
            while len(self._open) > self._launches.maxlen:
                self._open.pop(next(iter(self._open)))  # leak backstop
            self._overhead_s += time.perf_counter() - t_in
        return rec["seq"]

    def watch_outputs(self, seq: int, outputs: Any) -> None:
        """The async done-hook: hand the launch's OUTPUT buffers (the
        metrics tree — never the donated params) to the watcher thread,
        which blocks on them off the step path and stamps device-done.
        The step path pays one queue put."""
        if not self.enabled or seq <= 0:
            return
        t_in = time.perf_counter()
        self._watch_q.put((seq, outputs))
        with self._lock:
            self._overhead_s += time.perf_counter() - t_in
        self._ensure_watcher()
        self._ensure_drainer()

    def add_host_tax(self, seq: int, host_tax_s: float) -> None:
        """Merge the ``on_launch`` callback wall (report drain handoff +
        checkpoint fence) into an already-stamped record — the callback
        runs after the dispatch returned, so the tax arrives late."""
        if not self.enabled or seq <= 0:
            return
        t_in = time.perf_counter()
        with self._lock:
            rec = self._open.get(seq)
            if rec is None:
                for r in reversed(self._launches):
                    if r["seq"] == seq:
                        rec = r
                        break
            if rec is not None:
                rec["phases"]["host_tax"] += max(0.0, host_tax_s)
            self._overhead_s += time.perf_counter() - t_in

    def finalize_launch(self, seq: int, t_done: float) -> None:
        """Device-done: close the record — compute ``device_compute``
        (done minus dispatch-return) and the launch wall. Fired by the
        watcher thread; synthetic tests call it directly."""
        if not self.enabled:
            return
        t_in = time.perf_counter()
        with self._lock:
            rec = self._open.pop(seq, None)
            if rec is None:
                self._overhead_s += time.perf_counter() - t_in
                return
            rec["t_done"] = t_done
            rec["phases"]["device_compute"] = \
                max(0.0, t_done - rec["t_dispatch_end"])
            rec["wall_s"] = max(0.0, t_done - rec["t"])
            self._wall_total_s += rec["wall_s"]
            if self._prev_done_t is None or t_done > self._prev_done_t:
                self._prev_done_t = t_done
            self._overhead_s += time.perf_counter() - t_in

    def loader_dry(self) -> None:
        """Explicit dry-reset hook for loops that can see the iterator
        exhaust (epoch boundary): the next launch must not stamp a gap
        against a device that idled waiting for data."""
        if not self.enabled:
            return
        with self._lock:
            self._prev_done_t = None
            self._dry_resets += 1

    # -- watcher thread ----------------------------------------------------

    def _ensure_watcher(self) -> None:
        if self._watcher is not None and self._watcher.is_alive():
            return
        with self._lock:
            if self._closed or (self._watcher is not None
                                and self._watcher.is_alive()):
                return
            self._watcher = threading.Thread(
                target=self._watch_loop, daemon=True,
                name=f"rt-train-watch:{self.name}")
            self._watcher.start()

    def _watch_loop(self) -> None:
        while True:
            item = self._watch_q.get()
            if item is None:
                return
            seq, outputs = item
            try:
                self._block_on(outputs)
            except Exception:  # noqa: BLE001 — a deleted/odd buffer still
                pass           # gets a done stamp (device_compute ~ 0)
            self.finalize_launch(seq, time.time())

    @staticmethod
    def _block_on(outputs: Any) -> None:
        try:
            import jax

            jax.block_until_ready(outputs)
            return
        except ImportError:
            pass
        # duck-typed fallback: anything exposing block_until_ready
        stack = [outputs]
        while stack:
            x = stack.pop()
            if isinstance(x, dict):
                stack.extend(x.values())
            elif isinstance(x, (list, tuple)):
                stack.extend(x)
            elif hasattr(x, "block_until_ready"):
                x.block_until_ready()

    # -- derived accounting ------------------------------------------------

    def launches(self, limit: int = 0) -> List[Dict[str, Any]]:
        with self._lock:
            out = list(self._launches)
        return out[-limit:] if limit else out

    def _peak_total(self) -> float:
        """Aggregate peak FLOP/s across the devices this driver feeds."""
        if self._peak_total_cached is None:
            ndev = self.n_devices
            peak = self.peak_flops
            if peak is None or ndev <= 0:
                try:
                    import jax

                    from ray_tpu.util import flops as F

                    if peak is None:
                        peak = F.peak_flops_per_chip(jax.default_backend())
                    if ndev <= 0:
                        ndev = jax.local_device_count()
                except Exception:  # noqa: BLE001 — no jax here
                    peak = peak if peak is not None else 1e12
                    ndev = max(1, ndev)
            self._peak_total_cached = float(peak) * max(1, ndev)
        return self._peak_total_cached

    def summary(self) -> Dict[str, Any]:
        """The MFU-gap picture: what ``rt train stats``, the doctor
        findings, the gauges and the bench legs read."""
        with self._lock:
            recs = [r for r in self._launches if "t_done" in r]
            base = {"launches_total": self._launches_total,
                    "steps_total": self._steps_total,
                    "compiles": self._compiles,
                    "dry_resets": self._dry_resets,
                    "in_flight": len(self._open)}
        out = self._aggregate(recs)
        out.update(base)
        out["name"] = self.name
        self._overhead_fields(out)
        return out

    def window_summary(self, t0: float, t1: float) -> Dict[str, Any]:
        """Same aggregates restricted to launches that STARTED in
        [t0, t1) — the bench legs carve steady / data-starved /
        checkpoint-heavy windows out of one run with this."""
        with self._lock:
            recs = [r for r in self._launches
                    if "t_done" in r and t0 <= r["t"] < t1]
        return self._aggregate(recs)

    def _aggregate(self, recs: List[Dict[str, Any]]) -> Dict[str, Any]:
        out: Dict[str, Any] = {"window_launches": len(recs)}
        if not recs:
            return out
        phase_totals = {p: 0.0 for p in LAUNCH_PHASES}
        wall_sum = 0.0
        gaps: List[float] = []
        flops_tot = 0.0
        tokens_tot = 0
        steps_tot = 0
        device_s = 0.0
        mfus: List[float] = []
        peak_total = self._peak_total()
        for r in recs:
            wall_sum += r["wall_s"]
            for p, v in r["phases"].items():
                if v > 0.0:
                    phase_totals[p] = phase_totals.get(p, 0.0) + v
            if "gap_s" in r:
                gaps.append(r["gap_s"])
            flops_tot += r["flops"]
            tokens_tot += r["tokens"]
            steps_tot += r["k"]
            device_s += r["phases"]["dispatch"] \
                + r["phases"]["device_compute"]
            if r["flops"] > 0 and r["wall_s"] > 0:
                mfus.append(r["flops"] / (r["wall_s"] * peak_total))
        gaps.sort()
        phase_sum = sum(phase_totals.values())
        span = max(r["t_done"] for r in recs) - min(r["t"] for r in recs)
        if span <= 0:
            span = wall_sum
        out.update({
            "launch_wall_s": round(wall_sum, 6),
            "span_s": round(span, 6),
            "steps": steps_tot,
            "tokens": tokens_tot,
            "tokens_per_s": round(tokens_tot / span, 1) if span > 0
            else 0.0,
            "phase_s": {p: round(v, 6) for p, v in phase_totals.items()
                        if v > 0.0},
            # the tentpole's honesty bar: the stamped phases must explain
            # ≥95% of the launch wall or the attribution is fiction
            "phase_sum_ratio": round(phase_sum / wall_sum, 4)
            if wall_sum > 0 else 0.0,
            "launch_gap_p50_s": round(_pct(gaps, 0.50), 6),
            "launch_gap_p99_s": round(_pct(gaps, 0.99), 6),
            "launch_gap_max_s": round(gaps[-1], 6) if gaps else 0.0,
            # the doctor's "sustained" signal: the last few gaps, newest
            # last (all above the warn threshold = sustained starvation)
            "gap_recent": [round(r["gap_s"], 6) for r in recs
                           if "gap_s" in r][-8:],
            "data_wait_frac": round(phase_totals["data_wait"] / span, 4)
            if span > 0 else 0.0,
            "device_s": round(device_s, 6),
        })
        if mfus:
            out["marginal_mfu"] = round(mfus[-1], 6)
            out["marginal_mfu_mean"] = round(sum(mfus) / len(mfus), 6)
            out["marginal_mfu_recent"] = [round(m, 6) for m in mfus[-8:]]
        if flops_tot > 0 and span > 0:
            raw_mfu = flops_tot / (device_s * peak_total) \
                if device_s > 0 else 0.0
            achieved_mfu = flops_tot / (span * peak_total)
            out["raw_mfu"] = round(raw_mfu, 6)
            out["achieved_mfu"] = round(achieved_mfu, 6)
            # clamped at 0: watcher-lag jitter can book achieved a hair
            # above raw on a sync backend, and a negative "gap" is
            # measurement noise, not headroom
            out["mfu_gap_frac"] = round(
                max(0.0, 1.0 - achieved_mfu / raw_mfu), 4) \
                if raw_mfu > 0 else 0.0
            # the waterfall: raw sustained -> achieved, lost wall
            # attributed to the host-side buckets. host_tax can overlap
            # device compute, so attributions are SCALED onto the
            # measured lost wall when they over-explain it; when they
            # under-explain, the residual is surfaced as "uncovered" —
            # never silently stretched
            lost_s = max(0.0, span - device_s)
            raw_buckets = {"data_wait": phase_totals["data_wait"],
                           "launch_gap": sum(gaps),
                           "host_tax": phase_totals["host_tax"],
                           "compile": phase_totals["compile"]}
            attr = sum(raw_buckets.values())
            scale = lost_s / attr if attr > lost_s and attr > 0 else 1.0
            buckets = {b: raw_buckets[b] * scale
                       for b in WATERFALL_BUCKETS}
            uncovered = max(0.0, lost_s - sum(buckets.values()))
            waterfall = {"raw_mfu": round(raw_mfu, 6),
                         "achieved_mfu": round(achieved_mfu, 6),
                         "lost_s": round(lost_s, 6),
                         "buckets_s": {b: round(v, 6)
                                       for b, v in buckets.items()},
                         "uncovered_s": round(uncovered, 6)}
            if span > 0:
                # exact decomposition: achieved = raw * device_s / span,
                # so each bucket's MFU cost is raw_mfu * bucket_s / span
                waterfall["mfu_cost"] = {
                    b: round(raw_mfu * v / span, 6)
                    for b, v in buckets.items()}
                waterfall["mfu_cost"]["uncovered"] = \
                    round(raw_mfu * uncovered / span, 6)
            out["waterfall"] = waterfall
        return out

    def snapshot(self, launches_limit: int = 64) -> Dict[str, Any]:
        """The ``@train/`` KV payload: summary + launch-record tail,
        compact enough to push every couple of seconds (< 64 KB)."""
        out = self._snapshot_header()
        out["summary"] = self.summary()
        out["launches"] = [self._compact_launch(r)
                           for r in self.launches(launches_limit)]
        return out

    @staticmethod
    def _compact_launch(r: Dict[str, Any]) -> Dict[str, Any]:
        out = {"seq": r["seq"], "t": round(r["t"], 4), "k": r["k"],
               "tokens": r["tokens"], "shape": r["batch_shape"],
               "phases_ms": {p: round(v * 1e3, 3)
                             for p, v in r["phases"].items() if v > 0.0},
               "done": "t_done" in r}
        if "wall_s" in r:
            out["wall_ms"] = round(r["wall_s"] * 1e3, 3)
        if "gap_s" in r:
            out["gap_ms"] = round(r["gap_s"] * 1e3, 3)
        return out

    # -- off-step drain (template in recorder_core; hooks below) -----------

    def _pending_since(self, wm_attr: str) -> List[Dict]:
        """Finalized records past the watermark (an open record drains
        after its done-hook fires — the watcher is FIFO, so seqs close
        in order and the watermark never strands one)."""
        with self._lock:
            wm = getattr(self, wm_attr)
            return [r for r in self._launches
                    if "t_done" in r and r.get("seq", 0) > wm]

    def _drain_metrics(self) -> int:
        try:
            from ray_tpu.util import metrics as M
        except Exception:  # noqa: BLE001
            return 0
        h = _metric_handles(M)
        tags = {"driver": self.name}
        new = self._pending_since("_metrics_wm")
        for r in new:
            for p, v in r["phases"].items():
                if v > 0.0:
                    h["phase"].observe(v, tags={"driver": self.name,
                                                "phase": p})
            if "gap_s" in r:
                h["gap"].observe(r["gap_s"], tags=tags)
            h["launches"].inc(1.0, tags=tags)
        if new:
            self._metrics_wm = new[-1]["seq"]
        summ = self.summary()
        if summ.get("window_launches"):
            if "marginal_mfu" in summ:
                h["mfu"].set(summ["marginal_mfu"], tags=tags)
            if "mfu_gap_frac" in summ:
                h["mfu_gap"].set(summ["mfu_gap_frac"], tags=tags)
            h["data_wait"].set(summ["data_wait_frac"], tags=tags)
            h["toks"].set(summ.get("tokens_per_s", 0.0), tags=tags)
            h["overhead"].set(summ["overhead_frac"], tags=tags)
        return len(new)

    def _build_events(self, node: str, pid: int):
        """Launch records as GCS task events — one Perfetto lane slice
        per fused launch; the advance closure runs only after a
        successful push."""
        events = []
        new = self._pending_since("_event_wm")
        for r in new[-256:]:
            events.append({
                "task_id": f"trainlaunch:{node}:{pid}:{self.name}:"
                           f"{r['seq']}",
                "name": f"launch:{self.name}", "state": "FINISHED",
                "node_id": node,
                "times": {"RUNNING": r["t"], "FINISHED": r["t_done"]},
                "train_launch": {**{k: v for k, v in r.items()
                                    if k != "t_dispatch_end"},
                                 "driver": self.name}})

        def advance() -> None:
            if new:
                self._event_wm = new[-1]["seq"]

        return events, advance

    def close(self) -> None:
        """Stop the watcher and drain threads after one final drain.
        Unlike the engine recorder, the ``@train/`` snapshot is NOT
        deleted: the postmortem (``rt train stats`` with no driver
        attach) is the whole point — the doctor's stale-skip handles
        the leftover key."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self.REGISTRY.unregister(self)
        try:
            self._watch_q.put(None)
        except Exception:  # noqa: BLE001
            pass
        try:
            self.drain_now()
        except Exception:  # noqa: BLE001
            pass


_metric_cache: Optional[Dict[str, Any]] = None
_PHASE_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                  0.25, 0.5, 1.0, 2.5, 5.0)
_GAP_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 0.5, 1.0)


def _metric_handles(M) -> Dict[str, Any]:
    """Lazily registered ``rt_train_*`` recorder series (drain thread
    only)."""
    global _metric_cache
    if _metric_cache is None:
        _metric_cache = {
            "phase": M.get_or_create(
                M.Histogram, "rt_train_launch_phase_seconds",
                "Per-launch StepDriver phase wall (data_wait / h2d / "
                "dispatch / device_compute / host_tax / compile)",
                boundaries=_PHASE_BUCKETS, tag_keys=("driver", "phase")),
            "gap": M.get_or_create(
                M.Histogram, "rt_train_launch_gap_seconds",
                "Wall between a launch's dispatch and the previous "
                "launch's device-done while a stacked batch was already "
                "available (devices idle, host's fault)",
                boundaries=_GAP_BUCKETS, tag_keys=("driver",)),
            "launches": M.get_or_create(
                M.Counter, "rt_train_launches_total",
                "Fused-K launches recorded by the train flight recorder",
                tag_keys=("driver",)),
            "mfu": M.get_or_create(
                M.Gauge, "rt_train_marginal_mfu",
                "Latest launch's analytic FLOPs / (launch wall x "
                "aggregate peak) — the per-launch MFU series",
                tag_keys=("driver",)),
            "mfu_gap": M.get_or_create(
                M.Gauge, "rt_train_mfu_gap_frac",
                "1 - achieved_mfu/raw_mfu over the rolling window (the "
                "waterfall's headline: wall the devices were not "
                "computing)",
                tag_keys=("driver",)),
            "data_wait": M.get_or_create(
                M.Gauge, "rt_train_data_wait_fraction",
                "Fraction of the rolling window's wall spent blocked on "
                "the loader (data_wait / span)",
                tag_keys=("driver",)),
            "toks": M.get_or_create(
                M.Gauge, "rt_train_tokens_per_s",
                "Trained tokens per second over the rolling window",
                tag_keys=("driver",)),
            "overhead": M.get_or_create(
                M.Gauge, "rt_train_recorder_overhead_ratio",
                "Recorder self-time as a fraction of recorded launch "
                "wall",
                tag_keys=("driver",)),
        }
    return _metric_cache
