"""Distributed tracing: trace-context propagation across task/actor calls.

Reference analog: ``ray/util/tracing/tracing_helper.py`` — OpenTelemetry
span injection around submit/execute with context carried in the task spec.
Redesign without the otel dependency: a (trace_id, span_id) pair rides the
task payload; every task/actor call executed while tracing is enabled
becomes a span whose parent is the calling task's span. Spans land in the
GCS task-event store (the same table ``ray_tpu.timeline()`` exports), so a
trace is a filterable view of the timeline: ``get_trace(trace_id)`` returns
the span tree.

Every traced task additionally carries a PER-PHASE latency breakdown
(reference: the task-event phase records behind Ray's State API,
``gcs_task_manager`` + ``task_events.proto``): the driver, raylet, and
executing worker each stamp the phases they own, and the union lands in the
span's GCS event as ``phases`` — a partition of the submit→reply interval:

  submit          driver-side residual: arg serialization + submit RPC + wire
  queue_wait      raylet queue time (enqueue → dispatch claim, including
                  dispatch-loop latency)
  spillback       present only when the task moved nodes: the ORIGIN
                  raylet's wait + routing overhead up to hand-off (the
                  executing node's queue_wait starts after the hop); the
                  span's ``spill_hops`` list names each from→to hop and
                  why the origin was rejected
  worker_acquire  worker checkout (``worker_source`` says spawn vs warm)
  transfer        push RPC + payload marshalling around the worker's span
  arg_fetch       dependency resolution + deserialization in the worker
  execute         the user function
  result_store    return serialization (+ plasma seal for large returns)
  driver_get      post-reply deserialization in the caller's ``get``

Phase stamping rides the span context: a task with no ``trace`` in its
payload pays exactly one predicate check per hop (the step-profiler
discipline). ``format_trace`` renders the span tree with phase tables and
names the critical path — the ``rt trace`` CLI prints it.

Usage::

    from ray_tpu.util import tracing
    tracing.enable()
    ref = my_task.remote(...)      # root span, fresh trace_id
    ...
    spans = tracing.get_trace(tracing.last_trace_id())
    print(tracing.format_trace(spans))
"""

from __future__ import annotations

import contextvars
import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

_enabled = os.environ.get("RT_TRACING", "") not in ("", "0", "false")
_current: "contextvars.ContextVar[Optional[Dict[str, str]]]" = \
    contextvars.ContextVar("rt_trace_ctx", default=None)
_last_trace_id: Optional[str] = None


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


def current_context() -> Optional[Dict[str, str]]:
    """The ambient span context ({trace_id, span_id}) or None."""
    return _current.get()


def context_for_submit() -> Optional[Dict[str, str]]:
    """Called by the core worker at submit time: the child span's wire
    context. A fresh trace starts when no span is ambient (driver root);
    in a worker WITHOUT an ambient span, no context is minted even if a
    previous traced task ran here — only explicit enable() or an inherited
    span starts spans."""
    global _last_trace_id
    parent = _current.get()
    if not _enabled and parent is None:
        return None
    span_id = uuid.uuid4().hex[:16]
    if parent is None:
        trace_id = uuid.uuid4().hex
        _last_trace_id = trace_id
        return {"trace_id": trace_id, "span_id": span_id,
                "parent_span_id": None}
    return {"trace_id": parent["trace_id"], "span_id": span_id,
            "parent_span_id": parent["span_id"]}


def activate(ctx: Optional[Dict[str, str]]):
    """Executor side: make the received context ambient for nested calls
    (grandchildren propagate through the ambient span, NOT a process flag —
    the worker returns to untraced once this task finishes). Returns a
    token for ``deactivate``."""
    if ctx is None:
        return None
    return _current.set({"trace_id": ctx["trace_id"],
                         "span_id": ctx["span_id"]})


def deactivate(token) -> None:
    if token is not None:
        _current.reset(token)


def last_trace_id() -> Optional[str]:
    """Trace id of the most recent root span started by this process."""
    return _last_trace_id


_submit_entry = threading.local()


def mark_submit_entry() -> None:
    """Called at the public submit entry (core/worker.py) so the ``submit``
    phase covers driver-side arg serialization too, not just the RPC.
    One predicate when tracing is off."""
    if _enabled or _current.get() is not None:
        _submit_entry.t = time.perf_counter()


def take_submit_entry() -> Optional[float]:
    """Consume the entry stamp (backend submit path); None when untraced."""
    t = getattr(_submit_entry, "t", None)
    _submit_entry.t = None
    return t


def get_trace(trace_id: str) -> List[Dict[str, Any]]:
    """All spans of one trace, parents before children where possible."""
    import ray_tpu

    backend = ray_tpu.global_worker()._require_backend()
    events = backend.io.run(
        backend._gcs.call("list_tasks",
                          {"limit": 10000, "serve": "include"}))
    spans = [e for e in events
             if (e.get("trace") or {}).get("trace_id") == trace_id]
    by_span = {(s["trace"] or {}).get("span_id"): s for s in spans}

    def depth(s, seen=()):
        parent = (s["trace"] or {}).get("parent_span_id")
        if parent is None or parent not in by_span or parent in seen:
            return 0
        return 1 + depth(by_span[parent],
                         seen + ((s["trace"] or {}).get("span_id"),))

    return sorted(spans, key=depth)


# ---------------------------------------------------------------------------
# Phase records
# ---------------------------------------------------------------------------

# Wall-clock partition of one task's submit→reply interval, in causal order
# (driver_get trails the reply). ``format_trace`` and the dashboard render
# phases in this order; unknown keys sort after.
PHASE_ORDER = ("submit", "queue_wait", "spillback", "worker_acquire",
               "transfer", "arg_fetch", "execute", "result_store",
               "driver_get")

# Serve request spans (serve/obs.py) carry their own phase vocabulary —
# ranked after the task partition, in causal order per hop (proxy:
# route→handle→respond/stream; handle: route→call; replica:
# queue_wait→execute, which reuses the task names above).
SERVE_PHASE_ORDER = ("proxy_route", "handle", "route", "call",
                     "call_stream", "respond", "stream")

# Engine flight-recorder spans (util/engine_recorder.py) — the request
# lifecycle inside ContinuousEngine, in causal order (queue-wait until a
# slot frees, KV restore of the cached prefix, prefill of the suffix,
# then the decode ticks until the last token). Tick records additionally
# use decode_step/token_delivery/swap_barrier.
ENGINE_PHASE_ORDER = ("queue_wait", "kv_restore", "prefill",
                      "decode_step", "decode", "token_delivery",
                      "swap_barrier")


def sorted_phases(phases: Dict[str, float]) -> List[Any]:
    """(name, seconds) pairs in canonical phase order."""
    _all = PHASE_ORDER + SERVE_PHASE_ORDER + tuple(
        p for p in ENGINE_PHASE_ORDER
        if p not in PHASE_ORDER + SERVE_PHASE_ORDER)
    rank = {p: i for i, p in enumerate(_all)}
    n = len(_all)
    return sorted(phases.items(), key=lambda kv: (rank.get(kv[0], n), kv[0]))


def span_tree(spans: List[Dict[str, Any]]) -> List[Any]:
    """Nest spans by parentage: [(span, [children...]), ...] roots first."""
    by_span: Dict[str, Any] = {}
    for s in spans:
        sid = (s.get("trace") or {}).get("span_id")
        if sid is not None:
            by_span[sid] = (s, [])
    roots: List[Any] = []
    for s in spans:
        ctx = s.get("trace") or {}
        sid, parent = ctx.get("span_id"), ctx.get("parent_span_id")
        node = by_span.get(sid) or (s, [])
        if parent is not None and parent in by_span and parent != sid:
            by_span[parent][1].append(node)
        else:
            roots.append(node)
    return roots


def _span_duration(span: Dict[str, Any]) -> float:
    phases = span.get("phases") or {}
    if phases:
        return sum(v for k, v in phases.items() if k != "driver_get")
    times = span.get("times") or {}
    start = times.get("PENDING") or times.get("RUNNING")
    end = times.get("FINISHED") or times.get("FAILED")
    if start is not None and end is not None:
        return max(0.0, end - start)
    return 0.0


def critical_path(spans: List[Dict[str, Any]]) -> List[Any]:
    """The root→leaf chain that dominates end-to-end latency, each hop
    tagged with its heaviest phase: [(span, phase_name, seconds), ...].
    At each level the child with the largest span duration wins (children
    of one parent overlap in wall time; the longest one gates the parent).
    """
    roots = span_tree(spans)
    if not roots:
        return []
    path: List[Any] = []
    node = max(roots, key=lambda n: _span_duration(n[0]))
    while node is not None:
        span, children = node
        phases = span.get("phases") or {}
        if phases:
            name, dur = max(phases.items(), key=lambda kv: kv[1])
        else:
            name, dur = "total", _span_duration(span)
        path.append((span, name, dur))
        node = max(children, key=lambda n: _span_duration(n[0])) \
            if children else None
    return path


def format_trace(spans: List[Dict[str, Any]]) -> str:
    """Human-readable span tree with per-phase tables and the named
    critical path — what ``rt trace`` prints."""
    if not spans:
        return "(no spans)"
    lines: List[str] = []

    def emit(node, indent: int) -> None:
        span, children = node
        dur = _span_duration(span)
        pad = "  " * indent
        lines.append(
            f"{pad}{'└─ ' if indent else ''}{span.get('name') or 'task'}  "
            f"[{span.get('state', '?')}]  {dur * 1e3:.1f} ms  "
            f"task_id={span.get('task_id', '')[:16]}")
        phases = span.get("phases") or {}
        if phases:
            total = sum(phases.values()) or 1.0
            for pname, secs in sorted_phases(phases):
                bar = "#" * max(1, int(20 * secs / total)) if secs > 0 else ""
                extra = ""
                if pname == "worker_acquire" and span.get("worker_source"):
                    extra = f" ({span['worker_source']})"
                elif pname == "spillback" and span.get("spill_hops"):
                    # the hop chain: from-node → to-node (why)
                    extra = " (" + " -> ".join(
                        f"{(h.get('from') or '?')[:8]}→"
                        f"{(h.get('to') or '?')[:8]} {h.get('reason', '')}"
                        for h in span["spill_hops"]) + ")"
                lines.append(f"{pad}     {pname:<15}{secs * 1e3:>10.2f} ms"
                             f"  {bar}{extra}")
        for child in sorted(children,
                            key=lambda n: -_span_duration(n[0])):
            emit(child, indent + 1)

    trace_id = (spans[0].get("trace") or {}).get("trace_id", "?")
    lines.append(f"trace {trace_id} — {len(spans)} span(s)")
    for root in span_tree(spans):
        emit(root, 0)
    cp = critical_path(spans)
    if cp:
        hops = " -> ".join(
            f"{s.get('name') or 'task'}:{phase} ({dur * 1e3:.1f} ms)"
            for s, phase, dur in cp)
        lines.append(f"critical path: {hops}")
    return "\n".join(lines)
