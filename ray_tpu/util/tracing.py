"""Distributed tracing: trace-context propagation across task/actor calls.

Reference analog: ``ray/util/tracing/tracing_helper.py`` — OpenTelemetry
span injection around submit/execute with context carried in the task spec.
Redesign without the otel dependency: a (trace_id, span_id) pair rides the
task payload; every task/actor call executed while tracing is enabled
becomes a span whose parent is the calling task's span. Spans land in the
GCS task-event store (the same table ``ray_tpu.timeline()`` exports), so a
trace is a filterable view of the timeline: ``get_trace(trace_id)`` returns
the span tree.

Usage::

    from ray_tpu.util import tracing
    tracing.enable()
    ref = my_task.remote(...)      # root span, fresh trace_id
    ...
    spans = tracing.get_trace(tracing.last_trace_id())
"""

from __future__ import annotations

import contextvars
import os
import uuid
from typing import Any, Dict, List, Optional

_enabled = os.environ.get("RT_TRACING", "") not in ("", "0", "false")
_current: "contextvars.ContextVar[Optional[Dict[str, str]]]" = \
    contextvars.ContextVar("rt_trace_ctx", default=None)
_last_trace_id: Optional[str] = None


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def enabled() -> bool:
    return _enabled


def current_context() -> Optional[Dict[str, str]]:
    """The ambient span context ({trace_id, span_id}) or None."""
    return _current.get()


def context_for_submit() -> Optional[Dict[str, str]]:
    """Called by the core worker at submit time: the child span's wire
    context. A fresh trace starts when no span is ambient (driver root);
    in a worker WITHOUT an ambient span, no context is minted even if a
    previous traced task ran here — only explicit enable() or an inherited
    span starts spans."""
    global _last_trace_id
    parent = _current.get()
    if not _enabled and parent is None:
        return None
    span_id = uuid.uuid4().hex[:16]
    if parent is None:
        trace_id = uuid.uuid4().hex
        _last_trace_id = trace_id
        return {"trace_id": trace_id, "span_id": span_id,
                "parent_span_id": None}
    return {"trace_id": parent["trace_id"], "span_id": span_id,
            "parent_span_id": parent["span_id"]}


def activate(ctx: Optional[Dict[str, str]]):
    """Executor side: make the received context ambient for nested calls
    (grandchildren propagate through the ambient span, NOT a process flag —
    the worker returns to untraced once this task finishes). Returns a
    token for ``deactivate``."""
    if ctx is None:
        return None
    return _current.set({"trace_id": ctx["trace_id"],
                         "span_id": ctx["span_id"]})


def deactivate(token) -> None:
    if token is not None:
        _current.reset(token)


def last_trace_id() -> Optional[str]:
    """Trace id of the most recent root span started by this process."""
    return _last_trace_id


def get_trace(trace_id: str) -> List[Dict[str, Any]]:
    """All spans of one trace, parents before children where possible."""
    import ray_tpu

    backend = ray_tpu.global_worker()._require_backend()
    events = backend.io.run(
        backend._gcs.call("list_tasks", {"limit": 10000}))
    spans = [e for e in events
             if (e.get("trace") or {}).get("trace_id") == trace_id]
    by_span = {(s["trace"] or {}).get("span_id"): s for s in spans}

    def depth(s, seen=()):
        parent = (s["trace"] or {}).get("parent_span_id")
        if parent is None or parent not in by_span or parent in seen:
            return 0
        return 1 + depth(by_span[parent],
                         seen + ((s["trace"] or {}).get("span_id"),))

    return sorted(spans, key=depth)
