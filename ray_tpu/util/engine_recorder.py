"""Engine flight recorder: per-tick phase attribution, request lifecycle
records, and SLO/goodput accounting for ``ContinuousEngine``.

Every observability layer so far (step profiler, task phase tracing,
memory/failure planes, serve request spans, placement receipts) stops at
the engine boundary — the continuous-batching tick loop is a black-box
background thread. This module is the missing lens: the engine thread
stamps bounded, lock-light records on every tick and every request, and a
separate drain thread ships the derived telemetry everywhere the other
planes already live.

What one TICK record holds — a partition of the tick's wall into the
phases the loop actually runs (``models/serving.py`` stamps them):

  admission       slot bookkeeping around admitting pending requests
                  (queue pop, cancel checks, emit of the first token)
  kv_restore      prefix-cache lookup + retained-page upload for warm
                  admissions (the TTFT-collapse path)
  prefill         the compiled prefill call for the uncached suffix
  decode_step     the fused ``step_many(k)`` launch across active slots
  token_delivery  handing each tick's token bursts to their consumers
  swap_barrier    applying a drain-barrier weight swap, when one landed

plus active-slot count, the bucket the decode launch compiled for
(lone-row vs full-engine), the k-step fusion stride, and the decode
TICK-GAP: the wall between consecutive decode launches while slots were
active — the single number that spikes when a long-prompt prefill (or
anything else) starves decode, and the diagnostic baseline the
prefill/decode disaggregation arc is judged against.

What one REQUEST record holds: queue-wait, cached-vs-computed prefill
tokens (from the batcher's ``last_admission``), decode ticks, TTFT, TPOT,
and the terminal state (done / cancelled). Requests submitted under an
ambient serve request context JOIN the request span tree: the drain emits
an ``engine:<name>`` span parented on the serve span, so ``rt trace
<request-id>`` descends from proxy→replica into engine phases.

Derived SLO/goodput accounting (``summary()``): rolling TTFT/TPOT
SLO-attainment ratios against configurable targets
(``RT_ENGINE_TTFT_SLO_MS`` / ``RT_ENGINE_TPOT_SLO_MS``), goodput tok/s
(tokens of SLO-attaining requests) vs the raw-capacity estimate
(``bucket × k`` tokens per decode launch), and occupancy-weighted decode
efficiency (tokens actually emitted / slot-tokens the launches paid for).

Discipline (the PR 15 ``@memkv/`` lesson, measured: a blocking GCS push
on the tick path froze admission AND decode, warm p99 181 ms → 2.6 s):
the tick path ONLY appends to bounded in-process deques under a
microsecond lock — metrics observation, span emission, the ``@engine/``
KV snapshot and the timeline event push all happen on the drain thread.
The ring-buffer + watermark-drain + self-timing substrate lives in
``util/recorder_core.py`` (shared with the RLHF and train recorders);
this module owns only the engine-specific vocabulary and accounting.
The recorder times itself: ``overhead_s`` accumulates the wall spent
inside recorder calls on the engine thread, and ``summary()`` reports it
as a fraction of recorded tick wall (the bench gate holds it ≤ 2%).

Disable with ``RT_ENGINE_RECORDER=0`` — every hook then costs one
predicate check per tick.
"""

from __future__ import annotations

import os
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

from ray_tpu.util.recorder_core import (RecorderCore, RecorderRegistry,
                                        pct as _pct)

_ENABLED_DEFAULT = os.environ.get("RT_ENGINE_RECORDER", "1") \
    not in ("", "0", "false")
_CAP = int(os.environ.get("RT_ENGINE_RECORDER_CAP", "2048"))
_SLO_WINDOW = int(os.environ.get("RT_ENGINE_SLO_WINDOW", "256"))
_DRAIN_S = float(os.environ.get("RT_ENGINE_DRAIN_S", "2.0"))
_KV_PREFIX = "@engine/"

#: canonical tick-phase vocabulary, in tick-loop order (the timeline
#: tick lane and ``rt engine ticks`` render phases in this order)
TICK_PHASES = ("admission", "kv_restore", "prefill", "decode_step",
               "token_delivery", "swap_barrier")

_REGISTRY = RecorderRegistry()


def live_recorders() -> List["EngineRecorder"]:
    """Every recorder constructed in this process and not yet closed —
    the local engine_stats path and tests read through this."""
    return _REGISTRY.live()


class EngineRecorder(RecorderCore):
    """Bounded flight recorder for one ``ContinuousEngine``.

    The ENGINE THREAD is the only writer of tick records and the only
    caller of ``request_admitted`` / ``request_tokens``; ``request_done``
    may additionally fire from client threads (cancel). All shared state
    lives behind one lock held for O(1) appends — never across a device
    call, an RPC, or a metrics observation.
    """

    KV_PREFIX = _KV_PREFIX
    DRAIN_S = _DRAIN_S
    THREAD_NAME = "rt-engine-rec"
    REGISTRY = _REGISTRY

    def __init__(self, name: str = "engine", *, max_slots: int = 8,
                 ttft_slo_s: Optional[float] = None,
                 tpot_slo_s: Optional[float] = None,
                 cap: int = _CAP, enabled: Optional[bool] = None):
        self.name = name or "engine"
        self.max_slots = max(1, int(max_slots))
        self.enabled = _ENABLED_DEFAULT if enabled is None else bool(enabled)
        self.ttft_slo_s = float(
            os.environ.get("RT_ENGINE_TTFT_SLO_MS", "1500")) / 1e3 \
            if ttft_slo_s is None else float(ttft_slo_s)
        self.tpot_slo_s = float(
            os.environ.get("RT_ENGINE_TPOT_SLO_MS", "150")) / 1e3 \
            if tpot_slo_s is None else float(tpot_slo_s)
        cap = max(64, int(cap))
        self._init_core(self.name)
        self._ticks: "deque[Dict[str, Any]]" = deque(maxlen=cap)  # rt: guarded-by(_lock)
        self._active: "OrderedDict[int, Dict[str, Any]]" = \
            OrderedDict()  # rt: guarded-by(_lock)
        self._done: "deque[Dict[str, Any]]" = deque(maxlen=cap)  # rt: guarded-by(_lock)
        self._window: "deque[Dict[str, Any]]" = \
            deque(maxlen=_SLO_WINDOW)  # rt: guarded-by(_lock)
        self._tick_seq = 0  # rt: guarded-by(_lock)
        self._req_seq = 0  # rt: guarded-by(_lock)
        self._swaps = 0  # rt: guarded-by(_lock)
        self._last_swap: Optional[Dict[str, Any]] = None  # rt: guarded-by(_lock)
        self._requests_total = 0  # rt: guarded-by(_lock)
        self._cancelled_total = 0  # rt: guarded-by(_lock)
        # drain-side watermarks (drain thread only; the lock still guards
        # the snapshot reads that feed them)
        self._metrics_tick_wm = 0
        self._metrics_req_wm = 0
        self._span_req_wm = 0
        self._event_tick_wm = 0
        self._event_req_wm = 0

    # -- tick path (engine thread) ---------------------------------------

    def record_tick(self, *, t_start: float, wall_s: float,
                    phases: Dict[str, float], active: int, pending: int,
                    bucket: int, k: int, tokens: int, admitted: int,
                    gap_s: Optional[float]) -> None:
        """One engine tick: phase partition + the decode tick-gap. The
        ONLY thing this does is append to a bounded deque — no metrics,
        no I/O (drained off-thread)."""
        if not self.enabled:
            return
        t0 = time.perf_counter()
        rec = {"t": t_start, "wall_s": wall_s,
               "phases": {p: phases.get(p, 0.0) for p in TICK_PHASES
                          if phases.get(p, 0.0) > 0.0},
               "active": active, "pending": pending, "bucket": bucket,
               "k": k, "tokens": tokens, "admitted": admitted}
        if gap_s is not None:
            rec["gap_s"] = gap_s
        with self._lock:
            self._tick_seq += 1
            rec["seq"] = self._tick_seq
            self._ticks.append(rec)
            self._wall_total_s += wall_s
            self._overhead_s += time.perf_counter() - t0
        self._ensure_drainer()

    def request_admitted(self, rid: int, *, t_submit: float, t_admit: float,
                         prompt_tokens: int, cached_tokens: int,
                         prefill_s: float, kv_restore_s: float,
                         slot: int = -1,
                         obs_ctx: Optional[Dict[str, str]] = None) -> None:
        """Lifecycle start: admission produced the first token, so this
        stamp IS the TTFT stamp (queue_wait = admission - submit)."""
        if not self.enabled:
            return
        t0 = time.perf_counter()
        rec = {"rid": rid, "t_submit": t_submit, "t_admit": t_admit,
               "t_first": t_admit, "queue_wait_s": max(0.0,
                                                       t_admit - t_submit),
               "prompt_tokens": int(prompt_tokens),
               "cached_tokens": int(cached_tokens),
               "computed_tokens": int(prompt_tokens) - int(cached_tokens),
               "prefill_s": prefill_s, "kv_restore_s": kv_restore_s,
               "slot": slot, "tokens": 1, "decode_ticks": 0,
               "state": "active"}
        if obs_ctx:
            rec["request_id"] = obs_ctx.get("request_id")
            rec["parent_span_id"] = obs_ctx.get("span_id")
        with self._lock:
            self._requests_total += 1
            self._active[rid] = rec
            while len(self._active) > self._done.maxlen:
                self._active.popitem(last=False)  # runaway-leak backstop
            self._overhead_s += time.perf_counter() - t0

    def request_tokens(self, rid: int, n: int, t: float,
                       done: bool = False) -> None:
        """A decode tick delivered ``n`` tokens to request ``rid``."""
        if not self.enabled:
            return
        t0 = time.perf_counter()
        with self._lock:
            rec = self._active.get(rid)
            if rec is not None:
                rec["tokens"] += n
                rec["decode_ticks"] += 1
                rec["t_last"] = t
            self._overhead_s += time.perf_counter() - t0
        if done:
            self.request_done(rid, t=t, state="done")

    def request_done(self, rid: int, *, t: float,
                     state: str = "done") -> None:
        """Finalize a lifecycle record: compute TTFT/TPOT, move it to the
        done ring, and enter it into the rolling SLO window."""
        if not self.enabled:
            return
        t0 = time.perf_counter()
        with self._lock:
            rec = self._active.pop(rid, None)
            if rec is None:
                self._overhead_s += time.perf_counter() - t0
                return
            rec["state"] = state
            rec["t_done"] = t
            rec["ttft_s"] = max(0.0, rec["t_first"] - rec["t_submit"])
            n = rec["tokens"]
            rec["tpot_s"] = (max(0.0, t - rec["t_first"]) / (n - 1)
                             if n > 1 else 0.0)
            self._req_seq += 1
            rec["seq"] = self._req_seq
            self._done.append(rec)
            if state == "done":
                self._window.append({"t": t, "ttft_s": rec["ttft_s"],
                                     "tpot_s": rec["tpot_s"],
                                     "tokens": n,
                                     "decode_ticks": rec["decode_ticks"]})
            else:
                self._cancelled_total += 1
            self._overhead_s += time.perf_counter() - t0

    def record_swap(self, apply_s: float, drained_reqs: int = 0) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._swaps += 1
            # the swap-barrier join: the RLHF transfer receipt reads this
            # back so one record shows ship -> fetch -> barrier -> swap
            self._last_swap = {"t": time.time(),
                               "apply_s": round(apply_s, 6),
                               "drained_reqs": int(drained_reqs)}

    def set_slo(self, *, ttft_slo_s: Optional[float] = None,
                tpot_slo_s: Optional[float] = None) -> None:
        """Retune the SLO targets; attainment is computed against the
        CURRENT targets at summary time, so this applies retroactively
        to the rolling window (bench calibration uses it)."""
        if ttft_slo_s is not None:
            self.ttft_slo_s = float(ttft_slo_s)
        if tpot_slo_s is not None:
            self.tpot_slo_s = float(tpot_slo_s)

    # -- derived accounting ----------------------------------------------

    def ticks(self, limit: int = 0) -> List[Dict[str, Any]]:
        with self._lock:
            out = list(self._ticks)
        return out[-limit:] if limit else out

    def requests(self, limit: int = 0) -> List[Dict[str, Any]]:
        with self._lock:
            out = list(self._done)
        return out[-limit:] if limit else out

    def summary(self) -> Dict[str, Any]:
        """The rolling SLO/goodput picture: what ``engine_stats()``,
        ``rt engine stats``, the doctor findings and the gauges read."""
        with self._lock:
            ticks = list(self._ticks)
            window = list(self._window)
            active = len(self._active)
            base = {"requests_total": self._requests_total,
                    "cancelled_total": self._cancelled_total,
                    "swaps": self._swaps, "ticks_total": self._tick_seq}
            if self._last_swap is not None:
                base["last_swap"] = dict(self._last_swap)
        out = self._aggregate(ticks, window)
        out.update(base)
        out["name"] = self.name
        out["active"] = active
        out["max_slots"] = self.max_slots
        out["ttft_slo_s"] = self.ttft_slo_s
        out["tpot_slo_s"] = self.tpot_slo_s
        self._overhead_fields(out)
        return out

    def window_summary(self, t0: float, t1: float) -> Dict[str, Any]:
        """Same aggregates restricted to records stamped in [t0, t1) —
        the bench legs carve steady/burst/recovery windows with this."""
        with self._lock:
            ticks = [t for t in self._ticks if t0 <= t["t"] < t1]
            window = [{"t": r["t_done"], "ttft_s": r["ttft_s"],
                       "tpot_s": r["tpot_s"], "tokens": r["tokens"],
                       "decode_ticks": r["decode_ticks"]}
                      for r in self._done
                      if r["state"] == "done" and t0 <= r["t_done"] < t1]
        return self._aggregate(ticks, window)

    def _aggregate(self, ticks: List[Dict[str, Any]],
                   window: List[Dict[str, Any]]) -> Dict[str, Any]:
        phase_totals = {p: 0.0 for p in TICK_PHASES}
        wall = 0.0
        gaps: List[float] = []
        cap_tokens = 0
        tokens_emitted = 0
        occ_weighted = 0.0
        decode_wall = 0.0
        for t in ticks:
            wall += t["wall_s"]
            for p, v in t["phases"].items():
                phase_totals[p] = phase_totals.get(p, 0.0) + v
            if "gap_s" in t:
                gaps.append(t["gap_s"])
            d = t["phases"].get("decode_step", 0.0)
            if d > 0.0:
                # capacity this launch paid for: bucket rows × k fused
                # steps would emit bucket*k tokens at full occupancy
                cap_tokens += t["bucket"] * t["k"]
                decode_wall += d
                occ_weighted += d * (t["active"] / self.max_slots)
        tokens_emitted = sum(t["tokens"] for t in ticks)
        gaps.sort()
        phase_sum = sum(phase_totals.values())
        out: Dict[str, Any] = {
            "window_ticks": len(ticks),
            "tick_wall_s": round(wall, 6),
            "phase_s": {p: round(v, 6) for p, v in phase_totals.items()
                        if v > 0.0},
            "phase_sum_ratio": round(phase_sum / wall, 4) if wall > 0
            else 0.0,
            "tick_gap_p50_s": round(_pct(gaps, 0.50), 6),
            "tick_gap_p99_s": round(_pct(gaps, 0.99), 6),
            "tick_gap_max_s": round(gaps[-1], 6) if gaps else 0.0,
            # the doctor's "sustained" signal: the last few gaps, newest
            # last (all above the warn threshold = sustained starvation)
            "gap_recent": [round(t["gap_s"], 6) for t in ticks
                           if "gap_s" in t][-8:],
            "tokens": tokens_emitted,
            "decode_wall_s": round(decode_wall, 6),
            "decode_efficiency": round(tokens_emitted / cap_tokens, 4)
            if cap_tokens else 0.0,
            "occupancy": round(occ_weighted / decode_wall, 4)
            if decode_wall > 0 else 0.0,
            "capacity_tok_s": round(cap_tokens / decode_wall, 1)
            if decode_wall > 0 else 0.0,
        }
        n = len(window)
        out["window_completed"] = n
        if n:
            ttft_ok = sum(1 for w in window
                          if w["ttft_s"] <= self.ttft_slo_s)
            # single-token requests have no inter-token interval; they
            # trivially attain TPOT
            tpot_ok = sum(1 for w in window
                          if w["tpot_s"] <= self.tpot_slo_s)
            out["ttft_attainment"] = round(ttft_ok / n, 4)
            out["tpot_attainment"] = round(tpot_ok / n, 4)
            ttfts = sorted(w["ttft_s"] for w in window)
            tpots = sorted(w["tpot_s"] for w in window)
            out["ttft_p50_s"] = round(_pct(ttfts, 0.50), 6)
            out["ttft_p99_s"] = round(_pct(ttfts, 0.99), 6)
            out["tpot_p50_s"] = round(_pct(tpots, 0.50), 6)
            out["tpot_p99_s"] = round(_pct(tpots, 0.99), 6)
            span = max(w["t"] for w in window) - min(w["t"] for w in window)
            good = sum(w["tokens"] for w in window
                       if w["ttft_s"] <= self.ttft_slo_s
                       and w["tpot_s"] <= self.tpot_slo_s)
            total = sum(w["tokens"] for w in window)
            if span > 0:
                out["goodput_tok_s"] = round(good / span, 1)
                out["window_tok_s"] = round(total / span, 1)
            out["goodput_frac"] = round(good / total, 4) if total else 0.0
        return out

    def snapshot(self, ticks_limit: int = 64,
                 requests_limit: int = 64) -> Dict[str, Any]:
        """The ``@engine/`` KV payload: summary + record tails, compact
        enough to push every couple of seconds."""
        out = self._snapshot_header()
        out["summary"] = self.summary()
        out["ticks"] = [self._compact_tick(t)
                        for t in self.ticks(ticks_limit)]
        out["requests"] = [self._compact_req(r)
                           for r in self.requests(requests_limit)]
        return out

    @staticmethod
    def _compact_tick(t: Dict[str, Any]) -> Dict[str, Any]:
        out = {"seq": t["seq"], "t": round(t["t"], 4),
               "wall_ms": round(t["wall_s"] * 1e3, 3),
               "phases_ms": {p: round(v * 1e3, 3)
                             for p, v in t["phases"].items()},
               "active": t["active"], "pending": t["pending"],
               "bucket": t["bucket"], "k": t["k"], "tokens": t["tokens"],
               "admitted": t["admitted"]}
        if "gap_s" in t:
            out["gap_ms"] = round(t["gap_s"] * 1e3, 3)
        return out

    @staticmethod
    def _compact_req(r: Dict[str, Any]) -> Dict[str, Any]:
        out = {"rid": r["rid"], "state": r["state"],
               "queue_wait_ms": round(r["queue_wait_s"] * 1e3, 3),
               "prompt_tokens": r["prompt_tokens"],
               "cached_tokens": r["cached_tokens"],
               "computed_tokens": r["computed_tokens"],
               "tokens": r["tokens"], "decode_ticks": r["decode_ticks"],
               "slot": r["slot"]}
        if "ttft_s" in r:
            out["ttft_ms"] = round(r["ttft_s"] * 1e3, 3)
            out["tpot_ms"] = round(r["tpot_s"] * 1e3, 3)
        if r.get("request_id"):
            out["request_id"] = r["request_id"]
        return out

    # -- off-tick drain (template in recorder_core; hooks below) ----------

    def _pending_since(self, wm_attr: str, ticks: bool) -> List[Dict]:
        with self._lock:
            src = self._ticks if ticks else self._done
            wm = getattr(self, wm_attr)
            return [r for r in src if r.get("seq", 0) > wm]

    def _drain_metrics(self) -> int:
        try:
            from ray_tpu.util import metrics as M
        except Exception:  # noqa: BLE001
            return 0
        h = _metric_handles(M)
        tags = {"engine": self.name}
        new_ticks = self._pending_since("_metrics_tick_wm", ticks=True)
        for t in new_ticks:
            for p, v in t["phases"].items():
                h["phase"].observe(v, tags={"engine": self.name,
                                            "phase": p})
            if "gap_s" in t:
                h["gap"].observe(t["gap_s"], tags=tags)
            h["ticks"].inc(1.0, tags=tags)
        new_reqs = self._pending_since("_metrics_req_wm", ticks=False)
        for r in new_reqs:
            h["requests"].inc(1.0, tags={"engine": self.name,
                                         "state": r["state"]})
            if "ttft_s" in r and r["state"] == "done":
                h["ttft"].observe(r["ttft_s"], tags=tags)
                if r["tokens"] > 1:
                    h["tpot"].observe(r["tpot_s"], tags=tags)
        if new_ticks:
            self._metrics_tick_wm = new_ticks[-1]["seq"]
        if new_reqs:
            self._metrics_req_wm = new_reqs[-1]["seq"]
        summ = self.summary()
        if summ.get("window_completed"):
            h["slo"].set(summ["ttft_attainment"],
                         tags={"engine": self.name, "slo": "ttft"})
            h["slo"].set(summ["tpot_attainment"],
                         tags={"engine": self.name, "slo": "tpot"})
            h["goodput"].set(summ.get("goodput_tok_s", 0.0), tags=tags)
        if summ.get("window_ticks"):
            h["eff"].set(summ["decode_efficiency"], tags=tags)
            h["overhead"].set(summ["overhead_frac"], tags=tags)
        return len(new_ticks) + len(new_reqs)

    def _drain_spans(self) -> int:
        """Completed requests with a serve context become children of
        their serve span — ``rt trace <rid>`` descends into the engine."""
        pending = self._pending_since("_span_req_wm", ticks=False)
        if not pending:
            return 0
        # advance past everything seen (context-less requests included) so
        # a cluster-less drain doesn't re-emit the same spans every pass
        self._span_req_wm = pending[-1]["seq"]
        new_reqs = [r for r in pending if r.get("request_id")]
        if not new_reqs:
            return 0
        try:
            from ray_tpu.serve import obs
        except Exception:  # noqa: BLE001
            return 0
        n = 0
        for r in new_reqs:
            try:
                span = obs.new_span_id()
                phases = {"queue_wait": r["queue_wait_s"],
                          "prefill": r["prefill_s"]}
                if r["kv_restore_s"] > 0:
                    phases["kv_restore"] = r["kv_restore_s"]
                if "t_done" in r:
                    phases["decode"] = max(0.0, r["t_done"] - r["t_first"])
                obs.emit_span(
                    f"serve:{r['request_id']}:engine:{span[:8]}",
                    f"engine:{self.name}",
                    request_id=r["request_id"], span_id=span,
                    parent_span_id=r.get("parent_span_id"),
                    t_start=r["t_submit"],
                    t_end=r.get("t_done", r["t_first"]),
                    phases=phases,
                    state="FINISHED" if r["state"] == "done"
                    else "CANCELLED")
                n += 1
            except Exception:  # noqa: BLE001 — span plane best-effort
                pass
        return n

    def _build_events(self, node: str, pid: int):
        """Tick + request records as GCS task events; the advance
        closure runs only after a successful push."""
        events = []
        new_ticks = self._pending_since("_event_tick_wm", ticks=True)
        for t in new_ticks[-256:]:
            events.append({
                "task_id": f"engtick:{node}:{pid}:{self.name}:{t['seq']}",
                "name": f"tick:{self.name}", "state": "FINISHED",
                "node_id": node,
                "times": {"RUNNING": t["t"],
                          "FINISHED": t["t"] + t["wall_s"]},
                "engine_tick": {**t, "engine": self.name}})
        new_reqs = self._pending_since("_event_req_wm", ticks=False)
        for r in new_reqs[-256:]:
            events.append({
                "task_id": f"engreq:{node}:{pid}:{self.name}:{r['seq']}",
                "name": f"req:{r['rid']}", "state": "FINISHED",
                "node_id": node,
                "times": {"RUNNING": r["t_submit"],
                          "FINISHED": r.get("t_done", r["t_first"])},
                "engine_request": {**{k: v for k, v in r.items()
                                      if not k.startswith("parent_")},
                                   "engine": self.name}})

        def advance() -> None:
            if new_ticks:
                self._event_tick_wm = new_ticks[-1]["seq"]
            if new_reqs:
                self._event_req_wm = new_reqs[-1]["seq"]

        return events, advance


_metric_cache: Optional[Dict[str, Any]] = None
_GAP_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0,
                2.5, 5.0)
_TTFT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                 5.0, 10.0)
_TPOT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                 0.5, 1.0)


def _metric_handles(M) -> Dict[str, Any]:
    """Lazily registered ``rt_engine_*`` series (drain thread only)."""
    global _metric_cache
    if _metric_cache is None:
        _metric_cache = {
            "phase": M.get_or_create(
                M.Histogram, "rt_engine_tick_phase_seconds",
                "Per-tick engine phase wall (admission / kv_restore / "
                "prefill / decode_step / token_delivery / swap_barrier)",
                boundaries=_GAP_BUCKETS, tag_keys=("engine", "phase")),
            "gap": M.get_or_create(
                M.Histogram, "rt_engine_tick_gap_seconds",
                "Wall between consecutive decode launches while slots "
                "were active (spikes when prefill starves decode)",
                boundaries=_GAP_BUCKETS, tag_keys=("engine",)),
            "ticks": M.get_or_create(
                M.Counter, "rt_engine_ticks_total",
                "Engine ticks recorded by the flight recorder",
                tag_keys=("engine",)),
            "requests": M.get_or_create(
                M.Counter, "rt_engine_requests_total",
                "Engine request lifecycles completed, by terminal state",
                tag_keys=("engine", "state")),
            "ttft": M.get_or_create(
                M.Histogram, "rt_engine_ttft_seconds",
                "Engine-level time to first token (submit to admission's "
                "first token, transport excluded)",
                boundaries=_TTFT_BUCKETS, tag_keys=("engine",)),
            "tpot": M.get_or_create(
                M.Histogram, "rt_engine_tpot_seconds",
                "Engine-level time per output token (mean inter-token "
                "interval per completed request)",
                boundaries=_TPOT_BUCKETS, tag_keys=("engine",)),
            "slo": M.get_or_create(
                M.Gauge, "rt_engine_slo_attainment",
                "Rolling fraction of completed requests meeting the SLO "
                "target, slo=ttft|tpot",
                tag_keys=("engine", "slo")),
            "goodput": M.get_or_create(
                M.Gauge, "rt_engine_goodput_tokens_per_s",
                "Rolling tok/s from requests that met BOTH SLO targets",
                tag_keys=("engine",)),
            "eff": M.get_or_create(
                M.Gauge, "rt_engine_decode_efficiency",
                "Tokens emitted / slot-tokens the decode launches paid "
                "for (occupancy-weighted decode efficiency)",
                tag_keys=("engine",)),
            "overhead": M.get_or_create(
                M.Gauge, "rt_engine_recorder_overhead_ratio",
                "Recorder self-time as a fraction of recorded tick wall",
                tag_keys=("engine",)),
        }
    return _metric_cache
