"""multiprocessing.Pool API on top of tasks.

Reference analog: ``python/ray/util/multiprocessing/`` — a drop-in Pool
whose workers are cluster tasks instead of forked processes, so pools span
nodes. Supported surface: map/map_async/imap/imap_unordered/starmap/apply/
apply_async, chunking, context manager.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu


class AsyncResult:
    def __init__(self, refs: List[Any], single: bool = False):
        self._refs = refs
        self._single = single

    def get(self, timeout: Optional[float] = None):
        values = ray_tpu.get(self._refs, timeout=timeout)
        if self._single:
            return values[0]
        return list(itertools.chain.from_iterable(values))

    def wait(self, timeout: Optional[float] = None) -> None:
        ray_tpu.wait(self._refs, num_returns=len(self._refs), timeout=timeout)

    def ready(self) -> bool:
        done, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                               timeout=0)
        return len(done) == len(self._refs)

    def successful(self) -> bool:
        try:
            self.get(timeout=0)
            return True
        except Exception:  # noqa: BLE001
            return False


def _chunk(seq: List[Any], chunksize: int) -> List[List[Any]]:
    return [seq[i:i + chunksize] for i in range(0, len(seq), chunksize)]


@ray_tpu.remote
def _run_chunk(fn: Callable, chunk: List[Any], star: bool) -> List[Any]:
    if star:
        return [fn(*args) for args in chunk]
    return [fn(x) for x in chunk]


class Pool:
    def __init__(self, processes: Optional[int] = None,
                 initializer: Optional[Callable] = None,
                 initargs: tuple = ()):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        total = ray_tpu.cluster_resources().get("CPU", 1)
        self._processes = processes or max(1, int(total))
        # initializer support: wrap fn calls (per-chunk, idempotent)
        self._initializer = initializer
        self._initargs = initargs

    def _wrap(self, fn: Callable) -> Callable:
        if self._initializer is None:
            return fn
        init, initargs = self._initializer, self._initargs

        def wrapped(*a, **kw):
            flag = "_rt_pool_initialized"
            import builtins

            if not getattr(builtins, flag, False):
                init(*initargs)
                setattr(builtins, flag, True)
            return fn(*a, **kw)

        return wrapped

    def _default_chunksize(self, n: int) -> int:
        return max(1, n // (self._processes * 4) or 1)

    def _map_refs(self, fn, iterable, chunksize, star):
        items = list(iterable)
        chunksize = chunksize or self._default_chunksize(len(items))
        return [_run_chunk.remote(self._wrap(fn), c, star)
                for c in _chunk(items, chunksize)]

    # -- blocking -------------------------------------------------------------
    def map(self, fn: Callable, iterable: Iterable,
            chunksize: Optional[int] = None) -> List[Any]:
        return AsyncResult(self._map_refs(fn, iterable, chunksize,
                                          False)).get()

    def starmap(self, fn: Callable, iterable: Iterable,
                chunksize: Optional[int] = None) -> List[Any]:
        return AsyncResult(self._map_refs(fn, iterable, chunksize,
                                          True)).get()

    def apply(self, fn: Callable, args: tuple = (), kwds: Optional[dict] = None):
        return self.apply_async(fn, args, kwds).get()

    # -- async ----------------------------------------------------------------
    def map_async(self, fn, iterable, chunksize=None) -> AsyncResult:
        return AsyncResult(self._map_refs(fn, iterable, chunksize, False))

    def starmap_async(self, fn, iterable, chunksize=None) -> AsyncResult:
        return AsyncResult(self._map_refs(fn, iterable, chunksize, True))

    def apply_async(self, fn, args: tuple = (),
                    kwds: Optional[dict] = None) -> AsyncResult:
        kwds = kwds or {}
        wrapped = self._wrap(fn)
        ref = ray_tpu.remote(
            lambda: wrapped(*args, **kwds)).remote()
        return AsyncResult([ref], single=True)

    # -- lazy -----------------------------------------------------------------
    def imap(self, fn, iterable, chunksize: Optional[int] = None):
        refs = self._map_refs(fn, iterable, chunksize or 1, False)
        for ref in refs:
            yield from ray_tpu.get(ref)

    def imap_unordered(self, fn, iterable, chunksize: Optional[int] = None):
        refs = self._map_refs(fn, iterable, chunksize or 1, False)
        pending = list(refs)
        while pending:
            done, pending = ray_tpu.wait(pending, num_returns=1)
            yield from ray_tpu.get(done[0])

    # -- lifecycle ------------------------------------------------------------
    def close(self) -> None:
        pass  # tasks are stateless; nothing to tear down

    def terminate(self) -> None:
        pass

    def join(self) -> None:
        pass

    def __enter__(self) -> "Pool":
        return self

    def __exit__(self, *exc) -> None:
        self.terminate()
