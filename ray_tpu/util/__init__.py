"""User-facing utilities (placement groups, actor pools, queues, scheduling
strategies) — the ``ray.util`` surface."""
