"""Llama-family decoder-only transformer, TPU-first.

Design choices (vs. a torch port):
  - Params are a plain pytree; layers are STACKED along a leading axis and the
    forward pass is one ``lax.scan`` over them — a single compiled layer body
    regardless of depth (fast compiles, friendly to pipeline partitioning).
  - bf16 compute / fp32 params + fp32 softmax+loss accumulation.
  - ``jax.checkpoint`` (remat) around the scanned block body with a
    dots-saveable policy: trades HBM for recompute, the standard TPU recipe.
  - Sharding is declarative: ``sharding_rules()`` returns rules mapping the
    param tree onto a (dp, fsdp, tp) mesh; batch rides (dp, fsdp), matrices
    shard (fsdp, tp). XLA inserts the collectives.

Capability parity note: the reference has no model zoo of its own — its Train
library wraps torch models (SURVEY.md §2.3). Here models are first-class
because the flagship benchmark (BASELINE.md config 3: Llama-7B tokens/s/chip)
lives inside the framework.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.ops.attention import mha
from ray_tpu.ops.norms import rmsnorm
from ray_tpu.ops.rope import apply_rope, rope_angles
from ray_tpu.parallel.sharding import ShardingRules
from jax.sharding import PartitionSpec as P

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    d_model: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    d_ff: int = 11008
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    tie_embeddings: bool = False
    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    remat: bool = True
    # Cross-entropy sequence chunk: >0 computes the loss in [B, chunk, V]
    # slices so the full fp32 logits tensor never materializes (at 32k vocab
    # the [B,S,V] logits + cotangent dominate HBM and cap the batch size).
    loss_chunk: int = 0
    # Attention backend: "xla" (fused einsum), "flash" (pallas kernel),
    # "ring" / "ulysses" (sequence-parallel over the mesh "sp" axis; needs
    # an ambient mesh_scope).
    attn_impl: str = "xla"
    # Pipeline parallelism: set to "pp" to split the layer stack over that
    # mesh axis (incompatible with ring/ulysses attn). Schedule: "gpipe"
    # (fwd scan + autodiff backward, stash grows with M) or "1f1b"
    # (interleaved manual-VJP schedule, stash is O(P) — pipeline.py).
    pipeline_axis: Optional[str] = None
    pipeline_microbatches: int = 4
    pipeline_schedule: str = "gpipe"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def num_params(self) -> int:
        d, f, v, l = self.d_model, self.d_ff, self.vocab_size, self.n_layers
        hd = self.head_dim
        per_layer = (d * self.n_heads * hd + 2 * d * self.n_kv_heads * hd
                     + self.n_heads * hd * d + 3 * d * f + 2 * d)
        head = 0 if self.tie_embeddings else d * v
        return v * d + l * per_layer + d + head


PRESETS: Dict[str, LlamaConfig] = {
    "debug": LlamaConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                         n_kv_heads=2, d_ff=128, max_seq_len=128),
    # genuinely-smaller draft for speculative decoding against "debug"
    # (same vocab, ~1/8 the compute) — the CPU bench path must never
    # alias draft == target and call the result a speedup
    "debug_draft": LlamaConfig(vocab_size=256, d_model=32, n_layers=1,
                               n_heads=2, n_kv_heads=1, d_ff=64,
                               max_seq_len=128),
    "160m": LlamaConfig(vocab_size=32000, d_model=768, n_layers=12, n_heads=12,
                        n_kv_heads=12, d_ff=2048, max_seq_len=2048),
    "410m": LlamaConfig(vocab_size=32000, d_model=1024, n_layers=24, n_heads=16,
                        n_kv_heads=16, d_ff=2816, max_seq_len=2048),
    "1b": LlamaConfig(vocab_size=32000, d_model=2048, n_layers=22, n_heads=32,
                      n_kv_heads=4, d_ff=5632, max_seq_len=2048),
    "7b": LlamaConfig(),
}


def init_params(rng: jax.Array, cfg: LlamaConfig) -> Params:
    """Scaled-normal init; layer params stacked on a leading [n_layers] axis."""
    d, f = cfg.d_model, cfg.d_ff
    hq, hkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    L = cfg.n_layers
    keys = jax.random.split(rng, 8)

    def norm_init(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (1.0 / math.sqrt(fan_in))).astype(cfg.param_dtype)

    params: Params = {
        "embed": norm_init(keys[0], (cfg.vocab_size, d), d),
        "layers": {
            "attn_norm": jnp.ones((L, d), cfg.param_dtype),
            "wq": norm_init(keys[1], (L, d, hq * hd), d),
            "wk": norm_init(keys[2], (L, d, hkv * hd), d),
            "wv": norm_init(keys[3], (L, d, hkv * hd), d),
            "wo": norm_init(keys[4], (L, hq * hd, d), hq * hd),
            "mlp_norm": jnp.ones((L, d), cfg.param_dtype),
            "w_gate": norm_init(keys[5], (L, d, f), d),
            "w_up": norm_init(keys[6], (L, d, f), d),
            "w_down": norm_init(keys[7], (L, f, d), f),
        },
        "final_norm": jnp.ones((d,), cfg.param_dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = norm_init(jax.random.fold_in(rng, 99), (d, cfg.vocab_size), d)
    return params


def attention_half(cfg: LlamaConfig, x: jax.Array, layer: Params,
                   sin: jax.Array, cos: jax.Array,
                   segment_ids: Optional[jax.Array]) -> jax.Array:
    """Pre-norm attention + residual — shared by every model family
    (llama's dense blocks, moe's expert blocks)."""
    b, s, d = x.shape
    hq, hkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    cdt = cfg.compute_dtype

    h = rmsnorm(x, layer["attn_norm"].astype(cdt), cfg.norm_eps)
    q = (h @ layer["wq"].astype(cdt)).reshape(b, s, hq, hd)
    k = (h @ layer["wk"].astype(cdt)).reshape(b, s, hkv, hd)
    v = (h @ layer["wv"].astype(cdt)).reshape(b, s, hkv, hd)
    q = apply_rope(q, sin, cos)
    k = apply_rope(k, sin, cos)
    if cfg.attn_impl != "xla" and segment_ids is not None:
        raise NotImplementedError(
            f"segment_ids (packed sequences) require attn_impl='xla'; "
            f"got {cfg.attn_impl!r} — failing loudly rather than attending "
            f"across document boundaries")
    if cfg.attn_impl in ("ring", "ulysses"):
        from ray_tpu.parallel.context import sequence_parallel_attention

        attn = sequence_parallel_attention(q, k, v, impl=cfg.attn_impl,
                                           causal=True)
    elif cfg.attn_impl == "flash":
        from ray_tpu.ops.pallas.flash import flash_attention

        attn = flash_attention(q, k, v, causal=True)
    else:
        attn = mha(q, k, v, causal=True, segment_ids=segment_ids)
    return x + attn.reshape(b, s, hq * hd) @ layer["wo"].astype(cdt)


def ffn_half(cfg: LlamaConfig, x: jax.Array, layer: Params) -> jax.Array:
    """Pre-norm SwiGLU MLP + residual — shared by train and decode paths."""
    cdt = cfg.compute_dtype
    h = rmsnorm(x, layer["mlp_norm"].astype(cdt), cfg.norm_eps)
    gate = jax.nn.silu(h @ layer["w_gate"].astype(cdt))
    up = h @ layer["w_up"].astype(cdt)
    return x + (gate * up) @ layer["w_down"].astype(cdt)


def _block(cfg: LlamaConfig, x: jax.Array, layer: Params,
           sin: jax.Array, cos: jax.Array,
           segment_ids: Optional[jax.Array]) -> jax.Array:
    """One decoder block: pre-norm attn + pre-norm SwiGLU MLP."""
    x = attention_half(cfg, x, layer, sin, cos, segment_ids)
    return ffn_half(cfg, x, layer)


def _stage_scan(cfg: LlamaConfig, stage_layers: Params, h: jax.Array,
                seg: Optional[jax.Array]) -> jax.Array:
    """One pipeline stage: scan this rank's layer slice over ``h`` — the
    stage body shared by the GPipe and 1F1B schedules. RoPE tables are
    recomputed inside (cheap, XLA-hoisted) so the shard_map body closes
    over no tracers."""
    sin, cos = rope_angles(h.shape[1], cfg.head_dim, cfg.rope_theta,
                           cfg.compute_dtype)
    body = lambda hh, layer: (_block(cfg, hh, layer, sin, cos, seg), None)
    h, _ = jax.lax.scan(body, h, stage_layers)
    return h


def _pipelined_layers(layers: Params, x: jax.Array, cfg: LlamaConfig,
                      segment_ids: Optional[jax.Array]) -> jax.Array:
    """Layer stack split over the ``pp`` mesh axis, GPipe-microbatched.

    RoPE tables are recomputed inside the stage (cheap, XLA-hoisted) so the
    shard_map body closes over no tracers. Ring/Ulysses attention can't nest
    inside the pipeline shard_map — validated here.
    """
    from ray_tpu.parallel.context import current_mesh
    from ray_tpu.parallel.pipeline import pipeline_apply

    if cfg.attn_impl in ("ring", "ulysses"):
        raise ValueError("pipeline_axis is incompatible with ring/ulysses "
                         "attention (nested shard_map); use attn_impl="
                         "'flash' or 'xla'")
    mesh = current_mesh()
    if mesh is None:
        raise ValueError("pipeline_axis needs an ambient mesh "
                         "(parallel.context.mesh_scope)")

    def stage(stage_layers, h, seg=None):
        return _stage_scan(cfg, stage_layers, h, seg)

    # Batch rides (dp, fsdp, tp) inside the pipeline region: tp lanes would
    # otherwise run fully redundant stage compute (stage weights are
    # replicated across them at the shard_map boundary — v1 limitation; a
    # manual-collective FSDP-within-stage layout is the follow-up).
    return pipeline_apply(
        stage, layers, x, mesh,
        axis_name=cfg.pipeline_axis,
        num_microbatches=cfg.pipeline_microbatches,
        batch_axes=(("dp", "fsdp", "tp"),),
        remat=cfg.remat,
        extras=segment_ids)


def forward_hidden(params: Params, tokens: jax.Array, cfg: LlamaConfig,
                   segment_ids: Optional[jax.Array] = None
                   ) -> Tuple[jax.Array, jax.Array]:
    """tokens [batch, seq] -> (final-norm hidden [batch, seq, d], head [d, V]),
    both in compute dtype — callers project to logits (possibly chunked)."""
    cdt = cfg.compute_dtype
    x = params["embed"].astype(cdt)[tokens]
    sin, cos = rope_angles(tokens.shape[1], cfg.head_dim, cfg.rope_theta, cdt)

    if cfg.pipeline_axis is not None:
        x = _pipelined_layers(params["layers"], x, cfg, segment_ids)
    else:
        body = lambda x, layer: (_block(cfg, x, layer, sin, cos, segment_ids), None)
        if cfg.remat:
            body = jax.checkpoint(
                body,
                policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
        x, _ = jax.lax.scan(body, x, params["layers"])

    x = rmsnorm(x, params["final_norm"].astype(cdt), cfg.norm_eps)
    head = (params["embed"].T if cfg.tie_embeddings else params["lm_head"]).astype(cdt)
    return x, head


def forward(params: Params, tokens: jax.Array, cfg: LlamaConfig,
            segment_ids: Optional[jax.Array] = None) -> jax.Array:
    """tokens [batch, seq] -> logits [batch, seq, vocab] (fp32)."""
    x, head = forward_hidden(params, tokens, cfg, segment_ids)
    return (x @ head).astype(jnp.float32)


def lm_loss(params: Params, batch: Dict[str, jax.Array], cfg: LlamaConfig) -> jax.Array:
    """Next-token cross entropy; ``batch`` has tokens [B, S+1] (+opt. mask).

    With ``cfg.loss_chunk`` set (and dividing S), the vocab projection +
    softmax run chunk-by-chunk under a ``lax.scan`` with full remat, so peak
    HBM holds one [B, chunk, V] fp32 slice instead of [B, S, V] plus its
    cotangent — the logits, not the activations, are what cap batch size at
    32k vocab. Extra cost: the head matmul is recomputed in backward (~3% of
    step FLOPs at 410M scale).
    """
    tokens = batch["tokens"]
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    x, head = forward_hidden(params, inputs, cfg, batch.get("segment_ids"))
    return chunked_ce(x, head, targets, batch.get("loss_mask"),
                      cfg.loss_chunk)


def lm_loss_and_grads_1f1b(params: Params, batch: Dict[str, jax.Array],
                           cfg: LlamaConfig):
    """(loss, grads) via the interleaved 1F1B pipeline (manual per-stage
    VJPs — ``parallel/pipeline.py:pipeline_1f1b``). The embedding lookup is
    differentiated OUTSIDE the pipeline (its vjp scatter-adds the collected
    per-microbatch input cotangents); final norm + head live INSIDE the last
    stage's loss so the backward can start there. Selected by
    ``cfg.pipeline_schedule == "1f1b"`` in ``make_train_step``.
    """
    from ray_tpu.parallel import pipeline as pl
    from ray_tpu.parallel.context import current_mesh

    if cfg.tie_embeddings:
        raise NotImplementedError(
            "1f1b needs untied embeddings (the head lives inside the "
            "pipeline's last stage; the embedding outside it)")
    if cfg.attn_impl in ("ring", "ulysses"):
        raise ValueError("pipeline schedules are incompatible with "
                         "ring/ulysses attention (nested shard_map); use "
                         "attn_impl='flash' or 'xla'")
    mesh = current_mesh()
    if mesh is None:
        raise ValueError("1f1b needs an ambient mesh "
                         "(parallel.context.mesh_scope)")
    cdt = cfg.compute_dtype
    tokens = batch["tokens"]
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    segs = batch.get("segment_ids")
    mask = batch.get("loss_mask")

    def embed_fn(embed_w):
        return embed_w.astype(cdt)[inputs]

    x, embed_vjp = jax.vjp(embed_fn, params["embed"])

    def stage_fn(stage_layers, h, seg):
        return _stage_scan(cfg, stage_layers, h, seg)

    def head_loss_fn(head_bundle, y, tgt, msk):
        y = rmsnorm(y, head_bundle["final_norm"].astype(cdt), cfg.norm_eps)
        head = head_bundle["lm_head"].astype(cdt)
        return chunked_ce(y, head, tgt, msk, cfg.loss_chunk)

    head_bundle = {"final_norm": params["final_norm"],
                   "lm_head": params["lm_head"]}
    loss, g_layers, g_head, g_x = pl.pipeline_1f1b(
        stage_fn, head_loss_fn, params["layers"], head_bundle, x, targets,
        mesh,
        axis_name=cfg.pipeline_axis,
        num_microbatches=cfg.pipeline_microbatches,
        batch_axes=("dp", "fsdp", "tp"),
        segments=segs, loss_mask=mask)
    g_embed, = embed_vjp(g_x)
    grads = {"embed": g_embed, "layers": g_layers,
             "final_norm": g_head["final_norm"],
             "lm_head": g_head["lm_head"]}
    return loss, grads


def chunked_ce(x: jax.Array, head: jax.Array, targets: jax.Array,
               mask: Optional[jax.Array], chunk: int) -> jax.Array:
    """Cross entropy from final hiddens; shared by every model family."""
    S = targets.shape[1]
    if chunk and S % chunk == 0 and S > chunk:
        n_chunks = S // chunk
        xs = x.reshape(x.shape[0], n_chunks, chunk, -1).swapaxes(0, 1)
        ts = targets.reshape(targets.shape[0], n_chunks, chunk).swapaxes(0, 1)
        ms = (jnp.ones_like(ts, jnp.float32) if mask is None
              else mask.reshape(mask.shape[0], n_chunks, chunk).swapaxes(0, 1)
              .astype(jnp.float32))

        def chunk_nll(carry, sl):
            xc, tc, mc = sl
            logits = (xc @ head).astype(jnp.float32)
            logp = jax.nn.log_softmax(logits, axis=-1)
            nll = -jnp.take_along_axis(logp, tc[..., None], axis=-1)[..., 0]
            s, cnt = carry
            return (s + (nll * mc).sum(), cnt + mc.sum()), None

        body = jax.checkpoint(
            chunk_nll, policy=jax.checkpoint_policies.nothing_saveable)
        (total, count), _ = jax.lax.scan(
            body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
            (xs, ts, ms))
        return total / jnp.maximum(count, 1)

    logits = (x @ head).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    if mask is None:
        return nll.mean()
    return (nll * mask).sum() / jnp.maximum(mask.sum(), 1)


def sharding_rules(pipeline: bool = False) -> ShardingRules:
    """Param partitioning over the (pp, dp, fsdp, tp) mesh (scaling-book
    layout).

    The leading stacked-layer axis is sharded over ``pp`` when pipelining
    (else unsharded); matrices put their contracting/output dims on
    (fsdp, tp) so forward matmuls all-gather over fsdp (ZeRO-3) and reduce
    over tp.

    Pipelined layer weights keep their non-layer dims REPLICATED: the
    pipeline shard_map consumes stage weights whole (``pipeline_apply``
    in_specs = P("pp")), and storing them fsdp/tp-sharded would force a
    replicate-then-partition reshard at the boundary — the
    ``spmd_partitioner`` "involuntary full rematerialization" warning — on
    every step's backward transpose. Storage layout == consumption layout;
    the embed/lm_head (outside the pipeline region) stay fsdp/tp-sharded.
    """
    if pipeline:
        # Embed/head replicated too: feature-sharded embeddings make GSPMD
        # carry feature-tiled activations into/out of the batch-tiled
        # pipeline region — the same boundary reshard in disguise.
        return ShardingRules([
            (r"layers/", P("pp")),
            (r".*", P()),
        ])
    return ShardingRules([
        (r"embed$", P("tp", "fsdp")),
        (r"lm_head$", P("fsdp", "tp")),
        (r"layers/w[qkv]$", P(None, "fsdp", "tp")),
        (r"layers/wo$", P(None, "tp", "fsdp")),
        (r"layers/w_(gate|up)$", P(None, "fsdp", "tp")),
        (r"layers/w_down$", P(None, "tp", "fsdp")),
        (r"layers/.*norm", P(None)),
        (r"norm", P()),
    ])


def data_rules() -> ShardingRules:
    return ShardingRules([(r".*", P(("dp", "fsdp"), None))])
