"""ViT: Vision Transformer classification family.

Reference analog: the vision workloads the reference's Train/Data docs
target (torchvision models on TorchTrainer); here the TPU-native
equivalent — a pre-LN ViT (Dosovitskiy et al. 2020) written in the same
stacked-layer/pjit style as ``models/llama.py``: layer params carry a
leading ``[n_layers]`` axis consumed by ``lax.scan``, compute runs in
bfloat16 on the MXU, parameters shard over the (dp, fsdp, tp) mesh with
the same scaling-book layout, and patch embedding is a single reshaped
matmul (no conv needed for non-overlapping patches).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict

import jax
import jax.numpy as jnp

from ray_tpu.parallel.sharding import P, ShardingRules

Params = Dict[str, Any]


@dataclasses.dataclass
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    channels: int = 3
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    num_classes: int = 1000
    norm_eps: float = 1e-6
    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    remat: bool = True

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.channels * self.patch_size ** 2

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def num_params(self) -> int:
        d, f, L = self.d_model, self.d_ff, self.n_layers
        per_layer = 4 * d * d + 2 * d * f + 4 * d + f + d  # attn+mlp+ln
        return (self.patch_dim * d + d                      # patch proj
                + (self.num_patches + 1) * d + d            # pos + cls
                + L * per_layer + 2 * d                     # final ln
                + d * self.num_classes + self.num_classes)  # head


PRESETS: Dict[str, ViTConfig] = {
    "debug": ViTConfig(image_size=32, patch_size=8, d_model=64,
                       n_layers=2, n_heads=4, d_ff=128, num_classes=10),
    "s16": ViTConfig(d_model=384, n_layers=12, n_heads=6, d_ff=1536),
    "b16": ViTConfig(),  # ViT-B/16
}


def init_params(rng: jax.Array, cfg: ViTConfig) -> Params:
    d, f, L = cfg.d_model, cfg.d_ff, cfg.n_layers
    keys = jax.random.split(rng, 10)

    def ninit(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (1.0 / math.sqrt(fan_in))).astype(cfg.param_dtype)

    return {
        "patch_proj": ninit(keys[0], (cfg.patch_dim, d), cfg.patch_dim),
        "patch_bias": jnp.zeros((d,), cfg.param_dtype),
        "cls": jnp.zeros((1, 1, d), cfg.param_dtype),
        "pos": (jax.random.normal(keys[1], (cfg.num_patches + 1, d),
                                  jnp.float32)
                * 0.02).astype(cfg.param_dtype),
        "layers": {
            "ln1": jnp.ones((L, d), cfg.param_dtype),
            "ln1_b": jnp.zeros((L, d), cfg.param_dtype),
            "wq": ninit(keys[2], (L, d, d), d),
            "wk": ninit(keys[3], (L, d, d), d),
            "wv": ninit(keys[4], (L, d, d), d),
            "wo": ninit(keys[5], (L, d, d), d),
            "ln2": jnp.ones((L, d), cfg.param_dtype),
            "ln2_b": jnp.zeros((L, d), cfg.param_dtype),
            "w_up": ninit(keys[6], (L, d, f), d),
            "b_up": jnp.zeros((L, f), cfg.param_dtype),
            "w_down": ninit(keys[7], (L, f, d), f),
            "b_down": jnp.zeros((L, d), cfg.param_dtype),
        },
        "final_ln": jnp.ones((d,), cfg.param_dtype),
        "final_ln_b": jnp.zeros((d,), cfg.param_dtype),
        "head": ninit(keys[8], (d, cfg.num_classes), d),
        "head_b": jnp.zeros((cfg.num_classes,), cfg.param_dtype),
    }


def _ln(x, g, b, eps):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * g + b


def patchify(images: jax.Array, cfg: ViTConfig) -> jax.Array:
    """[B, H, W, C] -> [B, num_patches, patch_dim] (non-overlapping
    patches as a reshape — equivalent to the stride-P conv)."""
    B = images.shape[0]
    p = cfg.patch_size
    n = cfg.image_size // p
    x = images.reshape(B, n, p, n, p, cfg.channels)
    x = x.transpose(0, 1, 3, 2, 4, 5)  # [B, n, n, p, p, C]
    return x.reshape(B, n * n, cfg.patch_dim)


def _block(cfg: ViTConfig, x: jax.Array, lp: Params) -> jax.Array:
    B, S, d = x.shape
    h = _ln(x, lp["ln1"], lp["ln1_b"], cfg.norm_eps)
    q = (h @ lp["wq"]).reshape(B, S, cfg.n_heads, cfg.head_dim)
    k = (h @ lp["wk"]).reshape(B, S, cfg.n_heads, cfg.head_dim)
    v = (h @ lp["wv"]).reshape(B, S, cfg.n_heads, cfg.head_dim)
    att = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(cfg.head_dim)
    att = jax.nn.softmax(att.astype(jnp.float32),
                         axis=-1).astype(x.dtype)  # no mask: bidirectional
    out = jnp.einsum("bhqk,bkhd->bqhd", att, v).reshape(B, S, d)
    x = x + out @ lp["wo"]
    h = _ln(x, lp["ln2"], lp["ln2_b"], cfg.norm_eps)
    h = jax.nn.gelu(h @ lp["w_up"] + lp["b_up"])
    return x + (h @ lp["w_down"] + lp["b_down"])


def forward(params: Params, images: jax.Array, cfg: ViTConfig) -> jax.Array:
    """[B, H, W, C] float images -> [B, num_classes] logits."""
    cd = cfg.compute_dtype
    x = patchify(images.astype(cd), cfg)
    x = x @ params["patch_proj"].astype(cd) \
        + params["patch_bias"].astype(cd)
    B = x.shape[0]
    cls = jnp.broadcast_to(params["cls"].astype(cd),
                           (B, 1, cfg.d_model))
    x = jnp.concatenate([cls, x], axis=1) + params["pos"].astype(cd)

    def body(h, lp):
        lp = jax.tree_util.tree_map(lambda t: t.astype(cd), lp)
        return _block(cfg, h, lp), None

    if cfg.remat:
        body = jax.checkpoint(body)
    x, _ = jax.lax.scan(body, x, params["layers"])
    x = _ln(x.astype(jnp.float32), params["final_ln"],
            params["final_ln_b"], cfg.norm_eps)
    cls_out = x[:, 0]
    return cls_out @ params["head"].astype(jnp.float32) \
        + params["head_b"].astype(jnp.float32)


def cls_loss(params: Params, batch: Dict[str, jax.Array],
             cfg: ViTConfig) -> jax.Array:
    """Softmax cross-entropy over ``batch["images"]/["labels"]``."""
    logits = forward(params, batch["images"], cfg)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(
        logp, batch["labels"][:, None].astype(jnp.int32), axis=-1)[:, 0]
    return nll.mean()


def sharding_rules() -> ShardingRules:
    """Scaling-book layout over (dp, fsdp, tp): attention/MLP matrices put
    contracting/output dims on (fsdp, tp); vectors replicated."""
    return ShardingRules([
        (r"patch_proj$", P("fsdp", "tp")),
        (r"head$", P("fsdp", "tp")),
        (r"layers/w[qkv]$", P(None, "fsdp", "tp")),
        (r"layers/wo$", P(None, "tp", "fsdp")),
        (r"layers/w_up$", P(None, "fsdp", "tp")),
        (r"layers/w_down$", P(None, "tp", "fsdp")),
        (r".*", P()),
    ])


def data_rules() -> ShardingRules:
    return ShardingRules([(r".*", P(("dp", "fsdp")))])
