"""Model zoo: pure-pytree JAX models designed for sharding-annotated jit."""

from ray_tpu.models.llama import LlamaConfig  # noqa: F401
from ray_tpu.models.vit import ViTConfig  # noqa: F401
