"""Mixtral-style sparse-MoE transformer — the second flagship model family.

TPU-first design (no reference counterpart — Ray ships no model code; the
recipe is the public GShard/Switch einsum formulation): the router performs
STATIC top-k capacity dispatch, so every tensor shape is fixed at trace
time and XLA tiles the expert FFNs onto the MXU as one batched einsum.
Experts shard over the mesh's ``ep`` axis (each device group holds
n_experts/ep experts); GSPMD inserts the all-to-alls implied by the
dispatch/combine einsums over ICI. Attention blocks, RoPE, norms and the
chunked loss are shared with :mod:`ray_tpu.models.llama`.

Routing (per token): softmax router logits -> top-k experts -> each chosen
token takes a slot in its expert's capacity buffer
(``capacity_factor * tokens * top_k / n_experts`` slots per expert);
overflow tokens drop that expert (standard Switch behavior — the residual
stream carries them).
Load-balancing aux loss: ``n_experts * sum_e(fraction_e * prob_e)``.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.models import llama
from ray_tpu.parallel.sharding import ShardingRules
from jax.sharding import PartitionSpec as P

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class MoEConfig(llama.LlamaConfig):
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    router_aux_coef: float = 0.01

    def num_params(self) -> int:
        d, f, v, l = self.d_model, self.d_ff, self.vocab_size, self.n_layers
        hd = self.head_dim
        attn = (d * self.n_heads * hd + 2 * d * self.n_kv_heads * hd
                + self.n_heads * hd * d)
        moe = self.n_experts * 3 * d * f + d * self.n_experts  # experts+router
        per_layer = attn + moe + 2 * d
        head = 0 if self.tie_embeddings else d * v
        return v * d + l * per_layer + d + head

    def active_params(self) -> int:
        """Params touched per token (top-k experts) — the FLOPs-relevant
        count for MFU estimates."""
        d, f, v, l = self.d_model, self.d_ff, self.vocab_size, self.n_layers
        hd = self.head_dim
        attn = (d * self.n_heads * hd + 2 * d * self.n_kv_heads * hd
                + self.n_heads * hd * d)
        moe = self.top_k * 3 * d * f + d * self.n_experts
        head = 0 if self.tie_embeddings else d * v
        return v * d + l * (attn + moe + 2 * d) + d + head


PRESETS: Dict[str, MoEConfig] = {
    "moe-debug": MoEConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                           n_kv_heads=4, d_ff=128, max_seq_len=256,
                           n_experts=4, top_k=2),
    "8x160m": MoEConfig(vocab_size=32000, d_model=768, n_layers=12,
                        n_heads=12, n_kv_heads=12, d_ff=2048,
                        max_seq_len=2048, n_experts=8, top_k=2),
    "8x410m": MoEConfig(vocab_size=32000, d_model=1024, n_layers=24,
                        n_heads=16, n_kv_heads=16, d_ff=2816,
                        max_seq_len=2048, n_experts=8, top_k=2),
}


def init_params(rng: jax.Array, cfg: MoEConfig) -> Params:
    """Llama init plus stacked expert FFNs [L, E, ...] and routers."""
    d, f, E, L = cfg.d_model, cfg.d_ff, cfg.n_experts, cfg.n_layers
    base = llama.init_params(rng, cfg)
    k = jax.random.fold_in(rng, 7)
    k1, k2, k3, k4 = jax.random.split(k, 4)

    def norm_init(key, shape, fan_in):
        return (jax.random.normal(key, shape, jnp.float32)
                * (1.0 / math.sqrt(fan_in))).astype(cfg.param_dtype)

    layers = base["layers"]
    for name in ("w_gate", "w_up", "w_down"):  # dense FFN -> experts
        del layers[name]
    layers["router"] = norm_init(k1, (L, d, E), d)
    layers["e_gate"] = norm_init(k2, (L, E, d, f), d)
    layers["e_up"] = norm_init(k3, (L, E, d, f), d)
    layers["e_down"] = norm_init(k4, (L, E, f, d), f)
    return base


def _moe_ffn(cfg: MoEConfig, h: jax.Array, layer: Params
             ) -> Tuple[jax.Array, jax.Array]:
    """[B, S, d] -> ([B, S, d], aux_loss). Static-shape top-k capacity
    dispatch (GShard einsum formulation)."""
    b, s, d = h.shape
    E, K = cfg.n_experts, cfg.top_k
    G = b * s
    C = max(1, int(cfg.capacity_factor * G * K / E))
    tokens = h.reshape(G, d)

    logits = (tokens @ layer["router"].astype(jnp.float32)).astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)                       # [G, E]
    topk_probs, topk_idx = jax.lax.top_k(probs, K)                # [G, K]
    # renormalize the selected gates (Mixtral convention)
    topk_probs = topk_probs / (topk_probs.sum(-1, keepdims=True) + 1e-9)

    # capacity slots: position of each token within its expert's queue,
    # counted over the flattened [K, G] selection order
    sel_onehot = jax.nn.one_hot(topk_idx, E, dtype=jnp.int32)     # [G, K, E]
    flat = sel_onehot.transpose(1, 0, 2).reshape(K * G, E)        # [K*G, E]
    pos_flat = jnp.cumsum(flat, axis=0) - flat                    # slot idx
    pos = pos_flat.reshape(K, G, E).transpose(1, 0, 2)            # [G, K, E]
    slot = jnp.sum(pos * sel_onehot, axis=-1)                     # [G, K]
    keep = slot < C                                               # overflow

    gates = topk_probs * keep                                      # [G, K]
    # dispatch/combine tensors [G, E, C]
    slot_onehot = jax.nn.one_hot(slot, C, dtype=h.dtype)          # [G, K, C]
    dispatch = jnp.einsum("gke,gkc->gec",
                          sel_onehot.astype(h.dtype) * keep[..., None],
                          slot_onehot)
    combine = jnp.einsum("gke,gkc,gk->gec",
                         sel_onehot.astype(h.dtype), slot_onehot,
                         gates.astype(h.dtype))

    expert_in = jnp.einsum("gd,gec->ecd", tokens, dispatch)       # [E, C, d]
    gate = jax.nn.silu(jnp.einsum("ecd,edf->ecf", expert_in,
                                  layer["e_gate"].astype(h.dtype)))
    up = jnp.einsum("ecd,edf->ecf", expert_in,
                    layer["e_up"].astype(h.dtype))
    expert_out = jnp.einsum("ecf,efd->ecd", gate * up,
                            layer["e_down"].astype(h.dtype))
    out = jnp.einsum("ecd,gec->gd", expert_out, combine)

    # Switch aux loss: balance token fraction vs router probability mass
    frac = jnp.mean(sel_onehot[:, 0, :].astype(jnp.float32), axis=0)  # top-1
    prob_mean = jnp.mean(probs, axis=0)
    aux = E * jnp.sum(frac * prob_mean)
    return out.reshape(b, s, d), aux


def ffn_half(cfg: MoEConfig, x: jax.Array, layer: Params,
             drop_free: bool = False) -> Tuple[jax.Array, jax.Array]:
    """Pre-norm MoE FFN + residual; returns (hidden, aux_loss).
    ``drop_free``: capacity covers every selection (inference routing —
    capacity drops are a training-time load-balancing construct)."""
    c = (dataclasses.replace(cfg, capacity_factor=float(cfg.n_experts))
         if drop_free else cfg)
    h = llama.rmsnorm(x, layer["mlp_norm"].astype(cfg.compute_dtype),
                      cfg.norm_eps)
    ffn, aux = _moe_ffn(c, h, layer)
    return x + ffn, aux


def _moe_block(cfg: MoEConfig, x: jax.Array, layer: Params,
               sin: jax.Array, cos: jax.Array,
               segment_ids) -> Tuple[jax.Array, jax.Array]:
    """Shared llama attention half + MoE FFN; returns (hidden, aux_loss)."""
    x = llama.attention_half(cfg, x, layer, sin, cos, segment_ids)
    return ffn_half(cfg, x, layer)


def forward_hidden(params: Params, tokens: jax.Array, cfg: MoEConfig,
                   segment_ids=None) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """-> (hidden, head, total_aux_loss)."""
    if cfg.pipeline_axis is not None:
        raise NotImplementedError(
            "pipeline parallelism for the MoE family is not implemented "
            "(use dp/fsdp/tp/ep); silently ignoring pipeline_axis would "
            "train an unpipelined model under pipeline shardings")
    cdt = cfg.compute_dtype
    x = params["embed"].astype(cdt)[tokens]
    sin, cos = llama.rope_angles(tokens.shape[1], cfg.head_dim,
                                 cfg.rope_theta, cdt)

    def body(carry, layer):
        x, aux = carry
        x, a = _moe_block(cfg, x, layer, sin, cos, segment_ids)
        return (x, aux + a), None

    if cfg.remat:
        body = jax.checkpoint(
            body,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
    (x, aux), _ = jax.lax.scan(body, (x, jnp.zeros((), jnp.float32)),
                               params["layers"])
    x = llama.rmsnorm(x, params["final_norm"].astype(cdt), cfg.norm_eps)
    head = (params["embed"].T if cfg.tie_embeddings
            else params["lm_head"]).astype(cdt)
    return x, head, aux / cfg.n_layers


def forward(params: Params, tokens: jax.Array, cfg: MoEConfig,
            segment_ids=None) -> jax.Array:
    x, head, _ = forward_hidden(params, tokens, cfg, segment_ids)
    return (x @ head).astype(jnp.float32)


def lm_loss(params: Params, batch: Dict[str, jax.Array],
            cfg: MoEConfig) -> jax.Array:
    """Next-token CE + router aux loss (llama's chunked CE reused)."""
    tokens = batch["tokens"]
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    x, head, aux = forward_hidden(params, inputs, cfg,
                                  batch.get("segment_ids"))
    ce = llama.chunked_ce(x, head, targets, batch.get("loss_mask"),
                          cfg.loss_chunk)
    return ce + cfg.router_aux_coef * aux


def sharding_rules(pipeline: bool = False) -> ShardingRules:
    """Llama rules + expert tensors: experts over ``ep``, expert matrices'
    ff dim over ``tp`` (fsdp shards the model dim like the dense path)."""
    if pipeline:
        raise NotImplementedError(
            "pipeline parallelism for the MoE family is not implemented")
    return ShardingRules([
        (r"embed$", P("tp", "fsdp")),
        (r"lm_head$", P("fsdp", "tp")),
        (r"layers/w[qkv]$", P(None, "fsdp", "tp")),
        (r"layers/wo$", P(None, "tp", "fsdp")),
        (r"layers/router$", P(None, "fsdp", None)),
        (r"layers/e_(gate|up)$", P(None, "ep", "fsdp", "tp")),
        (r"layers/e_down$", P(None, "ep", "tp", "fsdp")),
        (r"layers/.*norm", P(None)),
        (r"norm", P()),
    ])
