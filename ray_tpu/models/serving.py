"""Continuous batching for autoregressive serving.

The vLLM-style capability (no reference counterpart — Ray pairs with
external engines for this), designed static-shape for XLA/TPU instead of
paged dynamic memory:

- ONE static KV cache [L, max_slots, max_len, hkv, hd]; a request
  occupies a SLOT for its lifetime. No paging, no dynamic shapes — the
  compiled programs never change as requests come and go.
- Admission is a per-request prefill that scatters the prompt's KV into
  the free slot (`dynamic_update_slice` on the slot axis) and returns
  the first generated token.
- Every engine tick is ONE compiled step decoding ALL slots together:
  the per-slot absolute position rides a [slots] vector, handled by
  ``vmap``-ing the single-row cached forward (per-row rope positions,
  per-row cache writes become scatters, causal masking by each row's own
  position). Free slots compute garbage that is never observed and is
  overwritten from position 0 by the next admission's prefill.
- Greedy decoding — each request's output is EXACTLY
  ``generate.generate(...)`` on its own prompt, regardless of what else
  shares the batch (the test asserts this token-for-token).

Prefill compiles once per (batch=1, prompt_len) via the module's lru
cache; production use would bucket prompt lengths — admission cost, not
a steady-state one (the decode step is length-independent).
"""

from __future__ import annotations

import functools
import itertools
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models import generate as G
from ray_tpu.models import llama

Params = Dict[str, Any]


class _Request:
    __slots__ = ("req_id", "slot", "remaining", "tokens")

    def __init__(self, req_id: int, slot: int, remaining: int):
        self.req_id = req_id
        self.slot = slot
        self.remaining = remaining
        self.tokens: List[int] = []


class ContinuousBatcher:
    """Slot-based continuous batching engine around one model."""

    def __init__(self, params: Params, cfg: llama.LlamaConfig, *,
                 max_slots: int = 8, max_len: int = 512):
        self.params = params
        self.cfg = cfg
        self.max_slots = max_slots
        self.max_len = max_len
        shape = (cfg.n_layers, max_slots, max_len, cfg.n_kv_heads,
                 cfg.head_dim)
        self._ck = jnp.zeros(shape, cfg.compute_dtype)
        self._cv = jnp.zeros(shape, cfg.compute_dtype)
        self._free: List[int] = list(range(max_slots))
        self._active: Dict[int, _Request] = {}  # slot -> request
        self._cur = np.zeros(max_slots, np.int32)   # token AT pos, per slot
        self._pos = np.zeros(max_slots, np.int32)   # absolute position
        self._ids = itertools.count()
        self._step_fn = _compiled_rowwise_step(cfg, max_slots, max_len)

    # -- admission --------------------------------------------------------

    def submit(self, prompt: np.ndarray, max_new_tokens: int) -> int:
        """Admit one request (prompt: int array [S]); returns req_id.
        Raises RuntimeError when no slot is free (caller queues/retries —
        admission control belongs to the serving layer)."""
        if not self._free:
            raise RuntimeError("no free slots")
        s = len(prompt)
        if s + max_new_tokens + 1 > self.max_len:
            raise ValueError(f"prompt {s} + new {max_new_tokens} exceeds "
                             f"max_len {self.max_len}")
        slot = self._free.pop()
        fn = _compiled_slot_prefill(self.cfg, s, self.max_slots,
                                    self.max_len)
        self._ck, self._cv, first = fn(
            self.params, self._ck, self._cv,
            jnp.asarray(prompt, jnp.int32)[None, :], slot)
        req = _Request(next(self._ids), slot, max_new_tokens)
        first_tok = int(first[0])
        req.tokens.append(first_tok)
        req.remaining -= 1
        self._cur[slot] = first_tok
        self._pos[slot] = s
        if req.remaining <= 0:
            self._free.append(slot)
        else:
            self._active[slot] = req
        return req.req_id

    # -- the engine tick --------------------------------------------------

    def step(self) -> List[Tuple[int, int, bool]]:
        """ONE decode step for every active slot; returns
        [(req_id, token, done)] for requests that produced a token."""
        if not self._active:
            return []
        self._ck, self._cv, nxt = self._step_fn(
            self.params, self._ck, self._cv,
            jnp.asarray(self._cur), jnp.asarray(self._pos))
        nxt = np.asarray(nxt)
        out = []
        for slot, req in list(self._active.items()):
            tok = int(nxt[slot])
            req.tokens.append(tok)
            req.remaining -= 1
            self._cur[slot] = tok
            self._pos[slot] += 1
            done = req.remaining <= 0
            if done:
                del self._active[slot]
                self._free.append(slot)
            out.append((req.req_id, tok, done))
        return out

    @property
    def num_active(self) -> int:
        return len(self._active)

    def run_to_completion(self) -> Dict[int, List[int]]:
        """Drain all active requests; returns req_id -> generated tokens
        (convenience for tests/batch jobs; serving calls step())."""
        results: Dict[int, List[int]] = {
            r.req_id: r.tokens for r in self._active.values()}
        while self._active:
            reqs = {r.req_id: r for r in self._active.values()}
            for rid, tok, done in self.step():
                results.setdefault(rid, reqs[rid].tokens)
        return results


@functools.lru_cache(maxsize=64)
def _compiled_slot_prefill(cfg, s: int, max_slots: int, max_len: int):
    """Prefill ONE prompt into ONE slot of the shared cache; returns the
    updated cache and the first greedy token."""

    @jax.jit
    def run(params, ck, cv, prompt, slot):
        row = {"k": jnp.zeros((cfg.n_layers, 1, max_len, cfg.n_kv_heads,
                               cfg.head_dim), cfg.compute_dtype),
               "v": jnp.zeros((cfg.n_layers, 1, max_len, cfg.n_kv_heads,
                               cfg.head_dim), cfg.compute_dtype)}
        logits, row = G._forward_with_cache(params, prompt, cfg, row, 0)
        first = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
        ck = jax.lax.dynamic_update_slice(ck, row["k"], (0, slot, 0, 0, 0))
        cv = jax.lax.dynamic_update_slice(cv, row["v"], (0, slot, 0, 0, 0))
        return ck, cv, first

    return run


@functools.lru_cache(maxsize=16)
def _compiled_rowwise_step(cfg, max_slots: int, max_len: int):
    """One decode step for ALL slots with PER-SLOT positions: vmap the
    single-row cached forward over the slot axis — per-row rope, per-row
    cache scatter, per-row causal masking, one compiled program."""

    def one_row(params, ck_row, cv_row, tok, pos):
        cache = {"k": ck_row[:, None], "v": cv_row[:, None]}
        logits, cache = G._forward_with_cache(
            params, tok[None, None], cfg, cache, pos)
        nxt = jnp.argmax(logits[0, -1, :]).astype(jnp.int32)
        return cache["k"][:, 0], cache["v"][:, 0], nxt

    @jax.jit
    def run(params, ck, cv, cur, pos):
        ck_rows = ck.swapaxes(0, 1)  # [slots, L, T, hkv, hd]
        cv_rows = cv.swapaxes(0, 1)
        ck_rows, cv_rows, nxt = jax.vmap(
            one_row, in_axes=(None, 0, 0, 0, 0))(
            params, ck_rows, cv_rows, cur, pos)
        return (ck_rows.swapaxes(0, 1), cv_rows.swapaxes(0, 1), nxt)

    return run
