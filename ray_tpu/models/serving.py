"""Continuous batching for autoregressive serving.

The vLLM-style capability (no reference counterpart — Ray pairs with
external engines for this), designed static-shape for XLA/TPU instead of
paged dynamic memory:

- ONE static KV cache [L, max_slots, max_len, hkv, hd]; a request
  occupies a SLOT for its lifetime. No paging, no dynamic shapes — the
  compiled programs never change as requests come and go.
- Admission is a per-request prefill that scatters the prompt's KV into
  the free slot (`dynamic_update_slice` on the slot axis) and returns
  the first generated token.
- Every engine tick is ONE compiled launch decoding the ACTIVE slots
  together: the per-slot absolute position rides a vector, handled by
  ``vmap``-ing the single-row cached forward (per-row rope positions,
  per-row cache writes become scatters, causal masking by each row's own
  position). Occupied rows are gathered into a {1, max_slots} bucket
  (a lone straggler pays one row, not the whole engine) and a
  ``lax.scan`` fuses K decode
  steps per launch (dispatch overhead amortized K-fold — the decode-side
  ``make_multi_step``). Stale KV in freed slots is never observed: the
  next admission prefills the slot from position 0.
- Greedy decoding — each request's output is EXACTLY
  ``generate.generate(...)`` on its own prompt, regardless of what else
  shares the batch (the test asserts this token-for-token).

Prefill compiles once per (batch=1, prompt_len) via the module's lru
cache; production use would bucket prompt lengths — admission cost, not
a steady-state one (the decode step is length-independent).
"""

from __future__ import annotations

import functools
import itertools
import queue as _queue
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models import generate as G
from ray_tpu.models import llama

Params = Dict[str, Any]


class _Request:
    __slots__ = ("req_id", "slot", "remaining", "tokens")

    def __init__(self, req_id: int, slot: int, remaining: int):
        self.req_id = req_id
        self.slot = slot
        self.remaining = remaining
        self.tokens: List[int] = []


class ContinuousBatcher:
    """Slot-based continuous batching engine around one model."""

    def __init__(self, params: Params, cfg: llama.LlamaConfig, *,
                 max_slots: int = 8, max_len: int = 512):
        self.params = params
        self.cfg = cfg
        self.max_slots = max_slots
        self.max_len = max_len
        shape = (cfg.n_layers, max_slots, max_len, cfg.n_kv_heads,
                 cfg.head_dim)
        self._ck = jnp.zeros(shape, cfg.compute_dtype)
        self._cv = jnp.zeros(shape, cfg.compute_dtype)
        self._free: List[int] = list(range(max_slots))
        self._active: Dict[int, _Request] = {}  # slot -> request
        self._cur = np.zeros(max_slots, np.int32)   # token AT pos, per slot
        self._pos = np.zeros(max_slots, np.int32)   # absolute position
        self._ids = itertools.count()

    # -- admission --------------------------------------------------------

    def submit(self, prompt: np.ndarray, max_new_tokens: int) -> int:
        """Admit one request (prompt: int array [S]); returns req_id.
        Raises RuntimeError when no slot is free (caller queues/retries —
        admission control belongs to the serving layer)."""
        return self.submit_ex(prompt, max_new_tokens)[0]

    def submit_ex(self, prompt: np.ndarray,
                  max_new_tokens: int) -> Tuple[int, int, bool]:
        """``submit`` plus the prefill's first token: returns
        (req_id, first_token, done) — the streaming engine needs the
        token the admission itself produced (for a 1-token request the
        slot is already freed and no ``step()`` will ever report it)."""
        if not self._free:
            raise RuntimeError("no free slots")
        s = len(prompt)
        if s + max_new_tokens + 1 > self.max_len:
            raise ValueError(f"prompt {s} + new {max_new_tokens} exceeds "
                             f"max_len {self.max_len}")
        slot = self._free.pop()
        try:
            fn = _compiled_slot_prefill(self.cfg, s, self.max_slots,
                                        self.max_len)
            self._ck, self._cv, first = fn(
                self.params, self._ck, self._cv,
                jnp.asarray(prompt, jnp.int32)[None, :], slot)
        except BaseException:
            # a failed prefill must not leak the slot: callers (the
            # engine's admit loop) catch and continue, and a leaked slot
            # per transient XLA error would silently shrink the engine
            # to zero capacity with no recovery path
            self._free.append(slot)
            raise
        req = _Request(next(self._ids), slot, max_new_tokens)
        first_tok = int(first[0])
        req.tokens.append(first_tok)
        req.remaining -= 1
        self._cur[slot] = first_tok
        self._pos[slot] = s
        done = req.remaining <= 0
        if done:
            self._free.append(slot)
        else:
            self._active[slot] = req
        return req.req_id, first_tok, done

    # -- the engine tick --------------------------------------------------

    def step(self) -> List[Tuple[int, int, bool]]:
        """ONE decode step for every active slot; returns
        [(req_id, token, done)] for requests that produced a token."""
        return [(rid, toks[0], done)
                for rid, toks, done in self.step_many(1)]

    def step_many(self, k: int = 1) -> List[Tuple[int, List[int], bool]]:
        """Up to ``k`` FUSED decode steps for every active slot in ONE
        compiled program; returns [(req_id, tokens, done)].

        Two launch-amortization levers compose here (this runtime's
        measured per-launch overhead is ~ms — the make_multi_step story,
        applied to decode):

        - Bucketed active-slot stepping: occupied slots are gathered,
          stepped, scattered back — a lone straggler on an 8-slot engine
          pays one row, not eight (buckets: {1, max_slots}).
        - K-step fusion: a ``lax.scan`` decodes ``k`` tokens per launch,
          so dispatch overhead is paid once per K tokens instead of per
          token. A request finishing mid-tick just has its surplus
          tokens discarded (its rows compute independently; the freed
          slot's stale KV is overwritten by the next prefill).

        Two programs (lone-row, full-engine) compile per distinct ``k``.
        """
        if not self._active:
            return []
        slots = sorted(self._active)
        n = len(slots)
        # two buckets only — a lone row or the full engine: K-fusion
        # already amortizes dispatch, so finer occupancy buckets buy
        # little compute but each costs a warmup compile (~seconds);
        # the lone-straggler case is the one worth its own program
        bucket = 1 if n == 1 else self.max_slots
        # pad with a repeat of the first active slot: the duplicate
        # rows compute the SAME update from the same inputs, so the
        # duplicate scatter writes identical values (deterministic)
        idx = np.asarray(slots + [slots[0]] * (bucket - n), np.int32)
        fn = _compiled_bucket_scan(self.cfg, bucket, self.max_slots,
                                   self.max_len, k)
        self._ck, self._cv, toks = fn(
            self.params, self._ck, self._cv,
            jnp.asarray(self._cur[idx]), jnp.asarray(self._pos[idx]),
            jnp.asarray(idx))
        toks = np.asarray(toks)  # [k, bucket]
        out = []
        for j, slot in enumerate(slots):
            req = self._active[slot]
            take = min(k, req.remaining)
            mine = [int(t) for t in toks[:take, j]]
            req.tokens.extend(mine)
            req.remaining -= take
            self._cur[slot] = mine[-1]
            self._pos[slot] += take
            done = req.remaining <= 0
            if done:
                del self._active[slot]
                self._free.append(slot)
            out.append((req.req_id, mine, done))
        return out

    @property
    def num_active(self) -> int:
        return len(self._active)

    @property
    def max_remaining(self) -> int:
        return max((r.remaining for r in self._active.values()), default=0)

    def warmup(self, prompt_lens: Tuple[int, ...] = (),
               strides: Tuple[int, ...] = (1,)) -> None:
        """Compile every decode program (the {1, max_slots} buckets
        step_many uses, for each tick stride) and optionally the
        prefills for the given prompt lengths, BEFORE traffic arrives.
        Without this the first request at each new occupancy level pays
        a mid-flight XLA compile that stalls every active stream —
        under Poisson load the stall backlog saturates the slots and
        never recovers. Keep this bucket set in lockstep with
        step_many's choice."""
        cur = jnp.asarray(self._cur)
        pos = jnp.asarray(self._pos)
        for k in sorted(set(strides)):
            for bucket in sorted({1, self.max_slots}):
                fn = _compiled_bucket_scan(self.cfg, bucket, self.max_slots,
                                           self.max_len, int(k))
                idx = jnp.zeros(bucket, jnp.int32)
                np.asarray(fn(self.params, self._ck, self._cv,
                              cur[:bucket], pos[:bucket], idx)[2])
        for s in prompt_lens:
            fn = _compiled_slot_prefill(self.cfg, int(s), self.max_slots,
                                        self.max_len)
            np.asarray(fn(self.params, self._ck, self._cv,
                          jnp.zeros((1, int(s)), jnp.int32), 0)[2])

    def cancel(self, req_id: int) -> bool:
        """Free a request's slot mid-flight (client disconnect). The slot's
        stale KV needs no scrub: the next admission prefills from 0."""
        for slot, req in list(self._active.items()):
            if req.req_id == req_id:
                del self._active[slot]
                self._free.append(slot)
                return True
        return False

    def run_to_completion(self) -> Dict[int, List[int]]:
        """Drain all active requests; returns req_id -> generated tokens
        (convenience for tests/batch jobs; serving calls step())."""
        results: Dict[int, List[int]] = {
            r.req_id: r.tokens for r in self._active.values()}
        while self._active:
            reqs = {r.req_id: r for r in self._active.values()}
            for rid, tok, done in self.step():
                results.setdefault(rid, reqs[rid].tokens)
        return results


_STREAM_END = None  # sentinel a token stream's queue yields when done


class _EngineRequest:
    __slots__ = ("prompt", "max_new_tokens", "out", "on_token", "req_id",
                 "cancelled")

    def __init__(self, prompt: np.ndarray, max_new_tokens: int,
                 on_token: Optional[Callable[[Optional[int]], None]] = None):
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.on_token = on_token
        # at most max_new_tokens items + the end sentinel ever sit here,
        # so an unbounded queue is bounded in practice and the shared
        # engine thread can never block on a slow consumer
        self.out: Optional["_queue.Queue"] = (
            None if on_token is not None else _queue.Queue())
        self.req_id: Optional[int] = None  # assigned at admission
        self.cancelled = False

    def emit(self, tok: Optional[int]) -> None:
        self.emit_many([tok])

    def emit_many(self, toks: List[Optional[int]]) -> None:
        """Hand a tick's token burst to the consumer in ONE callback —
        per-token cross-thread wakeups (call_soon_threadsafe pipe writes)
        were a measurable share of the serve path's token ceiling."""
        if self.on_token is not None:
            try:
                self.on_token(toks)
            except Exception:  # noqa: BLE001 — a consumer callback must
                pass           # never take the shared engine thread down
        else:
            for tok in toks:
                self.out.put(tok)


class ContinuousEngine:
    """The slot-admission loop that makes :class:`ContinuousBatcher` live.

    ONE background thread owns the model: it admits pending requests into
    free slots (per-request prefill) and runs the rowwise decode step
    across all active slots, pushing each token into the submitting
    request's thread-safe queue the moment it is sampled. Serving wraps
    the queue in an async generator, so tokens flow out through the
    replica stream pump / proxy ``_stream_response`` path with per-token
    latency — and admission happens MID-FLIGHT: a request arriving while
    others decode joins the next tick instead of waiting for a batch
    boundary (the continuous-batching property the static ``@serve.batch``
    control lacks).

    ``on_tick(active_slots, max_slots)`` fires after every decode step —
    the serve layer hangs slot-occupancy telemetry on it without this
    module importing serve.
    """

    def __init__(self, params: Params, cfg: llama.LlamaConfig, *,
                 max_slots: int = 8, max_len: int = 512,
                 decode_stride: int = 8,
                 on_tick: Optional[Callable[[int, int], None]] = None,
                 warmup: bool = True):
        self._batcher = ContinuousBatcher(params, cfg, max_slots=max_slots,
                                          max_len=max_len)
        self.decode_stride = max(1, int(decode_stride))
        if warmup:
            # pay every decode-program compile HERE (replica init — the
            # controller's readiness probe covers it) instead of at the
            # first request of each occupancy level
            self._batcher.warmup(
                strides=(1, self.decode_stride) if self.decode_stride > 1
                else (1,))
        self.max_slots = max_slots
        self.max_len = max_len
        self._on_tick = on_tick
        self._pending: "deque[_EngineRequest]" = deque()  # rt: guarded-by(_work)
        self._live: Dict[int, _EngineRequest] = {}  # rt: guarded-by(_work)
        self._admitting: Optional[_EngineRequest] = None  # mid-prefill
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._stopped = False
        self._dead: Optional[str] = None  # fatal engine error, if any
        self._steps = 0
        self._admitted = 0
        self._tokens_out = 0
        self._requests_completed = 0  # rt: guarded-by(_work)
        self._weight_swaps = 0  # rt: guarded-by(_work)
        # (new_params, state dict) queued by load_params; applied by the
        # engine thread once every active slot has drained
        self._pending_swap: Optional[Tuple] = None  # rt: guarded-by(_work)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="rt-cb-engine")
        self._thread.start()

    # -- client side ------------------------------------------------------

    def submit_stream(self, prompt: np.ndarray,
                      max_new_tokens: int) -> "_queue.Queue":
        """Queue one request; returns its token queue (ints, then the
        ``None`` end sentinel). Admission control beyond the pending queue
        belongs to the serving layer (``max_ongoing_requests``)."""
        return self._submit(prompt, max_new_tokens, None).out

    def submit_cb(self, prompt: np.ndarray, max_new_tokens: int,
                  on_token: Callable[[List[Optional[int]]], None]):
        """Callback form: ``on_token(burst)`` fires from the engine
        thread with each tick's token burst (a list of ints; a ``None``
        element marks end-of-stream). Zero consumer threads — an asyncio
        server bridges with ONE ``loop.call_soon_threadsafe`` per burst
        instead of parking an executor thread per stream on a queue (the
        thread-starvation ceiling a 2-core box hits at ~6 streams).
        Returns an opaque handle for :meth:`cancel`."""
        return self._submit(prompt, max_new_tokens, on_token)

    def _submit(self, prompt: np.ndarray, max_new_tokens: int,
                on_token) -> "_EngineRequest":
        s = len(prompt)
        if s + max_new_tokens + 1 > self.max_len:
            raise ValueError(f"prompt {s} + new {max_new_tokens} exceeds "
                             f"max_len {self.max_len}")
        req = _EngineRequest(np.asarray(prompt, np.int32), max_new_tokens,
                             on_token)
        with self._work:
            if self._stopped:
                raise RuntimeError("engine is shut down")
            if self._dead is not None:
                raise RuntimeError(f"engine died: {self._dead}")
            self._pending.append(req)
            self._work.notify()
        return req

    def cancel(self, handle) -> None:
        """Drop a request (disconnect): pending requests unqueue, active
        ones free their slot on the next tick. The stream still ends
        with the ``None`` sentinel — a consumer that is NOT the
        canceller (a supervisor thread timing the request out) must not
        block on the queue forever. ``handle`` is the queue
        ``submit_stream`` returned or the handle from ``submit_cb``."""
        with self._work:
            for req in list(self._pending):
                if req is handle or req.out is handle:
                    req.cancelled = True
                    self._pending.remove(req)
                    req.emit_many([_STREAM_END])
                    return
            admitting = self._admitting
            if admitting is not None and (admitting is handle
                                          or admitting.out is handle):
                # mid-prefill (the engine thread runs admission outside
                # the lock): flag it — the post-prefill bookkeeping
                # frees the slot and ends the stream
                admitting.cancelled = True
                return
            for req in self._live.values():
                if req is handle or req.out is handle:
                    req.cancelled = True
                    self._work.notify()
                    return

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = {"active": len(self._live),
                   "pending": len(self._pending),
                   "max_slots": self.max_slots,
                   "steps": self._steps,
                   "admitted": self._admitted,
                   "tokens_out": self._tokens_out,
                   # monotonic counters (never reset for the engine's
                   # lifetime): the RLHF bench and `rt serve status`
                   # difference these across polls instead of sampling
                   # instantaneous slot occupancy
                   "tokens_generated": self._tokens_out,
                   "requests_completed": self._requests_completed,
                   "weight_swaps": self._weight_swaps}
            if self._dead is not None:
                out["dead"] = self._dead
            return out

    def load_params(self, params: Params,
                    timeout_s: float = 120.0) -> Dict[str, Any]:
        """Drain-barrier weight swap: queue ``params`` as the engine's
        next weights and block until the engine thread has applied them.

        The swap CANNOT be immediate — every active slot's KV cache was
        prefilled with the old weights, and decoding old-KV rows under
        new weights would produce tokens belonging to neither model. So
        the engine thread (a) stops admitting new requests the moment a
        swap is queued (pending requests stay queued, nothing is
        dropped), (b) decodes the active slots to completion under the
        OLD weights — in-flight streams stay token-exact — and then
        (c) swaps and resumes admission, so every later request runs
        token-exact under the NEW weights. A second ``load_params``
        racing the first simply replaces the queued weights (latest
        wins; both callers unblock when the final swap lands).
        """
        state = {"event": threading.Event(), "applied": False,
                 "error": None}
        t0 = time.perf_counter()
        # commit the leaves to the device HERE, once: shipped weights
        # arrive as numpy arrays, and installing those raw would make
        # every subsequent decode tick re-transfer the full model
        # host-to-device when jit commits its arguments
        params = jax.tree_util.tree_map(jnp.asarray, params)
        with self._work:
            if self._stopped:
                raise RuntimeError("engine is shut down")
            if self._dead is not None:
                raise RuntimeError(f"engine died: {self._dead}")
            prev = self._pending_swap
            self._pending_swap = (params, [state])
            if prev is not None:
                # coalesce: the superseded swap's waiters ride this one
                self._pending_swap[1].extend(prev[1])
            self._work.notify()
        if not state["event"].wait(timeout_s):
            raise TimeoutError(
                f"weight swap did not drain within {timeout_s}s "
                f"(active requests still decoding)")
        if state["error"] is not None:
            raise RuntimeError(f"weight swap failed: {state['error']}")
        return {"drain_s": round(time.perf_counter() - t0, 4),
                "weight_swaps": self._weight_swaps}

    def check_alive(self) -> None:
        """Raise if the engine thread died on a fatal decode error — the
        serve replica's health check calls this so the controller
        replaces a wedged replica instead of routing into a black hole."""
        with self._lock:
            if self._dead is not None:
                raise RuntimeError(f"continuous engine died: {self._dead}")

    def shutdown(self, timeout_s: float = 5.0) -> None:
        with self._work:
            self._stopped = True
            self._work.notify()
        self._thread.join(timeout=timeout_s)

    # -- the engine thread ------------------------------------------------

    def _admit_all(self) -> None:
        """Prefill pending requests into free slots. The jax prefill —
        which can hide a multi-second XLA compile for a new prompt
        length — runs OUTSIDE the lock, so submit/cancel/stats/
        check_alive stay responsive while it compiles (the batcher
        itself is engine-thread-owned and needs no lock); only the
        pending/live bookkeeping is locked."""
        while True:
            with self._work:
                # honor shutdown BEFORE paying another prefill (each can
                # hide a multi-second compile) — the stopped branch in
                # _run ends the remaining streams
                if self._stopped:
                    return
                if self._pending_swap is not None:
                    # drain barrier: a queued weight swap holds admission
                    # (a prefill under the old weights admitted now would
                    # decode under the new ones after the swap)
                    return
                if not (self._pending and self._batcher._free):
                    return
                req = self._pending.popleft()
                if req.cancelled:
                    continue
                self._admitting = req
            try:
                req_id, first_tok, done = self._batcher.submit_ex(
                    req.prompt, req.max_new_tokens)
            except Exception:  # noqa: BLE001 — ONE request's prefill
                # failing (bad shape, transient XLA error) must fail that
                # request, not wedge the shared engine thread
                with self._work:
                    self._admitting = None
                req.emit_many([_STREAM_END])
                continue
            with self._work:
                self._admitting = None
                req.req_id = req_id
                if req.cancelled:
                    # cancelled mid-prefill: free the slot, end the stream
                    if not done:
                        self._batcher.cancel(req_id)
                    req.emit_many([_STREAM_END])
                    continue
                self._admitted += 1
                req.emit_many([first_tok, _STREAM_END] if done
                              else [first_tok])
                self._tokens_out += 1
                if done:
                    self._requests_completed += 1
                else:
                    self._live[req_id] = req

    def _maybe_swap_locked(self) -> None:
        """Apply a queued weight swap once the engine is fully drained
        (no active slots, no prefill in flight). Caller holds _work."""
        if (self._pending_swap is None or self._live
                or self._admitting is not None):
            return
        params, waiters = self._pending_swap
        self._pending_swap = None
        self._batcher.params = params
        self._weight_swaps += 1
        for st in waiters:
            st["applied"] = True
            st["event"].set()

    def _fail_swap_locked(self, reason: str) -> None:
        """Unblock load_params waiters when the engine stops or dies
        before their swap could land. Caller holds _work."""
        if self._pending_swap is None:
            return
        _, waiters = self._pending_swap
        self._pending_swap = None
        for st in waiters:
            st["error"] = reason
            st["event"].set()

    def _run(self) -> None:
        while True:
            with self._work:
                # reap cancellations before admitting into their slots
                for rid in [rid for rid, r in self._live.items()
                            if r.cancelled]:
                    self._batcher.cancel(rid)
                    self._live[rid].emit_many([_STREAM_END])
                    del self._live[rid]
                self._maybe_swap_locked()
            self._admit_all()
            with self._work:
                if self._stopped:
                    self._fail_swap_locked("engine shut down mid-drain")
                    for req in list(self._live.values()):
                        req.emit_many([_STREAM_END])
                    self._live.clear()
                    for req in list(self._pending):
                        req.emit_many([_STREAM_END])
                    self._pending.clear()
                    return
                if not self._live:
                    self._maybe_swap_locked()
                    if self._pending or self._pending_swap is not None:
                        continue  # freshly unblocked work: no idle wait
                    self._work.wait(timeout=0.5)
                    continue
            # decode OUTSIDE the lock: submit/cancel stay responsive
            # while the step runs (the jax call is the long pole).
            # Tick stride: fuse decode_stride steps per launch while any
            # active request still wants that many; drop to single steps
            # for the stragglers' tail so no request overruns its budget
            # by a whole stride of discarded work.
            k = (self.decode_stride
                 if self._batcher.max_remaining >= self.decode_stride
                 else 1)
            try:
                emitted = self._batcher.step_many(k)
            except Exception as e:  # noqa: BLE001 — a failed decode step
                # poisons the shared cache state: end every stream NOW
                # (clients see truncation, not a hang) and mark the
                # engine dead so the replica health check fails and the
                # controller replaces the replica
                with self._work:
                    self._dead = f"{type(e).__name__}: {e}"[:300]
                    self._fail_swap_locked(self._dead)
                    for req in list(self._live.values()):
                        req.emit_many([_STREAM_END])
                    self._live.clear()
                    for req in list(self._pending):
                        req.emit_many([_STREAM_END])
                    self._pending.clear()
                return
            with self._work:
                self._steps += 1
                for rid, toks, done in emitted:
                    req = self._live.get(rid)
                    if req is None:
                        continue  # cancelled between step and dispatch
                    burst = [int(t) for t in toks]
                    self._tokens_out += len(burst)
                    if done:
                        burst.append(_STREAM_END)
                        del self._live[rid]
                        self._requests_completed += 1
                    req.emit_many(burst)
                tick, cap = len(self._live), self.max_slots
            if self._on_tick is not None:
                try:
                    self._on_tick(tick, cap)
                except Exception:  # noqa: BLE001 — telemetry only
                    pass


@functools.lru_cache(maxsize=64)
def _compiled_slot_prefill(cfg, s: int, max_slots: int, max_len: int):
    """Prefill ONE prompt into ONE slot of the shared cache; returns the
    updated cache and the first greedy token."""

    @jax.jit
    def run(params, ck, cv, prompt, slot):
        row = {"k": jnp.zeros((cfg.n_layers, 1, max_len, cfg.n_kv_heads,
                               cfg.head_dim), cfg.compute_dtype),
               "v": jnp.zeros((cfg.n_layers, 1, max_len, cfg.n_kv_heads,
                               cfg.head_dim), cfg.compute_dtype)}
        logits, row = G._forward_with_cache(params, prompt, cfg, row, 0)
        first = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
        ck = jax.lax.dynamic_update_slice(ck, row["k"], (0, slot, 0, 0, 0))
        cv = jax.lax.dynamic_update_slice(cv, row["v"], (0, slot, 0, 0, 0))
        return ck, cv, first

    return run


def _one_row_step(cfg):
    """The single-row cached decode body shared by the full-engine and
    bucketed step programs: per-row rope, per-row cache scatter, per-row
    causal masking."""

    def one_row(params, ck_row, cv_row, tok, pos):
        cache = {"k": ck_row[:, None], "v": cv_row[:, None]}
        logits, cache = G._forward_with_cache(
            params, tok[None, None], cfg, cache, pos)
        nxt = jnp.argmax(logits[0, -1, :]).astype(jnp.int32)
        return cache["k"][:, 0], cache["v"][:, 0], nxt

    return one_row


@functools.lru_cache(maxsize=128)
def _compiled_bucket_scan(cfg, bucket: int, max_slots: int, max_len: int,
                          k: int):
    """``k`` fused decode steps for ``bucket`` ACTIVE slots out of
    ``max_slots``: gather the occupied rows, ``lax.scan`` the vmapped
    single-row forward ``k`` times, scatter the updated KV back, return
    the [k, bucket] token block. One launch per K tokens per occupancy
    bucket — the decode-side make_multi_step."""
    one_row = _one_row_step(cfg)

    @jax.jit
    def run(params, ck, cv, cur, pos, idx):
        ck_rows = ck.swapaxes(0, 1)[idx]  # [bucket, L, T, hkv, hd]
        cv_rows = cv.swapaxes(0, 1)[idx]

        def body(carry, _):
            ck_r, cv_r, cur, pos = carry
            ck_r, cv_r, nxt = jax.vmap(
                one_row, in_axes=(None, 0, 0, 0, 0))(
                params, ck_r, cv_r, cur, pos)
            return (ck_r, cv_r, nxt, pos + 1), nxt

        (ck_rows, cv_rows, _, _), toks = jax.lax.scan(
            body, (ck_rows, cv_rows, cur, pos), None, length=k)
        ck = ck.swapaxes(0, 1).at[idx].set(ck_rows).swapaxes(0, 1)
        cv = cv.swapaxes(0, 1).at[idx].set(cv_rows).swapaxes(0, 1)
        return ck, cv, toks  # [k, bucket]

    return run
