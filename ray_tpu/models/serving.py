"""Continuous batching for autoregressive serving.

The vLLM-style capability (no reference counterpart — Ray pairs with
external engines for this), designed static-shape for XLA/TPU instead of
paged dynamic memory:

- ONE static KV cache [L, max_slots, max_len, hkv, hd]; a request
  occupies a SLOT for its lifetime. No paging, no dynamic shapes — the
  compiled programs never change as requests come and go.
- Admission is a per-request prefill that scatters the prompt's KV into
  the free slot (`dynamic_update_slice` on the slot axis) and returns
  the first generated token.
- Every engine tick is ONE compiled launch decoding the ACTIVE slots
  together: the per-slot absolute position rides a vector, handled by
  ``vmap``-ing the single-row cached forward (per-row rope positions,
  per-row cache writes become scatters, causal masking by each row's own
  position). Occupied rows are gathered into a {1, max_slots} bucket
  (a lone straggler pays one row, not the whole engine) and a
  ``lax.scan`` fuses K decode
  steps per launch (dispatch overhead amortized K-fold — the decode-side
  ``make_multi_step``). Stale KV in freed slots is never observed: the
  next admission prefills the slot from position 0.
- Greedy decoding — each request's output is EXACTLY
  ``generate.generate(...)`` on its own prompt, regardless of what else
  shares the batch (the test asserts this token-for-token).

Prefill compiles once per (batch=1, prompt_len) via the module's lru
cache; production use would bucket prompt lengths — admission cost, not
a steady-state one (the decode step is length-independent).
"""

from __future__ import annotations

import functools
import itertools
import os
import queue as _queue
import threading
import time
import weakref
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models import generate as G
from ray_tpu.models import llama
from ray_tpu.util import engine_recorder as _rec
from ray_tpu.util import prefix_hash as PH

Params = Dict[str, Any]


# ---------------------------------------------------------------------------
# Prefix/KV-cache reuse (ROADMAP item 4): retain completed slots' KV pages,
# admit shared-prefix requests by restoring them so prefill runs only on
# the uncached suffix.
# ---------------------------------------------------------------------------


class _PrefixEntry:
    __slots__ = ("key", "length", "k", "v", "nbytes", "chunk_keys",
                 "chunk_digests", "created_at")

    def __init__(self, key: bytes, length: int, k: np.ndarray, v: np.ndarray,
                 chunk_keys: List[bytes], chunk_digests: List[str]):
        self.key = key
        self.length = length
        self.k = k
        self.v = v
        self.nbytes = int(k.nbytes + v.nbytes)
        self.chunk_keys = chunk_keys
        self.chunk_digests = chunk_digests
        self.created_at = time.time()


# live caches in this process, for `rt memory` (util/memory.py reads this
# registry for the local view; remote replicas publish @memkv/ snapshots)
_kv_registry_lock = threading.Lock()
_kv_registry: "weakref.WeakSet" = weakref.WeakSet()  # rt: guarded-by(_kv_registry_lock)


def live_kv_cache_stats() -> List[Dict[str, Any]]:
    """Stats of every live PrefixKVCache in this process (memory plane)."""
    with _kv_registry_lock:
        caches = list(_kv_registry)
    return [c.stats() for c in caches]


class PrefixKVCache:
    """Bytes-budgeted LRU of chunk-aligned token-prefix KV pages.

    Pages are host numpy copies ``[L, c, hkv, hd]`` of a slot row's first
    ``c`` positions, keyed by the EXACT token bytes of the prefix (no
    hash-collision risk; equality is byte equality). One entry of length
    ``n`` serves every chunk-aligned prefix ``c <= n`` through the chunk
    index, so a multi-turn session's growing context is one entry, not a
    ladder of copies. Eviction is LRU by entry under a bytes budget
    (``RT_KV_CACHE_BYTES`` default when unset); a weight swap must
    :meth:`clear` the whole cache — every page was computed under the old
    weights and would silently corrupt post-swap prefills.

    Thread-safe: the engine thread mutates, stats/digest readers come
    from replica RPC threads.
    """

    def __init__(self, *, chunk: Optional[int] = None,
                 max_bytes: Optional[int] = None, label: str = ""):
        self.chunk = int(chunk or PH.chunk_size())
        if max_bytes is None:
            max_bytes = int(os.environ.get("RT_KV_CACHE_BYTES",
                                           str(256 * 1024 * 1024)))
        self.max_bytes = int(max_bytes)
        self.label = label
        self._lock = threading.Lock()
        # full-prefix key -> entry, in LRU order (oldest first)
        self._entries: "OrderedDict[bytes, _PrefixEntry]" = \
            OrderedDict()  # rt: guarded-by(_lock)
        # chunk-aligned prefix key -> full key of an entry covering it
        self._index: Dict[bytes, bytes] = {}  # rt: guarded-by(_lock)
        self._bytes = 0  # rt: guarded-by(_lock)
        self._hits = 0  # rt: guarded-by(_lock)
        self._misses = 0  # rt: guarded-by(_lock)
        self._evictions = 0  # rt: guarded-by(_lock)
        self._inserts = 0  # rt: guarded-by(_lock)
        self._invalidations = 0  # rt: guarded-by(_lock)
        self._hit_tokens = 0  # rt: guarded-by(_lock)
        with _kv_registry_lock:
            _kv_registry.add(self)

    def aligned(self, n: int) -> int:
        return PH.aligned_len(n, self.chunk)

    def lookup(self, tokens: np.ndarray
               ) -> Optional[Tuple[int, np.ndarray, np.ndarray]]:
        """Longest cached QUANTIZED prefix of ``tokens``:
        ``(c, k_pages[L, c, hkv, hd], v_pages)`` or None. ``c`` is capped
        at ``len(tokens) - 1`` — admission always prefills at least one
        suffix token (the first generated token comes from the last
        prompt position's logits) — and the probe ladder is GEOMETRIC
        (power-of-two multiples of the chunk): the warm prefill compiles
        one XLA program per (cached, suffix) shape on the engine thread,
        where a mid-serve compile stalls every live stream, so restore
        lengths are quantized to bound the program set at O(log) per
        prompt length instead of one per chunk multiple."""
        cmax = self.aligned(len(tokens) - 1)
        if cmax < self.chunk:
            return None
        # largest power-of-two multiple of chunk <= cmax
        c = self.chunk * (1 << ((cmax // self.chunk).bit_length() - 1))
        buf = PH.token_key(tokens, c)  # pack once, slice per length
        with self._lock:
            while c >= self.chunk:
                key = buf[:PH.TOKEN_WIDTH * c]
                fk = self._index.get(key)
                if fk is None:
                    c //= 2
                    continue
                e = self._entries.get(fk)
                if e is None or e.length < c or not e.key.startswith(key):
                    self._index.pop(key, None)  # stale index row
                    c //= 2
                    continue
                self._entries.move_to_end(fk)
                self._hits += 1
                self._hit_tokens += c
                return (c, e.k[:, :c], e.v[:, :c])
            self._misses += 1
        return None

    def cached_len(self, tokens: np.ndarray) -> int:
        """Longest cached aligned prefix length WITHOUT touching hit/miss
        counters or LRU order (capture-skip probe)."""
        cmax = self.aligned(len(tokens))
        if cmax < self.chunk:
            return 0
        buf = PH.token_key(tokens, cmax)
        with self._lock:
            for c in range(cmax, 0, -self.chunk):
                fk = self._index.get(buf[:PH.TOKEN_WIDTH * c])
                if fk is None:
                    continue
                e = self._entries.get(fk)
                if e is not None and e.length >= c:
                    return c
        return 0

    def insert(self, tokens: np.ndarray, k_pages: np.ndarray,
               v_pages: np.ndarray) -> bool:
        """Retain ``tokens``' KV pages (length must be chunk-aligned).
        Returns False when already resident or larger than the budget."""
        n = len(tokens)
        key = PH.token_key(tokens, n)
        nbytes = int(k_pages.nbytes + v_pages.nbytes)
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                return False
            if nbytes > self.max_bytes:
                return False
            chunk_keys = [key[:PH.TOKEN_WIDTH * c]
                          for c in range(self.chunk, n + 1, self.chunk)]
            chunk_digests = PH.chunked_digests(key, self.chunk)
            # coalesce: an older entry that IS a prefix of this one is now
            # fully covered — drop it, or a growing session would retain a
            # ladder of duplicate unreachable pages against the budget
            ck_set = set(chunk_keys)
            for fk in [fk for fk in self._entries if fk in ck_set]:
                covered = self._entries.pop(fk)
                self._bytes -= covered.nbytes
            e = _PrefixEntry(key, n, k_pages, v_pages, chunk_keys,
                             chunk_digests)
            self._entries[key] = e
            self._bytes += nbytes
            self._inserts += 1
            for ck in chunk_keys:
                self._index[ck] = key  # newest entry serves the prefix
            while self._bytes > self.max_bytes and len(self._entries) > 1:
                self._evict_one_locked()
            if self._bytes > self.max_bytes:  # lone oversized survivor
                self._evict_one_locked()
                return False
        return True

    def _evict_one_locked(self) -> None:
        _, old = self._entries.popitem(last=False)
        self._bytes -= old.nbytes
        self._evictions += 1
        for ck in old.chunk_keys:
            if self._index.get(ck) != old.key:
                continue
            # repoint to a surviving covering entry (sessions that share
            # only a short prefix overlap on its chunk rows) — deleting
            # outright would stop resident entries serving those hits.
            # token_key is fixed-width per token, so byte-prefix equality
            # IS token-prefix equality.
            for fk in reversed(self._entries):  # MRU first
                if fk.startswith(ck):
                    self._index[ck] = fk
                    break
            else:
                del self._index[ck]

    def clear(self) -> int:
        """Weight-swap invalidation: every page was computed under the
        old weights — poisoned, drop them all. Returns pages dropped."""
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self._index.clear()
            self._bytes = 0
            self._invalidations += n
        return n

    def digests(self, limit: int = 2 * PH.MAX_PROBE_CHUNKS) -> List[str]:
        """Chunk digests of resident entries (residency report for
        cache-affinity routing), bounded. INTERLEAVED round-robin across
        entries in MRU order, longest-prefix-first within each — one
        long entry (64 chunks fills the whole report) must not hide
        every other resident context from the router; the router scores
        by set membership, so coverage beats order."""
        per_entry: List[List[str]] = []
        with self._lock:
            # this runs on EVERY handle_request reply: bound the work
            # under the lock to O(limit^2) worst case — at most `limit`
            # MRU entries, at most `limit` digests each (reverse slice,
            # not a whole-list copy)
            for e in reversed(self._entries.values()):
                if len(per_entry) >= limit:
                    break
                per_entry.append(e.chunk_digests[:-limit - 1:-1])
        out: List[str] = []
        for i in range(max((len(d) for d in per_entry), default=0)):
            for d in per_entry:
                if i < len(d):
                    out.append(d[i])
                    if len(out) >= limit:
                        return out
        return out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"label": self.label, "chunk": self.chunk,
                    "bytes": self._bytes, "max_bytes": self.max_bytes,
                    "pages": len(self._entries),
                    "hits": self._hits, "misses": self._misses,
                    "evictions": self._evictions, "inserts": self._inserts,
                    "invalidations": self._invalidations,
                    "hit_tokens": self._hit_tokens}


class _Request:
    __slots__ = ("req_id", "slot", "remaining", "tokens", "prompt")

    def __init__(self, req_id: int, slot: int, remaining: int,
                 prompt: Optional[np.ndarray] = None):
        self.req_id = req_id
        self.slot = slot
        self.remaining = remaining
        self.tokens: List[int] = []
        self.prompt = prompt


class ContinuousBatcher:
    """Slot-based continuous batching engine around one model."""

    def __init__(self, params: Params, cfg: llama.LlamaConfig, *,
                 max_slots: int = 8, max_len: int = 512,
                 prefix_cache: Optional[PrefixKVCache] = None,
                 sampling: bool = False):
        self.params = params
        self.cfg = cfg
        self.max_slots = max_slots
        self.max_len = max_len
        shape = (cfg.n_layers, max_slots, max_len, cfg.n_kv_heads,
                 cfg.head_dim)
        self._ck = jnp.zeros(shape, cfg.compute_dtype)
        self._cv = jnp.zeros(shape, cfg.compute_dtype)
        self._free: List[int] = list(range(max_slots))
        self._active: Dict[int, _Request] = {}  # slot -> request
        self._cur = np.zeros(max_slots, np.int32)   # token AT pos, per slot
        self._pos = np.zeros(max_slots, np.int32)   # absolute position
        self._ids = itertools.count()
        # prefix/KV reuse: retained pages of completed/cancelled slots
        self.prefix_cache = prefix_cache
        # sampling decode: per-slot temperature / top-k / PRNG-key chain.
        # Built into the compiled programs only when enabled — a greedy
        # engine compiles the exact PR 9 programs.
        self.sampling = bool(sampling)
        self._temp = np.zeros(max_slots, np.float32)
        self._topk = np.zeros(max_slots, np.int32)
        self._keys = np.zeros((max_slots, 2), np.uint32)
        # set by every submit_ex: admission telemetry the engine reads
        # (cached_tokens rides the request span; TTFT-collapse evidence;
        # kv_restore_s/prefill_s feed the flight recorder's tick phases)
        self.last_admission: Dict[str, Any] = {}

    # -- admission --------------------------------------------------------

    def submit(self, prompt: np.ndarray, max_new_tokens: int, *,
               temperature: float = 0.0, top_k: int = 0,
               seed: int = 0) -> int:
        """Admit one request (prompt: int array [S]); returns req_id.
        Raises RuntimeError when no slot is free (caller queues/retries —
        admission control belongs to the serving layer)."""
        return self.submit_ex(prompt, max_new_tokens,
                              temperature=temperature, top_k=top_k,
                              seed=seed)[0]

    def submit_ex(self, prompt: np.ndarray, max_new_tokens: int, *,
                  temperature: float = 0.0, top_k: int = 0,
                  seed: int = 0) -> Tuple[int, int, bool]:
        """``submit`` plus the prefill's first token: returns
        (req_id, first_token, done) — the streaming engine needs the
        token the admission itself produced (for a 1-token request the
        slot is already freed and no ``step()`` will ever report it).

        With a prefix cache attached, admission restores the longest
        cached chunk-aligned prefix into the slot and prefills ONLY the
        uncached suffix — the TTFT-collapse path. The restored pages were
        produced by the identical per-position math (K/V at position i
        depends only on tokens <= i and every op is row-independent), so
        warm output is token-exact vs a cold prefill (asserted in
        tests/test_zz_kv_cache.py)."""
        if not self._free:
            raise RuntimeError("no free slots")
        s = len(prompt)
        if s + max_new_tokens + 1 > self.max_len:
            raise ValueError(f"prompt {s} + new {max_new_tokens} exceeds "
                             f"max_len {self.max_len}")
        if (temperature > 0 or top_k > 0) and not self.sampling:
            raise ValueError(
                "sampling request on a greedy engine: construct the "
                "batcher/engine with sampling=True")
        slot = self._free.pop()
        prompt_arr = np.asarray(prompt, np.int32)
        cached = 0
        t_kv0 = time.perf_counter()
        hit = (self.prefix_cache.lookup(prompt_arr)
               if self.prefix_cache is not None else None)
        kv_restore_s = 0.0
        try:
            if hit is not None:
                cached, pk, pv = hit
                fn = _compiled_cached_prefill(
                    self.cfg, cached, s - cached, self.max_slots,
                    self.max_len, self.sampling)
                args = (self.params, self._ck, self._cv,
                        jnp.asarray(pk), jnp.asarray(pv),
                        jnp.asarray(prompt_arr[cached:])[None, :], slot)
                # warm admission's restore cost: the lookup + uploading
                # the retained pages (the compiled call scatters them)
                kv_restore_s = time.perf_counter() - t_kv0
            else:
                fn = _compiled_slot_prefill(self.cfg, s, self.max_slots,
                                            self.max_len, self.sampling)
                args = (self.params, self._ck, self._cv,
                        jnp.asarray(prompt_arr)[None, :], slot)
            t_pf0 = time.perf_counter()
            if self.sampling:
                key0 = jnp.asarray(
                    np.asarray(jax.random.PRNGKey(int(seed)), np.uint32))
                self._ck, self._cv, first, new_key = fn(
                    *args, jnp.float32(temperature), jnp.int32(top_k),
                    key0)
            else:
                self._ck, self._cv, first = fn(*args)
            prefill_s = time.perf_counter() - t_pf0
        except BaseException:
            # a failed prefill must not leak the slot: callers (the
            # engine's admit loop) catch and continue, and a leaked slot
            # per transient XLA error would silently shrink the engine
            # to zero capacity with no recovery path
            self._free.append(slot)
            raise
        req = _Request(next(self._ids), slot, max_new_tokens, prompt_arr)
        first_tok = int(first[0])
        req.tokens.append(first_tok)
        req.remaining -= 1
        self._cur[slot] = first_tok
        self._pos[slot] = s
        if self.sampling:
            self._temp[slot] = temperature
            self._topk[slot] = top_k
            self._keys[slot] = np.asarray(new_key)
        self.last_admission = {"cached_tokens": cached, "prompt_tokens": s,
                               "slot": slot, "kv_restore_s": kv_restore_s,
                               "prefill_s": prefill_s}
        done = req.remaining <= 0
        if done:
            self._capture(slot, req)
            self._free.append(slot)
        else:
            self._active[slot] = req
        return req.req_id, first_tok, done

    def _capture(self, slot: int, req: _Request) -> None:
        """Retain the freed slot's KV pages: the valid span is
        ``[0, pos)`` — prompt plus the generated tokens whose KV a decode
        step actually wrote (the final emitted token's KV is only written
        by the step that would produce its successor). Skipped when the
        aligned prefix is already resident (the common warm-hit case —
        re-capturing the shared system prompt per request would be pure
        copy overhead)."""
        cache = self.prefix_cache
        if cache is None or req.prompt is None:
            return
        pos = int(self._pos[slot])
        cap = cache.aligned(min(pos, self.max_len))
        if cap < cache.chunk:
            return
        gen_used = max(0, pos - len(req.prompt))
        tokens = req.prompt
        if gen_used:
            tokens = np.concatenate(
                [req.prompt, np.asarray(req.tokens[:gen_used], np.int32)])
        tokens = tokens[:cap]
        if cache.cached_len(tokens) >= cap:
            return
        # one gather per aligned length (bounded program count): host
        # copies so retained pages survive slot reuse and weight swaps
        k = np.asarray(self._ck[:, slot, :cap])
        v = np.asarray(self._cv[:, slot, :cap])
        cache.insert(tokens, k, v)

    # -- the engine tick --------------------------------------------------

    def step(self) -> List[Tuple[int, int, bool]]:
        """ONE decode step for every active slot; returns
        [(req_id, token, done)] for requests that produced a token."""
        return [(rid, toks[0], done)
                for rid, toks, done in self.step_many(1)]

    def step_many(self, k: int = 1) -> List[Tuple[int, List[int], bool]]:
        """Up to ``k`` FUSED decode steps for every active slot in ONE
        compiled program; returns [(req_id, tokens, done)].

        Two launch-amortization levers compose here (this runtime's
        measured per-launch overhead is ~ms — the make_multi_step story,
        applied to decode):

        - Bucketed active-slot stepping: occupied slots are gathered,
          stepped, scattered back — a lone straggler on an 8-slot engine
          pays one row, not eight (buckets: {1, max_slots}).
        - K-step fusion: a ``lax.scan`` decodes ``k`` tokens per launch,
          so dispatch overhead is paid once per K tokens instead of per
          token. A request finishing mid-tick just has its surplus
          tokens discarded (its rows compute independently; the freed
          slot's stale KV is overwritten by the next prefill).

        Two programs (lone-row, full-engine) compile per distinct ``k``.
        """
        if not self._active:
            return []
        slots = sorted(self._active)
        n = len(slots)
        # two buckets only — a lone row or the full engine: K-fusion
        # already amortizes dispatch, so finer occupancy buckets buy
        # little compute but each costs a warmup compile (~seconds);
        # the lone-straggler case is the one worth its own program
        bucket = 1 if n == 1 else self.max_slots
        # pad with a repeat of the first active slot: the duplicate
        # rows compute the SAME update from the same inputs, so the
        # duplicate scatter writes identical values (deterministic)
        idx = np.asarray(slots + [slots[0]] * (bucket - n), np.int32)
        fn = _compiled_bucket_scan(self.cfg, bucket, self.max_slots,
                                   self.max_len, k, self.sampling)
        if self.sampling:
            self._ck, self._cv, toks, new_keys = fn(
                self.params, self._ck, self._cv,
                jnp.asarray(self._cur[idx]), jnp.asarray(self._pos[idx]),
                jnp.asarray(idx), jnp.asarray(self._temp[idx]),
                jnp.asarray(self._topk[idx]), jnp.asarray(self._keys[idx]))
            # duplicate padding rows carry the same key and compute the
            # same split chain, so the repeated write is identical
            self._keys[idx] = np.asarray(new_keys)
        else:
            self._ck, self._cv, toks = fn(
                self.params, self._ck, self._cv,
                jnp.asarray(self._cur[idx]), jnp.asarray(self._pos[idx]),
                jnp.asarray(idx))
        toks = np.asarray(toks)  # [k, bucket]
        out = []
        for j, slot in enumerate(slots):
            req = self._active[slot]
            take = min(k, req.remaining)
            mine = [int(t) for t in toks[:take, j]]
            req.tokens.extend(mine)
            req.remaining -= take
            self._cur[slot] = mine[-1]
            self._pos[slot] += take
            done = req.remaining <= 0
            if done:
                self._capture(slot, req)
                del self._active[slot]
                self._free.append(slot)
            out.append((req.req_id, mine, done))
        return out

    @property
    def num_active(self) -> int:
        return len(self._active)

    @property
    def max_remaining(self) -> int:
        return max((r.remaining for r in self._active.values()), default=0)

    def warmup(self, prompt_lens: Tuple[int, ...] = (),
               strides: Tuple[int, ...] = (1,)) -> None:
        """Compile every decode program (the {1, max_slots} buckets
        step_many uses, for each tick stride) and optionally the
        prefills for the given prompt lengths, BEFORE traffic arrives.
        Without this the first request at each new occupancy level pays
        a mid-flight XLA compile that stalls every active stream —
        under Poisson load the stall backlog saturates the slots and
        never recovers. Keep this bucket set in lockstep with
        step_many's choice."""
        cur = jnp.asarray(self._cur)
        pos = jnp.asarray(self._pos)
        for k in sorted(set(strides)):
            for bucket in sorted({1, self.max_slots}):
                fn = _compiled_bucket_scan(self.cfg, bucket, self.max_slots,
                                           self.max_len, int(k),
                                           self.sampling)
                idx = jnp.zeros(bucket, jnp.int32)
                args = (self.params, self._ck, self._cv,
                        cur[:bucket], pos[:bucket], idx)
                if self.sampling:
                    args += (jnp.asarray(self._temp[:bucket]),
                             jnp.asarray(self._topk[:bucket]),
                             jnp.asarray(self._keys[:bucket]))
                np.asarray(fn(*args)[2])
        for s in prompt_lens:
            fn = _compiled_slot_prefill(self.cfg, int(s), self.max_slots,
                                        self.max_len, self.sampling)
            args = (self.params, self._ck, self._cv,
                    jnp.zeros((1, int(s)), jnp.int32), 0)
            if self.sampling:
                args += (jnp.float32(0.0), jnp.int32(0),
                         jnp.asarray(self._keys[0]))
            np.asarray(fn(*args)[2])

    def cancel(self, req_id: int) -> bool:
        """Free a request's slot mid-flight (client disconnect). The slot's
        stale KV needs no scrub: the next admission prefills from 0. The
        written span is still retained in the prefix cache — a dropped
        multi-turn session's context stays warm for its next turn."""
        for slot, req in list(self._active.items()):
            if req.req_id == req_id:
                self._capture(slot, req)
                del self._active[slot]
                self._free.append(slot)
                return True
        return False

    def run_to_completion(self) -> Dict[int, List[int]]:
        """Drain all active requests; returns req_id -> generated tokens
        (convenience for tests/batch jobs; serving calls step())."""
        results: Dict[int, List[int]] = {
            r.req_id: r.tokens for r in self._active.values()}
        while self._active:
            reqs = {r.req_id: r for r in self._active.values()}
            for rid, tok, done in self.step():
                results.setdefault(rid, reqs[rid].tokens)
        return results


_STREAM_END = None  # sentinel a token stream's queue yields when done


class _EngineRequest:
    __slots__ = ("prompt", "max_new_tokens", "out", "on_token", "req_id",
                 "cancelled", "temperature", "top_k", "seed",
                 "cached_tokens", "t_submit", "obs_ctx")

    def __init__(self, prompt: np.ndarray, max_new_tokens: int,
                 on_token: Optional[Callable[[Optional[int]], None]] = None,
                 temperature: float = 0.0, top_k: int = 0, seed: int = 0,
                 obs_ctx: Optional[Dict[str, str]] = None):
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.on_token = on_token
        self.temperature = temperature
        self.top_k = top_k
        self.seed = seed
        self.cached_tokens: Optional[int] = None  # set at admission
        self.t_submit = time.time()  # queue-wait starts here
        # ambient serve span context ({request_id, span_id}), when the
        # submitter rode a serve request — the flight recorder parents
        # the engine lifecycle span on it so `rt trace <rid>` descends
        # from proxy/replica into engine phases
        self.obs_ctx = obs_ctx
        # at most max_new_tokens items + the end sentinel ever sit here,
        # so an unbounded queue is bounded in practice and the shared
        # engine thread can never block on a slow consumer
        self.out: Optional["_queue.Queue"] = (
            None if on_token is not None else _queue.Queue())
        self.req_id: Optional[int] = None  # assigned at admission
        self.cancelled = False

    def emit(self, tok: Optional[int]) -> None:
        self.emit_many([tok])

    def emit_many(self, toks: List[Optional[int]]) -> None:
        """Hand a tick's token burst to the consumer in ONE callback —
        per-token cross-thread wakeups (call_soon_threadsafe pipe writes)
        were a measurable share of the serve path's token ceiling."""
        if self.on_token is not None:
            try:
                self.on_token(toks)
            except Exception:  # noqa: BLE001 — a consumer callback must
                pass           # never take the shared engine thread down
        else:
            for tok in toks:
                self.out.put(tok)


class ContinuousEngine:
    """The slot-admission loop that makes :class:`ContinuousBatcher` live.

    ONE background thread owns the model: it admits pending requests into
    free slots (per-request prefill) and runs the rowwise decode step
    across all active slots, pushing each token into the submitting
    request's thread-safe queue the moment it is sampled. Serving wraps
    the queue in an async generator, so tokens flow out through the
    replica stream pump / proxy ``_stream_response`` path with per-token
    latency — and admission happens MID-FLIGHT: a request arriving while
    others decode joins the next tick instead of waiting for a batch
    boundary (the continuous-batching property the static ``@serve.batch``
    control lacks).

    ``on_tick(active_slots, max_slots)`` fires after every decode step —
    the serve layer hangs slot-occupancy telemetry on it without this
    module importing serve.
    """

    def __init__(self, params: Params, cfg: llama.LlamaConfig, *,
                 max_slots: int = 8, max_len: int = 512,
                 decode_stride: int = 8,
                 on_tick: Optional[Callable[[int, int], None]] = None,
                 warmup: bool = True,
                 kv_cache_bytes: Optional[int] = None,
                 kv_label: str = "", sampling: bool = False):
        # kv_cache_bytes > 0 attaches the prefix/KV reuse plane (retained
        # pages budgeted in bytes, LRU-evicted, weight-swap-invalidated);
        # 0 keeps the exact PR 9 cold-prefill engine; None reads
        # RT_KV_CACHE_BYTES (default 0) so bare engines get the
        # documented env knob without the serve layer's explicit sizing.
        # The chunk size is
        # deliberately NOT a per-engine knob: the handle router hashes
        # request prefixes at the global RT_KV_CHUNK granularity, and a
        # drifting engine chunk would silently zero the affinity scores.
        if kv_cache_bytes is None:
            kv_cache_bytes = int(os.environ.get("RT_KV_CACHE_BYTES", "0"))
        cache = (PrefixKVCache(max_bytes=kv_cache_bytes, label=kv_label)
                 if kv_cache_bytes > 0 else None)
        self._batcher = ContinuousBatcher(params, cfg, max_slots=max_slots,
                                          max_len=max_len,
                                          prefix_cache=cache,
                                          sampling=sampling)
        self.decode_stride = max(1, int(decode_stride))
        if warmup:
            # pay every decode-program compile HERE (replica init — the
            # controller's readiness probe covers it) instead of at the
            # first request of each occupancy level
            self._batcher.warmup(
                strides=(1, self.decode_stride) if self.decode_stride > 1
                else (1,))
        self.max_slots = max_slots
        self.max_len = max_len
        self._on_tick = on_tick
        self._pending: "deque[_EngineRequest]" = deque()  # rt: guarded-by(_work)
        self._live: Dict[int, _EngineRequest] = {}  # rt: guarded-by(_work)
        self._admitting: Optional[_EngineRequest] = None  # mid-prefill
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._stopped = False
        self._dead: Optional[str] = None  # fatal engine error, if any
        self._steps = 0
        self._admitted = 0
        self._tokens_out = 0
        self._requests_completed = 0  # rt: guarded-by(_work)
        self._weight_swaps = 0  # rt: guarded-by(_work)
        # (new_params, state dict) queued by load_params; applied by the
        # engine thread once every active slot has drained
        self._pending_swap: Optional[Tuple] = None  # rt: guarded-by(_work)
        # flight recorder: the engine thread stamps tick/request records
        # into its bounded deques; a separate drain thread ships metrics/
        # spans/KV snapshots (NO GCS or metrics I/O on the tick path)
        self._recorder = _rec.EngineRecorder(kv_label or "engine",
                                             max_slots=max_slots)
        # engine-thread-confined tick state (never touched off-thread):
        # end of the previous decode launch (the tick-gap anchor; reset
        # to None when the engine goes idle) and the wall spent applying
        # a weight swap since the last recorded tick
        self._last_decode_end: Optional[float] = None
        self._tick_swap_s = 0.0
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="rt-cb-engine")
        self._thread.start()

    # -- client side ------------------------------------------------------

    def submit_stream(self, prompt: np.ndarray, max_new_tokens: int, *,
                      temperature: float = 0.0, top_k: int = 0,
                      seed: int = 0, obs_ctx: Optional[Dict] = None
                      ) -> "_queue.Queue":
        """Queue one request; returns its token queue (ints, then the
        ``None`` end sentinel). Admission control beyond the pending queue
        belongs to the serving layer (``max_ongoing_requests``).
        ``temperature``/``top_k``/``seed`` select sampled decode (engine
        must be built with ``sampling=True``); the default stays greedy.
        ``obs_ctx`` (a serve {request_id, span_id}) joins the request's
        flight-recorder lifecycle to the serve span tree."""
        return self._submit(prompt, max_new_tokens, None,
                            temperature=temperature, top_k=top_k,
                            seed=seed, obs_ctx=obs_ctx).out

    def submit_cb(self, prompt: np.ndarray, max_new_tokens: int,
                  on_token: Callable[[List[Optional[int]]], None], *,
                  temperature: float = 0.0, top_k: int = 0,
                  seed: int = 0, obs_ctx: Optional[Dict] = None):
        """Callback form: ``on_token(burst)`` fires from the engine
        thread with each tick's token burst (a list of ints; a ``None``
        element marks end-of-stream). Zero consumer threads — an asyncio
        server bridges with ONE ``loop.call_soon_threadsafe`` per burst
        instead of parking an executor thread per stream on a queue (the
        thread-starvation ceiling a 2-core box hits at ~6 streams).
        Returns an opaque handle for :meth:`cancel`."""
        return self._submit(prompt, max_new_tokens, on_token,
                            temperature=temperature, top_k=top_k,
                            seed=seed, obs_ctx=obs_ctx)

    def _submit(self, prompt: np.ndarray, max_new_tokens: int,
                on_token, *, temperature: float = 0.0, top_k: int = 0,
                seed: int = 0,
                obs_ctx: Optional[Dict] = None) -> "_EngineRequest":
        s = len(prompt)
        if s + max_new_tokens + 1 > self.max_len:
            raise ValueError(f"prompt {s} + new {max_new_tokens} exceeds "
                             f"max_len {self.max_len}")
        if (temperature > 0 or top_k > 0) and not self._batcher.sampling:
            raise ValueError("sampling request on a greedy engine: pass "
                             "sampling=True at engine construction")
        req = _EngineRequest(np.asarray(prompt, np.int32), max_new_tokens,
                             on_token, temperature=float(temperature),
                             top_k=int(top_k), seed=int(seed),
                             obs_ctx=obs_ctx)
        with self._work:
            if self._stopped:
                raise RuntimeError("engine is shut down")
            if self._dead is not None:
                raise RuntimeError(f"engine died: {self._dead}")
            self._pending.append(req)
            self._work.notify()
        return req

    def cancel(self, handle) -> None:
        """Drop a request (disconnect): pending requests unqueue, active
        ones free their slot on the next tick. The stream still ends
        with the ``None`` sentinel — a consumer that is NOT the
        canceller (a supervisor thread timing the request out) must not
        block on the queue forever. ``handle`` is the queue
        ``submit_stream`` returned or the handle from ``submit_cb``."""
        with self._work:
            for req in list(self._pending):
                if req is handle or req.out is handle:
                    req.cancelled = True
                    self._pending.remove(req)
                    req.emit_many([_STREAM_END])
                    return
            admitting = self._admitting
            if admitting is not None and (admitting is handle
                                          or admitting.out is handle):
                # mid-prefill (the engine thread runs admission outside
                # the lock): flag it — the post-prefill bookkeeping
                # frees the slot and ends the stream
                admitting.cancelled = True
                return
            for req in self._live.values():
                if req is handle or req.out is handle:
                    req.cancelled = True
                    self._work.notify()
                    return

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = {"active": len(self._live),
                   "pending": len(self._pending),
                   "max_slots": self.max_slots,
                   "steps": self._steps,
                   "admitted": self._admitted,
                   "tokens_out": self._tokens_out,
                   # monotonic counters (never reset for the engine's
                   # lifetime): the RLHF bench and `rt serve status`
                   # difference these across polls instead of sampling
                   # instantaneous slot occupancy
                   "tokens_generated": self._tokens_out,
                   "requests_completed": self._requests_completed,
                   "weight_swaps": self._weight_swaps}
            if self._dead is not None:
                out["dead"] = self._dead
        cache = self._batcher.prefix_cache
        if cache is not None:
            # kv stats ride replica stats_window -> controller win_stats
            # -> `rt serve status` hit-rate column / dashboard Serve tab
            out["kv"] = cache.stats()
        if self._recorder.enabled:
            # flight-recorder rollup (tick phases, tick-gap, SLO
            # attainment, goodput) — computed off the engine lock; rides
            # the same replica stats_window path into `rt serve status`
            out["recorder"] = self._recorder.summary()
        return out

    def kv_stats(self) -> Optional[Dict[str, Any]]:
        """Prefix-cache counters WITHOUT touching the engine lock (the
        cache has its own): the per-tick metric publisher reads this —
        taking ``_work`` there would contend with submit/cancel on every
        decode launch for four numbers the cache already exposes."""
        cache = self._batcher.prefix_cache
        return cache.stats() if cache is not None else None

    def kv_residency(self) -> List[str]:
        """Chunk digests of the prefixes this engine holds warm — the
        replica reports these so the handle router can bias power-of-two
        choice toward the replica whose cache already covers a request's
        prompt (cache-affinity routing)."""
        cache = self._batcher.prefix_cache
        return cache.digests() if cache is not None else []

    def load_params(self, params: Params,
                    timeout_s: float = 120.0) -> Dict[str, Any]:
        """Drain-barrier weight swap: queue ``params`` as the engine's
        next weights and block until the engine thread has applied them.

        The swap CANNOT be immediate — every active slot's KV cache was
        prefilled with the old weights, and decoding old-KV rows under
        new weights would produce tokens belonging to neither model. So
        the engine thread (a) stops admitting new requests the moment a
        swap is queued (pending requests stay queued, nothing is
        dropped), (b) decodes the active slots to completion under the
        OLD weights — in-flight streams stay token-exact — and then
        (c) swaps and resumes admission, so every later request runs
        token-exact under the NEW weights. A second ``load_params``
        racing the first simply replaces the queued weights (latest
        wins; both callers unblock when the final swap lands).
        """
        state = {"event": threading.Event(), "applied": False,
                 "error": None}
        t0 = time.perf_counter()
        # commit the leaves to the device HERE, once: shipped weights
        # arrive as numpy arrays, and installing those raw would make
        # every subsequent decode tick re-transfer the full model
        # host-to-device when jit commits its arguments
        params = jax.tree_util.tree_map(jnp.asarray, params)
        with self._work:
            if self._stopped:
                raise RuntimeError("engine is shut down")
            if self._dead is not None:
                raise RuntimeError(f"engine died: {self._dead}")
            prev = self._pending_swap
            self._pending_swap = (params, [state])
            if prev is not None:
                # coalesce: the superseded swap's waiters ride this one
                self._pending_swap[1].extend(prev[1])
            self._work.notify()
        if not state["event"].wait(timeout_s):
            raise TimeoutError(
                f"weight swap did not drain within {timeout_s}s "
                f"(active requests still decoding)")
        if state["error"] is not None:
            raise RuntimeError(f"weight swap failed: {state['error']}")
        return {"drain_s": round(time.perf_counter() - t0, 4),
                "apply_s": round(state.get("apply_s", 0.0), 6),
                "weight_swaps": self._weight_swaps}

    def check_alive(self) -> None:
        """Raise if the engine thread died on a fatal decode error — the
        serve replica's health check calls this so the controller
        replaces a wedged replica instead of routing into a black hole."""
        with self._lock:
            if self._dead is not None:
                raise RuntimeError(f"continuous engine died: {self._dead}")

    def stopped(self) -> bool:
        """True once the engine was shut down or its thread died — loops
        keyed on the engine's lifetime (the replica's kv-push thread)
        use this as their exit condition."""
        with self._lock:
            return self._stopped or self._dead is not None

    def shutdown(self, timeout_s: float = 5.0) -> None:
        with self._work:
            self._stopped = True
            self._work.notify()
        self._thread.join(timeout=timeout_s)
        # stop the drain thread and drop the @engine/ KV snapshot — the
        # doctor must not grade a dead engine's numbers
        self._recorder.close()

    # -- the engine thread ------------------------------------------------

    def _admit_all(self) -> Dict[str, Any]:
        """Prefill pending requests into free slots. The jax prefill —
        which can hide a multi-second XLA compile for a new prompt
        length — runs OUTSIDE the lock, so submit/cancel/stats/
        check_alive stay responsive while it compiles (the batcher
        itself is engine-thread-owned and needs no lock); only the
        pending/live bookkeeping is locked.

        Returns the tick's admission accounting for the flight recorder:
        {kv_restore, prefill, admitted} — the caller attributes its own
        wall minus these to the ``admission`` phase."""
        adm = {"kv_restore": 0.0, "prefill": 0.0, "admitted": 0}
        while True:
            with self._work:
                # honor shutdown BEFORE paying another prefill (each can
                # hide a multi-second compile) — the stopped branch in
                # _run ends the remaining streams
                if self._stopped:
                    return adm
                if self._pending_swap is not None:
                    # drain barrier: a queued weight swap holds admission
                    # (a prefill under the old weights admitted now would
                    # decode under the new ones after the swap)
                    return adm
                if not (self._pending and self._batcher._free):
                    return adm
                req = self._pending.popleft()
                if req.cancelled:
                    continue
                self._admitting = req
            try:
                req_id, first_tok, done = self._batcher.submit_ex(
                    req.prompt, req.max_new_tokens,
                    temperature=req.temperature, top_k=req.top_k,
                    seed=req.seed)
                la = self._batcher.last_admission
                req.cached_tokens = la.get("cached_tokens", 0)
            except Exception:  # noqa: BLE001 — ONE request's prefill
                # failing (bad shape, transient XLA error) must fail that
                # request, not wedge the shared engine thread
                with self._work:
                    self._admitting = None
                req.emit_many([_STREAM_END])
                continue
            with self._work:
                self._admitting = None
                req.req_id = req_id
                cancelled = req.cancelled
                if cancelled:
                    # cancelled mid-prefill: free the slot, end the stream
                    if not done:
                        self._batcher.cancel(req_id)
                    req.emit_many([_STREAM_END])
                else:
                    self._admitted += 1
                    req.emit_many([first_tok, _STREAM_END] if done
                                  else [first_tok])
                    self._tokens_out += 1
                    if done:
                        self._requests_completed += 1
                    else:
                        self._live[req_id] = req
            # lifecycle record, OUTSIDE the engine lock: admission just
            # produced the first token, so this stamp is the TTFT stamp
            adm["kv_restore"] += la.get("kv_restore_s", 0.0)
            adm["prefill"] += la.get("prefill_s", 0.0)
            adm["admitted"] += 1
            now = time.time()
            self._recorder.request_admitted(
                req_id, t_submit=req.t_submit, t_admit=now,
                prompt_tokens=len(req.prompt),
                cached_tokens=req.cached_tokens or 0,
                prefill_s=la.get("prefill_s", 0.0),
                kv_restore_s=la.get("kv_restore_s", 0.0),
                slot=la.get("slot", -1), obs_ctx=req.obs_ctx)
            if cancelled:
                self._recorder.request_done(req_id, t=now,
                                            state="cancelled")
            elif done:
                self._recorder.request_done(req_id, t=now, state="done")

    def _maybe_swap_locked(self) -> None:
        """Apply a queued weight swap once the engine is fully drained
        (no active slots, no prefill in flight). Caller holds _work."""
        if (self._pending_swap is None or self._live
                or self._admitting is not None):
            return
        t_swap0 = time.perf_counter()
        params, waiters = self._pending_swap
        self._pending_swap = None
        self._batcher.params = params
        if self._batcher.prefix_cache is not None:
            # every retained page was computed under the OLD weights: a
            # post-swap prefill restoring one would emit tokens belonging
            # to neither model — invalidate the whole cache at the swap
            self._batcher.prefix_cache.clear()
        self._weight_swaps += 1
        apply_s = time.perf_counter() - t_swap0
        for st in waiters:
            st["apply_s"] = apply_s
            st["applied"] = True
            st["event"].set()
        # swap-barrier phase: the apply wall (drain time shows up as the
        # preceding ticks' shrinking active counts, not here); consumed
        # by the next record_tick (engine-thread-confined accumulator)
        self._tick_swap_s += apply_s
        self._recorder.record_swap(apply_s)

    def _fail_swap_locked(self, reason: str) -> None:
        """Unblock load_params waiters when the engine stops or dies
        before their swap could land. Caller holds _work."""
        if self._pending_swap is None:
            return
        _, waiters = self._pending_swap
        self._pending_swap = None
        for st in waiters:
            st["error"] = reason
            st["event"].set()

    def _run(self) -> None:
        rec = self._recorder
        while True:
            t_tick0 = time.perf_counter()
            t_wall0 = time.time()
            with self._work:
                # reap cancellations before admitting into their slots
                doomed = [rid for rid, r in self._live.items()
                          if r.cancelled]
                for rid in doomed:
                    self._live[rid].emit_many([_STREAM_END])
                    del self._live[rid]
            # slot free + KV capture OUTSIDE the lock: _capture syncs
            # the device and copies the slot's pages to host — under
            # _work that stall would block every submit/cancel (the
            # batcher itself is engine-thread-confined, like step_many).
            # Captures must land BEFORE the swap check: a swap clears
            # the cache, and a doomed slot's pages are old-weight poison
            # the moment it applies.
            for rid in doomed:
                self._batcher.cancel(rid)
                rec.request_done(rid, t=t_wall0, state="cancelled")
            with self._work:
                self._maybe_swap_locked()
            t_adm0 = time.perf_counter()
            adm = self._admit_all()
            # admission phase = this tick's admission wall minus the
            # batcher-attributed kv-restore/prefill shares (slot
            # bookkeeping, cancel checks, first-token delivery)
            adm_phase = max(0.0, (time.perf_counter() - t_adm0)
                            - adm["kv_restore"] - adm["prefill"])
            with self._work:
                if self._stopped:
                    self._fail_swap_locked("engine shut down mid-drain")
                    for req in list(self._live.values()):
                        req.emit_many([_STREAM_END])
                    self._live.clear()
                    for req in list(self._pending):
                        req.emit_many([_STREAM_END])
                    self._pending.clear()
                    return
                if not self._live:
                    self._maybe_swap_locked()
                    swap_s = self._tick_swap_s
                    self._tick_swap_s = 0.0
                    if adm["admitted"] or swap_s > 0.0:
                        # admission-only tick (every admitted request
                        # finished at its first token, or a swap landed)
                        rec.record_tick(
                            t_start=t_wall0,
                            wall_s=time.perf_counter() - t_tick0,
                            phases={"admission": adm_phase,
                                    "kv_restore": adm["kv_restore"],
                                    "prefill": adm["prefill"],
                                    "swap_barrier": swap_s},
                            active=0, pending=len(self._pending),
                            bucket=0, k=0, tokens=adm["admitted"],
                            admitted=adm["admitted"], gap_s=None)
                    # engine going idle: the next decode launch starts a
                    # fresh gap baseline (an idle engine is not starved)
                    self._last_decode_end = None
                    if self._pending or self._pending_swap is not None:
                        continue  # freshly unblocked work: no idle wait
                    self._work.wait(timeout=0.5)
                    continue
            # decode OUTSIDE the lock: submit/cancel stay responsive
            # while the step runs (the jax call is the long pole).
            # Tick stride: fuse decode_stride steps per launch while any
            # active request still wants that many; drop to single steps
            # for the stragglers' tail so no request overruns its budget
            # by a whole stride of discarded work.
            k = (self.decode_stride
                 if self._batcher.max_remaining >= self.decode_stride
                 else 1)
            n_active = self._batcher.num_active
            bucket = 1 if n_active == 1 else self.max_slots
            t_dec0 = time.perf_counter()
            # tick-gap: decode-launch start minus the previous launch's
            # end, while slots stayed active — THE starvation signal (a
            # long-prompt prefill burst between launches shows up here)
            gap_s = (t_dec0 - self._last_decode_end
                     if self._last_decode_end is not None else None)
            try:
                emitted = self._batcher.step_many(k)
            except Exception as e:  # noqa: BLE001 — a failed decode step
                # poisons the shared cache state: end every stream NOW
                # (clients see truncation, not a hang) and mark the
                # engine dead so the replica health check fails and the
                # controller replaces the replica
                with self._work:
                    self._dead = f"{type(e).__name__}: {e}"[:300]
                    self._fail_swap_locked(self._dead)
                    for req in list(self._live.values()):
                        req.emit_many([_STREAM_END])
                    self._live.clear()
                    for req in list(self._pending):
                        req.emit_many([_STREAM_END])
                    self._pending.clear()
                return
            t_dec1 = time.perf_counter()
            self._last_decode_end = t_dec1
            tick_tokens = adm["admitted"]
            tok_events: List[Tuple[int, int, bool]] = []
            with self._work:
                self._steps += 1
                for rid, toks, done in emitted:
                    req = self._live.get(rid)
                    if req is None:
                        continue  # cancelled between step and dispatch
                    burst = [int(t) for t in toks]
                    self._tokens_out += len(burst)
                    tick_tokens += len(burst)
                    tok_events.append((rid, len(burst), done))
                    if done:
                        burst.append(_STREAM_END)
                        del self._live[rid]
                        self._requests_completed += 1
                    req.emit_many(burst)
                tick, cap = len(self._live), self.max_slots
                pending_n = len(self._pending)
            t_emit1 = time.perf_counter()
            swap_s = self._tick_swap_s
            self._tick_swap_s = 0.0
            now = time.time()
            for rid, nburst, done in tok_events:
                rec.request_tokens(rid, nburst, now, done)
            rec.record_tick(
                t_start=t_wall0, wall_s=t_emit1 - t_tick0,
                phases={"admission": adm_phase,
                        "kv_restore": adm["kv_restore"],
                        "prefill": adm["prefill"],
                        "decode_step": t_dec1 - t_dec0,
                        "token_delivery": t_emit1 - t_dec1,
                        "swap_barrier": swap_s},
                active=n_active, pending=pending_n, bucket=bucket, k=k,
                tokens=tick_tokens, admitted=adm["admitted"], gap_s=gap_s)
            if self._on_tick is not None:
                try:
                    self._on_tick(tick, cap)
                except Exception:  # noqa: BLE001 — telemetry only
                    pass


def _row_sample(logits, temp, top_k, sub):
    """One row's token rule: greedy when ``temp <= 0`` (selected by
    ``where`` so a greedy row in a sampling engine is bit-identical to
    the greedy program), else temperature softmax sampling, optionally
    top-k truncated (``top_k`` is a traced per-row value; 0 disables).
    The one sampling rule of ``generate._sample_token``, per-row."""
    greedy = jnp.argmax(logits).astype(jnp.int32)
    scaled = logits / jnp.where(temp > 0, temp, 1.0)
    v = logits.shape[-1]
    srt = jnp.sort(scaled)  # ascending
    kth = srt[jnp.clip(v - top_k, 0, v - 1)]
    thresh = jnp.where(top_k > 0, kth, -jnp.inf)
    masked = jnp.where(scaled < thresh, -jnp.inf, scaled)
    sampled = jax.random.categorical(sub, masked).astype(jnp.int32)
    return jnp.where(temp > 0, sampled, greedy)


def _first_token(logits_last, sample: bool, temp=None, top_k=None,
                 key=None):
    """Admission's first token from the last prompt position's logits
    ([1, V]); sampling consumes one split of the request's key chain."""
    if not sample:
        return jnp.argmax(logits_last, axis=-1).astype(jnp.int32), None
    key, sub = jax.random.split(key)
    return _row_sample(logits_last[0], temp, top_k, sub)[None], key


@functools.lru_cache(maxsize=64)
def _compiled_slot_prefill(cfg, s: int, max_slots: int, max_len: int,
                           sample: bool = False):
    """Prefill ONE prompt into ONE slot of the shared cache; returns the
    updated cache and the first token (greedy, or sampled off the
    request's key when the engine runs the sampling programs)."""

    def body(params, ck, cv, prompt, slot, temp=None, top_k=None,
             key=None):
        row = {"k": jnp.zeros((cfg.n_layers, 1, max_len, cfg.n_kv_heads,
                               cfg.head_dim), cfg.compute_dtype),
               "v": jnp.zeros((cfg.n_layers, 1, max_len, cfg.n_kv_heads,
                               cfg.head_dim), cfg.compute_dtype)}
        logits, row = G._forward_with_cache(params, prompt, cfg, row, 0)
        first, key = _first_token(logits[:, -1, :], sample, temp, top_k,
                                  key)
        ck = jax.lax.dynamic_update_slice(ck, row["k"], (0, slot, 0, 0, 0))
        cv = jax.lax.dynamic_update_slice(cv, row["v"], (0, slot, 0, 0, 0))
        return (ck, cv, first, key) if sample else (ck, cv, first)

    if sample:
        @jax.jit
        def run(params, ck, cv, prompt, slot, temp, top_k, key):
            return body(params, ck, cv, prompt, slot, temp, top_k, key)
    else:
        @jax.jit
        def run(params, ck, cv, prompt, slot):
            return body(params, ck, cv, prompt, slot)

    return run


@functools.lru_cache(maxsize=256)
def _compiled_cached_prefill(cfg, c: int, sl: int, max_slots: int,
                             max_len: int, sample: bool = False):
    """Warm admission: restore ``c`` cached prefix positions into the
    slot row and prefill ONLY the ``sl``-token suffix at offset ``c`` —
    prefill compute scales with the uncached suffix, which is the TTFT
    collapse on shared-prefix traffic. Token-exact vs the cold path: the
    restored K/V are the same per-position values a full prefill would
    recompute (each position's K/V depends only on tokens <= it, and
    attention always masks over the same full-length row cache)."""

    def body(params, ck, cv, pk, pv, suffix, slot, temp=None, top_k=None,
             key=None):
        zk = jnp.zeros((cfg.n_layers, 1, max_len, cfg.n_kv_heads,
                        cfg.head_dim), cfg.compute_dtype)
        row = {"k": zk.at[:, 0, :c].set(pk.astype(cfg.compute_dtype)),
               "v": zk.at[:, 0, :c].set(pv.astype(cfg.compute_dtype))}
        logits, row = G._forward_with_cache(params, suffix, cfg, row, c)
        first, key = _first_token(logits[:, -1, :], sample, temp, top_k,
                                  key)
        ck = jax.lax.dynamic_update_slice(ck, row["k"], (0, slot, 0, 0, 0))
        cv = jax.lax.dynamic_update_slice(cv, row["v"], (0, slot, 0, 0, 0))
        return (ck, cv, first, key) if sample else (ck, cv, first)

    if sample:
        @jax.jit
        def run(params, ck, cv, pk, pv, suffix, slot, temp, top_k, key):
            return body(params, ck, cv, pk, pv, suffix, slot, temp, top_k,
                        key)
    else:
        @jax.jit
        def run(params, ck, cv, pk, pv, suffix, slot):
            return body(params, ck, cv, pk, pv, suffix, slot)

    return run


def _one_row_step(cfg, sample: bool = False):
    """The single-row cached decode body shared by the full-engine and
    bucketed step programs: per-row rope, per-row cache scatter, per-row
    causal masking — plus per-row sampling state when enabled."""

    def one_row(params, ck_row, cv_row, tok, pos):
        cache = {"k": ck_row[:, None], "v": cv_row[:, None]}
        logits, cache = G._forward_with_cache(
            params, tok[None, None], cfg, cache, pos)
        nxt = jnp.argmax(logits[0, -1, :]).astype(jnp.int32)
        return cache["k"][:, 0], cache["v"][:, 0], nxt

    def one_row_sampled(params, ck_row, cv_row, tok, pos, temp, top_k,
                        key):
        cache = {"k": ck_row[:, None], "v": cv_row[:, None]}
        logits, cache = G._forward_with_cache(
            params, tok[None, None], cfg, cache, pos)
        key, sub = jax.random.split(key)
        nxt = _row_sample(logits[0, -1, :], temp, top_k, sub)
        return cache["k"][:, 0], cache["v"][:, 0], nxt, key

    return one_row_sampled if sample else one_row


@functools.lru_cache(maxsize=128)
def _compiled_bucket_scan(cfg, bucket: int, max_slots: int, max_len: int,
                          k: int, sample: bool = False):
    """``k`` fused decode steps for ``bucket`` ACTIVE slots out of
    ``max_slots``: gather the occupied rows, ``lax.scan`` the vmapped
    single-row forward ``k`` times, scatter the updated KV back, return
    the [k, bucket] token block. One launch per K tokens per occupancy
    bucket — the decode-side make_multi_step. The sampling variant
    additionally carries each row's PRNG key through the scan (one split
    per token, so a request's draw chain is independent of batch
    composition and tick stride — seeded determinism)."""
    one_row = _one_row_step(cfg, sample)

    if sample:
        @jax.jit
        def run(params, ck, cv, cur, pos, idx, temp, topk, keys):
            ck_rows = ck.swapaxes(0, 1)[idx]  # [bucket, L, T, hkv, hd]
            cv_rows = cv.swapaxes(0, 1)[idx]

            def body(carry, _):
                ck_r, cv_r, cur, pos, keys = carry
                ck_r, cv_r, nxt, keys = jax.vmap(
                    one_row, in_axes=(None, 0, 0, 0, 0, 0, 0, 0))(
                    params, ck_r, cv_r, cur, pos, temp, topk, keys)
                return (ck_r, cv_r, nxt, pos + 1, keys), nxt

            (ck_rows, cv_rows, _, _, keys), toks = jax.lax.scan(
                body, (ck_rows, cv_rows, cur, pos, keys), None, length=k)
            ck = ck.swapaxes(0, 1).at[idx].set(ck_rows).swapaxes(0, 1)
            cv = cv.swapaxes(0, 1).at[idx].set(cv_rows).swapaxes(0, 1)
            return ck, cv, toks, keys  # [k, bucket], [bucket, 2]
    else:
        @jax.jit
        def run(params, ck, cv, cur, pos, idx):
            ck_rows = ck.swapaxes(0, 1)[idx]  # [bucket, L, T, hkv, hd]
            cv_rows = cv.swapaxes(0, 1)[idx]

            def body(carry, _):
                ck_r, cv_r, cur, pos = carry
                ck_r, cv_r, nxt = jax.vmap(
                    one_row, in_axes=(None, 0, 0, 0, 0))(
                    params, ck_r, cv_r, cur, pos)
                return (ck_r, cv_r, nxt, pos + 1), nxt

            (ck_rows, cv_rows, _, _), toks = jax.lax.scan(
                body, (ck_rows, cv_rows, cur, pos), None, length=k)
            ck = ck.swapaxes(0, 1).at[idx].set(ck_rows).swapaxes(0, 1)
            cv = cv.swapaxes(0, 1).at[idx].set(cv_rows).swapaxes(0, 1)
            return ck, cv, toks  # [k, bucket]

    return run
