"""Autoregressive generation with a static KV cache — the inference path.

TPU-first decode (no reference counterpart — Ray ships no model code; this
is the standard JAX recipe): the cache is a STATIC [L, B, max_len, kv_heads,
head_dim] buffer written with ``dynamic_update_slice``, prefill runs the
whole prompt as one batched forward (MXU-friendly), and the decode loop is
a single ``lax.scan`` over steps — one compiled program regardless of how
many tokens are generated. Causality over the not-yet-written cache tail
falls out of ``mha(q_offset=pos)``'s mask. GQA works unchanged (the cache
holds kv heads).

Works for both model families: llama densely, MoE via its block functions
(each family exposes ``cache_block``-compatible attention weights).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.models import llama
from ray_tpu.ops.attention import mha
from ray_tpu.ops.norms import rmsnorm
from ray_tpu.ops.rope import apply_rope, rope_angles

Params = Dict[str, Any]


def init_cache(cfg: llama.LlamaConfig, batch: int, max_len: int) -> Dict:
    """Zeroed KV cache [L, B, max_len, kv_heads, head_dim] (compute dtype)."""
    shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.compute_dtype),
            "v": jnp.zeros(shape, cfg.compute_dtype)}


def _block_with_cache(cfg, x, layer, cache_k, cache_v, sin, cos, pos):
    """One decoder block over [B, S, d] at absolute position ``pos``,
    reading/writing the layer's [B, max_len, hkv, hd] cache slices.
    Returns (hidden, new_cache_k, new_cache_v)."""
    b, s, d = x.shape
    hq, hkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    cdt = cfg.compute_dtype

    if cfg.attn_impl in ("ring", "ulysses"):
        raise NotImplementedError(
            f"decode with attn_impl={cfg.attn_impl!r} (sequence-parallel "
            f"attention) is not supported — single-token decode has no "
            f"sequence to shard. 'flash' and 'xla' configs both decode via "
            f"the einsum path (same math; the pallas kernel is a "
            f"long-sequence training implementation).")
    h = rmsnorm(x, layer["attn_norm"].astype(cdt), cfg.norm_eps)
    positions = pos + jnp.arange(s)[None, :]  # [1, s] broadcasts over batch
    positions = jnp.broadcast_to(positions, (b, s))
    q = apply_rope((h @ layer["wq"].astype(cdt)).reshape(b, s, hq, hd),
                   sin, cos, positions)
    k = apply_rope((h @ layer["wk"].astype(cdt)).reshape(b, s, hkv, hd),
                   sin, cos, positions)
    v = (h @ layer["wv"].astype(cdt)).reshape(b, s, hkv, hd)
    cache_k = jax.lax.dynamic_update_slice(cache_k, k, (0, pos, 0, 0))
    cache_v = jax.lax.dynamic_update_slice(cache_v, v, (0, pos, 0, 0))
    attn = mha(q, cache_k, cache_v, causal=True, q_offset=pos)
    x = x + attn.reshape(b, s, hq * hd) @ layer["wo"].astype(cdt)

    if "w_gate" in layer:  # dense llama FFN (shared ffn_half)
        x = llama.ffn_half(cfg, x, layer)
    else:  # MoE FFN: drop-free inference routing (shared ffn_half)
        from ray_tpu.models import moe

        x, _ = moe.ffn_half(cfg, x, layer, drop_free=True)
    return x, cache_k, cache_v


def _forward_with_cache(params: Params, tokens: jax.Array,
                        cfg, cache: Dict, pos,
                        last_only: bool = True) -> Tuple[jax.Array, Dict]:
    """tokens [B, S] at absolute position ``pos`` -> (logits, updated
    cache). ``last_only`` projects ONLY the final position to the vocab —
    generation never needs the full [B, S, V] prefill logits, which at 32k
    vocab would dominate HBM (the same blowup llama's loss_chunk avoids)."""
    cdt = cfg.compute_dtype
    x = params["embed"].astype(cdt)[tokens]
    max_len = cache["k"].shape[2]
    sin, cos = rope_angles(max_len, cfg.head_dim, cfg.rope_theta, cdt)

    def body(carry, sl):
        x = carry
        layer, ck, cv = sl
        x, ck, cv = _block_with_cache(cfg, x, layer, ck, cv, sin, cos, pos)
        return x, (ck, cv)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"]))
    if last_only:
        x = x[:, -1:, :]
    x = rmsnorm(x, params["final_norm"].astype(cdt), cfg.norm_eps)
    head = (params["embed"].T if cfg.tie_embeddings
            else params["lm_head"]).astype(cdt)
    logits = (x @ head).astype(jnp.float32)
    return logits, {"k": new_k, "v": new_v}


def generate(params: Params, prompt: jax.Array, cfg,
             *, max_new_tokens: int, temperature: float = 0.0,
             top_k: Optional[int] = None,
             key: Optional[jax.Array] = None,
             max_len: Optional[int] = None) -> jax.Array:
    """prompt [B, S] -> generated tokens [B, max_new_tokens].

    ``temperature == 0``: greedy. Otherwise softmax sampling (optionally
    top-k truncated) with ``key``. The whole loop is one jit: prefill +
    ``lax.scan`` over decode steps.
    """
    b, s = prompt.shape
    total = max_len or (s + max_new_tokens)
    if total < s + max_new_tokens:
        raise ValueError(f"max_len {total} < prompt {s} + new {max_new_tokens}")
    if temperature > 0 and key is None:
        key = jax.random.key(0)
    run = _compiled_generate(cfg, b, s, total, max_new_tokens,
                             float(temperature), top_k)
    return run(params, prompt, key)


def _sample_token(last_logits, temperature: float, top_k: Optional[int],
                  key):
    """Greedy (temperature<=0) or temperature/top-k categorical sampling —
    the ONE sampling rule shared by the fused and streaming decode paths."""
    if temperature <= 0:
        return jnp.argmax(last_logits, axis=-1)
    scaled = last_logits / temperature
    if top_k is not None:
        kth = jnp.sort(scaled, axis=-1)[:, -top_k][:, None]
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    return jax.random.categorical(key, scaled)


@functools.lru_cache(maxsize=64)
def _compiled_generate(cfg, b: int, s: int, total: int, max_new_tokens: int,
                       temperature: float, top_k: Optional[int]):
    """One compiled program per (config, shapes, sampling) — repeat calls
    (the serve per-request path) hit jit's cache instead of re-tracing.
    Configs are frozen dataclasses, hence hashable cache keys."""

    @jax.jit
    def run(params, prompt, key):
        cache = init_cache(cfg, b, total)
        logits, cache = _forward_with_cache(params, prompt, cfg, cache, 0)
        last = logits[:, -1, :]

        def step(carry, i):
            cache, last_logits, key = carry
            if key is not None:
                key, sub = jax.random.split(key)
            else:
                sub = None
            tok = _sample_token(last_logits, temperature, top_k, sub)
            logits, cache = _forward_with_cache(
                params, tok[:, None], cfg, cache, s + i)
            return (cache, logits[:, -1, :], key), tok

        (_, _, _), toks = jax.lax.scan(
            step, (cache, last, key), jnp.arange(max_new_tokens))
        return toks.swapaxes(0, 1)  # [B, T]

    return run


@functools.lru_cache(maxsize=64)
def _compiled_prefill(cfg, b: int, s: int, total: int):
    @jax.jit
    def run(params, prompt):
        cache = init_cache(cfg, b, total)
        logits, cache = _forward_with_cache(params, prompt, cfg, cache, 0)
        return logits[:, -1, :], cache

    return run


@functools.lru_cache(maxsize=64)
def _compiled_decode_step(cfg, b: int, total: int):
    @jax.jit
    def run(params, cache, tok, pos):
        logits, cache = _forward_with_cache(
            params, tok[:, None], cfg, cache, pos)
        return logits[:, -1, :], cache

    return run


def generate_stream(params: Params, prompt: jax.Array, cfg,
                    *, max_new_tokens: int, temperature: float = 0.0,
                    top_k: Optional[int] = None,
                    key: Optional[jax.Array] = None,
                    max_len: Optional[int] = None):
    """Yield tokens [B] one at a time — the serve token-streaming path.

    Same math as ``generate`` but the decode loop runs in Python around a
    cached jitted single-step, so each token is observable as soon as it's
    sampled (a single fused scan can't stream). ``pos`` is a traced scalar:
    one compiled step serves every position.
    """
    b, s = prompt.shape
    total = max_len or (s + max_new_tokens)
    if total < s + max_new_tokens:
        raise ValueError(f"max_len {total} < prompt {s} + new {max_new_tokens}")
    if temperature > 0 and key is None:
        key = jax.random.key(0)

    last, cache = _compiled_prefill(cfg, b, s, total)(params, prompt)
    step = _compiled_decode_step(cfg, b, total)
    for i in range(max_new_tokens):
        if temperature <= 0:
            sub = None
        else:
            key, sub = jax.random.split(key)
        tok = _sample_token(last, temperature, top_k, sub)
        yield tok
        if i + 1 < max_new_tokens:
            last, cache = step(params, cache, tok, jnp.int32(s + i))
