"""Autoregressive generation with a static KV cache — the inference path.

TPU-first decode (no reference counterpart — Ray ships no model code; this
is the standard JAX recipe): the cache is a STATIC [L, B, max_len, kv_heads,
head_dim] buffer written with ``dynamic_update_slice``, prefill runs the
whole prompt as one batched forward (MXU-friendly), and the decode loop is
a single ``lax.scan`` over steps — one compiled program regardless of how
many tokens are generated. Causality over the not-yet-written cache tail
falls out of ``mha(q_offset=pos)``'s mask. GQA works unchanged (the cache
holds kv heads).

Works for both model families: llama densely, MoE via its block functions
(each family exposes ``cache_block``-compatible attention weights).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.models import llama
from ray_tpu.ops.attention import mha
from ray_tpu.ops.norms import rmsnorm
from ray_tpu.ops.rope import apply_rope, rope_angles
from ray_tpu.util import step_profiler

Params = Dict[str, Any]


def init_cache(cfg: llama.LlamaConfig, batch: int, max_len: int) -> Dict:
    """Zeroed KV cache [L, B, max_len, kv_heads, head_dim] (compute dtype)."""
    shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.compute_dtype),
            "v": jnp.zeros(shape, cfg.compute_dtype)}


def _block_with_cache(cfg, x, layer, cache_k, cache_v, sin, cos, pos):
    """One decoder block over [B, S, d] at absolute position ``pos``,
    reading/writing the layer's [B, max_len, hkv, hd] cache slices.
    Returns (hidden, new_cache_k, new_cache_v)."""
    b, s, d = x.shape
    hq, hkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    cdt = cfg.compute_dtype

    if cfg.attn_impl in ("ring", "ulysses"):
        raise NotImplementedError(
            f"decode with attn_impl={cfg.attn_impl!r} (sequence-parallel "
            f"attention) is not supported — single-token decode has no "
            f"sequence to shard. 'flash' and 'xla' configs both decode via "
            f"the einsum path (same math; the pallas kernel is a "
            f"long-sequence training implementation).")
    h = rmsnorm(x, layer["attn_norm"].astype(cdt), cfg.norm_eps)
    positions = pos + jnp.arange(s)[None, :]  # [1, s] broadcasts over batch
    positions = jnp.broadcast_to(positions, (b, s))
    q = apply_rope((h @ layer["wq"].astype(cdt)).reshape(b, s, hq, hd),
                   sin, cos, positions)
    k = apply_rope((h @ layer["wk"].astype(cdt)).reshape(b, s, hkv, hd),
                   sin, cos, positions)
    v = (h @ layer["wv"].astype(cdt)).reshape(b, s, hkv, hd)
    cache_k = jax.lax.dynamic_update_slice(cache_k, k, (0, pos, 0, 0))
    cache_v = jax.lax.dynamic_update_slice(cache_v, v, (0, pos, 0, 0))
    attn = mha(q, cache_k, cache_v, causal=True, q_offset=pos)
    x = x + attn.reshape(b, s, hq * hd) @ layer["wo"].astype(cdt)

    if "w_gate" in layer:  # dense llama FFN (shared ffn_half)
        x = llama.ffn_half(cfg, x, layer)
    else:  # MoE FFN: drop-free inference routing (shared ffn_half)
        from ray_tpu.models import moe

        x, _ = moe.ffn_half(cfg, x, layer, drop_free=True)
    return x, cache_k, cache_v


def _forward_with_cache(params: Params, tokens: jax.Array,
                        cfg, cache: Dict, pos,
                        last_only: bool = True) -> Tuple[jax.Array, Dict]:
    """tokens [B, S] at absolute position ``pos`` -> (logits, updated
    cache). ``last_only`` projects ONLY the final position to the vocab —
    generation never needs the full [B, S, V] prefill logits, which at 32k
    vocab would dominate HBM (the same blowup llama's loss_chunk avoids)."""
    cdt = cfg.compute_dtype
    x = params["embed"].astype(cdt)[tokens]
    max_len = cache["k"].shape[2]
    sin, cos = rope_angles(max_len, cfg.head_dim, cfg.rope_theta, cdt)

    def body(carry, sl):
        x = carry
        layer, ck, cv = sl
        x, ck, cv = _block_with_cache(cfg, x, layer, ck, cv, sin, cos, pos)
        return x, (ck, cv)

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"]))
    if last_only:
        x = x[:, -1:, :]
    x = rmsnorm(x, params["final_norm"].astype(cdt), cfg.norm_eps)
    head = (params["embed"].T if cfg.tie_embeddings
            else params["lm_head"]).astype(cdt)
    logits = (x @ head).astype(jnp.float32)
    return logits, {"k": new_k, "v": new_v}


def generate(params: Params, prompt: jax.Array, cfg,
             *, max_new_tokens: int, temperature: float = 0.0,
             top_k: Optional[int] = None,
             key: Optional[jax.Array] = None,
             max_len: Optional[int] = None) -> jax.Array:
    """prompt [B, S] -> generated tokens [B, max_new_tokens].

    ``temperature == 0``: greedy. Otherwise softmax sampling (optionally
    top-k truncated) with ``key``. The whole loop is one jit: prefill +
    ``lax.scan`` over decode steps.
    """
    b, s = prompt.shape
    total = max_len or (s + max_new_tokens)
    if total < s + max_new_tokens:
        raise ValueError(f"max_len {total} < prompt {s} + new {max_new_tokens}")
    if temperature > 0 and key is None:
        key = jax.random.key(0)
    run = _compiled_generate(cfg, b, s, total, max_new_tokens,
                             float(temperature), top_k)
    if not step_profiler.is_enabled():
        return run(params, prompt, key)
    from ray_tpu.util import flops as F

    return step_profiler.profiled_call(
        "generate", run, (params, prompt, key),
        key=("generate", cfg, b, s, total, max_new_tokens,
             float(temperature), top_k),
        tokens=b * max_new_tokens,
        flops=F.generate_flops(cfg, b, s, max_new_tokens),
        meta={"batch": b, "prompt_len": s})


def _sample_token(last_logits, temperature: float, top_k: Optional[int],
                  key):
    """Greedy (temperature<=0) or temperature/top-k categorical sampling —
    the ONE sampling rule shared by the fused and streaming decode paths."""
    if temperature <= 0:
        return jnp.argmax(last_logits, axis=-1)
    scaled = last_logits / temperature
    if top_k is not None:
        kth = jnp.sort(scaled, axis=-1)[:, -top_k][:, None]
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    return jax.random.categorical(key, scaled)


@functools.lru_cache(maxsize=64)
def _compiled_generate(cfg, b: int, s: int, total: int, max_new_tokens: int,
                       temperature: float, top_k: Optional[int]):
    """One compiled program per (config, shapes, sampling) — repeat calls
    (the serve per-request path) hit jit's cache instead of re-tracing.
    Configs are frozen dataclasses, hence hashable cache keys."""

    @jax.jit
    def run(params, prompt, key):
        cache = init_cache(cfg, b, total)
        logits, cache = _forward_with_cache(params, prompt, cfg, cache, 0)
        last = logits[:, -1, :]

        def step(carry, i):
            cache, last_logits, key = carry
            if key is not None:
                key, sub = jax.random.split(key)
            else:
                sub = None
            tok = _sample_token(last_logits, temperature, top_k, sub)
            logits, cache = _forward_with_cache(
                params, tok[:, None], cfg, cache, s + i)
            return (cache, logits[:, -1, :], key), tok

        (_, _, _), toks = jax.lax.scan(
            step, (cache, last, key), jnp.arange(max_new_tokens))
        return toks.swapaxes(0, 1)  # [B, T]

    return run


def generate_speculative(params: Params, draft_params: Params,
                         prompt: jax.Array, cfg, draft_cfg,
                         *, max_new_tokens: int, speculate_k: int = 4,
                         max_len: Optional[int] = None,
                         return_stats: bool = False) -> jax.Array:
    """Greedy speculative decoding: a small DRAFT model proposes
    ``speculate_k`` tokens per round; the TARGET verifies them in ONE
    forward (k+1 positions batched onto the MXU) and emits the longest
    matching prefix plus its own correction token. Output is EXACTLY the
    target's greedy continuation — the draft only changes how many
    target launches it takes (1 per ~(accepted+1) tokens instead of 1
    per token), which is the lever when decode is launch- or
    HBM-bound. (Leviathan et al. 2023; no reference counterpart — Ray
    ships no model code.)

    Batch semantics: acceptance is LOCKSTEP (min over rows). Each row's
    emitted tokens are still its own target-greedy tokens — a row that
    would have accepted more simply emits them over later rounds — so
    exactness holds for any batch size; speedup is highest at B=1 (the
    latency case).

    The whole loop is one jit: a ``lax.while_loop`` over rounds, a
    ``lax.scan`` for the draft's proposals inside. Stale cache entries
    past a rejection are overwritten before they can be attended (each
    round's k+1-wide write starts exactly at the first stale position).

    ``return_stats=True`` additionally returns
    ``{"rounds", "accept_per_round"}`` — the measured acceptance profile
    (tokens emitted per target launch minus the free correction token).
    Speedup claims are only honest next to this number: a draft the
    target never agrees with still "works" but pays k draft launches per
    emitted token.
    """
    b, s = prompt.shape
    total = max_len or (s + max_new_tokens + speculate_k + 1)
    if total < s + max_new_tokens + speculate_k + 1:
        raise ValueError(f"max_len {total} < prompt {s} + new "
                         f"{max_new_tokens} + k {speculate_k} + 1")
    run = _compiled_speculative(cfg, draft_cfg, b, s, total,
                                max_new_tokens, speculate_k)
    if not step_profiler.is_enabled():
        out, rounds = run(params, draft_params, prompt)
    else:
        from ray_tpu.util import flops as F

        # Analytic work: target prefill+decode plus the draft's proposals
        # (the draft runs ~1 forward per emitted token too — acceptance
        # only changes how many TARGET launches that took).
        out, rounds = step_profiler.profiled_call(
            "speculative", run, (params, draft_params, prompt),
            key=("speculative", cfg, draft_cfg, b, s, total, max_new_tokens,
                 speculate_k),
            tokens=b * max_new_tokens,
            flops=(F.generate_flops(cfg, b, s, max_new_tokens)
                   + F.generate_flops(draft_cfg, b, s, max_new_tokens)),
            meta={"batch": b, "prompt_len": s, "speculate_k": speculate_k})
    if not return_stats:
        return out
    n_rounds = int(rounds)
    stats = {"rounds": n_rounds,
             "accept_per_round": round(
                 max(0.0, max_new_tokens / max(1, n_rounds) - 1.0), 3)}
    return out, stats


@functools.lru_cache(maxsize=64)
def _compiled_speculative(cfg, draft_cfg, b: int, s: int, total: int,
                          max_new_tokens: int, k: int):
    @jax.jit
    def run(params, draft_params, prompt):
        # prefill BOTH models; invariant from here on: caches hold KV for
        # positions < pos, and cur is the (already decided) token AT pos
        tcache = init_cache(cfg, b, total)
        tlogits, tcache = _forward_with_cache(params, prompt, cfg,
                                              tcache, 0)
        dcache = init_cache(draft_cfg, b, total)
        _, dcache = _forward_with_cache(draft_params, prompt, draft_cfg,
                                        dcache, 0)
        cur = jnp.argmax(tlogits[:, -1, :], axis=-1)  # token at pos=s
        out = jnp.zeros((b, max_new_tokens + k + 1), jnp.int32)
        # out[0] is cur (the first generated token)
        out = out.at[:, 0].set(cur.astype(jnp.int32))

        rounds = jnp.int32(0)

        def cond(st):
            return st[0] < max_new_tokens

        def drafts_pad(d):
            return jnp.concatenate(
                [d, jnp.zeros((b, 1), d.dtype)], axis=1)

        def body(st):
            n, pos, cur, tcache, dcache, out, r = st

            # draft proposes k tokens autoregressively
            def dstep(carry, i):
                dcache, tok = carry
                logits, dcache = _forward_with_cache(
                    draft_params, tok[:, None], draft_cfg, dcache, pos + i)
                nxt = jnp.argmax(logits[:, -1, :], axis=-1)
                return (dcache, nxt), nxt

            (dcache, _), drafts = jax.lax.scan(
                dstep, (dcache, cur), jnp.arange(k))
            drafts = drafts.swapaxes(0, 1)  # [B, k]

            # target verifies cur + all k drafts in ONE forward
            block = jnp.concatenate([cur[:, None], drafts], axis=1)
            logits, tcache = _forward_with_cache(
                params, block, cfg, tcache, pos, last_only=False)
            t = jnp.argmax(logits, axis=-1)  # [B, k+1]; t[:, j] follows
            #                                   block position pos+j

            # longest accepted prefix, lockstep across the batch
            match = drafts == t[:, :k]                      # [B, k]
            a = jnp.min(jnp.argmin(
                jnp.concatenate([match, jnp.zeros((b, 1), bool)], 1), 1))
            # emitted block: draft tokens below a, target tokens from a on
            # (position a IS the correction; beyond is scratch that the
            # next round overwrites)
            emit = jnp.where(jnp.arange(k + 1)[None, :] < a, drafts_pad(
                drafts), t).astype(jnp.int32)
            out = jax.lax.dynamic_update_slice(out, emit, (0, n + 1))
            cur = jax.lax.dynamic_index_in_dim(emit, a, axis=1,
                                               keepdims=False)
            return (n + a + 1, pos + a + 1, cur, tcache, dcache, out,
                    r + 1)

        n, _, _, _, _, out, rounds = jax.lax.while_loop(
            cond, body, (jnp.int32(0), jnp.int32(s), cur, tcache,
                         dcache, out, rounds))
        return out[:, :max_new_tokens], rounds

    return run


@functools.lru_cache(maxsize=64)
def _compiled_prefill(cfg, b: int, s: int, total: int):
    @jax.jit
    def run(params, prompt):
        cache = init_cache(cfg, b, total)
        logits, cache = _forward_with_cache(params, prompt, cfg, cache, 0)
        return logits[:, -1, :], cache

    return run


@functools.lru_cache(maxsize=64)
def _compiled_decode_step(cfg, b: int, total: int):
    @jax.jit
    def run(params, cache, tok, pos):
        logits, cache = _forward_with_cache(
            params, tok[:, None], cfg, cache, pos)
        return logits[:, -1, :], cache

    return run


def generate_stream(params: Params, prompt: jax.Array, cfg,
                    *, max_new_tokens: int, temperature: float = 0.0,
                    top_k: Optional[int] = None,
                    key: Optional[jax.Array] = None,
                    max_len: Optional[int] = None):
    """Yield tokens [B] one at a time — the serve token-streaming path.

    Same math as ``generate`` but the decode loop runs in Python around a
    cached jitted single-step, so each token is observable as soon as it's
    sampled (a single fused scan can't stream). ``pos`` is a traced scalar:
    one compiled step serves every position.
    """
    b, s = prompt.shape
    total = max_len or (s + max_new_tokens)
    if total < s + max_new_tokens:
        raise ValueError(f"max_len {total} < prompt {s} + new {max_new_tokens}")
    if temperature > 0 and key is None:
        key = jax.random.key(0)

    profiled = step_profiler.is_enabled()
    if profiled:
        from ray_tpu.util import flops as F

    prefill = _compiled_prefill(cfg, b, s, total)
    if profiled:
        # per-launch records: the streamed path is the one that pays launch
        # overhead PER TOKEN, which is exactly what the profiler's
        # dispatch/sync split is built to expose
        last, cache = step_profiler.profiled_call(
            "prefill", prefill, (params, prompt),
            key=("prefill", cfg, b, s, total), tokens=b * s,
            flops=F.prefill_flops(cfg, b, s), meta={"batch": b})
    else:
        last, cache = prefill(params, prompt)
    step = _compiled_decode_step(cfg, b, total)
    for i in range(max_new_tokens):
        if temperature <= 0:
            sub = None
        else:
            key, sub = jax.random.split(key)
        tok = _sample_token(last, temperature, top_k, sub)
        yield tok
        if i + 1 < max_new_tokens:
            if profiled:
                last, cache = step_profiler.profiled_call(
                    "decode", step,
                    (params, cache, tok, jnp.int32(s + i)),
                    key=("decode", cfg, b, total), tokens=b,
                    flops=b * F.decode_flops_per_token(cfg, s + i))
            else:
                last, cache = step(params, cache, tok, jnp.int32(s + i))
