"""Workflow event system: external-event wait/resume.

Reference parity: ``python/ray/workflow/event_listener.py`` (the
``EventListener`` protocol with ``poll_for_event`` /
``event_checkpointed``) and ``http_event_provider.py`` (a serve deployment
receiving events over HTTP that listeners poll). Redesigned for this
engine: ``wait_for_event`` produces a normal DAG node executed as a remote
task, so the durable executor checkpoints the received event like any task
result — a resumed workflow does NOT re-wait for an event it already
consumed (the reference's ``event_checkpointed`` contract falls out of the
checkpoint machinery instead of a second callback path).
"""

from __future__ import annotations

import time
from typing import Any

__all__ = ["EventListener", "TimerListener", "HTTPListener",
           "wait_for_event", "http_event_provider"]


class EventListener:
    """Subclass and implement ``poll_for_event`` (sync or async). The
    instance is created inside the waiting task, once per (re)execution.

    Reference: ``workflow/event_listener.py:11``. ``event_checkpointed``
    is supported as an optional post-checkpoint hook for exactly-once
    integrations (e.g. committing a queue offset): it runs AFTER the
    durable executor has checkpointed the event, on a best-effort basis.
    """

    def poll_for_event(self, *args, **kwargs) -> Any:
        raise NotImplementedError

    def event_checkpointed(self, event: Any) -> None:
        """Optional commit hook; called after the event is durably
        checkpointed (may be sync or async)."""


class TimerListener(EventListener):
    """Fires once ``timestamp`` (unix seconds) has passed — the reference's
    canonical example listener."""

    def poll_for_event(self, timestamp: float) -> float:
        time.sleep(max(0.0, timestamp - time.time()))
        return timestamp


class HTTPListener(EventListener):
    """Polls the :func:`http_event_provider` serve deployment for an event
    posted to ``(workflow_id, event_key)``.

    Reference: ``http_event_provider.py`` ``HTTPListener``.
    """

    def poll_for_event(self, workflow_id: str, event_key: str,
                       poll_interval_s: float = 0.2) -> Any:
        from ray_tpu import serve

        handle = serve.get_app_handle("workflow-events")
        while True:
            found, payload = handle.get_event.remote(
                workflow_id, event_key).result(timeout=30)
            if found:
                return payload
            time.sleep(poll_interval_s)


def wait_for_event(listener_cls, *args, **kwargs):
    """A DAG node that completes when the listener observes its event;
    compose it into workflows like any other bound task.

    >>> gate = workflow.wait_for_event(HTTPListener, "wf1", "approved")
    >>> result = process.bind(gate)
    >>> workflow.run(result, workflow_id="wf1")

    The event value is checkpointed, so resume never re-waits.

    Reference: ``workflow/api.py`` ``wait_for_event``.
    """
    if not (isinstance(listener_cls, type)
            and issubclass(listener_cls, EventListener)):
        raise TypeError(
            f"wait_for_event needs an EventListener subclass, got "
            f"{listener_cls!r}")
    import ray_tpu

    @ray_tpu.remote
    def _wait_for_event(cls, wargs, wkwargs):
        import asyncio
        import inspect

        listener = cls()
        event = listener.poll_for_event(*wargs, **wkwargs)
        if inspect.isawaitable(event):
            event = asyncio.run(event)
        return event

    node = _wait_for_event.bind(listener_cls, args, kwargs)
    # the durable executor fires listener.event_checkpointed after writing
    # the checkpoint; mark the node so it knows which class to notify
    node._event_listener_cls = listener_cls
    return node


def http_event_provider(port_app_name: str = "workflow-events"):
    """Deploy the HTTP event provider (a serve application): external
    systems POST ``{"workflow_id": ..., "event_key": ..., "payload": ...}``
    to ``/workflow-events/send`` and workflows consume via
    :class:`HTTPListener`. Returns the deployment handle.

    Reference: ``http_event_provider.py`` ``HTTPEventProvider`` (also a
    serve deployment on the cluster's proxy).
    """
    from ray_tpu import serve

    @serve.deployment
    class EventProvider:
        MAX_PENDING = 10_000

        def __init__(self):
            self._events = {}  # (workflow_id, key) -> payload

        def get_event(self, workflow_id: str, event_key: str):
            # Consumed on delivery: exactly-once to the waiting workflow
            # (its checkpoint makes replay safe), no unbounded growth, and
            # a re-run workflow id waits for a FRESH event instead of
            # re-reading a stale one.
            k = (workflow_id, event_key)
            if k in self._events:
                return True, self._events.pop(k)
            return False, None

        def __call__(self, request):
            data = request.json()
            if not isinstance(data, dict) or "workflow_id" not in data \
                    or "event_key" not in data:
                return 400, "need workflow_id and event_key"
            if len(self._events) >= self.MAX_PENDING:
                # evict oldest undelivered: a dead workflow must not
                # brick the provider for live ones
                self._events.pop(next(iter(self._events)))
            self._events[(data["workflow_id"], data["event_key"])] = \
                data.get("payload")
            return {"accepted": True}

    return serve.run(EventProvider.bind(), name=port_app_name,
                     route_prefix="/workflow-events")
