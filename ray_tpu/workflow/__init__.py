"""Durable workflows: run a task DAG with per-task checkpoints and resume.

Capability parity with the reference's ``python/ray/workflow/`` (``workflow.run
:120`` / ``run_async :174`` in ``workflow/api.py``; per-task durable
checkpoints in ``workflow_storage.py``; ``WorkflowExecutor`` in
``workflow_executor.py``). Each DAG node's result is checkpointed to storage
as it completes; ``resume()`` re-executes only the nodes whose checkpoints are
missing, so a crashed workflow continues where it left off.
"""

from ray_tpu.workflow.api import (  # noqa: F401
    Continuation,
    WorkflowStatus,
    cancel,
    continuation,
    get_output,
    get_status,
    init,
    list_all,
    resume,
    run,
    run_async,
)
from ray_tpu.workflow.events import (  # noqa: F401
    EventListener,
    HTTPListener,
    TimerListener,
    http_event_provider,
    wait_for_event,
)

__all__ = [
    "init", "run", "run_async", "resume", "cancel", "get_status",
    "get_output", "list_all", "WorkflowStatus",
    "EventListener", "TimerListener", "HTTPListener", "wait_for_event",
    "http_event_provider",
]
