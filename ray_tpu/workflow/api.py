"""Workflow execution engine and storage.

Reference parity: ``python/ray/workflow/workflow_executor.py:32``
(``WorkflowExecutor``), ``workflow_storage.py`` (durable task results),
``workflow_access.py:88`` (management actor — here a module-level registry
since workflows are driver-scoped). Node keys are deterministic (function
name + topological position) so a resumed run maps checkpoints back onto the
same DAG.
"""

from __future__ import annotations

import enum
import hashlib
import json
import os

import cloudpickle as pickle
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.dag.dag_node import (
    ClassMethodNode,
    ClassNode,
    DAGNode,
    FunctionNode,
    InputAttributeNode,
    InputNode,
    _InputValue,
    _resolve,
)


class WorkflowStatus(str, enum.Enum):
    RUNNING = "RUNNING"
    SUCCESSFUL = "SUCCESSFUL"
    FAILED = "FAILED"
    RESUMABLE = "RESUMABLE"
    CANCELED = "CANCELED"


_storage_dir: Optional[str] = None
_running: Dict[str, threading.Thread] = {}
_cancel_flags: Dict[str, threading.Event] = {}
_lock = threading.Lock()


def init(storage: Optional[str] = None) -> None:
    """Set the durable storage root (default: a per-user tmp dir)."""
    global _storage_dir
    _storage_dir = storage or os.path.join(
        tempfile.gettempdir(), "ray_tpu_workflows")
    os.makedirs(_storage_dir, exist_ok=True)


def _root() -> str:
    if _storage_dir is None:
        init()
    return _storage_dir  # type: ignore[return-value]


def _wf_dir(workflow_id: str) -> str:
    d = os.path.join(_root(), workflow_id)
    os.makedirs(os.path.join(d, "tasks"), exist_ok=True)
    return d


def _write_status(workflow_id: str, status: WorkflowStatus,
                  error: Optional[str] = None) -> None:
    with open(os.path.join(_wf_dir(workflow_id), "status.json"), "w") as f:
        json.dump({"status": status.value, "error": error,
                   "updated_at": time.time()}, f)


def _read_status(workflow_id: str) -> Dict[str, Any]:
    path = os.path.join(_root(), workflow_id, "status.json")
    if not os.path.exists(path):
        raise ValueError(f"no workflow with id {workflow_id!r}")
    with open(path) as f:
        return json.load(f)


def _node_keys(dag: DAGNode) -> Dict[int, str]:
    """Deterministic per-node checkpoint keys: depth-first traversal order +
    callable name. Stable across runs of the same DAG-building code."""
    keys: Dict[int, str] = {}
    counter = [0]

    def walk(n: DAGNode):
        if id(n) in keys:
            return
        for c in n._children():
            walk(c)
        if isinstance(n, FunctionNode):
            name = n._remote_fn.underlying_function.__name__
        elif isinstance(n, ClassMethodNode):
            name = n._method_name
        elif isinstance(n, ClassNode):
            name = n._actor_cls.underlying_class.__name__
        else:
            name = type(n).__name__
        keys[id(n)] = f"{counter[0]:04d}_{name}"
        counter[0] += 1

    walk(dag)
    return keys


def _save_dag(workflow_id: str, dag: DAGNode, args: tuple, kwargs: dict) -> None:
    with open(os.path.join(_wf_dir(workflow_id), "dag.pkl"), "wb") as f:
        pickle.dump({"dag": dag, "args": args, "kwargs": kwargs}, f)


def _load_dag(workflow_id: str):
    with open(os.path.join(_root(), workflow_id, "dag.pkl"), "rb") as f:
        return pickle.load(f)


class Continuation:
    """A workflow task's return value saying "durably run THIS DAG and use
    its result as mine" (reference: ``ray.workflow.continuation`` — the
    primitive behind durable loops and recursion).

    Sub-DAG checkpoints live under the returning node's key, so a resumed
    workflow re-runs the (deterministic) parent task to regenerate the
    DAG but reuses every completed sub-step's checkpoint."""

    def __init__(self, dag: DAGNode):
        if not isinstance(dag, DAGNode):
            raise TypeError("continuation() takes a bound DAG node "
                            "(fn.bind(...))")
        self.dag = dag


def continuation(dag: DAGNode) -> Continuation:
    return Continuation(dag)


class _DurableExecutor:
    """Executes a DAG bottom-up, checkpointing each task's result."""

    def __init__(self, workflow_id: str, dag: DAGNode, input_val: _InputValue,
                 cancel_flag: threading.Event, key_prefix: str = ""):
        self.workflow_id = workflow_id
        self.dag = dag
        self.input_val = input_val
        self.keys = _node_keys(dag)
        self.key_prefix = key_prefix
        self.tasks_dir = os.path.join(_wf_dir(workflow_id), "tasks")
        self.cancel_flag = cancel_flag
        self._cache: Dict[int, Any] = {}
        # Actor state is rebuilt from scratch on resume, so loading SOME of a
        # ClassNode's method-call checkpoints while re-executing others would
        # run the re-executed calls against stale state. If any method call
        # of a ClassNode must re-execute, replay ALL of that node's calls
        # (methods are assumed deterministic, like workflow tasks).
        self._replay_class_nodes: set = set()
        by_class: Dict[int, List[ClassMethodNode]] = {}
        for n in dag.get_all_nodes():
            if isinstance(n, ClassMethodNode) and isinstance(n._class_node,
                                                             DAGNode):
                by_class.setdefault(id(n._class_node), []).append(n)
        for cls_id, methods in by_class.items():
            if any(not os.path.exists(self._ckpt_path(m)) for m in methods):
                self._replay_class_nodes.add(cls_id)

    def _ckpt_path(self, node: DAGNode) -> str:
        return os.path.join(self.tasks_dir,
                            self.key_prefix + self.keys[id(node)] + ".pkl")

    def _resolve_continuation(self, node: DAGNode, val):
        """Durably execute a returned sub-DAG; its checkpoints are
        namespaced under a HASH of the returning node's full path, so the
        filename stays fixed-length at any recursion depth (a literal
        path concatenation hits NAME_MAX at ~13 levels). Nested
        continuations inside the sub-DAG resolve in the sub-executor."""
        if not isinstance(val, Continuation):
            return val
        path_id = hashlib.sha1(
            (self.key_prefix + self.keys[id(node)]).encode()
        ).hexdigest()[:12]
        sub = _DurableExecutor(self.workflow_id, val.dag, self.input_val,
                               self.cancel_flag,
                               key_prefix=path_id + ".")
        return sub.run()

    def run(self) -> Any:
        # DAG resolution recurses over structure (args and continuation
        # sub-DAGs alike); give deep durable loops stack headroom — pure-
        # Python frames, heap-allocated on modern CPython. Raised
        # monotonically and NEVER restored: setrecursionlimit is
        # process-global, so a save/restore here would race with
        # concurrent run_async workflows still recursing on their
        # daemon threads (their deep stacks would suddenly overflow).
        import sys

        if sys.getrecursionlimit() < 20_000:
            sys.setrecursionlimit(20_000)
        return self._exec(self.dag)

    def _exec(self, node: DAGNode) -> Any:
        if id(node) in self._cache:
            return self._cache[id(node)]
        if self.cancel_flag.is_set():
            raise _Canceled()
        # Input nodes are re-evaluated, never checkpointed.
        if isinstance(node, (InputNode, InputAttributeNode)):
            val = node._execute_impl((), {}, self.input_val)
            self._cache[id(node)] = val
            return val
        path = self._ckpt_path(node)
        skip_ckpt = isinstance(node, ClassNode) or (
            isinstance(node, ClassMethodNode)
            and isinstance(node._class_node, DAGNode)
            and id(node._class_node) in self._replay_class_nodes)
        if os.path.exists(path) and not skip_ckpt:
            with open(path, "rb") as f:
                val = pickle.load(f)
            self._cache[id(node)] = val
            return val
        args = _resolve_with(self, node._bound_args)
        kwargs = _resolve_with(self, node._bound_kwargs)
        if isinstance(node, ClassNode):
            # Actors are live state, not checkpointable: re-create on resume.
            val = node._execute_impl(args, kwargs, self.input_val)
        elif isinstance(node, ClassMethodNode):
            handle = (self._exec(node._class_node)
                      if isinstance(node._class_node, DAGNode)
                      else node._class_node)
            from ray_tpu.core.worker import global_worker

            ref = getattr(handle, node._method_name).remote(*args, **kwargs)
            val = self._resolve_continuation(
                node, global_worker().get(ref))
            self._checkpoint(path, val)
        elif isinstance(node, FunctionNode):
            from ray_tpu.core.worker import global_worker

            ref = node._execute_impl(args, kwargs, self.input_val)
            # a Continuation resolves durably BEFORE the checkpoint: the
            # node's stored value is the continuation's final result
            val = self._resolve_continuation(
                node, global_worker().get(ref))
            self._checkpoint(path, val)
            # wait_for_event nodes: exactly-once commit hook fires AFTER
            # the event is durably checkpointed (workflow/events.py)
            listener_cls = getattr(node, "_event_listener_cls", None)
            if listener_cls is not None:
                try:
                    import asyncio
                    import inspect

                    r = listener_cls().event_checkpointed(val)
                    if inspect.isawaitable(r):
                        asyncio.run(r)
                except Exception:  # noqa: BLE001 — best-effort hook
                    pass
        else:
            val = node._execute_impl(args, kwargs, self.input_val)
        self._cache[id(node)] = val
        return val

    def _checkpoint(self, path: str, val: Any) -> None:
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(val, f)
        os.replace(tmp, path)  # atomic: a partial write never reads as done


def _resolve_with(ex: _DurableExecutor, value):
    if isinstance(value, DAGNode):
        return ex._exec(value)
    if isinstance(value, tuple):
        return tuple(_resolve_with(ex, v) for v in value)
    if isinstance(value, list):
        return [_resolve_with(ex, v) for v in value]
    if isinstance(value, dict):
        return {k: _resolve_with(ex, v) for k, v in value.items()}
    return value


class _Canceled(Exception):
    pass


def run(dag: DAGNode, *args, workflow_id: Optional[str] = None, **kwargs) -> Any:
    """Execute the DAG durably, blocking until the final result."""
    return _run_impl(dag, args, kwargs, workflow_id, wait=True)


def run_async(dag: DAGNode, *args, workflow_id: Optional[str] = None, **kwargs):
    """Start the workflow in the background; returns the workflow_id."""
    return _run_impl(dag, args, kwargs, workflow_id, wait=False)


def _run_impl(dag: DAGNode, args: tuple, kwargs: dict,
              workflow_id: Optional[str], wait: bool):
    if workflow_id is None:
        workflow_id = f"workflow-{int(time.time() * 1e6):x}"
    _save_dag(workflow_id, dag, args, kwargs)
    _write_status(workflow_id, WorkflowStatus.RUNNING)
    cancel_flag = threading.Event()
    with _lock:
        _cancel_flags[workflow_id] = cancel_flag

    def body():
        ex = _DurableExecutor(workflow_id, dag, _InputValue(args, kwargs),
                              cancel_flag)
        try:
            result = ex.run()
        except _Canceled:
            _write_status(workflow_id, WorkflowStatus.CANCELED)
            raise
        except BaseException as e:  # noqa: BLE001 — recorded then re-raised
            _write_status(workflow_id, WorkflowStatus.RESUMABLE, error=repr(e))
            raise
        with open(os.path.join(_wf_dir(workflow_id), "output.pkl"), "wb") as f:
            pickle.dump(result, f)
        _write_status(workflow_id, WorkflowStatus.SUCCESSFUL)
        return result

    if wait:
        return body()
    t = threading.Thread(target=_suppress(body), daemon=True,
                         name=f"workflow-{workflow_id}")
    with _lock:
        _running[workflow_id] = t
    t.start()
    return workflow_id


def _suppress(fn):
    def inner():
        try:
            fn()
        except BaseException:  # noqa: BLE001 — status already recorded
            pass

    return inner


def resume(workflow_id: str) -> Any:
    """Re-run a RESUMABLE/CANCELED workflow; completed tasks load from
    checkpoints instead of re-executing."""
    saved = _load_dag(workflow_id)
    return _run_impl(saved["dag"], saved["args"], saved["kwargs"],
                     workflow_id, wait=True)


def cancel(workflow_id: str) -> None:
    """Cancel a RUNNING workflow; a no-op for terminal/unknown workflows."""
    status = _read_status(workflow_id)  # raises for unknown ids
    if status["status"] != WorkflowStatus.RUNNING.value:
        return
    with _lock:
        flag = _cancel_flags.get(workflow_id)
    if flag is not None:
        flag.set()
    _write_status(workflow_id, WorkflowStatus.CANCELED)


def get_status(workflow_id: str) -> WorkflowStatus:
    return WorkflowStatus(_read_status(workflow_id)["status"])


def get_output(workflow_id: str, *, timeout: Optional[float] = None) -> Any:
    """Block until the workflow finishes, then return its result."""
    deadline = None if timeout is None else time.monotonic() + timeout
    while True:
        st = get_status(workflow_id)
        if st == WorkflowStatus.SUCCESSFUL:
            with open(os.path.join(_root(), workflow_id, "output.pkl"), "rb") as f:
                return pickle.load(f)
        if st in (WorkflowStatus.FAILED, WorkflowStatus.RESUMABLE,
                  WorkflowStatus.CANCELED):
            err = _read_status(workflow_id).get("error")
            raise RuntimeError(f"workflow {workflow_id} is {st.value}: {err}")
        if deadline is not None and time.monotonic() > deadline:
            raise TimeoutError(f"workflow {workflow_id} still {st.value}")
        time.sleep(0.02)


def list_all() -> List[Dict[str, Any]]:
    out = []
    root = _root()
    for wid in sorted(os.listdir(root)):
        try:
            st = _read_status(wid)
        except (ValueError, json.JSONDecodeError):
            continue
        out.append({"workflow_id": wid, **st})
    return out
