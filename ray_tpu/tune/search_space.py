"""Search-space domains for Tune.

Reference analog: ``python/ray/tune/search/sample.py`` (Domain/Float/Integer/
Categorical samplers) and ``tune/search/variant_generator.py`` (grid
expansion). Domains are declarative: the variant generator resolves them into
concrete configs; ``grid_search`` values are cross-producted, stochastic
domains are drawn per sample.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Sequence, Tuple


class Domain:
    """A sampleable hyperparameter domain."""

    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False,
                 q: float | None = None):
        if log and lower <= 0:
            raise ValueError("loguniform requires lower > 0")
        self.lower, self.upper, self.log, self.q = lower, upper, log, q

    def sample(self, rng: random.Random) -> float:
        if self.log:
            import math

            v = math.exp(rng.uniform(math.log(self.lower), math.log(self.upper)))
        else:
            v = rng.uniform(self.lower, self.upper)
        if self.q is not None:
            v = round(round(v / self.q) * self.q, 10)
        return v


class Integer(Domain):
    def __init__(self, lower: int, upper: int, log: bool = False,
                 q: int | None = None):
        self.lower, self.upper, self.log, self.q = lower, upper, log, q

    def sample(self, rng: random.Random) -> int:
        if self.log:
            import math

            v = int(math.exp(rng.uniform(math.log(self.lower),
                                         math.log(self.upper))))
        else:
            v = rng.randint(self.lower, self.upper - 1)
        if self.q is not None:
            v = int(round(v / self.q) * self.q)
        return max(self.lower, min(v, self.upper - 1))


class Categorical(Domain):
    def __init__(self, categories: Sequence[Any]):
        self.categories = list(categories)

    def sample(self, rng: random.Random) -> Any:
        return rng.choice(self.categories)


class Function(Domain):
    def __init__(self, fn: Callable):
        self.fn = fn

    def sample(self, rng: random.Random) -> Any:
        try:
            return self.fn({"rng": rng})
        except TypeError:
            return self.fn()


class GridSearch:
    """Marker for exhaustive grid expansion (cross-producted across keys)."""

    def __init__(self, values: Sequence[Any]):
        self.values = list(values)


def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def quniform(lower: float, upper: float, q: float) -> Float:
    return Float(lower, upper, q=q)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def qloguniform(lower: float, upper: float, q: float) -> Float:
    return Float(lower, upper, log=True, q=q)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def qrandint(lower: int, upper: int, q: int) -> Integer:
    return Integer(lower, upper, q=q)


def lograndint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper, log=True)


def choice(categories: Sequence[Any]) -> Categorical:
    return Categorical(categories)


def sample_from(fn: Callable) -> Function:
    return Function(fn)


def grid_search(values: Sequence[Any]) -> GridSearch:
    return GridSearch(values)


def _is_grid(v: Any) -> bool:
    return isinstance(v, GridSearch) or (
        isinstance(v, dict) and set(v.keys()) == {"grid_search"})


def _grid_values(v: Any) -> List[Any]:
    return v.values if isinstance(v, GridSearch) else list(v["grid_search"])


def _walk(space: Dict, path: Tuple = ()):  # yields (path, value)
    for k, v in space.items():
        if isinstance(v, dict) and not _is_grid(v):
            yield from _walk(v, path + (k,))
        else:
            yield path + (k,), v


def _set_path(cfg: Dict, path: Tuple, value: Any) -> None:
    for k in path[:-1]:
        cfg = cfg.setdefault(k, {})
    cfg[path[-1]] = value


def generate_variants(space: Dict, num_samples: int,
                      seed: int | None = None) -> List[Dict]:
    """Expand a param space into concrete configs.

    Grid keys cross-product; each of the ``num_samples`` repetitions draws
    fresh values for stochastic domains (reference semantics: num_samples
    multiplies the grid).
    """
    rng = random.Random(seed)
    grid_paths: List[Tuple[Tuple, List]] = []
    leaf_items = list(_walk(space))
    for path, v in leaf_items:
        if _is_grid(v):
            grid_paths.append((path, _grid_values(v)))

    def grid_combos(i: int = 0):
        if i == len(grid_paths):
            yield []
            return
        path, values = grid_paths[i]
        for v in values:
            for rest in grid_combos(i + 1):
                yield [(path, v)] + rest

    variants = []
    for _ in range(num_samples):
        for combo in grid_combos():
            cfg: Dict = {}
            fixed = dict(combo)
            for path, v in leaf_items:
                if path in fixed:
                    _set_path(cfg, path, fixed[path])
                elif isinstance(v, Domain):
                    _set_path(cfg, path, v.sample(rng))
                else:
                    _set_path(cfg, path, v)
            variants.append(cfg)
    return variants
