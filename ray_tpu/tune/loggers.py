"""Per-trial experiment loggers: JSONL, CSV, TensorBoard.

Reference analogs: ``tune/logger/json.py`` (``result.json`` JSON lines),
``tune/logger/csv.py`` (``progress.csv``), ``tune/logger/tensorboard.py``
(TBX events). Always-on like the reference's defaults; TensorBoard events
are written when a writer implementation is importable (torch's
SummaryWriter here — no tensorboardX dependency) and silently skipped
otherwise.
"""

from __future__ import annotations

import csv
import json
import os
from typing import Any, Dict, Optional


def _scalarize(v: Any) -> Any:
    if hasattr(v, "item"):
        try:
            return v.item()
        except Exception:  # noqa: BLE001 — non-scalar array
            return str(v)
    return v


class TrialLoggers:
    """One per live trial; append-on-result, close-on-finalize."""

    def __init__(self, trial_dir: str):
        self._dir = trial_dir
        os.makedirs(trial_dir, exist_ok=True)
        # Resume-aware: on restore the trial dir already has rows — count
        # prior results so the CSV header isn't re-written mid-file and TB
        # steps continue instead of zig-zagging back to 0.
        prior = 0
        csv_path = os.path.join(trial_dir, "progress.csv")
        self._resumed_fieldnames = None
        if os.path.exists(csv_path):
            with open(csv_path, newline="") as f:
                rows = list(csv.reader(f))
            if rows:
                self._resumed_fieldnames = rows[0]  # quote-aware parse
                prior = max(0, len(rows) - 1)
        self._jsonl = open(os.path.join(trial_dir, "result.json"), "a")
        self._csv_file = open(csv_path, "a", newline="")
        self._csv: Optional[csv.DictWriter] = None
        self._tb = None
        try:
            from torch.utils.tensorboard import SummaryWriter

            # purge events past the persisted row count: a crashed run may
            # have logged further steps TB-side than the CSV kept
            self._tb = SummaryWriter(log_dir=trial_dir,
                                     purge_step=prior + 1 if prior else None)
        except Exception:  # noqa: BLE001 — TB optional
            self._tb = None
        self._step = prior

    def on_result(self, result: Dict[str, Any]) -> None:
        self._step += 1
        row = {k: _scalarize(v) for k, v in result.items()}
        self._jsonl.write(json.dumps(row, default=str) + "\n")
        self._jsonl.flush()
        if self._csv is None:
            if self._resumed_fieldnames:
                self._csv = csv.DictWriter(
                    self._csv_file, fieldnames=self._resumed_fieldnames)
            else:
                self._csv = csv.DictWriter(self._csv_file,
                                           fieldnames=sorted(row))
                self._csv.writeheader()
        self._csv.writerow({k: row.get(k) for k in self._csv.fieldnames})
        self._csv_file.flush()
        if self._tb is not None:
            for k, v in row.items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    try:
                        self._tb.add_scalar(k, v, global_step=self._step)
                    except Exception:  # noqa: BLE001
                        pass
            self._tb.flush()

    def close(self) -> None:
        try:
            self._jsonl.close()
            self._csv_file.close()
        except Exception:  # noqa: BLE001
            pass
        if self._tb is not None:
            try:
                self._tb.close()
            except Exception:  # noqa: BLE001
                pass
