"""Per-trial experiment loggers: JSONL, CSV, TensorBoard.

Reference analogs: ``tune/logger/json.py`` (``result.json`` JSON lines),
``tune/logger/csv.py`` (``progress.csv``), ``tune/logger/tensorboard.py``
(TBX events). Always-on like the reference's defaults; TensorBoard events
are written when a writer implementation is importable (torch's
SummaryWriter here — no tensorboardX dependency) and silently skipped
otherwise.
"""

from __future__ import annotations

import csv
import json
import os
from typing import Any, Dict, Optional


def _scalarize(v: Any) -> Any:
    if hasattr(v, "item"):
        try:
            return v.item()
        except Exception:  # noqa: BLE001 — non-scalar array
            return str(v)
    return v


class TrialLoggers:
    """One per live trial; append-on-result, close-on-finalize."""

    def __init__(self, trial_dir: str):
        self._dir = trial_dir
        os.makedirs(trial_dir, exist_ok=True)
        self._jsonl = open(os.path.join(trial_dir, "result.json"), "a")
        self._csv_file = open(os.path.join(trial_dir, "progress.csv"), "a",
                              newline="")
        self._csv: Optional[csv.DictWriter] = None
        self._tb = None
        try:
            from torch.utils.tensorboard import SummaryWriter

            self._tb = SummaryWriter(log_dir=trial_dir)
        except Exception:  # noqa: BLE001 — TB optional
            self._tb = None
        self._step = 0

    def on_result(self, result: Dict[str, Any]) -> None:
        self._step += 1
        row = {k: _scalarize(v) for k, v in result.items()}
        self._jsonl.write(json.dumps(row, default=str) + "\n")
        self._jsonl.flush()
        if self._csv is None:
            self._csv = csv.DictWriter(self._csv_file,
                                       fieldnames=sorted(row))
            self._csv.writeheader()
        self._csv.writerow({k: row.get(k) for k in self._csv.fieldnames})
        self._csv_file.flush()
        if self._tb is not None:
            for k, v in row.items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    try:
                        self._tb.add_scalar(k, v, global_step=self._step)
                    except Exception:  # noqa: BLE001
                        pass
            self._tb.flush()

    def close(self) -> None:
        try:
            self._jsonl.close()
            self._csv_file.close()
        except Exception:  # noqa: BLE001
            pass
        if self._tb is not None:
            try:
                self._tb.close()
            except Exception:  # noqa: BLE001
                pass
