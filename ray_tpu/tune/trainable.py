"""Trainable: the unit of execution for a Tune trial.

Reference analog: ``tune/trainable/trainable.py`` (class API) and
``tune/trainable/function_trainable.py:373`` (function API — the user fn runs
in a thread and ``tune.report`` enqueues results into a queue the trial loop
drains, same contract as the reference's ``:199-264,:410-414``).

The trial runner actor (`_TrialRunner`) hosts one Trainable instance; the
controller drives it one ``train()`` call at a time.
"""

from __future__ import annotations

import os
import pickle
import queue
import threading
import time
from typing import Any, Callable, Dict, Optional

from ray_tpu.train.checkpoint import Checkpoint

# Sessions are keyed by the fn-runner thread id, NOT a single global: the
# local (threaded) backend hosts every trial actor in one process, and a
# process-wide session would let a newly started trial clobber earlier ones
# (tune.report() silently crediting metrics to the wrong trial).
_session_lock = threading.Lock()
_sessions: Dict[int, "_FunctionSession"] = {}


def _current_session() -> Optional["_FunctionSession"]:
    with _session_lock:
        s = _sessions.get(threading.get_ident())
        if s is not None:
            return s
        # helper threads spawned by the trial fn have no registered ident;
        # fall back to the unique active session when unambiguous (the
        # single-trial case — matches the old process-global behavior)
        alive = {id(v): v for v in _sessions.values()}
        if len(alive) == 1:
            return next(iter(alive.values()))
        return None

DONE = "done"
TRAINING_ITERATION = "training_iteration"


class _FunctionSession:
    def __init__(self, checkpoint: Optional[Checkpoint]):
        self.queue: "queue.Queue" = queue.Queue(maxsize=2)
        self.loaded_checkpoint = checkpoint
        self.error: Optional[BaseException] = None
        self.finished = threading.Event()

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None) -> None:
        self.queue.put(("report", dict(metrics), checkpoint))


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    """Report metrics (and optionally a checkpoint) from a function trainable.

    Inside a ``JaxTrainer`` train loop use ``ray_tpu.train.report``; this is
    the Tune-level equivalent for plain tune functions.
    """
    s = _current_session()
    if s is None:
        raise RuntimeError("tune.report() called outside a Tune trial")
    s.report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    s = _current_session()
    if s is None:
        raise RuntimeError("tune.get_checkpoint() called outside a Tune trial")
    return s.loaded_checkpoint


class Trainable:
    """Class API: subclass and implement ``setup``/``step`` (and optionally
    ``save_checkpoint``/``load_checkpoint`` for PBT / fault tolerance)."""

    def __init__(self, config: Dict[str, Any]):
        self.config = config
        self._iteration = 0
        self._start_time = time.time()
        self.setup(config)

    def setup(self, config: Dict[str, Any]) -> None:
        pass

    def step(self) -> Dict[str, Any]:
        raise NotImplementedError

    def save_checkpoint(self, checkpoint_dir: str) -> Optional[Dict]:
        return None

    def load_checkpoint(self, checkpoint: Dict) -> None:
        pass

    def reset_config(self, new_config: Dict[str, Any]) -> bool:
        return False

    def cleanup(self) -> None:
        pass

    # -- controller-facing --
    def train(self) -> Dict[str, Any]:
        result = self.step()
        self._iteration += 1
        result.setdefault(DONE, False)
        result[TRAINING_ITERATION] = self._iteration
        result.setdefault("time_total_s", time.time() - self._start_time)
        return result

    def save(self, checkpoint_dir: str) -> Optional[str]:
        os.makedirs(checkpoint_dir, exist_ok=True)
        data = self.save_checkpoint(checkpoint_dir)
        path = os.path.join(checkpoint_dir, "trainable.pkl")
        with open(path, "wb") as f:
            pickle.dump({"data": data, "iteration": self._iteration}, f)
        return checkpoint_dir

    def restore(self, checkpoint_dir: str) -> None:
        path = os.path.join(checkpoint_dir, "trainable.pkl")
        with open(path, "rb") as f:
            payload = pickle.load(f)
        self._iteration = payload["iteration"]
        if payload["data"] is not None:
            self.load_checkpoint(payload["data"])


class FunctionTrainable(Trainable):
    """Adapts ``fn(config)`` to the Trainable interface by running it in a
    thread and draining ``tune.report`` results one ``train()`` at a time."""

    _fn: Callable = None  # set by wrap_function subclassing

    def setup(self, config: Dict[str, Any]) -> None:
        self._thread: Optional[threading.Thread] = None
        self._fsession: Optional[_FunctionSession] = None
        self._restored_checkpoint: Optional[Checkpoint] = None
        self._last_checkpoint: Optional[Checkpoint] = None

    def _start(self) -> None:
        fsession = _FunctionSession(self._restored_checkpoint)

        def runner():
            # register under the runner thread's own id so report() from
            # within the fn resolves to *this* trial's session even with
            # many concurrent trials in one process (local backend)
            with _session_lock:
                _sessions[threading.get_ident()] = fsession
            try:
                self._fn(self.config)
            except BaseException as e:  # surfaced via train()
                fsession.error = e
            finally:
                with _session_lock:
                    _sessions.pop(threading.get_ident(), None)
                fsession.finished.set()
                fsession.queue.put(("end", None, None))

        self._fsession = fsession
        self._thread = threading.Thread(target=runner, daemon=True)
        self._thread.start()

    def train(self) -> Dict[str, Any]:
        if self._thread is None:
            self._start()
        kind, metrics, checkpoint = self._fsession.queue.get()
        if kind == "end":
            if self._fsession.error is not None:
                raise self._fsession.error
            result = dict(self._last_result) if hasattr(self, "_last_result") else {}
            result[DONE] = True
            result[TRAINING_ITERATION] = self._iteration
            return result
        self._iteration += 1
        result = dict(metrics)
        result.setdefault(DONE, False)
        result[TRAINING_ITERATION] = self._iteration
        self._last_result = result
        if checkpoint is not None:
            self._last_checkpoint = checkpoint
        return result

    def save_checkpoint(self, checkpoint_dir: str) -> Optional[Dict]:
        if self._last_checkpoint is not None:
            return {"checkpoint": self._last_checkpoint.to_dict()}
        return None

    def load_checkpoint(self, checkpoint: Dict) -> None:
        self._restored_checkpoint = Checkpoint.from_dict(checkpoint["checkpoint"])

    def cleanup(self) -> None:
        if self._fsession is not None:
            self._fsession.finished.wait(timeout=0)


def wrap_function(fn: Callable) -> type:
    """Build a FunctionTrainable subclass around ``fn(config)``."""

    class _Wrapped(FunctionTrainable):
        pass

    _Wrapped._fn = staticmethod(fn)
    _Wrapped.__name__ = getattr(fn, "__name__", "fn")
    res = getattr(fn, "_tune_resources", None)
    if res is not None:
        _Wrapped._tune_resources = dict(res)
    return _Wrapped


def with_resources(trainable, resources: Dict[str, float]):
    """Attach per-trial resource requirements without mutating the caller's
    trainable (a shared class/function must not leak one Tuner's resources
    into another's)."""
    import copy
    import functools
    import inspect

    if isinstance(trainable, type):
        return type(trainable.__name__, (trainable,),
                    {"_tune_resources": dict(resources)})
    if inspect.isfunction(trainable) or inspect.ismethod(trainable):
        @functools.wraps(trainable)
        def wrapper(*args, **kwargs):
            return trainable(*args, **kwargs)

        wrapper._tune_resources = dict(resources)
        return wrapper
    # instance trainables (e.g. JaxTrainer): shallow-copy so the attribute
    # doesn't leak into other Tuners sharing the instance
    clone = copy.copy(trainable)
    clone._tune_resources = dict(resources)
    return clone
