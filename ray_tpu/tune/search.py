"""Search algorithms.

Reference analogs: ``tune/search/searcher.py`` (Searcher interface),
``tune/search/basic_variant.py`` (grid/random via variant generation),
``tune/search/concurrency_limiter.py``. Model-based searchers in the
reference (hyperopt/optuna/...) are external-library adapters; here the
native model-based searcher is a simple TPE-style ``QuasiRandomSearch``
over the declarative domains.
"""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional

from ray_tpu.tune.search_space import (
    Categorical,
    Domain,
    Float,
    Integer,
    _is_grid,
    generate_variants,
)


class Searcher:
    def __init__(self, metric: Optional[str] = None, mode: Optional[str] = None):
        self._metric = metric
        self._mode = mode
        self._budget: Optional[int] = None  # TuneConfig.num_samples
        self._issued = 0

    def set_num_samples(self, n: int) -> None:
        """Trial budget (TuneConfig.num_samples). The controller keeps
        calling suggest() until it returns None — a searcher that never
        exhausts would spin the trial loop forever. A budget set explicitly
        at construction (e.g. QuasiRandomSearch(num_samples=...)) wins over
        the TuneConfig default."""
        if self._budget is None:
            self._budget = n

    def _take_budget(self) -> bool:
        if self._budget is not None and self._issued >= self._budget:
            return False
        self._issued += 1
        return True

    def set_search_properties(self, metric: Optional[str], mode: Optional[str],
                              config: Dict[str, Any]) -> bool:
        if self._metric is None:
            self._metric = metric
        if self._mode is None:
            self._mode = mode
        self._space = config
        return True

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        pass


class BasicVariantGenerator(Searcher):
    """Grid + random sampling via up-front variant expansion."""

    def __init__(self, points_to_evaluate: Optional[List[Dict]] = None,
                 max_concurrent: int = 0, seed: Optional[int] = None):
        super().__init__()
        self._points = list(points_to_evaluate or [])
        self._seed = seed
        self._variants: Optional[List[Dict]] = None
        self._idx = 0
        self._num_samples = 1
        # honored by Tuner.fit, which wraps this in a ConcurrencyLimiter
        self._max_concurrent = max_concurrent

    def set_num_samples(self, n: int) -> None:
        self._num_samples = n

    def set_search_properties(self, metric, mode, config) -> bool:
        super().set_search_properties(metric, mode, config)
        self._variants = self._points + generate_variants(
            config or {}, self._num_samples, seed=self._seed)
        return True

    @property
    def total_trials(self) -> int:
        return len(self._variants or [])

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._variants is None or self._idx >= len(self._variants):
            return None
        cfg = self._variants[self._idx]
        self._idx += 1
        return cfg


class QuasiRandomSearch(Searcher):
    """Model-based-ish native searcher: exploit the best known config's
    neighborhood with probability ``exploit_p`` once enough results exist,
    else explore by sampling the domains (a light-weight stand-in for the
    reference's external hyperopt/optuna adapters)."""

    def __init__(self, metric: Optional[str] = None, mode: Optional[str] = None,
                 num_samples: Optional[int] = None, exploit_p: float = 0.5,
                 min_observations: int = 4, seed: int = 0):
        super().__init__(metric, mode)
        self._rng = random.Random(seed)
        # explicit ctor budget wins; None defers to TuneConfig.num_samples
        self._budget = num_samples
        self._exploit_p = exploit_p
        self._min_obs = min_observations
        self._observed: List[Dict[str, Any]] = []
        self._configs: Dict[str, Dict] = {}

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if not self._take_budget():
            return None
        space = getattr(self, "_space", {}) or {}
        best = self._best_config()
        cfg: Dict[str, Any] = {}
        for key, v in space.items():
            if _is_grid(v):
                raise ValueError("grid_search is not supported by QuasiRandomSearch")
            if not isinstance(v, Domain):
                cfg[key] = v
                continue
            if best is not None and self._rng.random() < self._exploit_p:
                cfg[key] = self._perturb(v, best.get(key))
            else:
                cfg[key] = v.sample(self._rng)
        self._configs[trial_id] = cfg
        return cfg

    def _perturb(self, domain: Domain, base: Any) -> Any:
        if base is None:
            return domain.sample(self._rng)
        if isinstance(domain, Float):
            span = (domain.upper - domain.lower) * 0.2
            v = base + self._rng.uniform(-span, span)
            return min(max(v, domain.lower), domain.upper)
        if isinstance(domain, Integer):
            span = max(1, int((domain.upper - domain.lower) * 0.2))
            v = base + self._rng.randint(-span, span)
            return min(max(v, domain.lower), domain.upper - 1)
        if isinstance(domain, Categorical):
            return base if self._rng.random() < 0.5 else domain.sample(self._rng)
        return domain.sample(self._rng)

    def _best_config(self) -> Optional[Dict[str, Any]]:
        if len(self._observed) < self._min_obs:
            return None
        sign = 1 if (self._mode or "max") == "max" else -1
        best = max(self._observed, key=lambda o: sign * o["value"])
        return best["config"]

    def on_trial_complete(self, trial_id, result=None, error=False) -> None:
        if error or result is None or self._metric not in result:
            self._configs.pop(trial_id, None)
            return
        cfg = self._configs.pop(trial_id, None)
        if cfg is not None:
            self._observed.append({"config": cfg, "value": result[self._metric]})


class ConcurrencyLimiter(Searcher):
    def __init__(self, searcher: Searcher, max_concurrent: int):
        super().__init__(searcher._metric, searcher._mode)
        self._searcher = searcher
        self._max = max_concurrent
        self._live: set = set()

    def set_search_properties(self, metric, mode, config) -> bool:
        return self._searcher.set_search_properties(metric, mode, config)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if len(self._live) >= self._max:
            return None
        cfg = self._searcher.suggest(trial_id)
        if cfg is not None:
            self._live.add(trial_id)
        return cfg

    def on_trial_complete(self, trial_id, result=None, error=False) -> None:
        self._live.discard(trial_id)
        self._searcher.on_trial_complete(trial_id, result, error)


class TPESearcher(Searcher):
    """Tree-structured Parzen Estimator (native — the reference wraps
    hyperopt for this; ``tune/search/hyperopt``). After ``n_initial``
    random trials, observations split at the ``gamma`` quantile into
    good/bad sets; numeric dims sample candidates from a KDE over the good
    set and keep the candidate maximizing the good/bad density ratio;
    categorical dims sample by smoothed good-set counts over bad-set
    counts. Log-scaled domains model densities in log space."""

    def __init__(self, metric: Optional[str] = None, mode: Optional[str] = None,
                 n_initial: int = 10, gamma: float = 0.25,
                 n_candidates: int = 24, seed: Optional[int] = None):
        super().__init__(metric, mode)
        self._n_initial = n_initial
        self._gamma = gamma
        self._n_candidates = n_candidates
        self._rng = random.Random(seed)
        self._live: Dict[str, Dict[str, Any]] = {}
        self._obs: List[tuple] = []  # (config, score) — higher is better

    # -- density helpers -----------------------------------------------------
    @staticmethod
    def _to_model_space(domain, v: float) -> float:
        import math

        return math.log(v) if getattr(domain, "log", False) else float(v)

    @staticmethod
    def _kde_logpdf(xs: List[float], x: float, bw: float) -> float:
        import math

        if not xs:
            return 0.0
        acc = 0.0
        for mu in xs:
            acc += math.exp(-0.5 * ((x - mu) / bw) ** 2)
        return math.log(acc / (len(xs) * bw) + 1e-12)

    def _suggest_numeric(self, name: str, domain, good: List[Dict],
                         bad: List[Dict]):
        import math

        lo = self._to_model_space(domain, domain.lower)
        hi = self._to_model_space(domain, max(domain.upper, domain.lower + 1e-12))
        bw = max((hi - lo) / 5.0, 1e-6)
        gx = [self._to_model_space(domain, c[name]) for c in good]
        bx = [self._to_model_space(domain, c[name]) for c in bad]
        best_v, best_score = None, -float("inf")
        for _ in range(self._n_candidates):
            if gx and self._rng.random() < 0.8:
                center = self._rng.choice(gx)
                x = self._rng.gauss(center, bw)
                x = min(max(x, lo), hi)
            else:
                x = self._rng.uniform(lo, hi)
            score = (self._kde_logpdf(gx, x, bw)
                     - self._kde_logpdf(bx, x, bw))
            if score > best_score:
                best_score, best_v = score, x
        v = math.exp(best_v) if getattr(domain, "log", False) else best_v
        if isinstance(domain, Integer):
            return max(domain.lower, min(int(round(v)), domain.upper - 1))
        if getattr(domain, "q", None):
            v = round(v / domain.q) * domain.q
        return min(max(v, domain.lower), domain.upper)

    def _suggest_categorical(self, name: str, domain, good, bad):
        weights = []
        for choice in domain.categories:
            g = sum(1 for c in good if c[name] == choice) + 1.0
            b = sum(1 for c in bad if c[name] == choice) + 1.0
            weights.append(g / b)
        total = sum(weights)
        r = self._rng.random() * total
        acc = 0.0
        for choice, w in zip(domain.categories, weights):
            acc += w
            if r <= acc:
                return choice
        return domain.categories[-1]

    def _model_observations(self) -> List[tuple]:
        """(config, score) pairs the density model fits on — subclasses
        (BOHB) override to pick a fidelity-specific observation set."""
        return self._obs

    # -- Searcher API --------------------------------------------------------
    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if not self._take_budget():
            return None
        space = getattr(self, "_space", None) or {}
        config = {}
        obs = self._model_observations()
        enough = len(obs) >= self._n_initial
        if enough:
            ranked = sorted(obs, key=lambda o: -o[1])
            n_good = max(1, int(len(ranked) * self._gamma))
            good = [c for c, _ in ranked[:n_good]]
            bad = [c for c, _ in ranked[n_good:]] or good
        for name, domain in space.items():
            if _is_grid(domain):
                raise ValueError("grid_search is not supported by "
                                 "TPESearcher (use BasicVariantGenerator)")
            if not isinstance(domain, Domain):
                config[name] = domain
            elif not enough or not isinstance(domain,
                                              (Float, Integer, Categorical)):
                # warm-up, and Function/sample_from domains (no bounds to
                # model a density over) always sample directly
                config[name] = domain.sample(self._rng)
            elif isinstance(domain, Categorical):
                config[name] = self._suggest_categorical(name, domain,
                                                         good, bad)
            else:
                config[name] = self._suggest_numeric(name, domain, good, bad)
        self._live[trial_id] = config
        return dict(config)

    def on_trial_complete(self, trial_id, result=None, error=False) -> None:
        config = self._live.pop(trial_id, None)
        if config is None or error or not result:
            return
        value = result.get(self._metric)
        if value is None:
            return
        score = value if self._mode != "min" else -value
        self._obs.append((config, float(score)))


class BOHBSearcher(TPESearcher):
    """BOHB's model half: a TPE whose density model fits on the HIGHEST
    rung (fidelity) that has enough observations — fed intermediate rung
    results by ``HyperBandForBOHB`` (reference: ``tune/search/bohb`` +
    ``schedulers/hb_bohb.py``; the BOHB paper's per-budget KDE rule).
    Completed-trial results land on an implicit "final" rung above all
    scheduler rungs."""

    FINAL_RUNG = float("inf")

    def __init__(self, *args, min_points_per_rung: int = 6, **kwargs):
        super().__init__(*args, **kwargs)
        self._min_points = min_points_per_rung
        self._rung_obs: Dict[float, List[tuple]] = {}

    def on_rung_result(self, config: Dict[str, Any], score: float,
                       rung: float) -> None:
        """Called by the paired scheduler at every rung crossing with the
        sign-normalized (higher-is-better) score."""
        self._rung_obs.setdefault(rung, []).append((dict(config), score))

    def on_trial_complete(self, trial_id, result=None, error=False) -> None:
        config = self._live.get(trial_id)
        super().on_trial_complete(trial_id, result, error)
        if config is not None and result and not error:
            value = result.get(self._metric)
            if value is not None:
                score = value if self._mode != "min" else -value
                self.on_rung_result(config, float(score), self.FINAL_RUNG)

    def _model_observations(self) -> List[tuple]:
        for rung in sorted(self._rung_obs, reverse=True):
            if len(self._rung_obs[rung]) >= max(self._min_points,
                                                self._n_initial):
                return self._rung_obs[rung]
        # no rung is dense enough yet: pool everything (low-fidelity
        # evidence beats none — BOHB's own fallback)
        pooled = [o for obs in self._rung_obs.values() for o in obs]
        return pooled or self._obs
