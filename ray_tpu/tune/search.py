"""Search algorithms.

Reference analogs: ``tune/search/searcher.py`` (Searcher interface),
``tune/search/basic_variant.py`` (grid/random via variant generation),
``tune/search/concurrency_limiter.py``. Model-based searchers in the
reference (hyperopt/optuna/...) are external-library adapters; here the
native model-based searcher is a simple TPE-style ``QuasiRandomSearch``
over the declarative domains.
"""

from __future__ import annotations

import random
from typing import Any, Dict, List, Optional

from ray_tpu.tune.search_space import (
    Categorical,
    Domain,
    Float,
    Integer,
    _is_grid,
    generate_variants,
)


class Searcher:
    def __init__(self, metric: Optional[str] = None, mode: Optional[str] = None):
        self._metric = metric
        self._mode = mode

    def set_search_properties(self, metric: Optional[str], mode: Optional[str],
                              config: Dict[str, Any]) -> bool:
        if self._metric is None:
            self._metric = metric
        if self._mode is None:
            self._mode = mode
        self._space = config
        return True

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        pass


class BasicVariantGenerator(Searcher):
    """Grid + random sampling via up-front variant expansion."""

    def __init__(self, points_to_evaluate: Optional[List[Dict]] = None,
                 max_concurrent: int = 0, seed: Optional[int] = None):
        super().__init__()
        self._points = list(points_to_evaluate or [])
        self._seed = seed
        self._variants: Optional[List[Dict]] = None
        self._idx = 0
        self._num_samples = 1
        # honored by Tuner.fit, which wraps this in a ConcurrencyLimiter
        self._max_concurrent = max_concurrent

    def set_num_samples(self, n: int) -> None:
        self._num_samples = n

    def set_search_properties(self, metric, mode, config) -> bool:
        super().set_search_properties(metric, mode, config)
        self._variants = self._points + generate_variants(
            config or {}, self._num_samples, seed=self._seed)
        return True

    @property
    def total_trials(self) -> int:
        return len(self._variants or [])

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._variants is None or self._idx >= len(self._variants):
            return None
        cfg = self._variants[self._idx]
        self._idx += 1
        return cfg


class QuasiRandomSearch(Searcher):
    """Model-based-ish native searcher: exploit the best known config's
    neighborhood with probability ``exploit_p`` once enough results exist,
    else explore by sampling the domains (a light-weight stand-in for the
    reference's external hyperopt/optuna adapters)."""

    def __init__(self, metric: Optional[str] = None, mode: Optional[str] = None,
                 num_samples: int = 16, exploit_p: float = 0.5,
                 min_observations: int = 4, seed: int = 0):
        super().__init__(metric, mode)
        self._rng = random.Random(seed)
        self._budget = num_samples
        self._issued = 0
        self._exploit_p = exploit_p
        self._min_obs = min_observations
        self._observed: List[Dict[str, Any]] = []
        self._configs: Dict[str, Dict] = {}

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._issued >= self._budget:
            return None
        self._issued += 1
        space = getattr(self, "_space", {}) or {}
        best = self._best_config()
        cfg: Dict[str, Any] = {}
        for key, v in space.items():
            if _is_grid(v):
                raise ValueError("grid_search is not supported by QuasiRandomSearch")
            if not isinstance(v, Domain):
                cfg[key] = v
                continue
            if best is not None and self._rng.random() < self._exploit_p:
                cfg[key] = self._perturb(v, best.get(key))
            else:
                cfg[key] = v.sample(self._rng)
        self._configs[trial_id] = cfg
        return cfg

    def _perturb(self, domain: Domain, base: Any) -> Any:
        if base is None:
            return domain.sample(self._rng)
        if isinstance(domain, Float):
            span = (domain.upper - domain.lower) * 0.2
            v = base + self._rng.uniform(-span, span)
            return min(max(v, domain.lower), domain.upper)
        if isinstance(domain, Integer):
            span = max(1, int((domain.upper - domain.lower) * 0.2))
            v = base + self._rng.randint(-span, span)
            return min(max(v, domain.lower), domain.upper - 1)
        if isinstance(domain, Categorical):
            return base if self._rng.random() < 0.5 else domain.sample(self._rng)
        return domain.sample(self._rng)

    def _best_config(self) -> Optional[Dict[str, Any]]:
        if len(self._observed) < self._min_obs:
            return None
        sign = 1 if (self._mode or "max") == "max" else -1
        best = max(self._observed, key=lambda o: sign * o["value"])
        return best["config"]

    def on_trial_complete(self, trial_id, result=None, error=False) -> None:
        if error or result is None or self._metric not in result:
            self._configs.pop(trial_id, None)
            return
        cfg = self._configs.pop(trial_id, None)
        if cfg is not None:
            self._observed.append({"config": cfg, "value": result[self._metric]})


class ConcurrencyLimiter(Searcher):
    def __init__(self, searcher: Searcher, max_concurrent: int):
        super().__init__(searcher._metric, searcher._mode)
        self._searcher = searcher
        self._max = max_concurrent
        self._live: set = set()

    def set_search_properties(self, metric, mode, config) -> bool:
        return self._searcher.set_search_properties(metric, mode, config)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if len(self._live) >= self._max:
            return None
        cfg = self._searcher.suggest(trial_id)
        if cfg is not None:
            self._live.add(trial_id)
        return cfg

    def on_trial_complete(self, trial_id, result=None, error=False) -> None:
        self._live.discard(trial_id)
        self._searcher.on_trial_complete(trial_id, result, error)
