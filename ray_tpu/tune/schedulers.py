"""Trial schedulers: early stopping and population-based training.

Reference analogs: ``tune/schedulers/trial_scheduler.py`` (decision enum),
``async_hyperband.py`` (ASHA brackets/rungs), ``median_stopping_rule.py``,
``hyperband.py``, ``pbt.py``. The controller calls ``on_trial_result`` after
every result and acts on the returned decision.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.tune.trial import Trial

CONTINUE = "CONTINUE"
STOP = "STOP"
PAUSE = "PAUSE"


class TrialScheduler:
    def set_search_properties(self, metric: Optional[str],
                              mode: Optional[str]) -> None:
        if getattr(self, "_metric", None) is None:
            self._metric = metric
        if getattr(self, "_mode", None) is None:
            self._mode = mode

    def on_trial_add(self, trial: Trial) -> None:
        pass

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, trial: Trial, result: Dict[str, Any]) -> None:
        pass

    def on_trial_error(self, trial: Trial) -> None:
        pass

    # PBT hook: returns (new_config, restore_from_trial) or None
    def pop_mutation(self, trial: Trial):
        return None


class FIFOScheduler(TrialScheduler):
    """Run every trial to completion in submission order."""


def _score(value: float, mode: str) -> float:
    return value if mode == "max" else -value


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA: asynchronous successive halving.

    Rung milestones are ``grace_period * reduction_factor**k`` up to
    ``max_t``; at each rung a trial must beat the top ``1/reduction_factor``
    quantile of results recorded at that rung or be stopped
    (``tune/schedulers/async_hyperband.py`` semantics, single bracket by
    default).
    """

    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 3, brackets: int = 1):
        self._time_attr = time_attr
        self._metric = metric
        self._mode = mode
        self._max_t = max_t
        self._grace = grace_period
        self._rf = reduction_factor
        # per bracket: milestone list (bracket b starts at grace * rf^b) and
        # rung -> recorded sign-normalized scores
        self._bracket_milestones: List[List[int]] = []
        self._bracket_rungs: List[Dict[float, List[float]]] = []
        for b in range(max(1, brackets)):
            milestones = []
            t = int(grace_period * reduction_factor ** b)
            while t < max_t:
                milestones.append(t)
                t = math.ceil(t * reduction_factor)
            self._bracket_milestones.append(milestones)
            self._bracket_rungs.append({})
        self._num_brackets = max(1, brackets)
        self._next_bracket = 0
        self._trial_bracket: Dict[str, int] = {}
        self._trial_rung: Dict[str, int] = {}  # next milestone index per trial
        self._trial_recorded: Dict[str, Tuple[float, float]] = {}  # tid -> (rung, score)

    def on_trial_add(self, trial: Trial) -> None:
        self._trial_rung[trial.trial_id] = 0
        self._trial_bracket[trial.trial_id] = self._next_bracket
        self._next_bracket = (self._next_bracket + 1) % self._num_brackets

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        t = result.get(self._time_attr, 0)
        if t >= self._max_t:
            return STOP
        metric = result.get(self._metric)
        if metric is None:
            return CONTINUE
        bracket = self._trial_bracket.get(trial.trial_id, 0)
        milestones = self._bracket_milestones[bracket]
        rungs = self._bracket_rungs[bracket]
        idx = self._trial_rung.get(trial.trial_id, 0)
        decision = CONTINUE
        score = _score(metric, self._mode or "max")
        crossed = False
        while idx < len(milestones) and t >= milestones[idx]:
            crossed = True
            rung = milestones[idx]
            rungs.setdefault(rung, []).append(score)
            self._trial_recorded[trial.trial_id] = (rung, score)
            if self._below_cutoff(rungs, rung, score):
                decision = STOP
            idx += 1
        self._trial_rung[trial.trial_id] = idx
        if not crossed:
            # async demotion: a trial that passed its last rung early may fall
            # below the cutoff as slower trials record — stop it on its next
            # report rather than letting it run to the next rung.
            rec = self._trial_recorded.get(trial.trial_id)
            if rec is not None and self._below_cutoff(rungs, rec[0], rec[1]):
                decision = STOP
        return decision

    def _below_cutoff(self, rungs: Dict[float, List[float]], rung: float,
                      score: float) -> bool:
        scores = rungs.get(rung, [])
        if len(scores) < self._rf:
            return False
        scores_sorted = sorted(scores, reverse=True)
        cutoff = scores_sorted[max(0, int(len(scores) / self._rf) - 1)]
        return score < cutoff


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best result so far is worse than the median of the
    running means of all other trials at the same step
    (``tune/schedulers/median_stopping_rule.py``)."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 grace_period: int = 1, min_samples_required: int = 3):
        self._time_attr = time_attr
        self._metric = metric
        self._mode = mode
        self._grace = grace_period
        self._min_samples = min_samples_required
        self._means: Dict[str, float] = {}
        self._counts: Dict[str, int] = {}
        self._best: Dict[str, float] = {}

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        t = result.get(self._time_attr, 0)
        metric = result.get(self._metric)
        if metric is None:
            return CONTINUE
        score = _score(metric, self._mode or "max")
        tid = trial.trial_id
        n = self._counts.get(tid, 0) + 1
        self._counts[tid] = n
        self._means[tid] = self._means.get(tid, 0.0) + (score - self._means.get(tid, 0.0)) / n
        self._best[tid] = max(self._best.get(tid, score), score)
        if t < self._grace:
            return CONTINUE
        others = [m for k, m in self._means.items() if k != tid]
        if len(others) < self._min_samples:
            return CONTINUE
        others.sort()
        median = others[len(others) // 2]
        # reference semantics: the trial's BEST result so far vs the median
        # of other trials' running means — an improving trial isn't punished
        # for a poor start
        if self._best[tid] < median:
            return STOP
        return CONTINUE


class HyperBandScheduler(AsyncHyperBandScheduler):
    """HyperBand as multi-bracket async successive halving: trials are
    assigned round-robin to brackets whose grace periods grow by the
    reduction factor (the asynchronous variant dominates strict synchronous
    HyperBand in practice; the reference itself recommends ASHA)."""

    def __init__(self, *args, **kwargs):
        kwargs.setdefault("brackets", 3)
        super().__init__(*args, **kwargs)


class PopulationBasedTraining(TrialScheduler):
    """PBT: at every ``perturbation_interval``, a bottom-quantile trial
    clones the checkpoint of a top-quantile trial and perturbs its
    hyperparameters (``tune/schedulers/pbt.py`` exploit/explore).

    The controller implements the mechanics: on a STOP-with-mutation
    decision it stops the runner, rewrites trial.config / restore_path from
    ``pop_mutation`` and requeues the trial.
    """

    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25, seed: int = 0):
        self._time_attr = time_attr
        self._metric = metric
        self._mode = mode
        self._interval = perturbation_interval
        self._mutations = hyperparam_mutations or {}
        self._quantile = quantile_fraction
        self._resample_p = resample_probability
        self._rng = random.Random(seed)
        self._last_perturb: Dict[str, int] = {}
        self._latest: Dict[str, float] = {}  # trial_id -> normalized score
        self._trials: Dict[str, Trial] = {}
        self._pending_mutation: Dict[str, Any] = {}

    def on_trial_add(self, trial: Trial) -> None:
        self._trials[trial.trial_id] = trial

    def _quantiles(self):
        ranked = sorted(self._latest.items(), key=lambda kv: kv[1])
        n = len(ranked)
        k = max(1, int(n * self._quantile))
        bottom = [tid for tid, _ in ranked[:k]]
        top = [tid for tid, _ in ranked[-k:]]
        return bottom, top

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        from ray_tpu.tune.search_space import Domain

        new = dict(config)
        for key, spec in self._mutations.items():
            if self._rng.random() < self._resample_p or key not in new:
                if isinstance(spec, Domain):
                    new[key] = spec.sample(self._rng)
                elif isinstance(spec, list):
                    new[key] = self._rng.choice(spec)
                elif callable(spec):
                    new[key] = spec()
            else:
                cur = new[key]
                if isinstance(cur, bool):
                    new[key] = not cur if self._rng.random() < 0.5 else cur
                elif isinstance(cur, int):
                    factor = 1.2 if self._rng.random() > 0.5 else 0.8
                    perturbed = round(cur * factor)
                    if perturbed == cur:  # small ints must still move
                        perturbed = cur + (1 if factor > 1 else -1)
                    if cur >= 1:  # keep inherently positive ints positive
                        perturbed = max(1, perturbed)
                    new[key] = perturbed
                elif isinstance(cur, float):
                    factor = 1.2 if self._rng.random() > 0.5 else 0.8
                    new[key] = cur * factor
                elif isinstance(spec, list):
                    new[key] = self._rng.choice(spec)
        return new

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        t = result.get(self._time_attr, 0)
        metric = result.get(self._metric)
        if metric is None:
            return CONTINUE
        self._latest[trial.trial_id] = _score(metric, self._mode or "max")
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last < self._interval or len(self._latest) < 2:
            return CONTINUE
        self._last_perturb[trial.trial_id] = t
        bottom, top = self._quantiles()
        if trial.trial_id not in bottom or trial.trial_id in top:
            return CONTINUE
        exploit_id = self._rng.choice(top)
        exploit = self._trials.get(exploit_id)
        if exploit is None or exploit.checkpoint_path is None:
            return CONTINUE
        self._pending_mutation[trial.trial_id] = (
            self._explore(exploit.config), exploit.checkpoint_path)
        return PAUSE  # controller stops the runner, mutates, requeues

    def pop_mutation(self, trial: Trial):
        return self._pending_mutation.pop(trial.trial_id, None)
