"""Trial schedulers: early stopping and population-based training.

Reference analogs: ``tune/schedulers/trial_scheduler.py`` (decision enum),
``async_hyperband.py`` (ASHA brackets/rungs), ``median_stopping_rule.py``,
``hyperband.py``, ``pbt.py``. The controller calls ``on_trial_result`` after
every result and acts on the returned decision.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.tune.trial import Trial

CONTINUE = "CONTINUE"
STOP = "STOP"
PAUSE = "PAUSE"


class TrialScheduler:
    def set_search_properties(self, metric: Optional[str],
                              mode: Optional[str]) -> None:
        if getattr(self, "_metric", None) is None:
            self._metric = metric
        if getattr(self, "_mode", None) is None:
            self._mode = mode

    def on_trial_add(self, trial: Trial) -> None:
        pass

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        return CONTINUE

    def on_trial_complete(self, trial: Trial, result: Dict[str, Any]) -> None:
        pass

    def on_trial_error(self, trial: Trial) -> None:
        pass

    # PBT hook: returns (new_config, restore_from_trial) or None
    def pop_mutation(self, trial: Trial):
        return None


class FIFOScheduler(TrialScheduler):
    """Run every trial to completion in submission order."""


def _score(value: float, mode: str) -> float:
    return value if mode == "max" else -value


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA: asynchronous successive halving.

    Rung milestones are ``grace_period * reduction_factor**k`` up to
    ``max_t``; at each rung a trial must beat the top ``1/reduction_factor``
    quantile of results recorded at that rung or be stopped
    (``tune/schedulers/async_hyperband.py`` semantics, single bracket by
    default).
    """

    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 3, brackets: int = 1):
        self._time_attr = time_attr
        self._metric = metric
        self._mode = mode
        self._max_t = max_t
        self._grace = grace_period
        self._rf = reduction_factor
        # per bracket: milestone list (bracket b starts at grace * rf^b) and
        # rung -> recorded sign-normalized scores
        self._bracket_milestones: List[List[int]] = []
        self._bracket_rungs: List[Dict[float, List[float]]] = []
        for b in range(max(1, brackets)):
            milestones = []
            t = int(grace_period * reduction_factor ** b)
            while t < max_t:
                milestones.append(t)
                t = math.ceil(t * reduction_factor)
            self._bracket_milestones.append(milestones)
            self._bracket_rungs.append({})
        self._num_brackets = max(1, brackets)
        self._next_bracket = 0
        self._trial_bracket: Dict[str, int] = {}
        self._trial_rung: Dict[str, int] = {}  # next milestone index per trial
        self._trial_recorded: Dict[str, Tuple[float, float]] = {}  # tid -> (rung, score)

    def on_trial_add(self, trial: Trial) -> None:
        self._trial_rung[trial.trial_id] = 0
        self._trial_bracket[trial.trial_id] = self._next_bracket
        self._next_bracket = (self._next_bracket + 1) % self._num_brackets

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        t = result.get(self._time_attr, 0)
        if t >= self._max_t:
            return STOP
        metric = result.get(self._metric)
        if metric is None:
            return CONTINUE
        bracket = self._trial_bracket.get(trial.trial_id, 0)
        milestones = self._bracket_milestones[bracket]
        rungs = self._bracket_rungs[bracket]
        idx = self._trial_rung.get(trial.trial_id, 0)
        decision = CONTINUE
        score = _score(metric, self._mode or "max")
        crossed = False
        while idx < len(milestones) and t >= milestones[idx]:
            crossed = True
            rung = milestones[idx]
            rungs.setdefault(rung, []).append(score)
            self._trial_recorded[trial.trial_id] = (rung, score)
            if self._below_cutoff(rungs, rung, score):
                decision = STOP
            idx += 1
        self._trial_rung[trial.trial_id] = idx
        if not crossed:
            # async demotion: a trial that passed its last rung early may fall
            # below the cutoff as slower trials record — stop it on its next
            # report rather than letting it run to the next rung.
            rec = self._trial_recorded.get(trial.trial_id)
            if rec is not None and self._below_cutoff(rungs, rec[0], rec[1]):
                decision = STOP
        return decision

    def _below_cutoff(self, rungs: Dict[float, List[float]], rung: float,
                      score: float) -> bool:
        scores = rungs.get(rung, [])
        if len(scores) < self._rf:
            return False
        scores_sorted = sorted(scores, reverse=True)
        cutoff = scores_sorted[max(0, int(len(scores) / self._rf) - 1)]
        return score < cutoff


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best result so far is worse than the median of the
    running means of all other trials at the same step
    (``tune/schedulers/median_stopping_rule.py``)."""

    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 grace_period: int = 1, min_samples_required: int = 3):
        self._time_attr = time_attr
        self._metric = metric
        self._mode = mode
        self._grace = grace_period
        self._min_samples = min_samples_required
        self._means: Dict[str, float] = {}
        self._counts: Dict[str, int] = {}
        self._best: Dict[str, float] = {}

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        t = result.get(self._time_attr, 0)
        metric = result.get(self._metric)
        if metric is None:
            return CONTINUE
        score = _score(metric, self._mode or "max")
        tid = trial.trial_id
        n = self._counts.get(tid, 0) + 1
        self._counts[tid] = n
        self._means[tid] = self._means.get(tid, 0.0) + (score - self._means.get(tid, 0.0)) / n
        self._best[tid] = max(self._best.get(tid, score), score)
        if t < self._grace:
            return CONTINUE
        others = [m for k, m in self._means.items() if k != tid]
        if len(others) < self._min_samples:
            return CONTINUE
        others.sort()
        median = others[len(others) // 2]
        # reference semantics: the trial's BEST result so far vs the median
        # of other trials' running means — an improving trial isn't punished
        # for a poor start
        if self._best[tid] < median:
            return STOP
        return CONTINUE


class HyperBandScheduler(AsyncHyperBandScheduler):
    """HyperBand as multi-bracket async successive halving: trials are
    assigned round-robin to brackets whose grace periods grow by the
    reduction factor (the asynchronous variant dominates strict synchronous
    HyperBand in practice; the reference itself recommends ASHA)."""

    def __init__(self, *args, **kwargs):
        kwargs.setdefault("brackets", 3)
        super().__init__(*args, **kwargs)


class PopulationBasedTraining(TrialScheduler):
    """PBT: at every ``perturbation_interval``, a bottom-quantile trial
    clones the checkpoint of a top-quantile trial and perturbs its
    hyperparameters (``tune/schedulers/pbt.py`` exploit/explore).

    The controller implements the mechanics: on a STOP-with-mutation
    decision it stops the runner, rewrites trial.config / restore_path from
    ``pop_mutation`` and requeues the trial.
    """

    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25, seed: int = 0):
        self._time_attr = time_attr
        self._metric = metric
        self._mode = mode
        self._interval = perturbation_interval
        self._mutations = hyperparam_mutations or {}
        self._quantile = quantile_fraction
        self._resample_p = resample_probability
        self._rng = random.Random(seed)
        self._last_perturb: Dict[str, int] = {}
        self._latest: Dict[str, float] = {}  # trial_id -> normalized score
        self._trials: Dict[str, Trial] = {}
        self._pending_mutation: Dict[str, Any] = {}

    def on_trial_add(self, trial: Trial) -> None:
        self._trials[trial.trial_id] = trial

    def _quantiles(self):
        ranked = sorted(self._latest.items(), key=lambda kv: kv[1])
        n = len(ranked)
        k = max(1, int(n * self._quantile))
        bottom = [tid for tid, _ in ranked[:k]]
        top = [tid for tid, _ in ranked[-k:]]
        return bottom, top

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        from ray_tpu.tune.search_space import Domain

        new = dict(config)
        for key, spec in self._mutations.items():
            if self._rng.random() < self._resample_p or key not in new:
                if isinstance(spec, Domain):
                    new[key] = spec.sample(self._rng)
                elif isinstance(spec, list):
                    new[key] = self._rng.choice(spec)
                elif callable(spec):
                    new[key] = spec()
            else:
                cur = new[key]
                if isinstance(cur, bool):
                    new[key] = not cur if self._rng.random() < 0.5 else cur
                elif isinstance(cur, int):
                    factor = 1.2 if self._rng.random() > 0.5 else 0.8
                    perturbed = round(cur * factor)
                    if perturbed == cur:  # small ints must still move
                        perturbed = cur + (1 if factor > 1 else -1)
                    if cur >= 1:  # keep inherently positive ints positive
                        perturbed = max(1, perturbed)
                    new[key] = perturbed
                elif isinstance(cur, float):
                    factor = 1.2 if self._rng.random() > 0.5 else 0.8
                    new[key] = cur * factor
                elif isinstance(spec, list):
                    new[key] = self._rng.choice(spec)
        return new

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        t = result.get(self._time_attr, 0)
        metric = result.get(self._metric)
        if metric is None:
            return CONTINUE
        self._latest[trial.trial_id] = _score(metric, self._mode or "max")
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last < self._interval or len(self._latest) < 2:
            return CONTINUE
        self._last_perturb[trial.trial_id] = t
        bottom, top = self._quantiles()
        if trial.trial_id not in bottom or trial.trial_id in top:
            return CONTINUE
        exploit_id = self._rng.choice(top)
        exploit = self._trials.get(exploit_id)
        if exploit is None or exploit.checkpoint_path is None:
            return CONTINUE
        self._pending_mutation[trial.trial_id] = (
            self._explore(exploit.config), exploit.checkpoint_path)
        return PAUSE  # controller stops the runner, mutates, requeues

    def pop_mutation(self, trial: Trial):
        return self._pending_mutation.pop(trial.trial_id, None)


class HyperBandForBOHB(HyperBandScheduler):
    """BOHB's scheduling half (reference: ``tune/schedulers/hb_bohb.py``):
    multi-bracket successive halving that feeds every rung crossing back to
    the paired ``BOHBSearcher`` so its TPE model trains on the highest
    fidelity with enough data. Pair via::

        searcher = BOHBSearcher(...)
        scheduler = HyperBandForBOHB(searcher=searcher, ...)
        Tuner(..., tune_config=TuneConfig(search_alg=searcher,
                                          scheduler=scheduler))
    """

    def __init__(self, *args, searcher=None, **kwargs):
        super().__init__(*args, **kwargs)
        self._bohb_searcher = searcher
        self._last_reported: Dict[str, float] = {}

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        decision = super().on_trial_result(trial, result)
        if self._bohb_searcher is not None:
            rec = self._trial_recorded.get(trial.trial_id)
            if rec is not None and \
                    self._last_reported.get(trial.trial_id) != rec[0]:
                rung, score = rec
                self._last_reported[trial.trial_id] = rung
                self._bohb_searcher.on_rung_result(dict(trial.config),
                                                   score, rung)
        return decision


class PB2(PopulationBasedTraining):
    """PBT with GP-bandit explore (reference: ``tune/schedulers/pb2.py``).

    Instead of random 0.8x/1.2x perturbation, the explore step fits a
    Gaussian process on (normalized time, hyperparams) -> score improvement
    observed across the population, and picks the candidate maximizing a
    UCB acquisition within ``hyperparam_bounds`` — far more
    sample-efficient at small population sizes, which is the whole point
    (the PB2 paper's regime is 4-8 trials).

    ``hyperparam_bounds``: {key: (low, high)} continuous ranges. Keys not in
    bounds inherit the exploited trial's value unchanged.
    """

    def __init__(self, time_attr: str = "training_iteration",
                 metric: Optional[str] = None, mode: Optional[str] = None,
                 perturbation_interval: int = 4,
                 hyperparam_bounds: Optional[Dict[str, Tuple[float, float]]]
                 = None,
                 quantile_fraction: float = 0.25,
                 ucb_kappa: float = 1.0,
                 n_candidates: int = 64,
                 max_observations: int = 200, seed: int = 0):
        super().__init__(time_attr=time_attr, metric=metric, mode=mode,
                         perturbation_interval=perturbation_interval,
                         hyperparam_mutations={},
                         quantile_fraction=quantile_fraction, seed=seed)
        self._bounds = dict(hyperparam_bounds or {})
        self._kappa = ucb_kappa
        self._n_cand = n_candidates
        self._max_obs = max_observations
        # GP dataset: X rows = [t_norm, hp_norms...], y = score delta
        self._X: List[List[float]] = []
        self._y: List[float] = []
        self._prev: Dict[str, Tuple[float, float]] = {}  # tid -> (t, score)
        self._t_max = 1.0

    # -- data collection ------------------------------------------------------
    def _norm_hp(self, key: str, v: float) -> float:
        lo, hi = self._bounds[key]
        return (float(v) - lo) / max(hi - lo, 1e-12)

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        t = result.get(self._time_attr, 0)
        metric = result.get(self._metric)
        if metric is not None:
            score = _score(metric, self._mode or "max")
            self._t_max = max(self._t_max, float(t))
            prev = self._prev.get(trial.trial_id)
            if prev is not None and t > prev[0]:
                # RAW time stored; normalized by the CURRENT t_max at fit
                # time (normalizing at insertion would freeze each row's
                # scale to whatever t_max was then — early rows would drift
                # to a fictitious late-training position as t_max grows).
                x = [float(prev[0])] + [
                    self._norm_hp(k, trial.config.get(k, self._bounds[k][0]))
                    for k in sorted(self._bounds)]
                self._X.append(x)
                self._y.append((score - prev[1]) / (t - prev[0]))
                if len(self._y) > self._max_obs:
                    self._X.pop(0)
                    self._y.pop(0)
            self._prev[trial.trial_id] = (float(t), score)
        return super().on_trial_result(trial, result)

    def pop_mutation(self, trial: Trial):
        m = super().pop_mutation(trial)
        if m is not None:
            # the next report's score is the EXPLOITED checkpoint's, not a
            # continuation — a delta across that boundary would poison the GP
            self._prev.pop(trial.trial_id, None)
        return m

    # -- GP explore -----------------------------------------------------------
    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        import numpy as np

        new = dict(config)
        keys = sorted(self._bounds)
        if not keys:
            return new
        if len(self._y) < 4:
            # cold start: uniform resample within bounds
            for k in keys:
                lo, hi = self._bounds[k]
                v = self._rng.uniform(lo, hi)
                new[k] = int(round(v)) if isinstance(config.get(k), int) else v
            return new

        X = np.asarray(self._X, dtype=np.float64)
        X = X.copy()
        X[:, 0] /= self._t_max          # normalize raw times at fit time
        y = np.asarray(self._y, dtype=np.float64)
        y_std = y.std() or 1.0
        y_n = (y - y.mean()) / y_std
        ell, noise = 0.3, 1e-3

        def kern(a, b):
            d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
            return np.exp(-0.5 * d2 / ell ** 2)

        K = kern(X, X) + noise * np.eye(len(X))
        alpha = np.linalg.solve(K, y_n)
        K_inv = np.linalg.inv(K)

        t_now = 1.0  # explore for the NEXT interval: newest time
        cands = np.empty((self._n_cand, 1 + len(keys)))
        cands[:, 0] = t_now
        for j, k in enumerate(keys):
            cands[:, 1 + j] = [self._rng.random() for _ in
                               range(self._n_cand)]
        Ks = kern(cands, X)
        mu = Ks @ alpha
        var = np.maximum(1.0 - np.einsum("ij,jk,ik->i", Ks, K_inv, Ks), 1e-9)
        ucb = mu + self._kappa * np.sqrt(var)
        best = cands[int(np.argmax(ucb))]
        for j, k in enumerate(keys):
            lo, hi = self._bounds[k]
            v = lo + best[1 + j] * (hi - lo)
            new[k] = int(round(v)) if isinstance(config.get(k), int) else v
        return new


class ResourceChangingScheduler(TrialScheduler):
    """Reallocate trial resources while the experiment runs (reference:
    ``tune/schedulers/resource_changing_scheduler.py`` — wraps a base
    scheduler; a ``resources_allocation_function`` proposes new resources
    per result, and the trial is checkpoint-paused and relaunched with
    them).

    ``resources_allocation_function(trials, trial, result)`` receives the
    live trial list, the reporting trial, and its result; it returns a
    resource dict (``{"cpu": 2}``-style, the ``_tune_resources`` surface)
    or None for no change. The default evenly splits the cluster's CPUs
    across live trials, so finished trials hand capacity to survivors.
    """

    def __init__(self, base_scheduler: Optional[TrialScheduler] = None,
                 resources_allocation_function=None):
        self._base = base_scheduler or FIFOScheduler()
        self._alloc = resources_allocation_function or evenly_distribute_cpus
        self._trials: List[Trial] = []

    def set_search_properties(self, metric, mode) -> bool:
        return self._base.set_search_properties(metric, mode)

    def on_trial_add(self, trial: Trial) -> None:
        self._trials.append(trial)
        self._base.on_trial_add(trial)

    def on_trial_complete(self, trial: Trial, result) -> None:
        self._base.on_trial_complete(trial, result)

    def on_trial_error(self, trial: Trial) -> None:
        self._base.on_trial_error(trial)

    def pop_mutation(self, trial: Trial):
        return self._base.pop_mutation(trial)

    def on_trial_result(self, trial: Trial, result: Dict[str, Any]) -> str:
        decision = self._base.on_trial_result(trial, result)
        if decision != CONTINUE:
            return decision
        try:
            proposed = self._alloc(list(self._trials), trial, result)
        except Exception:  # noqa: BLE001 — allocator bugs must not kill runs
            return decision
        current = trial.resources or trial.base_resources or {}
        if proposed and proposed != current:
            # checkpoint-pause; the controller requeues and _start_trial
            # relaunches the runner with the new resources
            trial.resources = dict(proposed)
            return PAUSE
        return decision


def evenly_distribute_cpus(trials: List[Trial], trial: Trial,
                           result: Dict[str, Any]):
    """Default allocator: split the cluster's CPUs evenly across live
    trials (reference: ``DistributeResources``). Never shrinks below the
    trainable's base request."""
    import ray_tpu

    try:
        total = ray_tpu.cluster_resources().get("CPU", 0)
    except Exception:  # noqa: BLE001 — not connected (unit tests)
        return None
    from ray_tpu.tune.trial import PENDING, RUNNING

    live = [t for t in trials if t.status in (PENDING, RUNNING)]
    if not live or total <= 0:
        return None
    base = (trial.base_resources or {}).get("cpu", 1)
    current = (trial.resources or trial.base_resources or {}).get("cpu", 1)
    share = max(base, int(total) // len(live))  # never below the declared
    if share == current:
        return None
    return {"cpu": share}
