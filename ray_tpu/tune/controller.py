"""The Tune trial control loop.

Reference analog: ``tune/execution/tune_controller.py:81`` — an event loop
over trial-runner actors. Each trial is hosted by a ``_TrialRunner`` actor
(the reference's Trainable-actor); the controller drives one ``train()``
call at a time per trial, feeds results to the scheduler/searcher, applies
early-stop / PBT-mutation decisions, checkpoints trials and the experiment
state, and restarts failed trials up to ``max_failures``.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.tune import trial as trial_mod
from ray_tpu.tune.schedulers import CONTINUE, PAUSE, STOP, FIFOScheduler, TrialScheduler
from ray_tpu.tune.search import BasicVariantGenerator, ConcurrencyLimiter, Searcher
from ray_tpu.tune.trial import ERROR, PAUSED, PENDING, RUNNING, TERMINATED, Trial
from ray_tpu.tune.trainable import DONE, Trainable


@ray_tpu.remote
class _TrialRunner:
    """Hosts one Trainable instance inside its own worker process."""

    def __init__(self, trainable_cls: type, config: Dict[str, Any],
                 restore_dir: Optional[str] = None):
        self._t: Trainable = trainable_cls(config)
        if restore_dir:
            self._t.restore(restore_dir)

    def train(self) -> Dict[str, Any]:
        return self._t.train()

    def save(self, checkpoint_dir: str) -> Optional[str]:
        return self._t.save(checkpoint_dir)

    def stop(self) -> None:
        self._t.cleanup()


def _runner_options(trainable_cls: type,
                    override: Optional[Dict[str, Any]] = None
                    ) -> Dict[str, Any]:
    res = override or getattr(trainable_cls, "_tune_resources", None) \
        or {"cpu": 1}
    opts: Dict[str, Any] = {}
    custom: Dict[str, float] = {}
    for k, v in res.items():
        lk = k.lower()
        if lk in ("cpu", "num_cpus"):
            opts["num_cpus"] = v
        elif lk in ("tpu", "num_tpus"):
            opts["num_tpus"] = v
        elif lk in ("gpu", "num_gpus"):
            opts["num_gpus"] = v
        elif lk == "memory":
            opts["memory"] = v
        else:
            custom[k] = v
    if custom:
        opts["resources"] = custom
    return opts


class TuneController:
    def __init__(self, trainable_cls: type, searcher: Searcher,
                 scheduler: Optional[TrialScheduler],
                 experiment_dir: str, experiment_name: str,
                 metric: Optional[str], mode: str = "max",
                 max_concurrent: int = 0, max_failures: int = 0,
                 checkpoint_freq: int = 0,
                 stop: Optional[Any] = None,
                 restored_trials: Optional[List[Trial]] = None):
        self._cls = trainable_cls
        self._searcher = searcher
        self._scheduler = scheduler or FIFOScheduler()
        self._scheduler.set_search_properties(metric, mode)
        self._dir = experiment_dir
        self._name = experiment_name
        self._metric = metric
        self._mode = mode
        self._max_concurrent = max_concurrent
        self._max_failures = max_failures
        self._checkpoint_freq = checkpoint_freq
        self._stop_criteria = stop
        self._trials: List[Trial] = list(restored_trials or [])
        self._next_id = len(self._trials)
        self._exhausted = False
        self._loggers: Dict[str, Any] = {}
        os.makedirs(self._dir, exist_ok=True)
        for t in self._trials:
            self._scheduler.on_trial_add(t)

    # ---- trial lifecycle ----

    def _maybe_request_trials(self) -> None:
        while not self._exhausted:
            live = [t for t in self._trials if t.status in (PENDING, RUNNING)]
            if self._max_concurrent and len(live) >= self._max_concurrent:
                return
            trial_id = f"{self._name}_{self._next_id:05d}"
            cfg = self._searcher.suggest(trial_id)
            if cfg is None:
                if isinstance(self._searcher, ConcurrencyLimiter) and self._searcher._live:
                    return  # temporarily saturated, not exhausted
                self._exhausted = True
                return
            self._next_id += 1
            t = Trial(trial_id, cfg, self._name)
            t.base_resources = getattr(self._cls, "_tune_resources", None)
            self._trials.append(t)
            self._scheduler.on_trial_add(t)

    def _start_trial(self, t: Trial) -> None:
        opts = _runner_options(self._cls, t.resources)
        t.mark_running(_TrialRunner.options(**opts).remote(
            self._cls, t.config, t.restore_path))
        t.restore_path = None
        t.inflight = t.runner.train.remote()

    def _trial_dir(self, t: Trial) -> str:
        d = os.path.join(self._dir, t.trial_id)
        os.makedirs(d, exist_ok=True)
        return d

    def _save_trial_checkpoint(self, t: Trial) -> None:
        ckpt_dir = os.path.join(
            self._trial_dir(t), f"checkpoint_{t.training_iteration:06d}")
        try:
            ray_tpu.get(t.runner.save.remote(ckpt_dir), timeout=60)
            t.checkpoint_path = ckpt_dir
        except Exception as e:
            import logging

            logging.getLogger(__name__).warning(
                "trial %s: checkpoint save failed (%r); keeping previous "
                "checkpoint %s", t.trial_id, e, t.checkpoint_path)

    def _finalize(self, t: Trial, status: str, error: Optional[str] = None) -> None:
        if t.runner is not None:
            try:
                t.runner.stop.remote()
                ray_tpu.kill(t.runner, no_restart=True)
            except Exception:
                pass
        t.runner = None
        t.inflight = None
        t.status = status
        t.error = error
        self._searcher.on_trial_complete(
            t.trial_id, t.last_result or None, error=status == ERROR)
        self._scheduler.on_trial_complete(t, t.last_result)
        logger = self._loggers.pop(t.trial_id, None)
        if logger is not None:
            logger.close()
        with open(os.path.join(self._trial_dir(t), "final_result.json"), "w") as f:
            json.dump(t.state(), f, default=str)

    def _should_stop(self, t: Trial, result: Dict[str, Any]) -> bool:
        if result.get(DONE):
            return True
        s = self._stop_criteria
        if s is None:
            return False
        if callable(s):
            return bool(s(t.trial_id, result))
        if isinstance(s, dict):
            for k, v in s.items():
                r = result.get(k)
                if r is None:
                    continue
                # reference semantics: unconditional result[key] >= value
                # regardless of metric mode (min-mode users pass thresholds
                # already oriented this way)
                if r >= v:
                    return True
        return False

    def _trial_loggers(self, t: Trial):
        from ray_tpu.tune.loggers import TrialLoggers

        if t.trial_id not in self._loggers:
            self._loggers[t.trial_id] = TrialLoggers(self._trial_dir(t))
        return self._loggers[t.trial_id]

    def _handle_result(self, t: Trial, result: Dict[str, Any]) -> None:
        t.on_result(result)
        try:
            self._trial_loggers(t).on_result(result)
        except Exception:  # noqa: BLE001 — logging must not fail the trial
            pass
        if (self._checkpoint_freq
                and t.training_iteration % self._checkpoint_freq == 0):
            self._save_trial_checkpoint(t)
        if self._should_stop(t, result):
            if self._checkpoint_freq == 0 or t.checkpoint_path is None:
                self._save_trial_checkpoint(t)
            self._finalize(t, TERMINATED)
            return
        decision = self._scheduler.on_trial_result(t, result)
        if decision == STOP:
            self._finalize(t, TERMINATED)
        elif decision == PAUSE:
            mutation = self._scheduler.pop_mutation(t)
            if mutation is not None:
                new_config, restore_from = mutation
                if t.runner is not None:
                    try:
                        ray_tpu.kill(t.runner, no_restart=True)
                    except Exception:
                        pass
                t.runner, t.inflight = None, None
                t.config = new_config
                t.restore_path = restore_from
                t.status = PENDING
            # plain PAUSE without mutation: requeue as-is
            elif t.runner is not None:
                self._save_trial_checkpoint(t)
                ray_tpu.kill(t.runner, no_restart=True)
                t.runner, t.inflight = None, None
                t.restore_path = t.checkpoint_path
                t.status = PENDING
        else:
            t.inflight = t.runner.train.remote()

    def _handle_failure(self, t: Trial, err: Exception) -> None:
        t.num_failures += 1
        self._scheduler.on_trial_error(t)
        if t.runner is not None:
            try:
                ray_tpu.kill(t.runner, no_restart=True)
            except Exception:
                pass
        t.runner, t.inflight = None, None
        if t.num_failures <= self._max_failures:
            t.restore_path = t.checkpoint_path
            t.status = PENDING
        else:
            t.status = ERROR
            self._finalize(t, ERROR, error=repr(err))

    # ---- experiment state ----

    def _save_experiment_state(self) -> None:
        state = {
            "experiment_name": self._name,
            "timestamp": time.time(),
            "trials": [t.state() for t in self._trials],
        }
        tmp = os.path.join(self._dir, ".experiment_state.tmp")
        with open(tmp, "w") as f:
            json.dump(state, f, default=str)
        os.replace(tmp, os.path.join(self._dir, "experiment_state.json"))

    @staticmethod
    def load_experiment_state(experiment_dir: str) -> List[Trial]:
        path = os.path.join(experiment_dir, "experiment_state.json")
        with open(path) as f:
            state = json.load(f)
        trials = []
        for ts in state["trials"]:
            t = Trial.from_state(ts, state["experiment_name"])
            if t.status in (RUNNING, PENDING, PAUSED):
                t.status = PENDING
                t.restore_path = t.checkpoint_path
            trials.append(t)
        return trials

    # ---- main loop ----

    def run(self) -> List[Trial]:
        while True:
            self._maybe_request_trials()
            pending = [t for t in self._trials if t.status == PENDING]
            running = [t for t in self._trials if t.status == RUNNING]
            slots = (self._max_concurrent - len(running)
                     if self._max_concurrent else len(pending))
            for t in pending[:max(0, slots)]:
                self._start_trial(t)
            running = [t for t in self._trials if t.status == RUNNING and t.inflight]
            if not running:
                if self._exhausted and not any(
                        t.status == PENDING for t in self._trials):
                    break
                time.sleep(0.02)
                continue
            refs = [t.inflight for t in running]
            ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=5)
            for ref in ready:
                t = next(tr for tr in running if tr.inflight == ref)
                try:
                    result = ray_tpu.get(ref)
                except Exception as e:
                    self._handle_failure(t, e)
                else:
                    self._handle_result(t, result)
            self._save_experiment_state()
        self._save_experiment_state()
        return self._trials
