"""Tuner: the user-facing Tune entry point.

Reference analog: ``tune/tuner.py:59`` (``Tuner.fit :337``) +
``tune/impl/tuner_internal.py:63`` + ``ResultGrid``. Accepts a function
trainable, a Trainable subclass, or a ``JaxTrainer`` (the Train-on-Tune
layering of ``train/base_trainer.py:728`` — the trainer's driver loop runs
inside the trial actor).
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig
from ray_tpu.tune.controller import TuneController
from ray_tpu.tune.schedulers import TrialScheduler
from ray_tpu.tune.search import (
    BasicVariantGenerator,
    ConcurrencyLimiter,
    Searcher,
)
from ray_tpu.tune.trainable import Trainable, wrap_function
from ray_tpu.tune.trial import ERROR, TERMINATED, Trial


@dataclasses.dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int = 0
    search_alg: Optional[Searcher] = None
    scheduler: Optional[TrialScheduler] = None
    checkpoint_freq: int = 0
    seed: Optional[int] = None


class Result:
    def __init__(self, trial: Trial):
        self.metrics = trial.last_result
        self.config = trial.config
        self.error = trial.error
        self.path = trial.checkpoint_path
        self.metrics_history = trial.results
        self.trial_id = trial.trial_id

    @functools.cached_property
    def checkpoint(self) -> Optional[Checkpoint]:
        """Lazily unpickled — a ResultGrid over many trials must not load
        every checkpoint payload into driver memory up front."""
        if self.path:
            ckpt_file = os.path.join(self.path, "trainable.pkl")
            if os.path.exists(ckpt_file):
                import pickle

                with open(ckpt_file, "rb") as f:
                    payload = pickle.load(f)
                data = payload.get("data")
                if isinstance(data, dict) and "checkpoint" in data:
                    return Checkpoint.from_dict(data["checkpoint"])
        return None

    def __repr__(self) -> str:
        return f"Result({self.trial_id}, metrics={self.metrics})"


class ResultGrid:
    def __init__(self, trials: List[Trial], metric: Optional[str], mode: str):
        self._trials = trials
        self._metric = metric
        self._mode = mode
        self._results = [Result(t) for t in trials]

    def __len__(self) -> int:
        return len(self._results)

    def __getitem__(self, i: int) -> Result:
        return self._results[i]

    @property
    def errors(self) -> List[str]:
        return [t.error for t in self._trials if t.status == ERROR]

    @property
    def num_terminated(self) -> int:
        return sum(1 for t in self._trials if t.status == TERMINATED)

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("metric required (set TuneConfig.metric)")
        sign = 1 if mode == "max" else -1
        scored = [r for r in self._results if metric in (r.metrics or {})]
        if not scored:
            raise RuntimeError("no trial reported the metric " + metric)
        return max(scored, key=lambda r: sign * r.metrics[metric])

    def get_dataframe(self):
        import pandas as pd

        rows = []
        for r in self._results:
            row = dict(r.metrics or {})
            row["trial_id"] = r.trial_id
            for k, v in (r.config or {}).items():
                row[f"config/{k}"] = v
            rows.append(row)
        return pd.DataFrame(rows)


def _to_trainable_cls(trainable: Any, param_space: Dict) -> type:
    from ray_tpu.train.trainer import JaxTrainer

    if isinstance(trainable, type) and issubclass(trainable, Trainable):
        return trainable
    if isinstance(trainable, JaxTrainer):
        trainer = trainable

        def _train_fn(config: Dict[str, Any]) -> None:
            import dataclasses as dc

            import ray_tpu.tune as tune

            merged = dict(trainer.train_config or {})
            merged.update(config.get("train_loop_config", config))
            trial_run_cfg = dc.replace(
                trainer.run_config,
                name=(trainer.run_config.name or "trial")
                + f"_{os.getpid()}_{id(config):x}")
            run = JaxTrainer(
                trainer.train_fn, train_loop_config=merged,
                scaling_config=trainer.scaling, run_config=trial_run_cfg,
                datasets=trainer.datasets,
                use_jax_distributed=trainer.use_jax_distributed,
                resume_from_checkpoint=trainer.resume_checkpoint)
            result = run.fit()
            if result.error is not None:
                raise result.error
            for m in result.metrics_history:
                tune.report(m)

        cls = wrap_function(_train_fn)
        # The trial actor is only the train *driver*; the worker gang's
        # resources are reserved atomically by the trainer's own placement
        # group (reference: trial PG inheritance, backend_executor.py:179).
        # Reserving them here too would deadlock supervisor vs. gang.
        cls._tune_resources = getattr(trainer, "_tune_resources", None) or {
            "cpu": 1}
        return cls
    if callable(trainable):
        return wrap_function(trainable)
    raise TypeError(f"unsupported trainable: {trainable!r}")


class Tuner:
    def __init__(self, trainable: Any, *, param_space: Optional[Dict] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 _restored_trials: Optional[List[Trial]] = None):
        self._trainable = trainable
        self._param_space = param_space or {}
        self._tune_config = tune_config or TuneConfig()
        self._run_config = run_config or RunConfig()
        self._restored_trials = _restored_trials

    @classmethod
    def restore(cls, path: str, trainable: Any,
                tune_config: Optional[TuneConfig] = None,
                run_config: Optional[RunConfig] = None) -> "Tuner":
        """Resume an interrupted experiment from its directory.

        The original TuneConfig/RunConfig (stop criteria, failure budget,
        checkpoint cadence) are restored from the experiment's pickled meta
        unless overridden explicitly.
        """
        import pickle

        trials = TuneController.load_experiment_state(path)
        meta_path = os.path.join(path, "experiment_meta.pkl")
        if os.path.exists(meta_path) and (tune_config is None
                                          or run_config is None):
            with open(meta_path, "rb") as f:
                meta = pickle.load(f)
            tune_config = tune_config or meta.get("tune_config")
            run_config = run_config or meta.get("run_config")
        if run_config is None:
            run_config = RunConfig(name=os.path.basename(path),
                                   storage_path=os.path.dirname(path))
        return cls(trainable, tune_config=tune_config or TuneConfig(),
                   run_config=run_config, _restored_trials=trials)

    def fit(self) -> ResultGrid:
        tc = self._tune_config
        cls = _to_trainable_cls(self._trainable, self._param_space)
        searcher = tc.search_alg
        if searcher is None:
            searcher = BasicVariantGenerator(seed=tc.seed)
        inner = (searcher._searcher if isinstance(searcher, ConcurrencyLimiter)
                 else searcher)
        inner.set_num_samples(tc.num_samples)
        if isinstance(inner, BasicVariantGenerator):
            if inner._max_concurrent and not isinstance(
                    searcher, ConcurrencyLimiter):
                searcher = ConcurrencyLimiter(searcher, inner._max_concurrent)
        searcher.set_search_properties(tc.metric, tc.mode, self._param_space)

        name = self._run_config.name or "tune_experiment"
        storage = self._run_config.storage_path or os.path.join(
            os.path.expanduser("~"), "ray_tpu_results")
        experiment_dir = os.path.join(storage, name)
        os.makedirs(experiment_dir, exist_ok=True)
        import pickle

        try:
            with open(os.path.join(experiment_dir, "experiment_meta.pkl"),
                      "wb") as f:
                pickle.dump({"tune_config": tc,
                             "run_config": self._run_config}, f)
        except Exception:
            pass  # unpicklable search_alg/stop: restore falls back to args

        restored = self._restored_trials
        if restored is not None:
            # don't re-suggest configs for trials we already have
            class _NoMore(Searcher):
                def suggest(self, trial_id):
                    return None

                def on_trial_complete(self, *a, **k):
                    pass

            searcher = _NoMore()

        checkpoint_freq = tc.checkpoint_freq
        from ray_tpu.tune.schedulers import PopulationBasedTraining

        if isinstance(tc.scheduler, PopulationBasedTraining) and not checkpoint_freq:
            checkpoint_freq = 1  # PBT exploit needs regular checkpoints

        controller = TuneController(
            cls, searcher, tc.scheduler, experiment_dir, name,
            tc.metric, tc.mode,
            max_concurrent=tc.max_concurrent_trials,
            max_failures=self._run_config.failure_config.max_failures,
            checkpoint_freq=checkpoint_freq,
            stop=getattr(self._run_config, "stop", None),
            restored_trials=restored)
        trials = controller.run()
        return ResultGrid(trials, tc.metric, tc.mode)
