"""ray_tpu.tune: hyperparameter tuning (the reference's ``ray.tune``).

Tuner.fit drives a controller event loop over trial-runner actors; search
spaces are declarative domains; schedulers implement ASHA / median-stopping /
PBT early-stopping and population mutation on top of trial checkpoints.
"""

from ray_tpu.train.config import FailureConfig, RunConfig  # noqa: F401
from ray_tpu.tune.schedulers import (  # noqa: F401
    AsyncHyperBandScheduler,
    FIFOScheduler,
    HyperBandForBOHB,
    HyperBandScheduler,
    MedianStoppingRule,
    PB2,
    PopulationBasedTraining,
    ResourceChangingScheduler,
    TrialScheduler,
    evenly_distribute_cpus,
)
from ray_tpu.tune.search import (  # noqa: F401
    BasicVariantGenerator,
    BOHBSearcher,
    ConcurrencyLimiter,
    QuasiRandomSearch,
    TPESearcher,
    Searcher,
)
from ray_tpu.tune.search_space import (  # noqa: F401
    choice,
    grid_search,
    lograndint,
    loguniform,
    qloguniform,
    qrandint,
    quniform,
    randint,
    sample_from,
    uniform,
)
from ray_tpu.tune.trainable import (  # noqa: F401
    Trainable,
    get_checkpoint,
    report,
    with_resources,
)
from ray_tpu.tune.tuner import Result, ResultGrid, TuneConfig, Tuner  # noqa: F401
