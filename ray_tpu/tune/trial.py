"""Trial state.

Reference analog: ``python/ray/tune/experiment/trial.py:307`` (``Trial``) —
pared to the fields the controller, schedulers, and result reporting need.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


class Trial:
    _counter = 0

    def __init__(self, trial_id: str, config: Dict[str, Any],
                 experiment_name: str = ""):
        self.trial_id = trial_id
        self.config = config
        self.experiment_name = experiment_name
        self.status = PENDING
        self.results: List[Dict[str, Any]] = []
        self.last_result: Dict[str, Any] = {}
        self.error: Optional[str] = None
        self.num_failures = 0
        self.checkpoint_path: Optional[str] = None
        self.restore_path: Optional[str] = None  # set by PBT exploit / resume
        self.start_time: Optional[float] = None
        self.runner = None  # ActorHandle while RUNNING
        self.inflight = None  # ObjectRef of pending train() call
        # per-trial resource override (ResourceChangingScheduler); None =
        # use the trainable class's _tune_resources. base_resources is the
        # class's declared request, stamped by the controller so allocators
        # can floor at it.
        self.resources = None
        self.base_resources = None

    @property
    def training_iteration(self) -> int:
        return self.last_result.get("training_iteration", 0)

    def metric_value(self, metric: str) -> Optional[float]:
        return self.last_result.get(metric)

    def on_result(self, result: Dict[str, Any]) -> None:
        self.results.append(result)
        self.last_result = result

    def mark_running(self, runner) -> None:
        self.status = RUNNING
        self.runner = runner
        if self.start_time is None:
            self.start_time = time.time()

    def state(self) -> Dict[str, Any]:
        return {
            "trial_id": self.trial_id,
            "config": self.config,
            "status": self.status,
            "last_result": self.last_result,
            "error": self.error,
            "num_failures": self.num_failures,
            "checkpoint_path": self.checkpoint_path,
        }

    @classmethod
    def from_state(cls, state: Dict[str, Any],
                   experiment_name: str = "") -> "Trial":
        t = cls(state["trial_id"], state["config"], experiment_name)
        t.status = state["status"]
        t.last_result = state.get("last_result", {})
        if t.last_result:
            t.results = [t.last_result]
        t.error = state.get("error")
        t.num_failures = state.get("num_failures", 0)
        t.checkpoint_path = state.get("checkpoint_path")
        return t

    def __repr__(self) -> str:
        return f"Trial({self.trial_id}, {self.status}, it={self.training_iteration})"
