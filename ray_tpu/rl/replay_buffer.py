"""Replay buffers: uniform ring + proportional-prioritized.

Reference analog: ``rllib/utils/replay_buffers/`` (``segment_tree.py``,
``prioritized_replay_buffer.py``) — the priority tree here is a flat numpy
sum-tree (vectorized sampling, no per-leaf Python objects).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np


class ReplayBuffer:
    """Uniform FIFO ring buffer over columnar transition batches."""

    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = capacity
        self._storage: Optional[Dict[str, np.ndarray]] = None
        self._next = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add_batch(self, batch: Dict[str, np.ndarray]) -> None:
        n = len(next(iter(batch.values())))
        if self._storage is None:
            self._storage = {
                k: np.zeros((self.capacity,) + v.shape[1:], dtype=v.dtype)
                for k, v in batch.items()}
        idx = (self._next + np.arange(n)) % self.capacity
        for k, v in batch.items():
            self._storage[k][idx] = v
        self._next = int((self._next + n) % self.capacity)
        self._size = int(min(self._size + n, self.capacity))
        self._on_added(idx)

    def _on_added(self, idx: np.ndarray) -> None:
        pass

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._size, size=batch_size)
        return {k: v[idx] for k, v in self._storage.items()}


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritization with a numpy sum-tree."""

    def __init__(self, capacity: int, alpha: float = 0.6,
                 beta: float = 0.4, seed: int = 0):
        super().__init__(capacity, seed)
        self._alpha = alpha
        self.beta = beta
        depth = int(np.ceil(np.log2(max(2, capacity))))
        self._leaf_base = 2 ** depth
        self._tree = np.zeros(2 * self._leaf_base, dtype=np.float64)
        self._max_priority = 1.0

    def _set_priorities(self, idx: np.ndarray, priorities: np.ndarray) -> None:
        pos = idx + self._leaf_base
        self._tree[pos] = priorities ** self._alpha
        pos = np.unique(pos // 2)
        while pos[0] >= 1:
            self._tree[pos] = self._tree[2 * pos] + self._tree[2 * pos + 1]
            pos = np.unique(pos // 2)

    def _on_added(self, idx: np.ndarray) -> None:
        self._set_priorities(idx, np.full(len(idx), self._max_priority))

    def sample(self, batch_size: int
               ) -> Tuple[Dict[str, np.ndarray], np.ndarray, np.ndarray]:
        total = self._tree[1]
        targets = self._rng.uniform(0, total, size=batch_size)
        pos = np.ones(batch_size, dtype=np.int64)
        while pos[0] < self._leaf_base:
            left = self._tree[2 * pos]
            go_right = targets > left
            targets = np.where(go_right, targets - left, targets)
            pos = 2 * pos + go_right
        idx = np.minimum(pos - self._leaf_base, self._size - 1)
        probs = self._tree[idx + self._leaf_base] / max(total, 1e-12)
        weights = (self._size * probs + 1e-12) ** (-self.beta)
        weights = weights / weights.max()
        batch = {k: v[idx] for k, v in self._storage.items()}
        return batch, idx, weights.astype(np.float32)

    def update_priorities(self, idx: np.ndarray,
                          td_errors: np.ndarray) -> None:
        priorities = np.abs(td_errors) + 1e-6
        self._max_priority = max(self._max_priority, float(priorities.max()))
        self._set_priorities(idx, priorities)
