"""External-env policy serving: train on envs that live OUTSIDE the
cluster (simulators, games, real systems) and connect over HTTP.

Reference analog: ``rllib/env/policy_server_input.py`` +
``policy_client.py`` — rollout workers become HTTP servers; external
simulators drive episodes with ``start_episode`` / ``get_action`` /
``log_returns`` / ``end_episode`` and the experiences feed training.

Redesign: :class:`ExternalEnvRunner` is an actor with the SAME sampling
surface as :class:`ray_tpu.rl.env_runner.EnvRunner` (``sample(params)``
returns a columnar batch with GAE), so on-policy algorithms swap it in by
setting ``config.env = "external://<port>"`` — no special-cased training
loop. Inference for connected clients runs the same jitted forward the
in-cluster runners use.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rl import models
from ray_tpu.rl.env import EnvSpec
from ray_tpu.rl.env_runner import compute_gae


@ray_tpu.remote
class ExternalEnvRunner:
    """Serves get_action over HTTP; buffers the resulting transitions.

    ``sample(params)`` installs fresh policy params, then blocks until
    ``rollout_len * num_slots`` external steps accumulate and returns the
    standard columnar batch (advantages computed per completed episode
    segment).
    """

    def __init__(self, port: int, spec: Dict[str, Any], rollout_len: int,
                 num_slots: int = 1, gamma: float = 0.99,
                 lam: float = 0.95, seed: int = 0):
        import jax

        self.spec = EnvSpec(**spec)
        self._target_steps = rollout_len * num_slots
        self._gamma, self._lam = gamma, lam
        self._key = jax.random.key(seed)
        self._params = None
        self._episodes: Dict[str, Dict[str, List]] = {}
        self._done_rows: List[Dict[str, np.ndarray]] = []
        self._steps_buffered = 0
        self._completed_returns: List[float] = []
        self._port = port
        self._bound_port: Optional[int] = None

        spec_obj = self.spec

        @jax.jit
        def act(params, obs, key):
            import jax.numpy as jnp

            logits = models.policy_logits(params, obs)
            vals = models.value(params, obs)
            if spec_obj.discrete:
                actions = models.categorical_sample(key, logits)
                logp = models.categorical_logp(logits, actions)
            else:
                actions = models.gaussian_sample(key, logits,
                                                 params["log_std"])
                logp = models.gaussian_logp(logits, params["log_std"],
                                            actions)
            return actions, logp, vals

        self._act = act

    async def ready(self) -> int:
        if self._bound_port is not None:
            return self._bound_port
        from aiohttp import web

        app = web.Application()
        app.router.add_post("/episodes/{eid}/start", self._h_start)
        app.router.add_post("/episodes/{eid}/action", self._h_action)
        app.router.add_post("/episodes/{eid}/rewards", self._h_rewards)
        app.router.add_post("/episodes/{eid}/end", self._h_end)
        runner = web.AppRunner(app, access_log=None)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", self._port)
        await site.start()
        self._bound_port = site._server.sockets[0].getsockname()[1]
        return self._bound_port

    # ---- HTTP handlers ----------------------------------------------------
    async def _h_start(self, request):
        from aiohttp import web

        eid = request.match_info["eid"]
        self._episodes[eid] = {"obs": [], "actions": [], "logp": [],
                               "values": [], "rewards": [], "return": 0.0}
        return web.json_response({"ok": True})

    async def _h_action(self, request):
        import jax

        from aiohttp import web

        eid = request.match_info["eid"]
        ep = self._episodes.get(eid)
        if ep is None:
            return web.json_response({"error": "unknown episode"},
                                     status=404)
        body = await request.json()
        obs = np.asarray(body["obs"], dtype=np.float32)
        if self._params is None:
            return web.json_response({"error": "no policy yet"}, status=503)
        self._key, sub = jax.random.split(self._key)
        a, logp, val = self._act(self._params, obs[None], sub)
        a = np.asarray(a)[0]
        ep["obs"].append(obs)
        ep["actions"].append(a)
        ep["logp"].append(float(np.asarray(logp)[0]))
        ep["values"].append(float(np.asarray(val)[0]))
        action = a.tolist() if not self.spec.discrete else int(a)
        return web.json_response({"action": action})

    async def _h_rewards(self, request):
        from aiohttp import web

        eid = request.match_info["eid"]
        ep = self._episodes.get(eid)
        if ep is None:
            return web.json_response({"error": "unknown episode"},
                                     status=404)
        body = await request.json()
        r = float(body["reward"])
        ep["rewards"].append(r)
        ep["return"] += r
        return web.json_response({"ok": True})

    async def _h_end(self, request):
        from aiohttp import web

        eid = request.match_info["eid"]
        ep = self._episodes.pop(eid, None)
        if ep is None:
            return web.json_response({"error": "unknown episode"},
                                     status=404)
        self._finish_episode(ep, terminal=True)
        return web.json_response({"ok": True})

    def _finish_episode(self, ep: Dict, terminal: bool) -> int:
        """Consume the first T complete (action, reward) steps into a
        training segment; returns T so a mid-episode cut can leave the
        incomplete tail (an action whose reward hasn't arrived) in place
        — discarding it would misalign every later reward by one step."""
        T = min(len(ep["rewards"]), len(ep["actions"]))
        if T == 0:
            return 0
        rewards = np.asarray(ep["rewards"][:T], np.float32).reshape(T, 1)
        values = np.asarray(ep["values"][:T], np.float32).reshape(T, 1)
        dones = np.zeros((T, 1), dtype=bool)
        if terminal:
            dones[-1] = True
        # bootstrap a mid-episode cut from the NEXT state's value when the
        # pending tail holds one, else from the last consumed state
        if terminal:
            last_v = np.zeros(1, np.float32)
        elif len(ep["values"]) > T:
            last_v = np.asarray([ep["values"][T]], np.float32)
        else:
            last_v = values[-1]
        gae = compute_gae(rewards, values, dones, last_v,
                          self._gamma, self._lam)
        obs = np.asarray(ep["obs"][:T], np.float32)
        acts = np.asarray(ep["actions"][:T])
        next_obs = np.concatenate([obs[1:], obs[-1:]], axis=0)
        self._done_rows.append({
            "obs": obs, "actions": acts,
            "actions_executed": acts,
            "logp": np.asarray(ep["logp"][:T], np.float32),
            "values": values[:, 0], "rewards": rewards[:, 0],
            "dones": dones[:, 0], "next_obs": next_obs,
            "advantages": gae["advantages"][:, 0],
            "value_targets": gae["value_targets"][:, 0],
        })
        self._steps_buffered += T
        if terminal:
            self._completed_returns.append(ep["return"])
        return T

    # ---- EnvRunner protocol ----------------------------------------------
    def get_spec(self):
        return self.spec

    async def sample(self, params) -> Dict[str, np.ndarray]:
        import asyncio

        self._params = params
        # in-flight steps of OPEN episodes count toward the fragment — an
        # episode longer than the target (a trained CartPole balancing
        # forever, any continuing task) must still cut, like the
        # reference's rollout_fragment_length cut mid-episode
        def total_steps() -> int:
            open_steps = sum(
                min(len(ep["rewards"]), len(ep["actions"]))
                for ep in self._episodes.values())
            return self._steps_buffered + open_steps

        while total_steps() < self._target_steps:
            await asyncio.sleep(0.02)
        # cut still-open episodes at their last COMPLETE step; the
        # incomplete tail (action awaiting its reward) stays in place
        for ep in list(self._episodes.values()):
            t = self._finish_episode(ep, terminal=False)
            if t:
                for k in ("obs", "actions", "logp", "values", "rewards"):
                    ep[k] = ep[k][t:]
        rows, self._done_rows = self._done_rows, []
        self._steps_buffered = 0
        return {k: np.concatenate([r[k] for r in rows])
                for k in rows[0]}

    def pop_connector_deltas(self):
        return None

    def set_connector_globals(self, states) -> None:
        pass

    def episode_stats(self) -> Dict[str, float]:
        completed, self._completed_returns = self._completed_returns, []
        if not completed:
            return {"episodes": 0, "mean_return": float("nan")}
        return {"episodes": len(completed),
                "mean_return": float(np.mean(completed))}


class PolicyClient:
    """The external simulator's side (reference: ``policy_client.py``)."""

    def __init__(self, address: str):
        self._base = address.rstrip("/")
        self._n = 0

    def _post(self, path: str, payload: Optional[Dict] = None,
              retries: int = 50) -> Dict:
        import requests

        for attempt in range(retries):
            r = requests.post(f"{self._base}{path}", json=payload or {},
                              timeout=30)
            if r.status_code == 503:  # policy not installed yet
                time.sleep(0.2)
                continue
            r.raise_for_status()
            return r.json()
        raise TimeoutError(f"policy server never became ready: {path}")

    def start_episode(self, episode_id: Optional[str] = None) -> str:
        eid = episode_id or f"ep{self._n}"
        self._n += 1
        self._post(f"/episodes/{eid}/start")
        return eid

    def get_action(self, episode_id: str, obs) -> Any:
        reply = self._post(f"/episodes/{episode_id}/action",
                           {"obs": np.asarray(obs).tolist()})
        return reply["action"]

    def log_returns(self, episode_id: str, reward: float) -> None:
        self._post(f"/episodes/{episode_id}/rewards",
                   {"reward": float(reward)})

    def end_episode(self, episode_id: str, obs=None) -> None:
        self._post(f"/episodes/{episode_id}/end")
