"""Pixel-observation envs: the Atari-class path.

Reference analog: RLlib's Atari stack — gym's ``AtariPreprocessing``
(grayscale, resize, frame-skip) + ``FrameStack`` wrappers feeding a Nature
CNN (``rllib/models``' default vision net), exercised by
``rllib/tuned_examples/ppo/atari-ppo.yaml``. ALE isn't available in this
image (zero egress), so the capability ships in three pieces:

- :class:`PixelWrapper` — frame-skip (max-pooled), grayscale, area resize,
  [0,1] scaling over ANY pixel :class:`VectorEnv`;
- :class:`FrameStack` — channel-stacked history;
- :class:`CatchPixels` — a vectorized synthetic pixel control task (a
  falling ball must be caught by a 3px paddle) that trains a conv policy
  end-to-end in CI the way CartPole stands in for control tasks;
- :func:`gym_vector_env` — an adapter that wraps ``gymnasium`` vector envs
  (incl. real Atari) when the package is installed.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from ray_tpu.rl.env import EnvSpec, VectorEnv, register_env


class CatchPixels(VectorEnv):
    """N independent games of catch on an (H, W, 1) board.

    A ball falls one row per step from a random column; the bottom-row
    paddle (3px wide) moves left/stay/right. Reward +1 on catch, -1 on
    miss, episode ends when the ball reaches the bottom. Solvable to
    ~+1.0 mean return quickly — the CI stand-in for pixel control.
    """

    def __init__(self, num_envs: int, seed: int = 0, size: int = 16):
        self.num_envs = num_envs
        self._size = size
        self._rng = np.random.default_rng(seed)
        self.spec = EnvSpec(num_actions=3, obs_shape=(size, size, 1))
        self._ball_r = np.zeros(num_envs, dtype=np.int64)
        self._ball_c = np.zeros(num_envs, dtype=np.int64)
        self._paddle = np.zeros(num_envs, dtype=np.int64)

    def _reset_envs(self, mask: np.ndarray) -> None:
        n = int(mask.sum())
        if n:
            self._ball_r[mask] = 0
            self._ball_c[mask] = self._rng.integers(0, self._size, size=n)
            self._paddle[mask] = self._rng.integers(
                1, self._size - 1, size=n)

    def _obs(self) -> np.ndarray:
        s = self._size
        obs = np.zeros((self.num_envs, s, s, 1), dtype=np.float32)
        idx = np.arange(self.num_envs)
        obs[idx, self._ball_r, self._ball_c, 0] = 1.0
        for d in (-1, 0, 1):
            cols = np.clip(self._paddle + d, 0, s - 1)
            obs[idx, s - 1, cols, 0] = 0.5
        return obs

    def reset(self) -> np.ndarray:
        self._reset_envs(np.ones(self.num_envs, dtype=bool))
        return self._obs()

    def step(self, actions: np.ndarray):
        a = np.asarray(actions).reshape(self.num_envs)
        self._paddle = np.clip(self._paddle + (a - 1), 1, self._size - 2)
        self._ball_r = self._ball_r + 1
        dones = self._ball_r >= self._size - 1
        caught = dones & (np.abs(self._ball_c - self._paddle) <= 1)
        rewards = np.where(dones, np.where(caught, 1.0, -1.0), 0.0
                           ).astype(np.float32)
        self._reset_envs(dones)
        return self._obs(), rewards, dones


class PixelWrapper(VectorEnv):
    """Atari-style preprocessing over any pixel VectorEnv: frame-skip with
    2-frame max-pool (flicker removal), grayscale, integer-factor area
    resize, float32 [0, 1] scaling."""

    def __init__(self, env: VectorEnv, frame_skip: int = 1,
                 grayscale: bool = True, resize_factor: int = 1):
        assert env.spec.is_pixel, "PixelWrapper needs a pixel env"
        self._env = env
        self.num_envs = env.num_envs
        self._skip = max(1, frame_skip)
        self._gray = grayscale
        self._factor = max(1, resize_factor)
        h, w, c = env.spec.obs_shape
        if h % self._factor or w % self._factor:
            raise ValueError(f"resize_factor {self._factor} must divide "
                             f"{(h, w)}")
        if grayscale and c not in (1, 3):
            raise ValueError(
                f"grayscale needs 1- or 3-channel frames, got C={c} "
                "(wrap BEFORE frame-stacking)")
        out = (h // self._factor, w // self._factor,
               1 if grayscale else c)
        self.spec = EnvSpec(num_actions=env.spec.num_actions,
                            action_dim=env.spec.action_dim,
                            action_low=env.spec.action_low,
                            action_high=env.spec.action_high,
                            obs_shape=out)

    def _transform(self, obs: np.ndarray) -> np.ndarray:
        raw = np.asarray(obs)
        x = raw.astype(np.float32)
        # scale by DTYPE, not value range: an all-dark uint8 batch must
        # land on the same scale as a bright one
        if raw.dtype == np.uint8:
            x = x / 255.0
        if self._gray and x.shape[-1] == 3:
            x = (x * np.array([0.299, 0.587, 0.114],
                              dtype=np.float32)).sum(-1, keepdims=True)
        f = self._factor
        if f > 1:
            n, h, w, c = x.shape
            x = x.reshape(n, h // f, f, w // f, f, c).mean((2, 4))
        return x

    def reset(self) -> np.ndarray:
        return self._transform(self._env.reset())

    def step(self, actions: np.ndarray):
        total = None
        prev = frame = None
        done_any = None
        dones = None
        for i in range(self._skip):
            frame, rewards, dones = self._env.step(actions)
            total = rewards if total is None else total + rewards
            done_any = dones if done_any is None else (done_any | dones)
            if i == self._skip - 2:
                prev = frame
            if dones.any():
                break  # env auto-resets; don't skip across the boundary
        if prev is not None:
            # flicker max-pool — but NOT across an auto-reset boundary:
            # done rows' `frame` is the NEXT episode's first obs, and
            # blending the dead episode's pixels into it would corrupt the
            # new episode's (and FrameStack's seeded) first observation
            pooled = np.maximum(frame, prev)
            frame = np.where(dones[:, None, None, None], frame, pooled)
        return self._transform(frame), total, done_any


class FrameStack(VectorEnv):
    """Channel-stacks the last k frames (the temporal context a
    feed-forward conv policy needs for velocity)."""

    def __init__(self, env: VectorEnv, k: int = 4):
        assert env.spec.is_pixel, "FrameStack needs a pixel env"
        self._env = env
        self._k = k
        self.num_envs = env.num_envs
        h, w, c = env.spec.obs_shape
        self.spec = EnvSpec(num_actions=env.spec.num_actions,
                            action_dim=env.spec.action_dim,
                            action_low=env.spec.action_low,
                            action_high=env.spec.action_high,
                            obs_shape=(h, w, c * k))
        self._frames: Optional[np.ndarray] = None

    def reset(self) -> np.ndarray:
        first = self._env.reset()
        # frame-major layout [f0|f1|...]: concatenate, NOT np.repeat —
        # repeat interleaves channels ([r,r,g,g,b,b]) which step()'s
        # oldest-frame slice would then scramble for C > 1
        self._frames = np.concatenate([first] * self._k, axis=-1)
        return self._frames.copy()

    def step(self, actions: np.ndarray):
        obs, rewards, dones = self._env.step(actions)
        c = obs.shape[-1]
        self._frames = np.concatenate([self._frames[..., c:], obs], axis=-1)
        if dones.any():
            # reset rows restart their stack from the post-reset frame
            self._frames[dones] = np.concatenate(
                [obs[dones]] * self._k, axis=-1)
        return self._frames.copy(), rewards, dones


def gym_vector_env(env_id: str, num_envs: int, seed: int = 0,
                   **kwargs) -> VectorEnv:
    """Wrap a gymnasium vector env (incl. real Atari via ale_py) into the
    VectorEnv protocol. Gated on the package being installed — this image
    has no gymnasium, so it is exercised only in environments that do."""
    try:
        import gymnasium as gym
    except ImportError as e:  # pragma: no cover — not in this image
        raise ImportError(
            "gym_vector_env requires gymnasium (pip install "
            "'gymnasium[atari]')") from e

    venv = gym.make_vec(env_id, num_envs=num_envs, **kwargs)

    class _GymAdapter(VectorEnv):  # pragma: no cover — needs gymnasium
        def __init__(self):
            self.num_envs = num_envs
            space = venv.single_observation_space
            act = venv.single_action_space
            if hasattr(act, "n"):
                spec = EnvSpec(num_actions=int(act.n))
            else:
                spec = EnvSpec(action_dim=int(np.prod(act.shape)),
                               action_low=float(np.min(act.low)),
                               action_high=float(np.max(act.high)))
            if len(space.shape) == 3:
                spec.obs_shape = tuple(space.shape)
            else:
                spec.obs_dim = int(np.prod(space.shape))
            self.spec = spec
            self._seeded = False

        def reset(self):
            obs, _ = venv.reset(seed=seed if not self._seeded else None)
            self._seeded = True
            return np.asarray(obs, dtype=np.float32)

        def step(self, actions):
            obs, rew, term, trunc, _ = venv.step(np.asarray(actions))
            return (np.asarray(obs, dtype=np.float32),
                    np.asarray(rew, dtype=np.float32),
                    np.asarray(term) | np.asarray(trunc))

    return _GymAdapter()


def _make_catch(config: Dict) -> VectorEnv:
    env = CatchPixels(config["num_envs"], seed=config.get("seed", 0),
                      size=config.get("size", 16))
    k = config.get("frame_stack", 0)
    return FrameStack(env, k) if k else env


register_env("CatchPixels-v0", _make_catch)
