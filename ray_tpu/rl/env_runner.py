"""EnvRunner: the sampling actor.

Reference analog: ``rllib/evaluation/rollout_worker.py:159`` (``sample
:660``) + GAE postprocessing (``evaluation/postprocessing.py:89/:158``).
An EnvRunner holds a vectorized env and a jitted policy forward; ``sample``
steps a fixed-length fragment (static shapes — one XLA compile) and returns
a columnar SampleBatch. A fleet of these actors feeds the Learner.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional

import numpy as np

import ray_tpu
from ray_tpu.rl import models
from ray_tpu.rl.env import make_env


def compute_gae(rewards: np.ndarray, values: np.ndarray, dones: np.ndarray,
                last_values: np.ndarray, gamma: float,
                lam: float) -> Dict[str, np.ndarray]:
    """Vectorized GAE over a [T, N] fragment (numpy scan, CPU-side)."""
    T, N = rewards.shape
    adv = np.zeros((T, N), dtype=np.float32)
    last_gae = np.zeros(N, dtype=np.float32)
    next_values = last_values
    for t in range(T - 1, -1, -1):
        nonterminal = 1.0 - dones[t].astype(np.float32)
        delta = rewards[t] + gamma * next_values * nonterminal - values[t]
        last_gae = delta + gamma * lam * nonterminal * last_gae
        adv[t] = last_gae
        next_values = values[t]
    returns = adv + values
    return {"advantages": adv, "value_targets": returns}


@ray_tpu.remote
class EnvRunner:
    """One sampling actor: vectorized env + jitted CPU inference."""

    def __init__(self, env_name: str, num_envs: int, rollout_len: int,
                 gamma: float = 0.99, lam: float = 0.95, seed: int = 0,
                 env_config: Optional[Dict] = None,
                 explore: str = "stochastic",
                 connectors: Optional[list] = None):
        import jax
        import jax.numpy as jnp

        from ray_tpu.rl.connectors import build_connectors

        self._env = make_env(env_name, num_envs, env_config, seed=seed)
        self.spec = self._env.spec
        self._rollout_len = rollout_len
        self._gamma, self._lam = gamma, lam
        self._key = jax.random.key(seed)
        self._obs = self._env.reset()          # RAW env obs
        self._episode_returns = np.zeros(num_envs, dtype=np.float64)
        self._completed: list = []
        # Connector pipeline (obs normalization / reward clipping); the
        # FILTERED view is what the policy sees and what the batch stores,
        # so actor and learner share one normalized space.
        # filters run per-last-axis: features for flat obs, channels for
        # pixel obs
        self._connectors = build_connectors(connectors,
                                            self.spec.obs_dims[-1])

        spec = self.spec

        @jax.jit
        def act(params, obs, key):
            logits = models.policy_logits(params, obs)
            if explore == "epsilon_greedy":
                vals = jnp.max(logits, axis=-1)  # Q-net has no value head
            else:
                vals = models.value(params, obs)
            if explore == "epsilon_greedy":
                # logits are Q-values; epsilon rides in the params pytree
                # so a fresh schedule value needs no recompile
                k1, k2 = jax.random.split(key)
                greedy = jnp.argmax(logits, axis=-1)
                rand = jax.random.randint(
                    k1, greedy.shape, 0, spec.num_actions)
                eps = params["epsilon"]
                pick = jax.random.uniform(k2, greedy.shape) < eps
                actions = jnp.where(pick, rand, greedy)
                logp = jnp.zeros(actions.shape)
            elif spec.discrete:
                actions = models.categorical_sample(key, logits)
                logp = models.categorical_logp(logits, actions)
            elif explore == "squashed_gaussian":
                # SAC-style: the EXECUTED action is the tanh-squashed
                # rescaled sample — matching the policy the learner
                # optimizes (logp unused by replay-based learners).
                std = jnp.exp(params["log_std"])
                pre = logits + std * jax.random.normal(key, logits.shape)
                half = (spec.action_high - spec.action_low) / 2.0
                mid = (spec.action_high + spec.action_low) / 2.0
                actions = mid + half * jnp.tanh(pre)
                logp = jnp.zeros(actions.shape[:-1])
            else:
                actions = models.gaussian_sample(
                    key, logits, params["log_std"])
                logp = models.gaussian_logp(
                    logits, params["log_std"], actions)
            return actions, logp, vals

        self._act = act
        if explore == "epsilon_greedy":
            self._value_fn = jax.jit(
                lambda p, o: jnp.max(models.policy_logits(p, o), axis=-1))
        else:
            self._value_fn = jax.jit(models.value)

    def get_pid(self) -> int:
        """Worker process id — chaos/fault-injection hook (reference:
        NodeKiller-style tests kill rollout workers by pid)."""
        import os

        return os.getpid()

    def get_spec(self):
        return self.spec

    def sample(self, params) -> Dict[str, np.ndarray]:
        """Collect one [T, N] fragment with the given policy params."""
        import jax

        T, N = self._rollout_len, self._env.num_envs
        obs_buf = np.zeros((T, N, *self.spec.obs_dims), dtype=np.float32)
        act_shape = (T, N) if self.spec.discrete else (
            T, N, self.spec.action_dim)
        act_buf = np.zeros(
            act_shape,
            dtype=np.int32 if self.spec.discrete else np.float32)
        logp_buf = np.zeros((T, N), dtype=np.float32)
        val_buf = np.zeros((T, N), dtype=np.float32)
        rew_buf = np.zeros((T, N), dtype=np.float32)
        done_buf = np.zeros((T, N), dtype=bool)
        next_obs_buf = np.zeros((T, N, *self.spec.obs_dims),
                                dtype=np.float32)

        exec_buf = (act_buf if self.spec.discrete
                    else np.zeros_like(act_buf))
        conn = self._connectors
        for t in range(T):
            self._key, sub = jax.random.split(self._key)
            obs_in = (conn.on_obs(self._obs) if conn is not None
                      else self._obs)
            actions, logp, vals = self._act(params, obs_in, sub)
            actions = np.asarray(actions)
            obs_buf[t] = obs_in
            # "actions" stores the raw policy sample (PPO's ratio needs the
            # logp-consistent action); "actions_executed" stores what the
            # env actually ran (what replay-based critics must train on)
            act_buf[t] = actions
            logp_buf[t] = np.asarray(logp)
            val_buf[t] = np.asarray(vals)
            if not self.spec.discrete:
                actions = np.clip(actions, self.spec.action_low,
                                  self.spec.action_high)
                exec_buf[t] = actions
            self._obs, rewards, dones = self._env.step(actions)
            # training signal may be clipped; episode stats stay RAW
            rew_buf[t] = (conn.on_reward(rewards) if conn is not None
                          else rewards)
            done_buf[t] = dones
            # post-reset obs on done rows is fine: (1-done) masks bootstrap.
            # update=False: this same obs is re-filtered (with update) as
            # obs_in at t+1 — stats must count it once.
            next_obs_buf[t] = (conn.on_obs(self._obs, update=False)
                               if conn is not None else self._obs)
            self._episode_returns += rewards
            if dones.any():
                for r in self._episode_returns[dones]:
                    self._completed.append(float(r))
                self._episode_returns[dones] = 0.0

        last_obs = (conn.on_obs(self._obs, update=False) if conn is not None
                    else self._obs)
        last_values = np.asarray(self._value_fn(params, last_obs))
        gae = compute_gae(rew_buf, val_buf, done_buf, last_values,
                          self._gamma, self._lam)
        flat = lambda a: a.reshape((T * N,) + a.shape[2:])  # noqa: E731
        return {
            "obs": flat(obs_buf), "actions": flat(act_buf),
            "actions_executed": flat(exec_buf),
            "logp": flat(logp_buf), "values": flat(val_buf),
            "rewards": flat(rew_buf), "dones": flat(done_buf),
            "next_obs": flat(next_obs_buf),
            "advantages": flat(gae["advantages"]),
            "value_targets": flat(gae["value_targets"]),
            # [N] bootstrap for off-policy corrections (IMPALA V-trace)
            "last_values": last_values.astype(np.float32),
        }

    # ---- connector state sync (reference: filter delta flush) ----------
    def pop_connector_deltas(self):
        return (self._connectors.pop_deltas()
                if self._connectors is not None else None)

    def set_connector_globals(self, states) -> None:
        if self._connectors is not None:
            self._connectors.set_globals(states)

    def episode_stats(self) -> Dict[str, float]:
        completed, self._completed = self._completed, []
        if not completed:
            return {"episodes": 0, "mean_return": float("nan")}
        return {"episodes": len(completed),
                "mean_return": float(np.mean(completed))}
