"""Anakin-fused rollouts: env + policy + learner in ONE compiled launch.

The Podracer architecture (arxiv 2104.06272) applied to this RL stack:
instead of the host loop in ``env_runner.py`` (numpy env steps
interleaved with per-step jitted inference — one dispatch per env step),
the whole iteration compiles into a single XLA program:

    rollout (``lax.scan`` over T steps, ``vmap`` over B envs)
      → GAE advantages (reverse ``lax.scan``)
        → PPO update (``lax.scan`` over epochs)

Zero host↔device transfers inside the iteration; the host only sees the
final metrics pytree. On a TPU mesh the same program shards over chips
(the batch axis is embarrassingly parallel); on CPU it still wins by
amortizing dispatch — the A/B bench (``bench_fused_vs_host``) measures
env-steps/s against the host-loop ``EnvRunner.sample`` path.

The fused step is compiled EXACTLY ONCE per (config, shapes):
``AnakinRunner.compile_count()`` exposes the jit cache size so tests can
assert the single-launch property instead of trusting the docstring.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl import models
from ray_tpu.rl.algorithms.ppo import make_ppo_loss
from ray_tpu.rl.jax_env import make_jax_env


@dataclasses.dataclass
class AnakinConfig:
    """One fused-iteration recipe (PPO on a pure-JAX env)."""

    env: str = "CartPole-v1"
    num_envs: int = 64
    rollout_len: int = 32
    hidden: Tuple[int, ...] = (64, 64)
    lr: float = 3e-4
    gamma: float = 0.99
    lam: float = 0.95
    clip_param: float = 0.2
    vf_coeff: float = 0.5
    entropy_coeff: float = 0.01
    num_epochs: int = 2
    grad_clip: float = 0.5
    seed: int = 0

    @property
    def env_steps_per_iter(self) -> int:
        return self.num_envs * self.rollout_len


def make_anakin_step(cfg: AnakinConfig, env_cls=None):
    """Build the fused iteration: ``step(carry) -> (carry, metrics)``.

    ``carry`` = (params, opt_state, env_state, obs, key). The function is
    pure and jit-ready; :class:`AnakinRunner` owns the single ``jax.jit``
    wrapping so the compile count is observable.
    """
    env_cls = env_cls or make_jax_env(cfg.env)
    spec = env_cls.spec
    loss_fn = make_ppo_loss(spec, cfg.clip_param, cfg.vf_coeff,
                            cfg.entropy_coeff)
    opt = optax.chain(optax.clip_by_global_norm(cfg.grad_clip),
                      optax.adam(cfg.lr))
    T = cfg.rollout_len

    def step(carry):
        params, opt_state, env_state, obs, key = carry

        def rollout_body(c, _):
            env_state, obs, key = c
            key, sub = jax.random.split(key)
            logits = models.policy_logits(params, obs)
            vals = models.value(params, obs)
            actions = models.categorical_sample(sub, logits)
            logp = models.categorical_logp(logits, actions)
            env_state, next_obs, rew, done = env_cls.step_batch(
                env_state, actions)
            return ((env_state, next_obs, key),
                    (obs, actions, logp, vals, rew, done))

        (env_state, obs, key), traj = jax.lax.scan(
            rollout_body, (env_state, obs, key), None, length=T)
        obs_t, act_t, logp_t, val_t, rew_t, done_t = traj
        last_val = models.value(params, obs)

        def gae_body(c, inp):
            last_gae, next_val = c
            rew, val, done = inp
            nonterminal = 1.0 - done.astype(jnp.float32)
            delta = rew + cfg.gamma * next_val * nonterminal - val
            last_gae = delta + cfg.gamma * cfg.lam * nonterminal * last_gae
            return (last_gae, val), last_gae

        (_, _), adv_t = jax.lax.scan(
            gae_body, (jnp.zeros_like(last_val), last_val),
            (rew_t, val_t, done_t), reverse=True)
        ret_t = adv_t + val_t

        flat = lambda a: a.reshape((T * cfg.num_envs,) + a.shape[2:])  # noqa: E731
        batch = {"obs": flat(obs_t), "actions": flat(act_t),
                 "logp": flat(logp_t), "advantages": flat(adv_t),
                 "value_targets": flat(ret_t)}

        def update_body(c, _):
            params, opt_state = c
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch, None)
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), (loss, aux["entropy"], aux["kl"])

        (params, opt_state), (losses, entropies, kls) = jax.lax.scan(
            update_body, (params, opt_state), None, length=cfg.num_epochs)

        metrics = {
            "reward_mean_per_step": jnp.mean(rew_t),
            "episodes_done": jnp.sum(done_t),
            "loss": losses[-1],
            "entropy": entropies[-1],
            "kl": kls[-1],
            "value_mean": jnp.mean(val_t),
        }
        return (params, opt_state, env_state, obs, key), metrics

    return step


class AnakinRunner:
    """Owns the fused step's single jit + the training carry.

    The entire iteration — rollout, advantage, update — is ONE launch;
    host code only converts the returned metrics. ``compile_count()``
    reports how many programs the jit cache holds (the fusion test
    asserts it stays at 1 across iterations).
    """

    def __init__(self, cfg: Optional[AnakinConfig] = None, **overrides):
        self.cfg = cfg or AnakinConfig(**overrides)
        env_cls = make_jax_env(self.cfg.env)
        self._env_cls = env_cls
        key = jax.random.key(self.cfg.seed)
        k_params, k_env, k_run = jax.random.split(key, 3)
        params = jax.tree_util.tree_map(
            jnp.asarray,
            models.init_policy(k_params, env_cls.spec,
                               hidden=self.cfg.hidden))
        opt = optax.chain(optax.clip_by_global_norm(self.cfg.grad_clip),
                          optax.adam(self.cfg.lr))
        opt_state = opt.init(params)
        env_state, obs = env_cls.reset_batch(k_env, self.cfg.num_envs)
        self._carry = (params, opt_state, env_state, obs, k_run)
        self._step_fn = jax.jit(make_anakin_step(self.cfg, env_cls))
        self.iterations = 0
        self.env_steps_total = 0

    @property
    def params(self):
        return self._carry[0]

    def compile_count(self) -> int:
        """Programs in the fused step's jit cache (1 == fully fused)."""
        return int(self._step_fn._cache_size())

    def train(self, iterations: int = 1) -> Dict[str, Any]:
        """Run N fused iterations; returns the LAST iteration's metrics
        (converted host-side, outside the compiled program)."""
        metrics = None
        for _ in range(iterations):
            self._carry, metrics = self._step_fn(self._carry)
        self.iterations += iterations
        self.env_steps_total += iterations * self.cfg.env_steps_per_iter
        out = {k: float(np.asarray(v)) for k, v in metrics.items()}
        out["env_steps_total"] = self.env_steps_total
        out["iterations"] = self.iterations
        return out

    def block(self) -> None:
        """Device-sync the carry (bench timing boundary)."""
        jax.block_until_ready(self._carry)


# ---------------------------------------------------------------------------
# A/B bench: fused Anakin vs the host-loop EnvRunner path
# ---------------------------------------------------------------------------


def bench_fused_vs_host(*, num_envs: int = 64, rollout_len: int = 32,
                        iters: int = 20, warmup: int = 3,
                        seed: int = 0) -> Dict[str, Any]:
    """env-steps/s of the fused Anakin iteration vs the host-loop
    ``EnvRunner`` path running the SAME work at the SAME (B, T) shape.

    Both legs execute one full PPO iteration per fragment — rollout,
    GAE, ``num_epochs`` full-batch updates with the identical loss and
    optimizer. The fused leg runs it all as ONE launch; the host leg is
    the existing architecture: numpy env stepped under per-step jitted
    inference (one dispatch + device→host readback per env step, numpy
    GAE), then the batch shipped host→device for a separately-launched
    update. The delta is therefore exactly the per-step ping-pong and
    launch overhead Anakin removes, not a difference in algorithm work.

    Methodology (stamped into the result): ``warmup`` untimed iterations
    first (XLA compiles + CPU dispatch-jitter dry runs), then ``iters``
    timed; the fused leg blocks on its carry before and after timing so
    async dispatch cannot hide work.
    """
    cfg = AnakinConfig(num_envs=num_envs, rollout_len=rollout_len,
                       seed=seed)
    runner = AnakinRunner(cfg)
    runner.train(warmup)
    runner.block()
    t0 = time.perf_counter()
    runner.train(iters)
    runner.block()
    fused_s = time.perf_counter() - t0
    fused_steps = iters * cfg.env_steps_per_iter

    # host loop: the plain EnvRunner class (no actor hop — this measures
    # the per-step host↔device architecture, not RPC overhead), plus the
    # same PPO update jitted as its own launch (batch crosses the host
    # boundary, as the existing Algorithm.training_step path does)
    from ray_tpu.rl.env_runner import EnvRunner

    host_cls = getattr(EnvRunner, "_cls", EnvRunner)
    host = host_cls("CartPole-v1", num_envs, rollout_len, seed=seed)
    host_params = jax.tree_util.tree_map(
        jnp.asarray, models.init_policy(jax.random.key(seed), host.spec,
                                        hidden=cfg.hidden))
    loss_fn = make_ppo_loss(host.spec, cfg.clip_param, cfg.vf_coeff,
                            cfg.entropy_coeff)
    opt = optax.chain(optax.clip_by_global_norm(cfg.grad_clip),
                      optax.adam(cfg.lr))
    opt_state = opt.init(host_params)

    @jax.jit
    def host_update(params, opt_state, batch):
        def body(c, _):
            params, opt_state = c
            (_, _), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch, None)
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, opt_state), None

        (params, opt_state), _ = jax.lax.scan(
            body, (params, opt_state), None, length=cfg.num_epochs)
        return params, opt_state

    def host_iter(params, opt_state):
        frag = host.sample(params)
        batch = {k: jnp.asarray(frag[k])
                 for k in ("obs", "actions", "logp", "advantages",
                           "value_targets")}
        params, opt_state = host_update(params, opt_state, batch)
        return params, opt_state

    for _ in range(warmup):
        host_params, opt_state = host_iter(host_params, opt_state)
    jax.block_until_ready(host_params)
    t0 = time.perf_counter()
    for _ in range(iters):
        host_params, opt_state = host_iter(host_params, opt_state)
    jax.block_until_ready(host_params)
    host_s = time.perf_counter() - t0
    host_steps = iters * num_envs * rollout_len

    fused_sps = fused_steps / max(fused_s, 1e-9)
    host_sps = host_steps / max(host_s, 1e-9)
    return {
        "num_envs": num_envs, "rollout_len": rollout_len,
        "iters": iters, "warmup": warmup,
        "fused_env_steps_per_s": round(fused_sps, 1),
        "host_env_steps_per_s": round(host_sps, 1),
        "fused_vs_host_ratio": round(fused_sps / max(host_sps, 1e-9), 2),
        "fused_compile_count": runner.compile_count(),
        "methodology": (
            "equal work both legs (rollout + GAE + {e}-epoch PPO update "
            "at B={b}, T={t}): {w} warmup iters (compiles + CPU "
            "dispatch-jitter dry runs) then {n} timed; fused leg is one "
            "launch per iter, block_until_ready-bounded; host leg is "
            "EnvRunner.sample (per-step jitted inference + numpy env + "
            "numpy GAE) + a separately-launched jitted update".format(
                e=cfg.num_epochs, w=warmup, n=iters, b=num_envs,
                t=rollout_len)),
    }
