"""Environments: vectorized interface + builtin envs.

Reference analog: ``rllib/env/`` (``VectorEnv``, ``gym`` wrappers). The
builtin envs are numpy-vectorized re-implementations of the classic control
dynamics (CartPole / Pendulum) so the RL stack tests and benches without a
gym dependency; external gymnasium envs plug in through the same interface
via ``register_env``.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional, Tuple

import numpy as np

_ENV_REGISTRY: Dict[str, Callable] = {}


def register_env(name: str, creator: Callable[[Dict], "VectorEnv"]) -> None:
    _ENV_REGISTRY[name] = creator


def make_env(name: str, num_envs: int, config: Optional[Dict] = None,
             seed: int = 0) -> "VectorEnv":
    if name in _ENV_REGISTRY:
        return _ENV_REGISTRY[name]({"num_envs": num_envs,
                                    "seed": seed, **(config or {})})
    if name == "CartPole-v1":
        return CartPole(num_envs, seed=seed)
    if name == "Pendulum-v1":
        return Pendulum(num_envs, seed=seed)
    raise KeyError(
        f"unknown env {name!r}; register it with rl.register_env")


@dataclasses.dataclass
class EnvSpec:
    obs_dim: int = 0
    num_actions: int = 0        # discrete action count (0 => continuous)
    action_dim: int = 0         # continuous action dim
    action_low: float = -1.0
    action_high: float = 1.0
    # Image observations (the Atari-class path): (H, W, C). When set,
    # obs_dim is ignored and policies get a conv encoder (models.py).
    obs_shape: Tuple[int, ...] = ()

    @property
    def discrete(self) -> bool:
        return self.num_actions > 0

    @property
    def obs_dims(self) -> Tuple[int, ...]:
        """Per-observation shape: (obs_dim,) for flat envs, (H, W, C) for
        pixel envs — the buffer/layout contract shared by runners."""
        return tuple(self.obs_shape) if self.obs_shape else (self.obs_dim,)

    @property
    def is_pixel(self) -> bool:
        return len(self.obs_shape) == 3


class VectorEnv:
    """N independent env copies stepped in lockstep; auto-resets on done."""

    spec: EnvSpec
    num_envs: int

    def reset(self) -> np.ndarray:
        raise NotImplementedError

    def step(self, actions: np.ndarray
             ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """returns (obs, rewards, dones); done envs are already reset."""
        raise NotImplementedError


class CartPole(VectorEnv):
    """Numpy-vectorized CartPole-v1 dynamics (500-step limit, +1/step)."""

    def __init__(self, num_envs: int, seed: int = 0):
        self.num_envs = num_envs
        self.spec = EnvSpec(obs_dim=4, num_actions=2)
        self._rng = np.random.default_rng(seed)
        self._state = np.zeros((num_envs, 4), dtype=np.float64)
        self._t = np.zeros(num_envs, dtype=np.int64)
        self._gravity, self._mc, self._mp = 9.8, 1.0, 0.1
        self._l, self._fmag, self._dt = 0.5, 10.0, 0.02
        self._theta_lim = 12 * 2 * np.pi / 360
        self._x_lim = 2.4
        self._max_t = 500

    def _reset_envs(self, mask: np.ndarray) -> None:
        n = int(mask.sum())
        if n:
            self._state[mask] = self._rng.uniform(-0.05, 0.05, size=(n, 4))
            self._t[mask] = 0

    def reset(self) -> np.ndarray:
        self._reset_envs(np.ones(self.num_envs, dtype=bool))
        return self._state.astype(np.float32)

    def step(self, actions: np.ndarray):
        x, x_dot, th, th_dot = self._state.T
        force = np.where(actions == 1, self._fmag, -self._fmag)
        cos, sin = np.cos(th), np.sin(th)
        total_m = self._mc + self._mp
        pm_l = self._mp * self._l
        temp = (force + pm_l * th_dot ** 2 * sin) / total_m
        th_acc = (self._gravity * sin - cos * temp) / (
            self._l * (4.0 / 3.0 - self._mp * cos ** 2 / total_m))
        x_acc = temp - pm_l * th_acc * cos / total_m
        x = x + self._dt * x_dot
        x_dot = x_dot + self._dt * x_acc
        th = th + self._dt * th_dot
        th_dot = th_dot + self._dt * th_acc
        self._state = np.stack([x, x_dot, th, th_dot], axis=1)
        self._t += 1
        dones = ((np.abs(x) > self._x_lim)
                 | (np.abs(th) > self._theta_lim)
                 | (self._t >= self._max_t))
        rewards = np.ones(self.num_envs, dtype=np.float32)
        self._reset_envs(dones)
        return self._state.astype(np.float32), rewards, dones


class Pendulum(VectorEnv):
    """Numpy-vectorized Pendulum-v1 (continuous torque, 200-step episodes)."""

    def __init__(self, num_envs: int, seed: int = 0):
        self.num_envs = num_envs
        self.spec = EnvSpec(obs_dim=3, action_dim=1,
                            action_low=-2.0, action_high=2.0)
        self._rng = np.random.default_rng(seed)
        self._th = np.zeros(num_envs)
        self._thdot = np.zeros(num_envs)
        self._t = np.zeros(num_envs, dtype=np.int64)
        self._max_t = 200
        self._g, self._m, self._l, self._dt = 10.0, 1.0, 1.0, 0.05

    def _obs(self) -> np.ndarray:
        return np.stack([np.cos(self._th), np.sin(self._th),
                         self._thdot], axis=1).astype(np.float32)

    def _reset_envs(self, mask: np.ndarray) -> None:
        n = int(mask.sum())
        if n:
            self._th[mask] = self._rng.uniform(-np.pi, np.pi, size=n)
            self._thdot[mask] = self._rng.uniform(-1.0, 1.0, size=n)
            self._t[mask] = 0

    def reset(self) -> np.ndarray:
        self._reset_envs(np.ones(self.num_envs, dtype=bool))
        return self._obs()

    def step(self, actions: np.ndarray):
        u = np.clip(np.asarray(actions).reshape(self.num_envs), -2.0, 2.0)
        th_norm = ((self._th + np.pi) % (2 * np.pi)) - np.pi
        costs = th_norm ** 2 + 0.1 * self._thdot ** 2 + 0.001 * u ** 2
        thdot = self._thdot + (
            3 * self._g / (2 * self._l) * np.sin(self._th)
            + 3.0 / (self._m * self._l ** 2) * u) * self._dt
        thdot = np.clip(thdot, -8.0, 8.0)
        self._th = self._th + thdot * self._dt
        self._thdot = thdot
        self._t += 1
        dones = self._t >= self._max_t
        rewards = (-costs).astype(np.float32)
        self._reset_envs(dones)
        return self._obs(), rewards, dones
