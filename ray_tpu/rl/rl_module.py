"""RLModule + Catalog: the configurable model-container layer.

Reference analogs: ``rllib/core/rl_module/rl_module.py`` (the RLModule
container with its three forward contracts), ``marl_module.py``
(``MultiAgentRLModule``), and the per-algorithm catalogs
(``rllib/algorithms/ppo/ppo_catalog.py`` etc. — the pluggable
spec -> encoder/head factory).

Here the container is a thin, functional wrapper over ``rl/models.py``
param pytrees: a ``ModuleSpec`` describes the architecture (encoder
family, widths, activation), the ``Catalog`` resolves it against an
``EnvSpec`` into an initialized ``RLModule``, and custom architectures
plug in via ``register_module_builder`` (the catalog-extension hook the
reference exposes by subclassing catalogs). Because the produced param
trees keep the framework's standard layout (``pi``/``vf``/``enc``/
``log_std`` keys), every algorithm, the EnvRunner fleet, and the
checkpoint machinery consume catalog-built modules unchanged —
``AlgorithmConfig.module_spec`` switches any on-policy algorithm onto a
custom architecture with no other code changes.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl import models
from ray_tpu.rl.env import EnvSpec


@dataclasses.dataclass
class ModuleSpec:
    """Architecture description, resolved by the Catalog.

    ``encoder``: "auto" (mlp for flat specs, cnn for pixel specs), "mlp",
    "cnn", or a name registered via ``register_module_builder``.
    """

    encoder: str = "auto"
    hidden: Sequence[int] = (64, 64)
    activation: str = "tanh"            # "tanh" | "relu"
    encoder_out: int = 512              # cnn feature width
    free_log_std: bool = True           # continuous: global learned std
    builder_kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)


# name -> builder(key, env_spec, module_spec) -> params pytree
_MODULE_BUILDERS: Dict[str, Callable] = {}


def register_module_builder(name: str, builder: Callable) -> None:
    """Catalog extension hook (reference: subclassing ``Catalog`` to
    swap encoders). ``builder(key, env_spec, module_spec)`` must return
    a params pytree with the standard ``pi``/``vf`` (+ ``enc`` /
    ``log_std``) layout."""
    _MODULE_BUILDERS[name] = builder


def _act_marker(activation: str) -> jnp.ndarray:
    if activation == "tanh":
        return jnp.zeros(0)
    if activation == "relu":
        return jnp.zeros(1)
    raise ValueError(f"unknown activation {activation!r} "
                     "(expected 'tanh' or 'relu')")


def _check_log_std(spec: EnvSpec, ms: ModuleSpec) -> None:
    if not spec.discrete and not ms.free_log_std:
        raise ValueError(
            "continuous-action modules require free_log_std=True (the "
            "losses and exploration paths read params['log_std']; a "
            "state-dependent std head is not supported)")


def _build_mlp_module(key, spec: EnvSpec, ms: ModuleSpec) -> Dict:
    _check_log_std(spec, ms)
    pk, vk = jax.random.split(key)
    out = spec.num_actions if spec.discrete else spec.action_dim
    marker = _act_marker(ms.activation)
    pi = models.init_mlp(pk, [spec.obs_dim, *ms.hidden, out])
    vf = models.init_mlp(vk, [spec.obs_dim, *ms.hidden, 1], out_scale=1.0)
    if marker.shape[0]:
        pi["act"] = marker
        vf["act"] = jnp.array(marker)
    params = {"pi": pi, "vf": vf}
    if not spec.discrete and ms.free_log_std:
        params["log_std"] = jnp.zeros(spec.action_dim)
    return params


def _build_cnn_module(key, spec: EnvSpec, ms: ModuleSpec) -> Dict:
    if not spec.is_pixel:
        raise ValueError("cnn encoder needs a pixel EnvSpec (obs_shape "
                         "of rank 3)")
    _check_log_std(spec, ms)
    pk, vk, ek = jax.random.split(key, 3)
    out = spec.num_actions if spec.discrete else spec.action_dim
    feat = ms.encoder_out
    params = {
        "enc": models.init_cnn(ek, spec.obs_shape, feat),
        "pi": models.init_mlp(pk, [feat, out]),
        "vf": models.init_mlp(vk, [feat, 1], out_scale=1.0),
    }
    if not spec.discrete and ms.free_log_std:
        params["log_std"] = jnp.zeros(spec.action_dim)
    return params


_MODULE_BUILDERS["mlp"] = _build_mlp_module
_MODULE_BUILDERS["cnn"] = _build_cnn_module


class RLModule:
    """Params + the three forward contracts of the reference RLModule:

    - ``forward_inference``: greedy/deterministic actions
    - ``forward_exploration``: stochastic actions + logp
    - ``forward_train``: logits/values for the learner loss
    """

    def __init__(self, params: Dict, env_spec: EnvSpec,
                 module_spec: Optional[ModuleSpec] = None):
        self.params = params
        self.env_spec = env_spec
        self.module_spec = module_spec or ModuleSpec()
        spec = env_spec

        @jax.jit
        def fwd_train(p, obs):
            return {"action_logits": models.policy_logits(p, obs),
                    "values": models.value(p, obs)}

        @jax.jit
        def fwd_inference(p, obs):
            logits = models.policy_logits(p, obs)
            if spec.discrete:
                return jnp.argmax(logits, axis=-1)
            return jnp.clip(logits, spec.action_low, spec.action_high)

        @jax.jit
        def fwd_exploration(p, obs, key):
            logits = models.policy_logits(p, obs)
            if spec.discrete:
                acts = models.categorical_sample(key, logits)
                logp = models.categorical_logp(logits, acts)
            else:
                acts = models.gaussian_sample(key, logits, p["log_std"])
                logp = models.gaussian_logp(logits, p["log_std"], acts)
                acts = jnp.clip(acts, spec.action_low, spec.action_high)
            return acts, logp

        self._fwd_train = fwd_train
        self._fwd_inference = fwd_inference
        self._fwd_exploration = fwd_exploration

    def forward_train(self, obs) -> Dict[str, jnp.ndarray]:
        return self._fwd_train(self.params, jnp.asarray(obs))

    def forward_inference(self, obs) -> np.ndarray:
        return np.asarray(self._fwd_inference(self.params,
                                              jnp.asarray(obs)))

    def forward_exploration(self, obs, key):
        acts, logp = self._fwd_exploration(self.params, jnp.asarray(obs),
                                           key)
        return np.asarray(acts), np.asarray(logp)

    # -- state ------------------------------------------------------------

    def get_state(self) -> Dict:
        return jax.tree_util.tree_map(np.asarray, self.params)

    def set_state(self, state: Dict) -> None:
        self.params = jax.tree_util.tree_map(jnp.asarray, state)

    def num_params(self) -> int:
        return models.num_params(self.params)


class Catalog:
    """Resolves (EnvSpec, ModuleSpec) -> initialized RLModule."""

    @staticmethod
    def build(env_spec: EnvSpec,
              module_spec: Optional[ModuleSpec] = None,
              seed: int = 0) -> RLModule:
        ms = module_spec or ModuleSpec()
        name = ms.encoder
        if name == "auto":
            name = "cnn" if env_spec.is_pixel else "mlp"
        if name not in _MODULE_BUILDERS:
            raise ValueError(
                f"unknown module builder {name!r}; registered: "
                f"{sorted(_MODULE_BUILDERS)}")
        params = _MODULE_BUILDERS[name](jax.random.key(seed), env_spec, ms)
        return RLModule(params, env_spec, ms)

    @staticmethod
    def build_params(env_spec: EnvSpec,
                     module_spec: Optional[ModuleSpec] = None,
                     seed: int = 0) -> Dict:
        """Just the initialized param pytree (what Algorithm.build_learner
        feeds its Learner when ``config.module_spec`` is set)."""
        return Catalog.build(env_spec, module_spec, seed).params


class MultiAgentRLModule:
    """Policy-id -> RLModule container (reference ``marl_module.py``)."""

    def __init__(self, modules: Dict[str, RLModule]):
        self._modules = dict(modules)

    def __getitem__(self, policy_id: str) -> RLModule:
        return self._modules[policy_id]

    def __contains__(self, policy_id: str) -> bool:
        return policy_id in self._modules

    def keys(self):
        return self._modules.keys()

    def items(self):
        return self._modules.items()

    def get_state(self) -> Dict[str, Dict]:
        return {pid: m.get_state() for pid, m in self._modules.items()}

    def set_state(self, state: Dict[str, Dict]) -> None:
        for pid, s in state.items():
            self._modules[pid].set_state(s)

    @staticmethod
    def build(env_specs: Dict[str, EnvSpec],
              module_specs: Optional[Dict[str, ModuleSpec]] = None,
              seed: int = 0) -> "MultiAgentRLModule":
        module_specs = module_specs or {}
        return MultiAgentRLModule({
            pid: Catalog.build(es, module_specs.get(pid), seed + i)
            for i, (pid, es) in enumerate(sorted(env_specs.items()))})
