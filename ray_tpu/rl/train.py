"""`rt rl train` / `rt rl evaluate` — the RL command-line entry.

Reference analogs: ``rllib/train.py`` (``rllib train --run PPO --env
CartPole-v1 --config '{...}' --stop '{...}'``), ``rllib/evaluate.py``
(rollouts from a checkpoint), and ``rllib/algorithms/registry.py`` (the
name -> algorithm map). The checkpoint directory stores the pickled
``AlgorithmConfig`` next to the Trainable payload so ``evaluate`` can
rebuild the exact algorithm without re-specifying flags.
"""

from __future__ import annotations

import json
import os
import pickle
import sys
import time
from typing import Any, Dict, Optional

import numpy as np


def algorithm_registry() -> Dict[str, type]:
    """name -> AlgorithmConfig class for every bundled algorithm."""
    from ray_tpu import rl

    return {
        "PPO": rl.PPOConfig, "APPO": rl.APPOConfig,
        "IMPALA": rl.IMPALAConfig, "A2C": rl.A2CConfig,
        "PG": rl.PGConfig, "MAML": rl.MAMLConfig,
        "MBMPO": rl.MBMPOConfig,
        "DQN": rl.DQNConfig, "APEXDQN": rl.ApexDQNConfig,
        "APEXDDPG": rl.ApexDDPGConfig,
        "SIMPLEQ": rl.SimpleQConfig,
        "SAC": rl.SACConfig,
        "DDPG": rl.DDPGConfig, "TD3": rl.TD3Config,
        "BC": rl.BCConfig, "MARWIL": rl.MARWILConfig,
        "CQL": rl.CQLConfig, "CRR": rl.CRRConfig, "DT": rl.DTConfig,
        "ES": rl.ESConfig, "ARS": rl.ARSConfig,
        "QMIX": rl.QMIXConfig, "MADDPG": rl.MADDPGConfig,
        "SLATEQ": rl.SlateQConfig, "DREAMERV3": rl.DreamerV3Config,
        "ALPHAZERO": rl.AlphaZeroConfig,
        "LEELACHESSZERO": rl.LeelaChessZeroConfig,
        "R2D2": rl.R2D2Config,
        "BANDITLINUCB": rl.BanditConfig, "BANDITLINTS": rl.BanditConfig,
    }


def get_algorithm_config(run: str):
    reg = algorithm_registry()
    key = run.replace("-", "").replace("_", "").upper()
    if key not in reg:
        raise ValueError(
            f"unknown algorithm {run!r}; available: {sorted(reg)}")
    cfg = reg[key]()
    # the two bandit flavors share a config class; pick the right algo
    if key in ("BANDITLINTS", "BANDITLINUCB"):
        from ray_tpu import rl

        cfg.algo_class = (rl.BanditLinTS if key == "BANDITLINTS"
                          else rl.BanditLinUCB)
    return cfg


def _load_overrides(config_json: Optional[str],
                    config_file: Optional[str]) -> Dict[str, Any]:
    overrides: Dict[str, Any] = {}
    if config_file:
        with open(config_file) as f:
            text = f.read()
        try:
            overrides.update(json.loads(text))
        except json.JSONDecodeError:
            import yaml

            overrides.update(yaml.safe_load(text) or {})
    if config_json:
        overrides.update(json.loads(config_json))
    return overrides


def run_train(run: str, env: Optional[str] = None,
              config_json: Optional[str] = None,
              config_file: Optional[str] = None,
              stop_iters: int = 10,
              stop_reward: Optional[float] = None,
              stop_timesteps: Optional[int] = None,
              checkpoint_dir: Optional[str] = None,
              out=sys.stdout) -> Dict[str, Any]:
    """Train `run` until a stop criterion fires; returns the last result."""
    cfg = get_algorithm_config(run)
    if env:
        cfg.env = env
    overrides = _load_overrides(config_json, config_file)
    if overrides:
        cfg.update_from_dict(overrides)
    algo = cfg.build()
    result: Dict[str, Any] = {}
    try:
        for i in range(stop_iters):
            t0 = time.monotonic()
            result = algo.train()
            dt = time.monotonic() - t0
            # display metric: best-effort fallback chain
            shown = result.get("episode_return_mean",
                               result.get("mean_return",
                                          result.get("reward_mean_per_step",
                                                     float("nan"))))
            steps = result.get("env_steps_total", 0)
            print(f"iter {i + 1}/{stop_iters}  reward={shown:.2f}  "
                  f"env_steps={steps}  {dt:.1f}s", file=out, flush=True)
            # stop metric: episode-return semantics only (mean_return for
            # the population-based algos, which never report episodes) —
            # never the per-step reward, whose scale is episode-length
            # smaller and would fire a threshold meant for episode returns
            stop_metric = result.get("episode_return_mean",
                                     result.get("mean_return"))
            if stop_reward is not None and stop_metric is not None \
                    and np.isfinite(stop_metric) \
                    and stop_metric >= stop_reward:
                print(f"stop: reward {stop_metric:.2f} >= {stop_reward}",
                      file=out)
                break
            if stop_timesteps is not None and steps >= stop_timesteps:
                print(f"stop: env steps {steps} >= {stop_timesteps}",
                      file=out)
                break
        if checkpoint_dir:
            path = algo.save(checkpoint_dir)
            with open(os.path.join(checkpoint_dir, "algo_config.pkl"),
                      "wb") as f:
                pickle.dump({"run": run, "config": cfg}, f)
            print(f"checkpoint saved to {path}", file=out)
    finally:
        stop = getattr(algo, "stop", None)
        if stop:
            stop()
    return result


def tuned_examples_dir() -> str:
    """The bundled convergence-config zoo (reference:
    ``rllib/tuned_examples/``)."""
    return os.path.join(os.path.dirname(__file__), "tuned_examples")


def load_tuned_example(name_or_path: str) -> Dict[str, Any]:
    """Load one experiment from a tuned-example YAML.

    Accepts a path or a bare name resolved against the bundled zoo
    (``cartpole-ppo`` -> ``rl/tuned_examples/cartpole-ppo.yaml``). The
    file uses the reference's format: one top-level experiment key with
    ``run`` / ``env`` / ``stop`` / ``config`` fields.
    """
    import yaml

    path = name_or_path
    if not os.path.exists(path):
        candidate = os.path.join(tuned_examples_dir(),
                                 name_or_path.replace(".yaml", "")
                                 + ".yaml")
        if os.path.exists(candidate):
            path = candidate
        else:
            raise FileNotFoundError(
                f"{name_or_path!r} is neither a file nor a bundled tuned "
                f"example; bundled: {sorted(list_tuned_examples())}")
    with open(path) as f:
        doc = yaml.safe_load(f)
    if not isinstance(doc, dict) or not doc:
        raise ValueError(f"{path}: expected one top-level experiment key")
    name, exp = next(iter(doc.items()))
    if "run" not in exp:
        raise ValueError(f"{path}: experiment {name!r} needs a 'run' key")
    return {"name": name, **exp}


def list_tuned_examples() -> list:
    d = tuned_examples_dir()
    if not os.path.isdir(d):
        return []
    return [f[:-5] for f in sorted(os.listdir(d)) if f.endswith(".yaml")]


def run_tuned_example(name_or_path: str,
                      checkpoint_dir: Optional[str] = None,
                      stop_iters: Optional[int] = None,
                      stop_reward: Optional[float] = None,
                      stop_timesteps: Optional[int] = None,
                      out=sys.stdout) -> Dict[str, Any]:
    """Train a bundled (or user) tuned example to its stop criteria.
    Explicit stop arguments override the YAML's ``stop`` block."""
    exp = load_tuned_example(name_or_path)
    stop = exp.get("stop") or {}
    return run_train(
        exp["run"], env=exp.get("env"),
        config_json=json.dumps(exp.get("config") or {}),
        stop_iters=int(stop_iters if stop_iters is not None
                       else stop.get("training_iteration", 100)),
        stop_reward=(stop_reward if stop_reward is not None
                     else stop.get("episode_return_mean")),
        stop_timesteps=(stop_timesteps if stop_timesteps is not None
                        else stop.get("timesteps_total")),
        checkpoint_dir=checkpoint_dir, out=out)


def run_evaluate(checkpoint_dir: str, run: Optional[str] = None,
                 episodes: int = 10, out=sys.stdout) -> Dict[str, Any]:
    """Roll out a trained policy and report episode returns."""
    meta_path = os.path.join(checkpoint_dir, "algo_config.pkl")
    if os.path.exists(meta_path):
        with open(meta_path, "rb") as f:
            meta = pickle.load(f)
        cfg = meta["config"]
        run = run or meta["run"]
    elif run:
        cfg = get_algorithm_config(run)
    else:
        raise ValueError(
            f"{meta_path} not found; pass --run to name the algorithm")
    algo = cfg.build()
    algo.restore(checkpoint_dir)
    try:
        eval_fn = getattr(algo, "evaluate", None)
        if eval_fn is None:
            raise ValueError(
                f"{type(algo).__name__} does not implement evaluate()")
        result = eval_fn(episodes)
        print(json.dumps(result, indent=2), file=out)
        return result
    finally:
        stop = getattr(algo, "stop", None)
        if stop:
            stop()
