"""Multi-agent RL: MultiAgentEnv protocol, policy mapping, multi-policy PPO.

Reference analogs: ``rllib/env/multi_agent_env.py`` (``MultiAgentEnv``),
``rllib/core/rl_module/marl_module.py`` (``MultiAgentRLModule`` — here a
dict of per-policy param trees), and the ``policy_mapping_fn`` config
(``algorithm_config.py`` ``multi_agent()``). Scope: simultaneous-move envs
(every agent acts every step, shared episode termination) — the common
cooperative/competitive matrix and particle settings; turn-based envs are
out of scope.

Per policy: an independent PPO learner (jitted clip-surrogate update).
Rollouts are vectorized in-process; each agent's trajectory is routed to
its policy's batch by ``policy_mapping_fn``, GAE computed per agent stream.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl import models
from ray_tpu.rl.algorithms.ppo import make_ppo_loss
from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.env import EnvSpec
from ray_tpu.rl.env_runner import compute_gae
from ray_tpu.rl.learner import Learner
from ray_tpu.tune.trainable import Trainable


class MultiAgentEnv:
    """N vectorized copies of a simultaneous-move multi-agent episode.

    - ``agents``: fixed agent-id list
    - ``reset() -> {agent: obs [N, obs_dim]}``
    - ``step({agent: actions [N]}) -> (obs, rewards, dones)`` where obs and
      rewards are per-agent dicts and ``dones`` is [N] (shared termination;
      done envs auto-reset).
    """

    agents: List[str]
    spec: Dict[str, EnvSpec]
    num_envs: int

    def reset(self) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def step(self, actions: Dict[str, np.ndarray]):
        raise NotImplementedError


class CoordinationGame(MultiAgentEnv):
    """Repeated 2-agent coordination: both pick one of K arms; reward 1 when
    they MATCH on the current round's 'good' arm pair, 0 otherwise. The good
    arm is observable, so coordinated policies reach reward ~1/step; random
    play earns ~1/K^2. Episodes last ``horizon`` rounds."""

    def __init__(self, num_envs: int = 8, k: int = 3, horizon: int = 16,
                 seed: int = 0):
        self.agents = ["a0", "a1"]
        self.num_envs = num_envs
        self.k = k
        self.horizon = horizon
        self._rng = np.random.default_rng(seed)
        obs_dim = k + 1  # one-hot good arm + normalized round index
        spec = EnvSpec(obs_dim=obs_dim, num_actions=k)
        self.spec = {a: spec for a in self.agents}
        self._t = np.zeros(num_envs, dtype=np.int64)
        self._good = self._rng.integers(0, k, num_envs)

    def _obs(self) -> Dict[str, np.ndarray]:
        onehot = np.eye(self.k, dtype=np.float32)[self._good]
        frac = (self._t / self.horizon).astype(np.float32)[:, None]
        obs = np.concatenate([onehot, frac], axis=1)
        return {a: obs.copy() for a in self.agents}

    def reset(self) -> Dict[str, np.ndarray]:
        self._t[:] = 0
        self._good = self._rng.integers(0, self.k, self.num_envs)
        return self._obs()

    def step(self, actions: Dict[str, np.ndarray]):
        a0, a1 = actions["a0"], actions["a1"]
        hit = (a0 == self._good) & (a1 == self._good)
        reward = hit.astype(np.float32)
        self._t += 1
        dones = self._t >= self.horizon
        # next round's good arm; reset finished envs
        self._good = self._rng.integers(0, self.k, self.num_envs)
        self._t[dones] = 0
        rewards = {a: reward.copy() for a in self.agents}
        return self._obs(), rewards, dones


class SpreadGame(MultiAgentEnv):
    """Continuous cooperative coverage (an MPE ``simple_spread``-style
    particle setting, the reference MADDPG's home env): 2 agents move on
    the [-1,1]^2 plane toward 2 landmarks; the SHARED dense reward is
    ``-sum_l min_a dist(a, l)``, maximized when each landmark has an agent
    on it. Actions are velocities in [-1,1]^2; fixed horizon, auto-reset."""

    def __init__(self, num_envs: int = 8, horizon: int = 25,
                 dt: float = 0.15, seed: int = 0):
        self.agents = ["a0", "a1"]
        self.num_envs = num_envs
        self.horizon = horizon
        self.dt = dt
        self._rng = np.random.default_rng(seed)
        # obs: own pos (2) + other pos (2) + both landmarks (4)
        spec = EnvSpec(obs_dim=8, action_dim=2,
                       action_low=-1.0, action_high=1.0)
        self.spec = {a: spec for a in self.agents}
        self._t = np.zeros(num_envs, dtype=np.int64)
        self._pos = np.zeros((num_envs, 2, 2), dtype=np.float32)
        self._land = np.zeros((num_envs, 2, 2), dtype=np.float32)
        self._reset_envs(np.ones(num_envs, dtype=bool))

    def _reset_envs(self, mask: np.ndarray) -> None:
        n = int(mask.sum())
        if not n:
            return
        self._pos[mask] = self._rng.uniform(-1, 1, (n, 2, 2))
        self._land[mask] = self._rng.uniform(-1, 1, (n, 2, 2))
        self._t[mask] = 0

    def _obs(self) -> Dict[str, np.ndarray]:
        land = self._land.reshape(self.num_envs, 4)
        out = {}
        for i, a in enumerate(self.agents):
            own = self._pos[:, i]
            other = self._pos[:, 1 - i]
            out[a] = np.concatenate([own, other, land],
                                    axis=1).astype(np.float32)
        return out

    def reset(self) -> Dict[str, np.ndarray]:
        self._reset_envs(np.ones(self.num_envs, dtype=bool))
        return self._obs()

    def _coverage_reward(self) -> np.ndarray:
        # dist[e, l, a] = || land[e,l] - pos[e,a] ||
        d = np.linalg.norm(self._land[:, :, None] - self._pos[:, None],
                           axis=-1)
        return -d.min(axis=2).sum(axis=1).astype(np.float32)

    def step(self, actions: Dict[str, np.ndarray]):
        for i, a in enumerate(self.agents):
            vel = np.clip(np.asarray(actions[a], dtype=np.float32), -1, 1)
            self._pos[:, i] = np.clip(self._pos[:, i] + self.dt * vel,
                                      -1, 1)
        reward = self._coverage_reward()
        self._t += 1
        dones = self._t >= self.horizon
        self._reset_envs(dones)
        rewards = {a: reward.copy() for a in self.agents}
        return self._obs(), rewards, dones


_MA_ENVS: Dict[str, Callable[..., MultiAgentEnv]] = {
    "coordination": CoordinationGame,
    "spread": SpreadGame,
}


def register_multi_agent_env(name: str, ctor: Callable[..., MultiAgentEnv]):
    _MA_ENVS[name] = ctor


class MultiAgentPPO(Trainable):
    """Independent-PPO over a policy map (reference: multi-agent PPO with
    ``policy_mapping_fn``; 'independent' = no centralized critic — the
    standard IPPO baseline)."""

    def setup(self, config: Dict[str, Any]) -> None:
        if "__algo_config" in config:
            self.config: AlgorithmConfig = config["__algo_config"]
        else:
            self.config = AlgorithmConfig(algo_class=type(self))\
                .update_from_dict(config)
        cfg = self.config
        ctor = _MA_ENVS[cfg.env] if isinstance(cfg.env, str) else cfg.env
        self.env = ctor(num_envs=cfg.num_envs_per_runner,
                        **(cfg.env_config or {}))
        self.policy_mapping_fn = (cfg.policy_mapping_fn
                                  or (lambda agent_id: agent_id))
        self.policies = sorted({self.policy_mapping_fn(a)
                                for a in self.env.agents})
        self.learners: Dict[str, Learner] = {}
        for i, pid in enumerate(self.policies):
            spec = self.env.spec[next(
                a for a in self.env.agents
                if self.policy_mapping_fn(a) == pid)]
            loss = make_ppo_loss(spec, cfg.clip_param, cfg.vf_coeff,
                                 cfg.entropy_coeff)
            params = models.init_policy(
                jax.random.key(cfg.seed + i), spec, cfg.hidden)
            self.learners[pid] = Learner(params, loss, cfg.lr,
                                        grad_clip=cfg.grad_clip,
                                        seed=cfg.seed + i)
        self._key = jax.random.key(cfg.seed + 777)
        self._obs = self.env.reset()

        # ONE jitted act per policy (the EnvRunner pattern): the rollout hot
        # loop must not pay op-by-op dispatch for logits/sample/logp/value
        @jax.jit
        def _jit_act(params, obs, key):
            logits = models.policy_logits(params, obs)
            action = jax.random.categorical(key, logits)
            logp = models.categorical_logp(logits, action)
            value = models.value(params, obs)
            return action, logp, value

        self._jit_act = _jit_act

    def _act(self, pid: str, obs: np.ndarray):
        self._key, k = jax.random.split(self._key)
        action, logp, value = self._jit_act(
            self.learners[pid].get_params(), jnp.asarray(obs), k)
        return (np.asarray(action), np.asarray(logp), np.asarray(value))

    def step(self) -> Dict[str, Any]:
        cfg = self.config
        T, N = cfg.rollout_fragment_length, self.env.num_envs
        agents = self.env.agents
        buf = {a: {k: [] for k in
                   ("obs", "actions", "logp", "values", "rewards", "dones")}
               for a in agents}
        for _ in range(T):
            acts, steps = {}, {}
            for a in agents:
                pid = self.policy_mapping_fn(a)
                action, logp, value = self._act(pid, self._obs[a])
                steps[a] = (self._obs[a], action, logp, value)
                acts[a] = action
            next_obs, rewards, dones = self.env.step(acts)
            for a in agents:
                o, act, lp, val = steps[a]
                b = buf[a]
                b["obs"].append(o)
                b["actions"].append(act)
                b["logp"].append(lp)
                b["values"].append(val)
                b["rewards"].append(rewards[a])
                b["dones"].append(dones)
            self._obs = next_obs

        metrics: Dict[str, Any] = {}
        mean_rewards = []
        per_policy: Dict[str, List[Dict[str, np.ndarray]]] = \
            {pid: [] for pid in self.policies}
        for a in agents:
            pid = self.policy_mapping_fn(a)
            b = {k: np.stack(v) for k, v in buf[a].items()}  # [T, N, ...]
            last_value = np.asarray(models.value(
                self.learners[pid].get_params(), jnp.asarray(self._obs[a])))
            gae = compute_gae(
                b["rewards"], b["values"], b["dones"], last_value,
                cfg.gamma, cfg.lambda_)
            adv, targets = gae["advantages"], gae["value_targets"]
            flat = lambda x: x.reshape((T * N,) + x.shape[2:])  # noqa: E731
            per_policy[pid].append({
                "obs": flat(b["obs"]), "actions": flat(b["actions"]),
                "logp": flat(b["logp"]), "advantages": flat(adv),
                "value_targets": flat(targets)})
            mean_rewards.append(float(b["rewards"].mean()))
        for pid in self.policies:
            batch = {k: np.concatenate([d[k] for d in per_policy[pid]])
                     for k in per_policy[pid][0]}
            m = self.learners[pid].update(
                batch, num_epochs=cfg.num_epochs,
                minibatch_size=cfg.minibatch_size,
                seed=cfg.seed + self._iteration)
            metrics.update({f"{pid}/{k}": v for k, v in m.items()})
        metrics["reward_mean_per_step"] = float(np.mean(mean_rewards))
        return metrics

    # -- checkpointing --------------------------------------------------------
    def save_checkpoint(self, checkpoint_dir: str) -> Optional[Dict]:
        return {pid: jax.tree_util.tree_map(np.asarray, ln.get_params())
                for pid, ln in self.learners.items()}

    def load_checkpoint(self, checkpoint: Dict) -> None:
        for pid, params in checkpoint.items():
            self.learners[pid].set_params(params)
