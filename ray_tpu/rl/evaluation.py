"""Shared in-process episode-rollout evaluation loop.

Reference analog: the evaluate path of ``rllib/algorithms/algorithm.py``
(fresh workers, n episodes, mean return). Trainables whose envs live
in-process (MADDPG, SlateQ, DreamerV3, ...) share this loop instead of
each carrying its own copy of the cap/bookkeeping.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import numpy as np


def run_episodes(step: Callable[[], tuple], num_episodes: int,
                 num_envs: int, max_steps: int = 4096) -> Dict[str, Any]:
    """Drive ``step() -> (rewards [N], dones [N])`` until ``num_episodes``
    episodes finish (or ``max_steps`` vector steps elapse) and report the
    mean episode return. The caller owns action selection and env state;
    this loop owns the return/termination bookkeeping."""
    done_returns = []
    ep_ret = np.zeros(num_envs, dtype=np.float64)
    for _ in range(max_steps):
        rewards, dones = step()
        ep_ret += rewards
        for i in np.nonzero(dones)[0]:
            done_returns.append(float(ep_ret[i]))
            ep_ret[i] = 0.0
        if len(done_returns) >= num_episodes:
            break
    return {"episodes": len(done_returns),
            "episode_return_mean": float(np.mean(done_returns))
            if done_returns else float("nan")}


class ReturnWindow:
    """Rolling window of finished-episode returns for training metrics
    (the ``episode_return_mean`` every in-process Trainable reports)."""

    def __init__(self, num_envs: int, size: int = 100):
        self._window: list = []
        self._ep = np.zeros(num_envs, dtype=np.float64)
        self._size = size

    def add(self, rewards: np.ndarray, dones: np.ndarray) -> None:
        self._ep += rewards
        for i in np.nonzero(dones)[0]:
            self._window.append(float(self._ep[i]))
            self._ep[i] = 0.0
        if len(self._window) > self._size:
            del self._window[:len(self._window) - self._size]

    def mean(self) -> Optional[float]:
        if not self._window:
            return None
        return float(np.mean(self._window))
