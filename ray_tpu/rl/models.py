"""RLModule: pure-JAX policy/value networks + action distributions.

Reference analog: ``rllib/core/rl_module/rl_module.py`` + the jax seeds in
``rllib/models/jax/`` (``fcnet.py``, ``jax_action_dist.py``). Params are
pytrees; forward fns are jittable and shared verbatim between the CPU
EnvRunners (inference) and the TPU Learner (training) — one definition,
two compilation targets.
"""

from __future__ import annotations

from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl.env import EnvSpec


def _dense_init(key, in_dim: int, out_dim: int, scale: float = 1.0):
    w_key, _ = jax.random.split(key)
    # orthogonal init: the standard PPO-stabilizing choice
    mat = jax.random.normal(w_key, (in_dim, out_dim))
    q, r = jnp.linalg.qr(mat)
    q = q * jnp.sign(jnp.diag(r))[None, : q.shape[1]]
    if q.shape != (in_dim, out_dim):
        q = jnp.resize(q, (in_dim, out_dim))
    return {"w": q * scale, "b": jnp.zeros(out_dim)}


def init_mlp(key, dims: Sequence[int], out_scale: float = 0.01) -> Dict:
    layers = []
    keys = jax.random.split(key, len(dims) - 1)
    for i, (a, b) in enumerate(zip(dims[:-1], dims[1:])):
        scale = out_scale if i == len(dims) - 2 else jnp.sqrt(2.0)
        layers.append(_dense_init(keys[i], a, b, scale))
    return {"layers": layers}


def mlp_forward(params: Dict, x: jnp.ndarray) -> jnp.ndarray:
    h = x
    n = len(params["layers"])
    # optional activation marker (rl_module catalogs): absent -> tanh;
    # a shape-(1,) "act" leaf -> relu. Shape-encoded so it stays static
    # under jit (same trick as the CNN stride leaves).
    relu = "act" in params and params["act"].shape[0] == 1
    for i, layer in enumerate(params["layers"]):
        h = h @ layer["w"] + layer["b"]
        if i < n - 1:
            h = jax.nn.relu(h) if relu else jnp.tanh(h)
    return h


# Conv encoder for pixel specs — the Nature CNN (Mnih et al. 2015, the
# stack RLlib's default vision net uses for Atari) for full-size frames,
# a compact stack for small boards (Nature's 8x4 front end collapses
# anything under 36px to zero). Shared by the policy and value heads
# (the standard actor-critic weight-sharing for pixels).
_NATURE_SPECS = ((32, 8, 4), (64, 4, 2), (64, 3, 1))  # (feat, kernel, stride)
_SMALL_SPECS = ((32, 3, 1), (64, 3, 2), (64, 3, 1))  # boards >= 9px


def _conv_specs_for(h: int, w: int):
    return _NATURE_SPECS if min(h, w) >= 36 else _SMALL_SPECS


def _conv_out_hw(h: int, w: int, specs) -> Tuple[int, int]:
    for _, k, s in specs:
        h = (h - k) // s + 1
        w = (w - k) // s + 1
        if h < 1 or w < 1:
            raise ValueError(f"obs too small for conv stack at {(h, w)}")
    return h, w


def init_cnn(key, obs_shape: Sequence[int], out_dim: int = 512) -> Dict:
    h, w, c = obs_shape
    specs = _conv_specs_for(h, w)
    convs = []
    keys = jax.random.split(key, len(specs) + 1)
    in_ch = c
    for i, (feat, k, stride) in enumerate(specs):
        fan_in = k * k * in_ch
        convs.append({
            "w": jax.random.normal(keys[i], (k, k, in_ch, feat))
            * jnp.sqrt(2.0 / fan_in),
            "b": jnp.zeros(feat),
            # stride rides as a SHAPE (static under jit; an int leaf would
            # trace) — cnn_forward reads conv["s"].shape[0]
            "s": jnp.zeros(stride),
        })
        in_ch = feat
    oh, ow = _conv_out_hw(h, w, specs)
    dense = _dense_init(keys[-1], oh * ow * in_ch, out_dim,
                        scale=jnp.sqrt(2.0))
    return {"convs": convs, "dense": dense}


def cnn_forward(params: Dict, x: jnp.ndarray) -> jnp.ndarray:
    """[..., H, W, C] pixels -> [..., F] features (relu conv stack)."""
    lead = x.shape[:-3]
    x = x.reshape((-1,) + x.shape[-3:])
    for conv in params["convs"]:
        stride = conv["s"].shape[0]
        x = jax.lax.conv_general_dilated(
            x, conv["w"], window_strides=(stride, stride), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC")) + conv["b"]
        x = jax.nn.relu(x)
    x = x.reshape(x.shape[0], -1)
    x = jax.nn.relu(x @ params["dense"]["w"] + params["dense"]["b"])
    return x.reshape(lead + (x.shape[-1],))


def init_policy(key, spec: EnvSpec, hidden: Sequence[int] = (64, 64)) -> Dict:
    pk, vk, ek = jax.random.split(key, 3)
    out = spec.num_actions if spec.discrete else spec.action_dim
    if spec.is_pixel:
        feat = 512
        params = {
            "enc": init_cnn(ek, spec.obs_shape, feat),
            "pi": init_mlp(pk, [feat, out]),
            "vf": init_mlp(vk, [feat, 1], out_scale=1.0),
        }
    else:
        params = {
            "pi": init_mlp(pk, [spec.obs_dim, *hidden, out]),
            "vf": init_mlp(vk, [spec.obs_dim, *hidden, 1], out_scale=1.0),
        }
    if not spec.discrete:
        params["log_std"] = jnp.zeros(spec.action_dim)
    return params


def _encode(params: Dict, obs: jnp.ndarray) -> jnp.ndarray:
    if "enc" in params:
        return cnn_forward(params["enc"], obs)
    return obs


def policy_logits(params: Dict, obs: jnp.ndarray) -> jnp.ndarray:
    return mlp_forward(params["pi"], _encode(params, obs))


def value(params: Dict, obs: jnp.ndarray) -> jnp.ndarray:
    return mlp_forward(params["vf"], _encode(params, obs))[..., 0]


# ---- distributions ----


def categorical_sample(key, logits: jnp.ndarray) -> jnp.ndarray:
    return jax.random.categorical(key, logits)


def categorical_logp(logits: jnp.ndarray, actions: jnp.ndarray) -> jnp.ndarray:
    logp = jax.nn.log_softmax(logits)
    return jnp.take_along_axis(logp, actions[..., None], axis=-1)[..., 0]


def categorical_entropy(logits: jnp.ndarray) -> jnp.ndarray:
    logp = jax.nn.log_softmax(logits)
    return -jnp.sum(jnp.exp(logp) * logp, axis=-1)


def gaussian_sample(key, mean: jnp.ndarray, log_std: jnp.ndarray):
    return mean + jnp.exp(log_std) * jax.random.normal(key, mean.shape)


def gaussian_logp(mean: jnp.ndarray, log_std: jnp.ndarray,
                  actions: jnp.ndarray) -> jnp.ndarray:
    var = jnp.exp(2 * log_std)
    return jnp.sum(
        -0.5 * ((actions - mean) ** 2 / var + 2 * log_std
                + jnp.log(2 * jnp.pi)), axis=-1)


def gaussian_entropy(log_std: jnp.ndarray) -> jnp.ndarray:
    return jnp.sum(log_std + 0.5 * jnp.log(2 * jnp.pi * jnp.e))


def num_params(params) -> int:
    return sum(int(np.prod(x.shape))
               for x in jax.tree_util.tree_leaves(params))
