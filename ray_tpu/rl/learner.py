"""Learner: jitted gradient updates, single-host or sharded over a mesh.

Reference analog: ``rllib/core/learner/learner.py:229`` +
``learner_group.py:61``. Where the reference syncs grads with torch DDP
(``torch_learner.py:368``), here a multi-device Learner jits the update
over a ``jax.sharding.Mesh`` data axis — XLA inserts the psum — and a
multi-*actor* LearnerGroup allreduces host-side through
``ray_tpu.collective`` (rendezvous over the same named-group pattern).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu


def adam_init(params) -> Dict:
    import jax

    zeros = jax.tree_util.tree_map(lambda p: np.zeros_like(p), params)
    return {"mu": zeros, "nu": zeros, "t": 0}


def adam_update(params, grads, state: Dict, lr: float,
                b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8):
    import jax
    import jax.numpy as jnp

    t = state["t"] + 1
    mu = jax.tree_util.tree_map(
        lambda m, g: b1 * m + (1 - b1) * g, state["mu"], grads)
    nu = jax.tree_util.tree_map(
        lambda v, g: b2 * v + (1 - b2) * g * g, state["nu"], grads)
    mhat_scale = 1.0 / (1 - b1 ** t)
    vhat_scale = 1.0 / (1 - b2 ** t)
    new_params = jax.tree_util.tree_map(
        lambda p, m, v: p - lr * (m * mhat_scale)
        / (jnp.sqrt(v * vhat_scale) + eps),
        params, mu, nu)
    return new_params, {"mu": mu, "nu": nu, "t": t}


def clip_global_norm(grads, max_norm: float):
    import jax
    import jax.numpy as jnp

    leaves = jax.tree_util.tree_leaves(grads)
    norm = jnp.sqrt(sum(jnp.sum(g * g) for g in leaves))
    scale = jnp.minimum(1.0, max_norm / (norm + 1e-8))
    return jax.tree_util.tree_map(lambda g: g * scale, grads), norm


class Learner:
    """Holds params + optimizer state; applies jitted minibatch updates.

    ``loss_fn(params, batch, key) -> (loss, metrics_dict)`` is supplied by
    the algorithm. With ``mesh`` set, the update is jitted over the mesh's
    ``dp`` axis (batch sharded, params replicated; XLA emits the grad
    psum over ICI).
    """

    def __init__(self, init_params, loss_fn: Callable, lr: float,
                 grad_clip: float = 0.0, mesh=None, seed: int = 0,
                 grad_sync: Optional[Callable] = None):
        import jax

        self.params = init_params
        self.opt_state = adam_init(init_params)
        self._loss_fn = loss_fn
        self._lr = lr
        self._key = jax.random.key(seed)
        self._mesh = mesh
        self._grad_sync = grad_sync

        def compute_grads(params, batch, key):
            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch, key)
            return grads, loss, metrics

        def apply_grads(params, opt_state, grads, loss, metrics):
            if grad_clip:
                grads, gnorm = clip_global_norm(grads, grad_clip)
                metrics = dict(metrics, grad_norm=gnorm)
            new_params, new_opt = adam_update(params, grads, opt_state, lr)
            metrics = dict(metrics, loss=loss)
            return new_params, new_opt, metrics

        def step(params, opt_state, batch, key):
            grads, loss, metrics = compute_grads(params, batch, key)
            return apply_grads(params, opt_state, grads, loss, metrics)

        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            self._batch_sharding = NamedSharding(mesh, P("dp"))
            replicated = NamedSharding(mesh, P())
            self._step = jax.jit(
                step,
                in_shardings=(replicated, replicated,
                              self._batch_sharding, replicated),
                out_shardings=(replicated, replicated, replicated))
            self.params = jax.device_put(self.params, replicated)
            self.opt_state = jax.device_put(self.opt_state, replicated)
        else:
            self._step = jax.jit(step)
        # split path for cross-actor DDP: grads leave the device, get
        # allreduced host-side, and re-enter the jitted optimizer step —
        # this keeps params AND adam moments bit-identical across learners
        self._compute_grads = jax.jit(compute_grads)
        self._apply_grads = jax.jit(apply_grads)

    def set_grad_sync(self, grad_sync: Optional[Callable]) -> None:
        """Install a cross-learner gradient allreduce (grads -> grads),
        applied per minibatch BEFORE the optimizer update (DDP semantics)."""
        self._grad_sync = grad_sync

    def update_minibatch(self, batch: Dict[str, np.ndarray]) -> Dict:
        import jax

        self._key, sub = jax.random.split(self._key)
        if self._mesh is not None:
            batch = {k: jax.device_put(v, self._batch_sharding)
                     for k, v in batch.items()}
        if self._grad_sync is not None:
            grads, loss, metrics = self._compute_grads(
                self.params, batch, sub)
            grads = self._grad_sync(grads)
            self.params, self.opt_state, metrics = self._apply_grads(
                self.params, self.opt_state, grads, loss, metrics)
        else:
            self.params, self.opt_state, metrics = self._step(
                self.params, self.opt_state, batch, sub)
        return metrics

    def update(self, batch: Dict[str, np.ndarray], *, num_epochs: int = 1,
               minibatch_size: Optional[int] = None,
               shuffle: bool = True, seed: int = 0) -> Dict[str, float]:
        """Epoch/minibatch loop (PPO-style); returns averaged metrics."""
        n = len(next(iter(batch.values())))
        mb = minibatch_size or n
        mb = min(mb, n)
        rng = np.random.default_rng(seed)
        all_metrics: List[Dict] = []
        for _ in range(num_epochs):
            idx = rng.permutation(n) if shuffle else np.arange(n)
            for start in range(0, n - mb + 1, mb):
                sel = idx[start:start + mb]
                all_metrics.append(self.update_minibatch(
                    {k: v[sel] for k, v in batch.items()}))
        out: Dict[str, float] = {}
        for k in all_metrics[0]:
            out[k] = float(np.mean([float(m[k]) for m in all_metrics]))
        return out

    def get_params(self):
        return self.params

    def set_params(self, params) -> None:
        self.params = params


@ray_tpu.remote
class _LearnerActor:
    """One member of a LearnerGroup: local update + host-collective grad
    sync (data-parallel across learner actors)."""

    def __init__(self, rank: int, world: int, group: str, learner_ctor):
        from ray_tpu import collective as col

        self._rank, self._world, self._group = rank, world, group
        col.init_collective_group(world, rank, group)
        self._learner: Learner = learner_ctor()
        self._sync_params()
        self._learner.set_grad_sync(self._allreduce_grads)

    def _allreduce_grads(self, grads):
        from ray_tpu import collective as col
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(grads)
        leaves = [np.asarray(col.allreduce(np.asarray(x), self._group))
                  / self._world for x in leaves]
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def _sync_params(self) -> None:
        from ray_tpu import collective as col
        import jax

        # broadcast rank-0 init so every learner starts identical
        leaves, treedef = jax.tree_util.tree_flatten(self._learner.params)
        leaves = [np.asarray(col.broadcast(np.asarray(x), 0, self._group))
                  for x in leaves]
        self._learner.params = jax.tree_util.tree_unflatten(treedef, leaves)

    def update(self, shard, num_epochs: int, minibatch_size: int,
               seed: int) -> Dict[str, float]:
        # grads are allreduced per minibatch via set_grad_sync (DDP
        # semantics: params and optimizer moments stay identical across
        # learners), so no post-hoc param averaging is needed
        return self._learner.update(
            shard, num_epochs=num_epochs, minibatch_size=minibatch_size,
            seed=seed)

    def get_params(self):
        return self._learner.params

    def set_params(self, params) -> None:
        self._learner.set_params(params)


class LearnerGroup:
    """N learner actors doing data-parallel updates with host collectives."""

    _counter = 0

    def __init__(self, learner_ctor: Callable[[], Learner], num_learners: int,
                 num_tpus_per_learner: float = 0):
        from ray_tpu import collective as col

        LearnerGroup._counter += 1
        group = f"learner_group_{LearnerGroup._counter}"
        col.create_collective_group(num_learners, group)
        opts: Dict[str, Any] = {}
        if num_tpus_per_learner:
            opts["num_tpus"] = num_tpus_per_learner
        cls = _LearnerActor.options(**opts) if opts else _LearnerActor
        self._actors = [cls.remote(i, num_learners, group, learner_ctor)
                        for i in range(num_learners)]

    def update(self, batch, *, num_epochs: int = 1,
               minibatch_size: Optional[int] = None,
               seed: int = 0) -> Dict[str, float]:
        n = len(next(iter(batch.values())))
        world = len(self._actors)
        # equal-size shards (truncate the remainder): per-minibatch grad
        # allreduce is a rank-synchronous collective, so every learner must
        # run the exact same number of minibatches or the group deadlocks
        n_even = n - (n % world)
        mb = minibatch_size or n_even // world
        results = ray_tpu.get([
            a.update.remote(
                {k: v[i:n_even:world] for k, v in batch.items()},
                num_epochs, mb, seed)
            for i, a in enumerate(self._actors)])
        return {k: float(np.mean([r[k] for r in results]))
                for k in results[0]}

    def get_params(self):
        return ray_tpu.get(self._actors[0].get_params.remote())

    def set_params(self, params) -> None:
        ray_tpu.get([a.set_params.remote(params) for a in self._actors])
