"""Connector pipeline: composable observation/reward transforms on the
sampling path.

Reference analog: ``rllib/connectors/connector.py:83`` (``Connector``,
``ConnectorPipeline``) and the classic impls — ``MeanStdFilter``
(obs normalization; reference ``rllib/utils/filter.py``), ``ClipReward``.
Redesign notes: the reference threads connectors through per-agent
view-requirement machinery; here a pipeline is a plain object owned by each
EnvRunner, applied at act time, with the *filtered* obs and reward stored in
the sample batch (so the learner trains in the same normalized space the
policy acts in).

Cross-runner stat sync follows the reference's delta-flush scheme: each
runner accumulates a local DELTA on top of the last broadcast global state;
the algorithm pops deltas every iteration, merges them (Chan's parallel
variance update), and broadcasts the new global — no runner ever
double-counts another's data.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np


class _RunningStats:
    """Welford/Chan running (count, mean, M2) with exact parallel merge."""

    __slots__ = ("count", "mean", "m2")

    def __init__(self, dim: int):
        self.count = 0.0
        self.mean = np.zeros(dim, dtype=np.float64)
        self.m2 = np.zeros(dim, dtype=np.float64)

    def push_batch(self, x: np.ndarray) -> None:
        x = x.reshape(-1, x.shape[-1]).astype(np.float64)
        n = x.shape[0]
        if n == 0:
            return
        b_mean = x.mean(axis=0)
        b_m2 = ((x - b_mean) ** 2).sum(axis=0)
        self._merge(n, b_mean, b_m2)

    def _merge(self, n: float, mean: np.ndarray, m2: np.ndarray) -> None:
        if n == 0:
            return
        tot = self.count + n
        delta = mean - self.mean
        self.mean = self.mean + delta * (n / tot)
        self.m2 = self.m2 + m2 + delta ** 2 * (self.count * n / tot)
        self.count = tot

    def merge_stats(self, other: "_RunningStats") -> None:
        self._merge(other.count, other.mean, other.m2)

    @property
    def std(self) -> np.ndarray:
        var = self.m2 / max(self.count, 1.0)
        return np.sqrt(np.maximum(var, 1e-8))

    def to_state(self) -> Dict[str, Any]:
        return {"count": self.count, "mean": self.mean.copy(),
                "m2": self.m2.copy()}

    @classmethod
    def from_state(cls, state: Dict[str, Any], dim: int) -> "_RunningStats":
        rs = cls(dim)
        if state:
            rs.count = float(state["count"])
            rs.mean = np.asarray(state["mean"], dtype=np.float64).copy()
            rs.m2 = np.asarray(state["m2"], dtype=np.float64).copy()
        return rs


class Connector:
    """One composable transform stage (obs and/or reward)."""

    def on_obs(self, obs: np.ndarray, update: bool = True) -> np.ndarray:
        return obs

    def on_reward(self, rewards: np.ndarray) -> np.ndarray:
        return rewards

    # delta-sync protocol (no-ops for stateless connectors)
    def pop_delta(self) -> Any:
        return None

    def merge_delta(self, global_state: Any, delta: Any) -> Any:
        return global_state

    def set_global(self, state: Any) -> None:
        pass

    def get_global(self) -> Any:
        return None


class MeanStdFilter(Connector):
    """Normalize observations by running mean/std (reference:
    ``rllib/utils/filter.py`` MeanStdFilter via the MeanStdObservationFilter
    connector). Essential for continuous control: Pendulum/SAC/DDPG targets
    diverge on raw obs scales."""

    def __init__(self, obs_dim: int, clip: float = 10.0):
        self.obs_dim = obs_dim
        self.clip = clip
        self._global = _RunningStats(obs_dim)
        self._delta = _RunningStats(obs_dim)

    def _effective(self) -> _RunningStats:
        eff = _RunningStats.from_state(self._global.to_state(), self.obs_dim)
        eff.merge_stats(self._delta)
        return eff

    def on_obs(self, obs: np.ndarray, update: bool = True) -> np.ndarray:
        if update:
            self._delta.push_batch(obs)
        eff = self._effective()
        if eff.count < 2:
            return obs.astype(np.float32)
        out = (obs - eff.mean) / eff.std
        return np.clip(out, -self.clip, self.clip).astype(np.float32)

    def pop_delta(self):
        d, self._delta = self._delta, _RunningStats(self.obs_dim)
        return d.to_state()

    def merge_delta(self, global_state, delta):
        g = _RunningStats.from_state(global_state or {}, self.obs_dim)
        if delta:
            g.merge_stats(_RunningStats.from_state(delta, self.obs_dim))
        return g.to_state()

    def set_global(self, state) -> None:
        self._global = _RunningStats.from_state(state or {}, self.obs_dim)

    def get_global(self):
        return self._global.to_state()


class ClipReward(Connector):
    """Clip (or sign-compress) rewards before they reach returns/GAE —
    reference: ``rllib/connectors/agent/clip_reward.py`` (the Atari
    convention)."""

    def __init__(self, limit: float = 1.0, sign: bool = False):
        self.limit = limit
        self.sign = sign

    def on_reward(self, rewards: np.ndarray) -> np.ndarray:
        if self.sign:
            return np.sign(rewards).astype(np.float32)
        return np.clip(rewards, -self.limit, self.limit).astype(np.float32)


class ClipObs(Connector):
    def __init__(self, limit: float = 10.0):
        self.limit = limit

    def on_obs(self, obs: np.ndarray, update: bool = True) -> np.ndarray:
        return np.clip(obs, -self.limit, self.limit).astype(np.float32)


class ConnectorPipeline:
    """Ordered connector stages; the unit EnvRunner owns and syncs."""

    def __init__(self, stages: List[Connector]):
        self.stages = list(stages)

    def on_obs(self, obs: np.ndarray, update: bool = True) -> np.ndarray:
        for s in self.stages:
            obs = s.on_obs(obs, update=update)
        return obs

    def on_reward(self, rewards: np.ndarray) -> np.ndarray:
        for s in self.stages:
            rewards = s.on_reward(rewards)
        return rewards

    def pop_deltas(self) -> List[Any]:
        return [s.pop_delta() for s in self.stages]

    def merge_deltas(self, global_states: Optional[List[Any]],
                     runner_deltas: List[List[Any]]) -> List[Any]:
        states = list(global_states or [None] * len(self.stages))
        for deltas in runner_deltas:
            states = [s.merge_delta(g, d)
                      for s, g, d in zip(self.stages, states, deltas)]
        return states

    def set_globals(self, states: Optional[List[Any]]) -> None:
        for s, st in zip(self.stages, states or [None] * len(self.stages)):
            s.set_global(st)

    def get_globals(self) -> List[Any]:
        return [s.get_global() for s in self.stages]


ConnectorSpec = Union[str, Dict[str, Any]]


def build_connectors(specs: Optional[Sequence[ConnectorSpec]],
                     obs_dim: int) -> Optional[ConnectorPipeline]:
    """Specs are strings or {"type": ..., **kwargs} dicts, e.g.
    ``["mean_std_filter", {"type": "clip_reward", "limit": 1.0}]``."""
    if not specs:
        return None
    stages: List[Connector] = []
    for spec in specs:
        if isinstance(spec, str):
            kind, kwargs = spec, {}
        else:
            spec = dict(spec)
            kind, kwargs = spec.pop("type"), spec
        if kind == "mean_std_filter":
            stages.append(MeanStdFilter(obs_dim, **kwargs))
        elif kind == "clip_reward":
            stages.append(ClipReward(**kwargs))
        elif kind == "clip_obs":
            stages.append(ClipObs(**kwargs))
        else:
            raise ValueError(f"unknown connector {kind!r}")
    return ConnectorPipeline(stages)
