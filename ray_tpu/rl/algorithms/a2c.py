"""A2C: synchronous advantage actor-critic.

Reference analog: ``rllib/algorithms/a2c/a2c.py`` (A2C as sync A3C,
sharing PPO's sampling but with the plain policy-gradient loss, one pass
over each batch). The loss is the unclipped surrogate on GAE advantages +
value regression + entropy bonus, jitted like every learner update.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from ray_tpu.rl import models
from ray_tpu.rl.algorithm import Algorithm
from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.env import EnvSpec
from ray_tpu.rl.learner import Learner, LearnerGroup


class A2CConfig(AlgorithmConfig):
    def __init__(self, **kwargs):
        super().__init__(algo_class=A2C, **kwargs)
        self.num_epochs = 1  # on-policy single pass — the A2C distinction


def make_a2c_loss(spec: EnvSpec, vf_coeff: float, entropy_coeff: float):
    def loss_fn(params, batch, key):
        obs = batch["obs"]
        logits = models.policy_logits(params, obs)
        if spec.discrete:
            logp = models.categorical_logp(logits, batch["actions"])
            entropy = models.categorical_entropy(logits).mean()
        else:
            logp = models.gaussian_logp(logits, params["log_std"],
                                        batch["actions"])
            entropy = models.gaussian_entropy(params["log_std"])
        adv = batch["advantages"]
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        policy_loss = -(logp * adv).mean()
        values = models.value(params, obs)
        vf_loss = jnp.mean((values - batch["value_targets"]) ** 2)
        total = policy_loss + vf_coeff * vf_loss - entropy_coeff * entropy
        return total, {"policy_loss": policy_loss, "vf_loss": vf_loss,
                       "entropy": entropy}

    return loss_fn


class A2C(Algorithm):
    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        return A2CConfig()

    def build_learner(self) -> None:
        cfg, spec = self.config, self.spec
        loss_fn = make_a2c_loss(spec, cfg.vf_coeff, cfg.entropy_coeff)
        seed, lr, clip = cfg.seed, cfg.lr, cfg.grad_clip
        init_params = self.init_policy_params()

        def ctor() -> Learner:
            params = jax.tree_util.tree_map(jnp.array, init_params)
            return Learner(params, loss_fn, lr, grad_clip=clip, seed=seed)

        if cfg.num_learners > 0:
            self.learner = LearnerGroup(ctor, cfg.num_learners,
                                        cfg.num_tpus_per_learner)
        else:
            self.learner = ctor()

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        params = self.learner.get_params()
        batch = self.synchronous_sample(params)
        metrics = self.learner.update(
            batch, num_epochs=1, minibatch_size=cfg.minibatch_size or 0)
        metrics.update(self.collect_episode_stats())
        return metrics
