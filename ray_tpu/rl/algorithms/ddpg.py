"""DDPG and TD3: deterministic policy gradients for continuous control.

Reference analogs: ``rllib/algorithms/ddpg/`` and ``rllib/algorithms/td3/``.
One implementation: TD3 = DDPG + twin critics + delayed policy updates +
target-policy smoothing; DDPG is the ``twin_q=False, policy_delay=1,
target_noise=0`` corner. Everything is a single jitted update.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl import models
from ray_tpu.rl.algorithm import Algorithm
from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.learner import Learner
from ray_tpu.rl.replay_buffer import PrioritizedReplayBuffer, ReplayBuffer


class DDPG(Algorithm):
    twin_q = False

    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        cfg = AlgorithmConfig(algo_class=cls)
        cfg.env = "Pendulum-v1"
        cfg.lr = 1e-3
        cfg.minibatch_size = 256
        cfg.learning_starts = 1_000
        if cls is DDPG:
            cfg.policy_delay = 1
            cfg.target_noise = 0.0
        return cfg

    def build_learner(self) -> None:
        cfg, spec = self.config, self.spec
        gamma, tau = cfg.gamma, cfg.tau
        low = jnp.asarray(spec.action_low)
        high = jnp.asarray(spec.action_high)
        twin = self.twin_q
        target_noise, noise_clip = cfg.target_noise, cfg.noise_clip

        key = jax.random.key(cfg.seed)
        k_pi, k_q1, k_q2 = jax.random.split(key, 3)
        qin = spec.obs_dim + spec.action_dim
        params = {
            "pi": models.init_mlp(
                k_pi, [spec.obs_dim, *cfg.hidden, spec.action_dim],
                out_scale=0.01),
            "q1": models.init_mlp(k_q1, [qin, *cfg.hidden, 1], out_scale=1.0),
        }
        if twin:
            params["q2"] = models.init_mlp(k_q2, [qin, *cfg.hidden, 1],
                                           out_scale=1.0)
        for name in list(params):
            params[f"{name}_target"] = jax.tree_util.tree_map(
                jnp.copy, params[name])

        def act(pi_params, obs):
            mid = (high + low) / 2.0
            half = (high - low) / 2.0
            return mid + half * jnp.tanh(models.mlp_forward(pi_params, obs))

        def q_val(q_params, obs, a):
            return models.mlp_forward(
                q_params, jnp.concatenate([obs, a], axis=-1))[..., 0]

        def critic_loss_fn(params, batch, key):
            obs, nobs, acts = batch["obs"], batch["next_obs"], batch["actions"]
            na = act(params["pi_target"], nobs)
            if target_noise > 0:  # TD3 target policy smoothing
                noise = jnp.clip(
                    target_noise * jax.random.normal(key, na.shape),
                    -noise_clip, noise_clip) * (high - low) / 2.0
                na = jnp.clip(na + noise, low, high)
            qt = q_val(params["q1_target"], nobs, na)
            if twin:
                qt = jnp.minimum(qt, q_val(params["q2_target"], nobs, na))
            nonterm = 1.0 - batch["dones"].astype(jnp.float32)
            target = jax.lax.stop_gradient(
                batch["rewards"] + gamma * nonterm * qt)
            td = q_val(params["q1"], obs, acts) - target
            weights = batch.get("weights", jnp.ones_like(td))
            loss = jnp.mean(weights * td ** 2)
            if twin:
                loss = loss + jnp.mean(
                    weights * (q_val(params["q2"], obs, acts) - target) ** 2)
            return loss, {"q_loss": loss,
                          "td": jax.lax.stop_gradient(td)}

        def actor_loss_fn(params, batch, key):
            obs = batch["obs"]
            a = act(params["pi"], obs)
            q = q_val(jax.lax.stop_gradient(params["q1"]), obs, a)
            loss = -jnp.mean(q)
            return loss, {"pi_loss": loss}

        def loss_fn(params, batch, key):
            cl, cm = critic_loss_fn(params, batch, key)
            al, am = actor_loss_fn(params, batch, key)
            do_actor = batch["do_actor"][0]
            total = cl + do_actor * al
            return total, {**cm, **am}

        self.learner = Learner(params, loss_fn, cfg.lr,
                               grad_clip=cfg.grad_clip, seed=cfg.seed)
        if cfg.prioritized_replay:  # Ape-X DDPG path
            self.buffer = PrioritizedReplayBuffer(
                cfg.buffer_size, alpha=cfg.replay_alpha,
                beta=cfg.replay_beta, seed=cfg.seed)
        else:
            self.buffer = ReplayBuffer(cfg.buffer_size, seed=cfg.seed)
        self._act = act
        self._updates = 0

        @jax.jit
        def polyak(params):
            new = dict(params)
            for name in ("pi", "q1") + (("q2",) if twin else ()):
                new[f"{name}_target"] = jax.tree_util.tree_map(
                    lambda t, s: (1 - tau) * t + tau * s,
                    params[f"{name}_target"], params[name])
            return new

        self._polyak = polyak

    def _runner_params(self, sigma: float = None):
        """Runner protocol adapter: deterministic mean + gaussian
        exploration noise via log_std. ``sigma`` overrides the config
        exploration noise (Ape-X DDPG's per-actor noise ladder)."""
        p = self.learner.get_params()
        obs_dim, adim = self.spec.obs_dim, self.spec.action_dim
        vf = {"layers": [{"w": jnp.zeros((obs_dim, 1)), "b": jnp.zeros(1)}]}
        if sigma is None:
            sigma = self.config.exploration_noise
        sigma = max(sigma, 1e-3)
        return {"pi": p["pi"], "vf": vf,
                "log_std": jnp.full((adim,), float(np.log(sigma)))}

    def _eval_params(self):
        """Deterministic actor (exploration noise ~0) for evaluate."""
        return {**self._runner_params(),
                "log_std": jnp.full((self.spec.action_dim,), -20.0)}

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        batch = self.synchronous_sample(self._runner_params())
        self.buffer.add_batch(
            {"obs": batch["obs"], "actions": batch["actions_executed"],
             "rewards": batch["rewards"], "next_obs": batch["next_obs"],
             "dones": batch["dones"]})
        metrics: Dict[str, Any] = {"buffer_size": len(self.buffer)}
        if len(self.buffer) >= cfg.learning_starts:
            num_updates = (cfg.updates_per_iter or
                           max(1, len(batch["rewards"]) // cfg.minibatch_size))
            metrics.update(self._replay_updates(num_updates))
        metrics.update(self.collect_episode_stats())
        return metrics

    def _replay_updates(self, num_updates: int) -> Dict[str, float]:
        """Shared DDPG-family update loop (also Ape-X DDPG): uniform or
        prioritized minibatches, delayed actor + polyak on actor steps,
        priorities refreshed from the critic's TD error."""
        cfg = self.config
        m: Dict[str, Any] = {}
        for _ in range(num_updates):
            if cfg.prioritized_replay:
                mb, idx, weights = self.buffer.sample(cfg.minibatch_size)
                mb = dict(mb, weights=weights)
            else:
                mb = self.buffer.sample(cfg.minibatch_size)
            self._updates += 1
            do_actor = float(self._updates % max(1, cfg.policy_delay) == 0)
            mb["do_actor"] = np.full(1, do_actor, dtype=np.float32)
            m = self.learner.update_minibatch(mb)
            if cfg.prioritized_replay:
                self.buffer.update_priorities(idx, np.asarray(m["td"]))
            if do_actor:
                self.learner.params = self._polyak(self.learner.params)
        return {k: float(v) for k, v in m.items() if np.ndim(v) == 0}


class TD3(DDPG):
    """Twin critics + delayed policy updates + target smoothing."""

    twin_q = True


class DDPGConfig(AlgorithmConfig):
    def __init__(self, **kwargs):
        super().__init__(algo_class=DDPG, **kwargs)
        self.env = "Pendulum-v1"
        self.minibatch_size = 256
        self.policy_delay = 1
        self.target_noise = 0.0


class TD3Config(AlgorithmConfig):
    def __init__(self, **kwargs):
        super().__init__(algo_class=TD3, **kwargs)
        self.env = "Pendulum-v1"
        self.minibatch_size = 256
