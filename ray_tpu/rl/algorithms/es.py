"""ES: OpenAI-style evolution strategies.

Reference analog: ``rllib/algorithms/es/es.py`` (Salimans et al. 2017 —
a fleet of workers evaluates antithetic parameter perturbations for whole
episodes; the driver combines centered-rank-weighted noise into a gradient
estimate). Redesigned: noise is reconstructed from integer seeds on both
sides (the reference's SharedNoiseTable trick — only seeds and returns
cross the wire, never parameter vectors), and each worker evaluates its
perturbation over a small vectorized env batch.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import numpy as np

import ray_tpu
from ray_tpu.rl import models
from ray_tpu.rl.algorithm import Algorithm
from ray_tpu.rl.config import AlgorithmConfig


class ESConfig(AlgorithmConfig):
    def __init__(self, **kwargs):
        super().__init__(algo_class=ES, **kwargs)
        self.episodes_per_perturbation = 2
        self.noise_std = 0.05
        self.num_perturbations = 16   # antithetic pairs per iteration
        self.lr = 0.02
        self.max_episode_len = 500


def _flatten(params) -> Tuple[np.ndarray, List]:
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(params)
    shapes = [(np.asarray(leaf).shape, np.asarray(leaf).dtype)
              for leaf in leaves]
    flat = np.concatenate([np.asarray(leaf).ravel() for leaf in leaves])
    return flat.astype(np.float64), (treedef, shapes)


def _unflatten(flat: np.ndarray, meta) -> Any:
    import jax

    treedef, shapes = meta
    leaves, off = [], 0
    for shape, dtype in shapes:
        n = int(np.prod(shape)) if shape else 1
        leaves.append(flat[off:off + n].reshape(shape).astype(dtype))
        off += n
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _noise(seed: int, dim: int, std: float) -> np.ndarray:
    return np.random.default_rng(seed).standard_normal(dim) * std


def _centered_ranks(x: np.ndarray) -> np.ndarray:
    """Fitness shaping: returns -> centered ranks in [-0.5, 0.5]
    (the reference's compute_centered_ranks)."""
    ranks = np.empty(len(x), dtype=np.float64)
    ranks[x.argsort()] = np.arange(len(x))
    return ranks / (len(x) - 1) - 0.5 if len(x) > 1 else np.zeros(1)


@ray_tpu.remote
class _ESWorker:
    """Evaluates policies for whole episodes, optionally normalizing
    observations with a fleet-shared running filter (the ARS-V2
    augmentation; ES runs with ``normalize_obs=False``). Filter deltas
    are popped by the driver, merged, and the global mean/var pushed
    back so every worker normalizes with fleet-wide statistics."""

    def __init__(self, env_name: str, env_config: Dict, seed: int,
                 hidden, noise_std: float, max_len: int,
                 normalize_obs: bool = False):
        import jax

        from ray_tpu.rl.env import make_env

        self._env = make_env(env_name, 1, env_config, seed=seed)
        self.spec = self._env.spec
        self._std = noise_std
        self._max_len = max_len
        self._normalize = normalize_obs
        base = models.init_policy(jax.random.key(0), self.spec, hidden)
        _, self._meta = _flatten(base)
        d = self.spec.obs_dim
        # global filter (mean/var used to normalize) + local delta
        self._mean = np.zeros(d, dtype=np.float64)
        self._var = np.ones(d, dtype=np.float64)
        self._delta = np.zeros((3, d), dtype=np.float64)  # count,sum,sumsq

        import jax.numpy as jnp

        spec = self.spec

        @jax.jit
        def act(params, obs):
            logits = models.policy_logits(params, obs)
            if spec.discrete:
                return jnp.argmax(logits, axis=-1)
            return logits  # deterministic mean action

        self._act = act

    def set_filter(self, mean: np.ndarray, var: np.ndarray) -> None:
        self._mean = np.asarray(mean, dtype=np.float64)
        self._var = np.asarray(var, dtype=np.float64)

    def pop_filter_delta(self) -> np.ndarray:
        out, self._delta = self._delta, np.zeros_like(self._delta)
        return out

    def _norm(self, obs: np.ndarray) -> np.ndarray:
        if not self._normalize:
            return obs
        self._delta[0] += 1.0
        self._delta[1] += obs[0]
        self._delta[2] += obs[0] ** 2
        return ((obs - self._mean)
                / np.sqrt(self._var + 1e-8)).astype(np.float32)

    def episode_return(self, flat: np.ndarray) -> Tuple[float, int]:
        params = _unflatten(np.asarray(flat), self._meta)
        obs = self._env.reset()
        total, steps = 0.0, 0
        for _ in range(self._max_len):
            a = np.asarray(self._act(params, self._norm(obs)))
            if not self.spec.discrete:
                a = np.clip(a, self.spec.action_low, self.spec.action_high)
            obs, r, d = self._env.step(a)
            total += float(r[0])
            steps += 1
            if d[0]:
                break
        return total, steps

    def evaluate(self, flat_center: np.ndarray, noise_seed: int,
                 episodes: int) -> Tuple[float, float, int]:
        """Antithetic pair: (mean return at center+eps, at center-eps,
        actual env steps consumed)."""
        center = np.asarray(flat_center)
        eps = _noise(noise_seed, len(center), self._std)
        steps = 0
        pos_r, neg_r = [], []
        for _ in range(episodes):
            r, n = self.episode_return(center + eps)
            pos_r.append(r)
            steps += n
            r, n = self.episode_return(center - eps)
            neg_r.append(r)
            steps += n
        return float(np.mean(pos_r)), float(np.mean(neg_r)), steps


class ES(Algorithm):
    need_env_runners = False  # whole-episode eval fleet instead

    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        return ESConfig()

    def build_learner(self) -> None:
        import jax

        cfg = self.config
        params = models.init_policy(jax.random.key(cfg.seed), self.spec,
                                    cfg.hidden)
        self._center, self._meta = _flatten(params)
        n_workers = max(1, cfg.num_env_runners)
        self._workers = [
            _ESWorker.options(num_cpus=cfg.num_cpus_per_runner).remote(
                cfg.env, cfg.env_config, cfg.seed + 7919 * i, cfg.hidden,
                cfg.noise_std, cfg.max_episode_len,
                getattr(cfg, "normalize_obs", False))
            for i in range(n_workers)
        ]
        self._rng = np.random.default_rng(cfg.seed)
        self.learner = self  # Algorithm.save/restore reach params via us

    def get_params(self):
        return _unflatten(self._center, self._meta)

    def set_params(self, params) -> None:
        self._center, self._meta = _flatten(params)

    def evaluate(self, num_episodes: int = 10) -> Dict[str, Any]:
        """Whole episodes at the unperturbed center parameters. Discards
        the workers' obs-filter deltas afterward so evaluation episodes
        never shift ARS's fleet normalization statistics."""
        refs = [self._workers[i % len(self._workers)]
                .episode_return.remote(self._center)
                for i in range(num_episodes)]
        rets = [r[0] for r in ray_tpu.get(refs)]
        ray_tpu.get([w.pop_filter_delta.remote() for w in self._workers])
        return {"episodes": num_episodes,
                "episode_return_mean": float(np.mean(rets))}

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        seeds = [int(s) for s in
                 self._rng.integers(0, 2 ** 31 - 1,
                                    size=cfg.num_perturbations)]
        # round-robin the pairs across the worker fleet
        pending = [
            self._workers[i % len(self._workers)].evaluate.remote(
                self._center, seeds[i], cfg.episodes_per_perturbation)
            for i in range(len(seeds))
        ]
        results = ray_tpu.get(pending)
        pos = np.array([r[0] for r in results])
        neg = np.array([r[1] for r in results])
        ranks = _centered_ranks(np.concatenate([pos, neg]))
        pos_r, neg_r = ranks[:len(pos)], ranks[len(pos):]
        grad = np.zeros_like(self._center)
        for seed, w in zip(seeds, pos_r - neg_r):
            grad += w * _noise(seed, len(self._center), cfg.noise_std)
        grad /= (len(seeds) * cfg.noise_std)
        self._center = self._center + cfg.lr * grad
        self._env_steps_total += int(sum(r[2] for r in results))
        return {
            "mean_return": float(np.mean(np.concatenate([pos, neg]))),
            "best_return": float(np.max(np.concatenate([pos, neg]))),
            "grad_norm": float(np.linalg.norm(grad)),
        }
