"""MADDPG: multi-agent DDPG with centralized critics.

Reference analog: ``rllib/algorithms/maddpg/maddpg.py`` (Lowe et al.
2017). Decentralized actors ``mu_i(o_i)`` act from local observations;
per-agent CENTRALIZED critics ``Q_i(o_1..o_n, a_1..a_n)`` see every
agent's observation and action during training (centralized training,
decentralized execution). Off-policy on a shared transition replay with
polyak target networks; exploration is decaying gaussian action noise.

Runs in-process on the ``MultiAgentEnv`` protocol (rl/multi_agent.py) —
its home setting is the continuous particle env ``"spread"``
(``SpreadGame``, an MPE simple-spread analog). All per-agent losses sum
into ONE jitted update: each term only touches its own agent's
parameters (critics are ``stop_gradient``-ed inside actor terms, so the
actor gradient flows through the action input alone — the MADDPG policy
gradient).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl import models
from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.learner import Learner
from ray_tpu.rl.multi_agent import _MA_ENVS, MultiAgentEnv
from ray_tpu.rl.replay_buffer import ReplayBuffer
from ray_tpu.tune.trainable import Trainable


class MADDPGConfig(AlgorithmConfig):
    def __init__(self, **kwargs):
        super().__init__(algo_class=MADDPG, **kwargs)
        self.env = "spread"
        self.lr = 1e-3
        self.minibatch_size = 256
        self.buffer_size = 100_000
        self.learning_starts = 1_000
        self.updates_per_iter = 32
        self.exploration_noise = 0.3
        self.noise_final = 0.05
        self.noise_decay_steps = 20_000
        self.hidden = (64, 64)


class MADDPG(Trainable):
    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        return MADDPGConfig()

    def setup(self, config: Dict[str, Any]) -> None:
        if "__algo_config" in config:
            self.config: AlgorithmConfig = config["__algo_config"]
        else:
            self.config = MADDPGConfig().update_from_dict(config)
        cfg = self.config
        ctor = _MA_ENVS[cfg.env] if isinstance(cfg.env, str) else cfg.env
        self.env: MultiAgentEnv = ctor(num_envs=cfg.num_envs_per_runner,
                                       **(cfg.env_config or {}))
        self.agents = list(self.env.agents)
        n = len(self.agents)
        specs = [self.env.spec[a] for a in self.agents]
        if any(s.discrete for s in specs):
            raise ValueError("MADDPG requires continuous actions (use "
                             "QMIX/IPPO for discrete cooperative games)")
        if len({(s.obs_dim, s.action_dim) for s in specs}) != 1:
            raise ValueError("MADDPG here assumes homogeneous per-agent "
                             "obs/action dims")
        spec = specs[0]
        do, da = spec.obs_dim, spec.action_dim
        low, high = spec.action_low, spec.action_high
        mid, span = (high + low) / 2.0, (high - low) / 2.0
        gamma, tau = cfg.gamma, cfg.tau
        qin = n * (do + da)

        key = jax.random.key(cfg.seed)
        keys = jax.random.split(key, 2 * n)
        actors = [models.init_mlp(keys[i], (do, *cfg.hidden, da),
                                  out_scale=0.01) for i in range(n)]
        critics = [models.init_mlp(keys[n + i], (qin, *cfg.hidden, 1),
                                   out_scale=1.0) for i in range(n)]
        params = {
            "actors": actors, "critics": critics,
            "actors_t": jax.tree_util.tree_map(jnp.array, actors),
            "critics_t": jax.tree_util.tree_map(jnp.array, critics),
        }

        def act_of(actor_p, obs):
            return mid + span * jnp.tanh(models.mlp_forward(actor_p, obs))

        def q_of(critic_p, obs_flat, acts_flat):
            x = jnp.concatenate([obs_flat, acts_flat], axis=-1)
            return models.mlp_forward(critic_p, x)[..., 0]

        def loss_fn(p, batch, key):
            del key
            obs = batch["obs"]              # [B, n, do]
            acts = batch["actions"]         # [B, n, da]
            nobs = batch["next_obs"]
            B = obs.shape[0]
            obs_flat = obs.reshape(B, -1)
            acts_flat = acts.reshape(B, -1)
            nobs_flat = nobs.reshape(B, -1)
            nonterm = 1.0 - batch["dones"].astype(jnp.float32)
            # target joint action from TARGET actors
            nacts_flat = jnp.concatenate(
                [act_of(p["actors_t"][j], nobs[:, j]) for j in range(n)],
                axis=-1)
            total = 0.0
            metrics: Dict[str, Any] = {}
            q_means = []
            for i in range(n):
                qt = q_of(p["critics_t"][i], nobs_flat, nacts_flat)
                y = jax.lax.stop_gradient(
                    batch["rewards"][:, i] + gamma * nonterm * qt)
                q_pred = q_of(p["critics"][i], obs_flat, acts_flat)
                critic_loss = jnp.mean((q_pred - y) ** 2)
                # actor i: replace column i with mu_i(o_i); the critic is
                # stop_gradient-ed so only the action path carries grads
                a_i = act_of(p["actors"][i], obs[:, i])
                joint = jnp.concatenate(
                    [a_i if j == i else acts[:, j] for j in range(n)],
                    axis=-1)
                frozen_critic = jax.lax.stop_gradient(p["critics"][i])
                actor_loss = -jnp.mean(q_of(frozen_critic, obs_flat,
                                            joint))
                total = total + critic_loss + actor_loss
                metrics[f"critic_loss_{i}"] = critic_loss
                metrics[f"actor_loss_{i}"] = actor_loss
                q_means.append(q_pred.mean())
            metrics["q_mean"] = jnp.mean(jnp.stack(q_means))
            return total, metrics

        self.learner = Learner(params, loss_fn, cfg.lr,
                               grad_clip=cfg.grad_clip, seed=cfg.seed)

        @jax.jit
        def polyak(p):
            new = dict(p)
            for src, dst in (("actors", "actors_t"),
                             ("critics", "critics_t")):
                new[dst] = jax.tree_util.tree_map(
                    lambda t, s: (1 - tau) * t + tau * s, p[dst], p[src])
            return new

        self._polyak = polyak
        self._act_all = jax.jit(
            lambda actors, obs: jnp.stack(
                [act_of(actors[j], obs[:, j]) for j in range(n)], axis=1))
        self._n, self._do, self._da = n, do, da
        self._low, self._high = low, high

        self.buffer = ReplayBuffer(cfg.buffer_size, seed=cfg.seed)
        self._rng = np.random.default_rng(cfg.seed)
        self._obs = self.env.reset()
        self._env_steps_total = 0
        from ray_tpu.rl.evaluation import ReturnWindow

        self._returns = ReturnWindow(self.env.num_envs)

    # -- rollout ----------------------------------------------------------

    def _stack_obs(self, obs: Dict[str, np.ndarray]) -> np.ndarray:
        return np.stack([obs[a] for a in self.agents],
                        axis=1).astype(np.float32)

    @property
    def _noise(self) -> float:
        cfg = self.config
        frac = min(1.0, self._env_steps_total
                   / max(1, cfg.noise_decay_steps))
        return cfg.exploration_noise \
            + frac * (cfg.noise_final - cfg.exploration_noise)

    def _collect(self, steps: int) -> float:
        cfg = self.config
        n_envs = self.env.num_envs
        reward_sum = 0.0
        for _ in range(steps):
            stacked = self._stack_obs(self._obs)
            acts = np.asarray(self._act_all(
                self.learner.get_params()["actors"], jnp.asarray(stacked)))
            acts = np.clip(
                acts + self._noise
                * self._rng.standard_normal(acts.shape).astype(np.float32),
                self._low, self._high)
            act_dict = {a: acts[:, i]
                        for i, a in enumerate(self.agents)}
            next_obs, rewards, dones = self.env.step(act_dict)
            rew = np.stack([rewards[a] for a in self.agents],
                           axis=1).astype(np.float32)   # [N, n]
            self.buffer.add_batch(
                {"obs": stacked, "actions": acts.astype(np.float32),
                 "rewards": rew, "dones": dones.astype(np.float32),
                 "next_obs": self._stack_obs(next_obs)})
            self._env_steps_total += n_envs
            team_r = rew.mean(axis=1)
            reward_sum += float(team_r.sum())
            self._returns.add(team_r, dones)
            self._obs = next_obs
        return reward_sum / max(1, steps * n_envs)

    # -- Trainable API ----------------------------------------------------

    def step(self) -> Dict[str, Any]:
        cfg = self.config
        mean_step_r = self._collect(cfg.rollout_fragment_length)
        metrics: Dict[str, Any] = {"reward_mean_per_step": mean_step_r,
                                   "noise": self._noise}
        if len(self.buffer) >= cfg.learning_starts:
            mlist = []
            for _ in range(cfg.updates_per_iter or 1):
                mb = self.buffer.sample(cfg.minibatch_size)
                mlist.append(self.learner.update_minibatch(mb))
                self.learner.set_params(
                    self._polyak(self.learner.get_params()))
            for k in mlist[0]:
                metrics[k] = float(np.mean([float(m[k]) for m in mlist]))
        metrics["env_steps_total"] = self._env_steps_total
        mean_ret = self._returns.mean()
        if mean_ret is not None:
            metrics["episode_return_mean"] = mean_ret
        return metrics

    def evaluate(self, num_episodes: int = 10) -> Dict[str, Any]:
        """Noise-free episodes on a fresh env instance."""
        from ray_tpu.rl.evaluation import run_episodes

        cfg = self.config
        ctor = _MA_ENVS[cfg.env] if isinstance(cfg.env, str) else cfg.env
        env: MultiAgentEnv = ctor(num_envs=cfg.num_envs_per_runner,
                                  **(cfg.env_config or {}))
        state = {"obs": env.reset()}
        actors = self.learner.get_params()["actors"]

        def step():
            stacked = self._stack_obs(state["obs"])
            acts = np.asarray(self._act_all(actors, jnp.asarray(stacked)))
            act_dict = {a: acts[:, i]
                        for i, a in enumerate(self.agents)}
            state["obs"], rewards, dones = env.step(act_dict)
            team = np.mean([rewards[a] for a in self.agents], axis=0)
            return team, dones

        return run_episodes(step, num_episodes, env.num_envs)

    # -- checkpointing ----------------------------------------------------

    def save_checkpoint(self, checkpoint_dir: str) -> Optional[Dict]:
        return {"params": jax.tree_util.tree_map(
            np.asarray, self.learner.get_params()),
            "env_steps_total": self._env_steps_total}

    def load_checkpoint(self, checkpoint: Dict) -> None:
        self.learner.set_params(checkpoint["params"])
        self._env_steps_total = checkpoint.get("env_steps_total", 0)
