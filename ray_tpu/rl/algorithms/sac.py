"""SAC: soft actor-critic for continuous control.

Reference analog: ``rllib/algorithms/sac/``. Twin soft-Q networks with
polyak-averaged targets, tanh-squashed gaussian policy via the
reparameterization trick, and automatic entropy-temperature tuning —
all one jitted update.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl import models
from ray_tpu.rl.algorithm import Algorithm
from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.learner import Learner
from ray_tpu.rl.replay_buffer import ReplayBuffer


def _squashed_sample_logp(mean, log_std, key, low, high):
    """tanh-squashed gaussian rescaled to [low, high], with exact logp."""
    std = jnp.exp(jnp.clip(log_std, -8.0, 2.0))
    eps = jax.random.normal(key, mean.shape)
    pre = mean + std * eps
    logp = jnp.sum(
        -0.5 * (eps ** 2 + 2 * jnp.log(std) + jnp.log(2 * jnp.pi)), axis=-1)
    tanh = jnp.tanh(pre)
    # d tanh correction
    logp = logp - jnp.sum(jnp.log(1 - tanh ** 2 + 1e-6), axis=-1)
    half_span = (high - low) / 2.0
    mid = (high + low) / 2.0
    action = mid + half_span * tanh
    logp = logp - action.shape[-1] * jnp.log(half_span)
    return action, logp


class SAC(Algorithm):
    # execute the same tanh-squashed policy the learner optimizes (the raw
    # runner protocol would act on pre-squash means — a different policy)
    explore_mode = "squashed_gaussian"

    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        cfg = AlgorithmConfig(algo_class=cls)
        cfg.env = "Pendulum-v1"
        cfg.lr = 3e-4
        cfg.minibatch_size = 256
        cfg.learning_starts = 1_000
        return cfg

    def build_learner(self) -> None:
        cfg, spec = self.config, self.spec
        gamma, tau = cfg.gamma, cfg.tau
        low, high = spec.action_low, spec.action_high
        target_entropy = -float(spec.action_dim)
        autotune = cfg.autotune_alpha

        key = jax.random.key(cfg.seed)
        k_pi, k_q1, k_q2 = jax.random.split(key, 3)
        qin = spec.obs_dim + spec.action_dim
        q1 = models.init_mlp(k_q1, [qin, *cfg.hidden, 1], out_scale=1.0)
        q2 = models.init_mlp(k_q2, [qin, *cfg.hidden, 1], out_scale=1.0)
        pi = models.init_mlp(
            k_pi, [spec.obs_dim, *cfg.hidden, 2 * spec.action_dim],
            out_scale=0.01)
        params = {
            "pi": pi, "q1": q1, "q2": q2,
            "q1_target": jax.tree_util.tree_map(jnp.copy, q1),
            "q2_target": jax.tree_util.tree_map(jnp.copy, q2),
            "log_alpha": jnp.asarray(float(np.log(cfg.initial_alpha))),
        }

        def pi_dist(pi_params, obs):
            out = models.mlp_forward(pi_params, obs)
            mean, log_std = jnp.split(out, 2, axis=-1)
            return mean, log_std

        def q_val(q_params, obs, act):
            return models.mlp_forward(
                q_params, jnp.concatenate([obs, act], axis=-1))[..., 0]

        def loss_fn(params, batch, key):
            k1, k2 = jax.random.split(key)
            obs, nobs = batch["obs"], batch["next_obs"]
            acts = batch["actions"]
            alpha = jnp.exp(params["log_alpha"])
            # --- critic target ---
            nmean, nlogstd = pi_dist(params["pi"], nobs)
            nact, nlogp = _squashed_sample_logp(nmean, nlogstd, k1, low, high)
            qt = jnp.minimum(q_val(params["q1_target"], nobs, nact),
                             q_val(params["q2_target"], nobs, nact))
            nonterminal = 1.0 - batch["dones"].astype(jnp.float32)
            target = batch["rewards"] + gamma * nonterminal * \
                jax.lax.stop_gradient(qt - alpha * nlogp)
            target = jax.lax.stop_gradient(target)
            q1_loss = jnp.mean((q_val(params["q1"], obs, acts) - target) ** 2)
            q2_loss = jnp.mean((q_val(params["q2"], obs, acts) - target) ** 2)
            # --- actor ---
            mean, log_std = pi_dist(params["pi"], obs)
            act_new, logp = _squashed_sample_logp(mean, log_std, k2, low, high)
            q_min = jnp.minimum(
                q_val(jax.lax.stop_gradient(params["q1"]), obs, act_new),
                q_val(jax.lax.stop_gradient(params["q2"]), obs, act_new))
            pi_loss = jnp.mean(
                jax.lax.stop_gradient(alpha) * logp - q_min)
            # --- temperature ---
            if autotune:
                alpha_loss = -jnp.mean(
                    params["log_alpha"]
                    * jax.lax.stop_gradient(logp + target_entropy))
            else:
                alpha_loss = 0.0
            total = q1_loss + q2_loss + pi_loss + alpha_loss
            return total, {"q1_loss": q1_loss, "pi_loss": pi_loss,
                           "alpha": alpha,
                           "entropy": -jnp.mean(logp)}

        self.learner = Learner(params, loss_fn, cfg.lr,
                               grad_clip=cfg.grad_clip, seed=cfg.seed)
        self.buffer = ReplayBuffer(cfg.buffer_size, seed=cfg.seed)

        @jax.jit
        def polyak(params):
            new = dict(params)
            for src, dst in (("q1", "q1_target"), ("q2", "q2_target")):
                new[dst] = jax.tree_util.tree_map(
                    lambda t, s: (1 - tau) * t + tau * s,
                    params[dst], params[src])
            return new

        self._polyak = polyak
        self._pi_dist = pi_dist

    def _runner_params(self):
        """Adapt SAC's pi-net to the EnvRunner protocol: the runner (in
        ``squashed_gaussian`` explore mode) executes mid + half*tanh(mean +
        std*eps) — the same squashed policy the learner optimizes, with a
        fixed exploration std (per-state log_std can't ride the protocol).
        Training recomputes exact squashed logps from the buffer."""
        p = self.learner.get_params()
        # runner calls policy_logits(params, obs) -> mean and uses
        # params["log_std"]; slice the pi-net's final layer to its mean half
        pi = jax.tree_util.tree_map(lambda x: x, p["pi"])
        last = pi["layers"][-1]
        adim = self.spec.action_dim
        pi["layers"][-1] = {"w": last["w"][:, :adim], "b": last["b"][:adim]}
        # dummy value head (obs -> 0): SAC ignores GAE values
        obs_dim = self.spec.obs_dim
        vf = {"layers": [{"w": jnp.zeros((obs_dim, 1)), "b": jnp.zeros(1)}]}
        # per-state log_std isn't expressible in the runner protocol; use a
        # moderate fixed exploration std
        return {"pi": pi, "vf": vf, "log_std": jnp.zeros(adim) - 0.5}

    def _eval_params(self):
        """Mean action (std ~0) for Algorithm.evaluate."""
        p = self._runner_params()
        return {**p, "log_std": jnp.zeros(self.spec.action_dim) - 20.0}

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        batch = self.synchronous_sample(self._runner_params())
        # train the critic on the action the env executed (clipped), not
        # the raw gaussian sample
        self.buffer.add_batch(
            {"obs": batch["obs"], "actions": batch["actions_executed"],
             "rewards": batch["rewards"], "next_obs": batch["next_obs"],
             "dones": batch["dones"]})
        metrics: Dict[str, Any] = {"buffer_size": len(self.buffer)}
        if len(self.buffer) >= cfg.learning_starts:
            num_updates = (cfg.updates_per_iter or
                           max(1, len(batch["rewards"]) // cfg.minibatch_size))
            for _ in range(num_updates):
                m = self.learner.update_minibatch(
                    self.buffer.sample(cfg.minibatch_size))
                self.learner.params = self._polyak(self.learner.params)
            metrics.update({k: float(v) for k, v in m.items()})
        metrics.update(self.collect_episode_stats())
        return metrics


class SACConfig(AlgorithmConfig):
    def __init__(self, **kwargs):
        super().__init__(algo_class=SAC, **kwargs)
        self.env = "Pendulum-v1"
        self.minibatch_size = 256
