"""IMPALA: async actor-learner with V-trace off-policy correction.

Reference analog: ``rllib/algorithms/impala/impala.py:68`` + the learner
thread pipeline (``execution/multi_gpu_learner_thread.py``) + V-trace
(``vtrace_torch.py``). Sampling is asynchronous: runners keep producing
fragments under slightly stale params; the learner consumes them as they
land (``ray_tpu.wait``) and corrects the off-policyness with V-trace —
computed inside the jitted loss via ``lax.scan``.
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

import ray_tpu
from ray_tpu.rl import models
from ray_tpu.rl.algorithm import Algorithm
from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.learner import Learner


def vtrace(behavior_logp, target_logp, rewards, values, bootstrap_value,
           dones, gamma, clip_rho: float = 1.0, clip_pg_rho: float = 1.0):
    """V-trace targets over a [T, N] fragment (Espeholt et al. 2018),
    as a jittable backward lax.scan."""
    rho = jnp.exp(target_logp - behavior_logp)
    clipped_rho = jnp.minimum(clip_rho, rho)
    clipped_pg_rho = jnp.minimum(clip_pg_rho, rho)
    nonterminal = 1.0 - dones.astype(jnp.float32)
    values_next = jnp.concatenate(
        [values[1:], bootstrap_value[None]], axis=0)
    deltas = clipped_rho * (
        rewards + gamma * nonterminal * values_next - values)

    def scan_fn(acc, t):
        delta_t, nonterm_t, c_t = t
        acc = delta_t + gamma * nonterm_t * c_t * acc
        return acc, acc

    cs = jnp.minimum(1.0, rho)
    _, vs_minus_v = jax.lax.scan(
        scan_fn, jnp.zeros_like(bootstrap_value),
        (deltas, nonterminal, cs), reverse=True)
    vs = vs_minus_v + values
    vs_next = jnp.concatenate([vs[1:], bootstrap_value[None]], axis=0)
    pg_advantages = clipped_pg_rho * (
        rewards + gamma * nonterminal * vs_next - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_advantages)


class IMPALA(Algorithm):
    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        cfg = AlgorithmConfig(algo_class=cls)
        cfg.num_env_runners = 2
        cfg.entropy_coeff = 0.01
        return cfg

    def build_learner(self) -> None:
        cfg, spec = self.config, self.spec
        T = cfg.rollout_fragment_length
        gamma = cfg.gamma
        vf_coeff, ent_coeff = cfg.vf_coeff, cfg.entropy_coeff
        clip_rho, clip_pg = cfg.vtrace_clip_rho, cfg.vtrace_clip_pg_rho

        def loss_fn(params, batch, key):
            # batch arrives flat [T*N, ...]; reshape to [T, N] for the scan
            N = batch["rewards"].shape[0] // T
            sh = lambda a: a.reshape((T, N) + a.shape[1:])  # noqa: E731
            obs = sh(batch["obs"])
            actions = sh(batch["actions"])
            logits = models.policy_logits(params, obs)
            if spec.discrete:
                target_logp = models.categorical_logp(logits, actions)
                entropy = models.categorical_entropy(logits).mean()
            else:
                target_logp = models.gaussian_logp(
                    logits, params["log_std"], actions)
                entropy = models.gaussian_entropy(params["log_std"])
            values = models.value(params, obs)
            bootstrap = batch["last_values"]  # [N]
            vs, pg_adv = vtrace(
                sh(batch["logp"]), target_logp, sh(batch["rewards"]),
                values, bootstrap, sh(batch["dones"]), gamma,
                clip_rho, clip_pg)
            pi_loss = -jnp.mean(target_logp * pg_adv)
            vf_loss = jnp.mean((values - vs) ** 2)
            total = pi_loss + vf_coeff * vf_loss - ent_coeff * entropy
            return total, {"pi_loss": pi_loss, "vf_loss": vf_loss,
                           "entropy": entropy}

        params = self.init_policy_params()
        self.learner = Learner(params, loss_fn, cfg.lr,
                               grad_clip=cfg.grad_clip, seed=cfg.seed)
        self._inflight: Dict[Any, Any] = {}
        self._runner_failures: Dict[Any, int] = {}

    # consecutive failures before a runner leaves the rotation: a runner
    # past max_restarts fails refs INSTANTLY — resubmitting forever would
    # win every wait() and starve live runners' fragments
    _MAX_CONSECUTIVE_FAILURES = 3

    def _submit(self, runner) -> None:
        if self._runner_failures.get(runner, 0) \
                >= self._MAX_CONSECUTIVE_FAILURES:
            return  # evicted from rotation
        ref = runner.sample.remote(self.learner.get_params())
        self._inflight[ref] = runner

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        for r in self.runners:  # keep every runner busy (async pipeline)
            if r not in self._inflight.values():
                self._submit(r)
        if not self._inflight:
            raise RuntimeError(
                "all env-runners failed permanently (each exceeded "
                f"{self._MAX_CONSECUTIVE_FAILURES} consecutive failures)")
        metrics_list: List[Dict] = []
        consumed = 0
        # consume as many fragments as there are runners per step; a dead
        # runner's fragment is dropped and the (restarting) runner is
        # resubmitted — fleet fault tolerance (reference:
        # FaultTolerantActorManager under the IMPALA aggregation path)
        for _ in range(len(self.runners)):
            if not self._inflight:
                break
            ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1)
            ref = ready[0]
            runner = self._inflight.pop(ref)
            try:
                batch = ray_tpu.get(ref)
            except Exception as e:  # noqa: BLE001 — fragment lost, not fatal
                import logging

                logging.getLogger("ray_tpu.rl").warning(
                    "IMPALA runner fragment lost (%s: %s) — resubmitting",
                    type(e).__name__, str(e)[:120])
                self._runner_failures[runner] = \
                    self._runner_failures.get(runner, 0) + 1
                self._submit(runner)  # restarted actor serves this
                continue
            self._runner_failures.pop(runner, None)
            self._submit(runner)  # immediately resubmit with fresh params
            consumed += len(batch["rewards"])
            self._env_steps_total += len(batch["rewards"])
            metrics_list.append(self.learner.update_minibatch(batch))
        if not metrics_list:
            return {"env_steps_this_iter": 0,
                    **self.collect_episode_stats()}
        out = {k: float(np.mean([float(m[k]) for m in metrics_list]))
               for k in metrics_list[0]}
        out["env_steps_this_iter"] = consumed
        out.update(self.collect_episode_stats())
        return out

    def stop(self) -> None:
        self._inflight.clear()
        super().stop()


class IMPALAConfig(AlgorithmConfig):
    def __init__(self, **kwargs):
        super().__init__(algo_class=IMPALA, **kwargs)
        self.num_env_runners = 2
