"""PPO: clipped-objective policy gradient.

Reference analog: ``rllib/algorithms/ppo/ppo.py:60`` (driver) +
``ppo/torch/ppo_torch_learner.py:29`` (loss). The loss is a single jitted
JAX function (clip surrogate + value loss + entropy bonus, advantages
normalized per-minibatch); the update runs epochs x minibatches on the
Learner (in-process, mesh-sharded, or a LearnerGroup of actors).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from ray_tpu.rl import models
from ray_tpu.rl.algorithm import Algorithm
from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.env import EnvSpec
from ray_tpu.rl.learner import Learner, LearnerGroup


def make_ppo_loss(spec: EnvSpec, clip_param: float, vf_coeff: float,
                  entropy_coeff: float):
    def loss_fn(params, batch, key):
        obs = batch["obs"]
        logits = models.policy_logits(params, obs)
        if spec.discrete:
            logp = models.categorical_logp(logits, batch["actions"])
            entropy = models.categorical_entropy(logits).mean()
        else:
            logp = models.gaussian_logp(logits, params["log_std"],
                                        batch["actions"])
            entropy = models.gaussian_entropy(params["log_std"])
        ratio = jnp.exp(logp - batch["logp"])
        adv = batch["advantages"]
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        surr = jnp.minimum(
            ratio * adv,
            jnp.clip(ratio, 1 - clip_param, 1 + clip_param) * adv)
        policy_loss = -surr.mean()
        values = models.value(params, obs)
        vf_loss = jnp.mean((values - batch["value_targets"]) ** 2)
        total = policy_loss + vf_coeff * vf_loss - entropy_coeff * entropy
        kl = jnp.mean(batch["logp"] - logp)
        return total, {"policy_loss": policy_loss, "vf_loss": vf_loss,
                       "entropy": entropy, "kl": kl}

    return loss_fn


class PPO(Algorithm):
    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        return AlgorithmConfig(algo_class=cls)

    def build_learner(self) -> None:
        cfg, spec = self.config, self.spec
        loss_fn = make_ppo_loss(spec, cfg.clip_param, cfg.vf_coeff,
                                cfg.entropy_coeff)
        seed, lr, clip = cfg.seed, cfg.lr, cfg.grad_clip
        init_params = self.init_policy_params()

        def ctor() -> Learner:
            params = jax.tree_util.tree_map(jnp.array, init_params)
            return Learner(params, loss_fn, lr, grad_clip=clip, seed=seed)

        if cfg.num_learners > 0:
            self.learner = LearnerGroup(ctor, cfg.num_learners,
                                        cfg.num_tpus_per_learner)
        else:
            self.learner = ctor()

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        params = self.learner.get_params()
        batch = self.synchronous_sample(params)
        metrics = self.learner.update(
            batch, num_epochs=cfg.num_epochs,
            minibatch_size=cfg.minibatch_size,
            seed=cfg.seed + self._iteration)
        result = dict(metrics)
        result.update(self.collect_episode_stats())
        result["env_steps_this_iter"] = len(batch["rewards"])
        return result


class PPOConfig(AlgorithmConfig):
    def __init__(self, **kwargs):
        super().__init__(algo_class=PPO, **kwargs)
