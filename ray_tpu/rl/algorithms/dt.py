"""DT: Decision Transformer — offline RL as sequence modeling.

Reference analog: ``rllib/algorithms/dt/dt.py`` (Chen et al. 2021).
Trajectories become token sequences ``(R_1, s_1, a_1, R_2, s_2, a_2, …)``
where ``R_t`` is the return-to-go; a small causal transformer is trained
to predict ``a_t`` from the prefix, and at evaluation time the policy is
conditioned on a target return (``target_return``) that decays by the
rewards actually received.

The transformer here is a compact pre-LN causal model written directly in
JAX (param dicts like the rest of ``rl/models.py``) — 3 tokens per
timestep, learned timestep embeddings, action read off the state-token
stream. Windows of ``context_len`` timesteps are sampled uniformly over
steps, left-padded, and masked; the whole update is one jitted call.
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl.algorithm import Algorithm
from ray_tpu.rl.algorithms.offline import _to_arrays
from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.learner import Learner


class DTConfig(AlgorithmConfig):
    def __init__(self, **kwargs):
        super().__init__(algo_class=DT, **kwargs)
        self.minibatch_size = 64
        self.context_len = 20       # K timesteps (3K tokens)
        self.d_model = 64
        self.n_layers = 2
        self.n_heads = 2
        self.max_ep_len = 1000      # timestep-embedding table size
        self.target_return = 200.0  # eval conditioning (env-specific)
        self.rtg_scale = 100.0      # divide returns-to-go for embedding
        self.updates_per_iter = 50


# ---- tiny causal transformer (param-dict style, mirrors rl/models.py) ----

def _linear_init(key, din, dout, scale=1.0):
    w = jax.random.normal(key, (din, dout)) * scale / np.sqrt(din)
    return {"w": w, "b": jnp.zeros((dout,))}


def _linear(p, x):
    return x @ p["w"] + p["b"]


def _ln(x, eps=1e-5):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps)


def init_dt_model(key, obs_dim: int, act_in: int, act_out: int,
                  d: int, n_layers: int, max_ep_len: int) -> Dict:
    ks = jax.random.split(key, 6 + 4 * n_layers)
    params: Dict[str, Any] = {
        "emb_rtg": _linear_init(ks[0], 1, d),
        "emb_obs": _linear_init(ks[1], obs_dim, d),
        "emb_act": _linear_init(ks[2], act_in, d),
        "emb_t": jax.random.normal(ks[3], (max_ep_len, d)) * 0.02,
        "head": _linear_init(ks[4], d, act_out, scale=0.01),
        "blocks": [],
    }
    for i in range(n_layers):
        b = {
            "qkv": _linear_init(ks[5 + 4 * i], d, 3 * d),
            "proj": _linear_init(ks[6 + 4 * i], d, d),
            "fc1": _linear_init(ks[7 + 4 * i], d, 4 * d),
            "fc2": _linear_init(ks[8 + 4 * i], 4 * d, d),
        }
        params["blocks"].append(b)
    return params


def dt_forward(params: Dict, rtg, obs, act_in, timesteps, pad_mask,
               n_heads: int):
    """rtg [B,K,1], obs [B,K,Do], act_in [B,K,Da], timesteps [B,K] int,
    pad_mask [B,K] (1=real). Returns action predictions [B,K,act_out]
    read from the state-token positions."""
    B, K = timesteps.shape
    d = params["emb_t"].shape[-1]
    te = params["emb_t"][timesteps]                       # [B,K,d]
    tok_r = _linear(params["emb_rtg"], rtg) + te
    tok_s = _linear(params["emb_obs"], obs) + te
    tok_a = _linear(params["emb_act"], act_in) + te
    # interleave (R, s, a) -> [B, 3K, d]
    x = jnp.stack([tok_r, tok_s, tok_a], axis=2).reshape(B, 3 * K, d)
    tok_mask = jnp.repeat(pad_mask, 3, axis=-1)           # [B, 3K]
    L = 3 * K
    causal = jnp.tril(jnp.ones((L, L), dtype=bool))
    attn_mask = causal[None] & tok_mask[:, None, :].astype(bool)
    neg = jnp.asarray(-1e9, x.dtype)
    hd = d // n_heads

    for blk in params["blocks"]:
        h = _ln(x)
        qkv = _linear(blk["qkv"], h).reshape(B, L, 3, n_heads, hd)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [B,L,H,hd]
        att = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(hd)
        att = jnp.where(attn_mask[:, None], att, neg)
        att = jax.nn.softmax(att, axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", att, v).reshape(B, L, d)
        x = x + _linear(blk["proj"], out)
        h = _ln(x)
        x = x + _linear(blk["fc2"], jax.nn.gelu(_linear(blk["fc1"], h)))

    x = _ln(x)
    state_stream = x.reshape(B, K, 3, d)[:, :, 1]         # after s_t token
    return _linear(params["head"], state_stream)          # [B,K,act_out]


def _episodes_from_arrays(data: Dict[str, np.ndarray],
                          gamma_unused: float) -> List[Dict[str, np.ndarray]]:
    """Split flat (obs, actions, rewards, dones[, env_ids]) rows into
    per-episode dicts with undiscounted returns-to-go (the DT target)."""
    eps: List[Dict[str, np.ndarray]] = []
    env_ids = data.get("env_ids")
    streams: Dict[Any, List[int]] = {}
    for i in range(len(data["rewards"])):
        e = env_ids[i] if env_ids is not None else 0
        streams.setdefault(e, []).append(i)
        if data["dones"][i]:
            idx = np.asarray(streams.pop(e))
            rew = data["rewards"][idx].astype(np.float64)
            rtg = np.cumsum(rew[::-1])[::-1]
            eps.append({"obs": data["obs"][idx],
                        "actions": data["actions"][idx],
                        "rewards": rew.astype(np.float32),
                        "rtg": rtg.astype(np.float32)})
    # trailing partial episodes still provide supervised windows
    for idx_list in streams.values():
        idx = np.asarray(idx_list)
        if len(idx) < 2:
            continue
        rew = data["rewards"][idx].astype(np.float64)
        rtg = np.cumsum(rew[::-1])[::-1]
        eps.append({"obs": data["obs"][idx],
                    "actions": data["actions"][idx],
                    "rewards": rew.astype(np.float32),
                    "rtg": rtg.astype(np.float32)})
    if not eps:
        raise ValueError("offline_data contains no completed episodes "
                         "(need dones markers)")
    return eps


class DT(Algorithm):
    need_env_runners = False  # offline: the dataset IS the experience

    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        return DTConfig()

    def build_learner(self) -> None:
        cfg, spec = self.config, self.spec
        if cfg.offline_data is None:
            raise ValueError("DT needs config.offline_data")
        data = _to_arrays(cfg.offline_data)
        for col in ("obs", "actions", "rewards", "dones"):
            if col not in data:
                raise ValueError(f"offline_data missing {col!r}")
        self._episodes = _episodes_from_arrays(data, cfg.gamma)
        self._ep_lens = np.asarray([len(e["rewards"])
                                    for e in self._episodes])
        self._rng = np.random.default_rng(cfg.seed)

        K = cfg.context_len
        act_in = spec.num_actions if spec.discrete else spec.action_dim
        act_out = act_in
        low, high = spec.action_low, spec.action_high
        scale = cfg.rtg_scale

        params = init_dt_model(
            jax.random.key(cfg.seed), spec.obs_dim, act_in, act_out,
            cfg.d_model, cfg.n_layers, cfg.max_ep_len)
        n_heads = cfg.n_heads
        discrete = spec.discrete

        def loss_fn(params, batch, key):
            pred = dt_forward(params, batch["rtg"][..., None] / scale,
                              batch["obs"], batch["act_in"],
                              batch["timesteps"], batch["mask"], n_heads)
            mask = batch["mask"]
            denom = mask.sum() + 1e-8
            if discrete:
                logp = jax.nn.log_softmax(pred, axis=-1)
                tgt = batch["actions"].astype(jnp.int32)
                nll = -jnp.take_along_axis(
                    logp, tgt[..., None], axis=-1)[..., 0]
                loss = (nll * mask).sum() / denom
                acc = ((jnp.argmax(pred, -1) == tgt) * mask).sum() / denom
                return loss, {"action_nll": loss, "action_acc": acc}
            err = ((pred - batch["actions"]) ** 2).sum(-1)
            loss = (err * mask).sum() / denom
            return loss, {"action_mse": loss}

        self.learner = Learner(params, loss_fn, cfg.lr,
                               grad_clip=cfg.grad_clip, seed=cfg.seed)

        @jax.jit
        def act_fn(params, rtg, obs, act_in, timesteps, mask):
            pred = dt_forward(params, rtg[..., None] / scale, obs, act_in,
                              timesteps, mask, n_heads)
            last = pred[:, -1]
            if discrete:
                return jnp.argmax(last, axis=-1)
            return jnp.clip(last, low, high)

        self._act_fn = act_fn
        self._K = K
        self._act_in_dim = act_in

    def _encode_actions(self, acts: np.ndarray) -> np.ndarray:
        if self.spec.discrete:
            out = np.zeros((len(acts), self.spec.num_actions),
                           dtype=np.float32)
            out[np.arange(len(acts)), acts.astype(np.int64)] = 1.0
            return out
        return np.atleast_2d(acts).astype(np.float32).reshape(
            len(acts), -1)

    def _minibatch(self, size: int) -> Dict[str, np.ndarray]:
        cfg, spec, K = self.config, self.spec, self._K
        p = self._ep_lens / self._ep_lens.sum()
        eps_idx = self._rng.choice(len(self._episodes), size=size, p=p)
        obs = np.zeros((size, K, spec.obs_dim), dtype=np.float32)
        act_in = np.zeros((size, K, self._act_in_dim), dtype=np.float32)
        if spec.discrete:
            actions = np.zeros((size, K), dtype=np.int64)
        else:
            actions = np.zeros((size, K, spec.action_dim), dtype=np.float32)
        rtg = np.zeros((size, K), dtype=np.float32)
        ts = np.zeros((size, K), dtype=np.int32)
        mask = np.zeros((size, K), dtype=np.float32)
        for b, ei in enumerate(eps_idx):
            ep = self._episodes[ei]
            n = len(ep["rewards"])
            start = int(self._rng.integers(0, n))
            seg = slice(start, min(start + K, n))
            ln = seg.stop - seg.start
            obs[b, -ln:] = ep["obs"][seg].reshape(ln, -1)
            # slot t holds a_t itself: the prediction for a_t is read at
            # the s_t token (index 3t+1), which the causal mask cuts off
            # BEFORE the a_t token (3t+2), so a_{t-1} is the newest action
            # visible — the canonical DT interleave
            act_in[b, -ln:] = self._encode_actions(ep["actions"][seg])
            if spec.discrete:
                actions[b, -ln:] = ep["actions"][seg]
            else:
                actions[b, -ln:] = ep["actions"][seg].reshape(ln, -1)
            rtg[b, -ln:] = ep["rtg"][seg]
            ts[b, -ln:] = np.clip(np.arange(seg.start, seg.stop),
                                  0, cfg.max_ep_len - 1)
            mask[b, -ln:] = 1.0
        return {"obs": obs, "act_in": act_in, "actions": actions,
                "rtg": rtg, "timesteps": ts, "mask": mask}

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        m: Dict[str, Any] = {}
        for _ in range(cfg.updates_per_iter or 50):
            m = self.learner.update_minibatch(
                self._minibatch(cfg.minibatch_size))
        self._env_steps_total += 0  # offline: no env interaction
        return {k: float(v) for k, v in m.items()}

    def evaluate(self, num_episodes: int = 5,
                 target_return: float = None) -> Dict[str, float]:
        """Return-conditioned rollout: condition on ``target_return`` and
        decay it by realized rewards (the DT evaluation protocol)."""
        from ray_tpu.rl.env import make_env

        cfg, spec, K = self.config, self.spec, self._K
        tgt0 = float(cfg.target_return if target_return is None
                     else target_return)
        env = make_env(cfg.env, 1, cfg.env_config)
        params = self.learner.get_params()
        returns = []
        for _ in range(num_episodes):
            obs = env.reset()
            hist_obs = [np.asarray(obs[0], dtype=np.float32).reshape(-1)]
            hist_act: List[np.ndarray] = []
            hist_rtg = [tgt0]
            ep_ret, t = 0.0, 0
            while t < cfg.max_ep_len:
                ln = min(len(hist_obs), K)
                o = np.zeros((1, K, spec.obs_dim), dtype=np.float32)
                a = np.zeros((1, K, self._act_in_dim), dtype=np.float32)
                r = np.zeros((1, K), dtype=np.float32)
                ts = np.zeros((1, K), dtype=np.int32)
                mk = np.zeros((1, K), dtype=np.float32)
                o[0, -ln:] = np.stack(hist_obs[-ln:])
                # slots -ln..-2 are past timesteps (their actions are
                # known); the current slot stays zero — the causal mask
                # keeps it invisible to this step's prediction anyway
                na = ln - 1
                if na > 0 and hist_act:
                    a[0, -ln:-1] = np.stack(hist_act[-na:])
                r[0, -ln:] = hist_rtg[-ln:]
                lo = len(hist_obs) - ln
                ts[0, -ln:] = np.clip(np.arange(lo, lo + ln),
                                      0, cfg.max_ep_len - 1)
                mk[0, -ln:] = 1.0
                act = np.asarray(self._act_fn(params, r, o, a, ts, mk))[0]
                step_act = (np.asarray([act])
                            if spec.discrete else act[None])
                obs, reward, done = env.step(step_act)
                ep_ret += float(reward[0])
                t += 1
                if done[0]:
                    break
                hist_obs.append(np.asarray(obs[0],
                                           dtype=np.float32).reshape(-1))
                hist_act.append(self._encode_actions(
                    np.asarray([act]).reshape(1, -1)
                    if not spec.discrete else np.asarray([act]))[0])
                hist_rtg.append(hist_rtg[-1] - float(reward[0]))
            returns.append(ep_ret)
        return {"episode_return_mean": float(np.mean(returns))}
