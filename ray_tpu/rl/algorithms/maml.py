"""MAML: model-agnostic meta-learning for RL.

Reference analog: ``rllib/algorithms/maml/maml.py`` (Finn et al. 2017).
Meta-train a policy initialization such that ONE (or a few) vanilla
policy-gradient steps on a new task's rollouts produce a good policy for
that task. JAX is the natural home for this: the inner adaptation step
is a ``jax.grad`` inside the outer loss, and ``jax.grad`` of the whole
thing gives the full second-order MAML gradient — no manual Hessian-vector
plumbing like the reference's torch autograd surgery.

Task distribution: ``PointGoal`` — a 2D point mass starting at the
origin must reach a per-task goal on a circle; the goal is NOT in the
observation, so the only way to locate it is to adapt on task rollouts
(the classic MAML-RL navigation benchmark). Tasks are episodic with a
dense ``-dist`` reward.

The outer objective is the post-adaptation REINFORCE surrogate on fresh
rollouts collected under the ADAPTED parameters (the standard MAML-RL
estimator; the sampling distribution's own dependence on theta —
E-MAML's exploration credit — is ignored, as in the reference).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl import models
from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.tune.trainable import Trainable


class PointGoal:
    """Vectorized 2D navigation to a hidden per-task goal."""

    def __init__(self, goal: Tuple[float, float], num_envs: int = 8,
                 horizon: int = 20, dt: float = 0.25, seed: int = 0):
        self.goal = np.asarray(goal, dtype=np.float32)
        self.num_envs = num_envs
        self.horizon = horizon
        self.dt = dt
        self._rng = np.random.default_rng(seed)
        self._pos = np.zeros((num_envs, 2), dtype=np.float32)
        self._t = np.zeros(num_envs, dtype=np.int64)

    def reset(self) -> np.ndarray:
        self._pos[:] = 0.02 * self._rng.standard_normal(
            self._pos.shape).astype(np.float32)
        self._t[:] = 0
        return self._pos.copy()

    def step(self, actions: np.ndarray):
        self._pos += self.dt * np.clip(actions, -1, 1)
        reward = -np.linalg.norm(self._pos - self.goal,
                                 axis=-1).astype(np.float32)
        self._t += 1
        dones = self._t >= self.horizon
        reset = dones
        if reset.any():
            self._pos[reset] = 0.02 * self._rng.standard_normal(
                (int(reset.sum()), 2)).astype(np.float32)
            self._t[reset] = 0
        return self._pos.copy(), reward, dones


class MAMLConfig(AlgorithmConfig):
    def __init__(self, **kwargs):
        super().__init__(algo_class=MAML, **kwargs)
        # two moderate inner steps beat one large one here: a single
        # aggressive step lets the outer optimizer drift the base policy
        # outward (post-adaptation reward degrades while the "gain"
        # grows); 2 x 0.5 keeps both improving (swept, round 4)
        self.inner_lr = 0.5
        self.inner_steps = 2
        self.meta_batch_size = 8       # tasks per meta-update
        self.num_envs_per_runner = 16  # vector envs per task rollout
        self.horizon = 16
        self.lr = 1e-3                 # outer (meta) learning rate
        self.hidden = (64, 64)
        self.goal_radius = 1.0


class MAML(Trainable):
    def setup(self, config: Dict[str, Any]) -> None:
        if "__algo_config" in config:
            self.config: AlgorithmConfig = config["__algo_config"]
        else:
            self.config = MAMLConfig().update_from_dict(config)
        cfg = self.config
        self._rng = np.random.default_rng(cfg.seed)
        self._key = jax.random.key(cfg.seed + 1)

        # gaussian policy: mean MLP + global log_std
        k = jax.random.key(cfg.seed)
        self.params = {
            "pi": models.init_mlp(k, (2, *cfg.hidden, 2), out_scale=0.01),
            "log_std": jnp.full((2,), -0.5),
        }
        import optax

        self._opt = optax.adam(cfg.lr)
        self._opt_state = self._opt.init(self.params)
        inner_lr, inner_steps = cfg.inner_lr, cfg.inner_steps
        self._env_steps_total = 0

        def logp_of(p, obs, acts):
            mean = models.mlp_forward(p["pi"], obs)
            return models.gaussian_logp(mean, p["log_std"], acts)

        def pg_loss(p, batch):
            ret = batch["returns"]
            ret = (ret - ret.mean()) / (ret.std() + 1e-8)
            return -jnp.mean(logp_of(p, batch["obs"], batch["acts"])
                             * ret)

        def adapt(p, batch):
            """inner_steps of plain SGD on the task's REINFORCE loss —
            differentiable, so the meta-gradient is second-order."""
            for _ in range(inner_steps):
                g = jax.grad(pg_loss)(p, batch)
                p = jax.tree_util.tree_map(
                    lambda w, gw: w - inner_lr * gw, p, g)
            return p

        def meta_loss(p, pre_batches, post_batches):
            total = 0.0
            for pre, post in zip(pre_batches, post_batches):
                total = total + pg_loss(adapt(p, pre), post)
            return total / len(pre_batches)

        self._adapt = jax.jit(adapt)
        self._meta_grad = jax.jit(jax.value_and_grad(meta_loss))

        @jax.jit
        def apply_meta(p, opt_state, grads):
            updates, opt_state = self._opt.update(grads, opt_state, p)
            return optax.apply_updates(p, updates), opt_state

        self._apply_meta = apply_meta

        @jax.jit
        def act(p, obs, key):
            mean = models.mlp_forward(p["pi"], obs)
            return mean + jnp.exp(p["log_std"]) \
                * jax.random.normal(key, mean.shape)

        self._act = act

    # -- rollouts ---------------------------------------------------------

    def _sample_task(self) -> Tuple[float, float]:
        theta = self._rng.uniform(0, 2 * np.pi)
        r = self.config.goal_radius
        return (r * np.cos(theta), r * np.sin(theta))

    def _rollout(self, env: PointGoal, params) -> Dict[str, jnp.ndarray]:
        """One horizon of vectorized steps -> flat REINFORCE batch with
        per-timestep discounted return-to-go."""
        cfg = self.config
        obs_l, act_l, rew_l = [], [], []
        obs = env.reset()
        for _ in range(env.horizon):
            self._key, sub = jax.random.split(self._key)
            acts = np.asarray(self._act(params, jnp.asarray(obs), sub))
            nobs, rew, _ = env.step(acts)
            obs_l.append(obs)
            act_l.append(acts)
            rew_l.append(rew)
            obs = nobs
        rews = np.stack(rew_l)                       # [T, N]
        rets = np.zeros_like(rews)
        acc = np.zeros(rews.shape[1], dtype=rews.dtype)
        for t in range(len(rews) - 1, -1, -1):
            acc = rews[t] + cfg.gamma * acc
            rets[t] = acc
        self._env_steps_total += rews.size
        batch = {"obs": jnp.asarray(np.concatenate(obs_l)),
                 "acts": jnp.asarray(np.concatenate(act_l)),
                 "returns": jnp.asarray(rets.reshape(-1))}
        return batch, float(rews.mean())

    # -- Trainable API ----------------------------------------------------

    def step(self) -> Dict[str, Any]:
        cfg = self.config
        pre_batches, post_batches = [], []
        pre_r, post_r = [], []
        for ti in range(cfg.meta_batch_size):
            goal = self._sample_task()
            env = PointGoal(goal, cfg.num_envs_per_runner, cfg.horizon,
                            seed=int(self._rng.integers(1 << 31)))
            pre, pre_mr = self._rollout(env, self.params)
            adapted = self._adapt(self.params, pre)
            post, post_mr = self._rollout(env, adapted)
            pre_r.append(pre_mr)
            post_r.append(post_mr)
            pre_batches.append(pre)
            post_batches.append(post)
        loss, grads = self._meta_grad(self.params, pre_batches,
                                      post_batches)
        self.params, self._opt_state = self._apply_meta(
            self.params, self._opt_state, grads)
        return {"meta_loss": float(loss),
                "pre_adapt_reward": float(np.mean(pre_r)),
                "post_adapt_reward": float(np.mean(post_r)),
                "adaptation_gain": float(np.mean(post_r) - np.mean(pre_r)),
                # the CLI's display/stop metric: post-adaptation reward is
                # the quantity MAML optimizes
                "mean_return": float(np.mean(post_r)),
                "env_steps_total": self._env_steps_total}

    def evaluate(self, num_tasks: int = 8) -> Dict[str, float]:
        """Adaptation gain on FRESH tasks: reward before vs after the
        inner-loop update (the quantity MAML optimizes). Training state
        (task rng, action key, step counters) is restored afterwards so
        mid-training evaluation never shifts the training trajectory."""
        cfg = self.config
        rng_state = self._rng.bit_generator.state
        key_before = self._key
        steps_before = self._env_steps_total
        try:
            pre_r, post_r = [], []
            for _ in range(num_tasks):
                env = PointGoal(self._sample_task(),
                                cfg.num_envs_per_runner, cfg.horizon,
                                seed=int(self._rng.integers(1 << 31)))
                pre, pre_mr = self._rollout(env, self.params)
                adapted = self._adapt(self.params, pre)
                post, post_mr = self._rollout(env, adapted)
                pre_r.append(pre_mr)
                post_r.append(post_mr)
        finally:
            self._rng.bit_generator.state = rng_state
            self._key = key_before
            self._env_steps_total = steps_before
        return {"pre_adapt_reward": float(np.mean(pre_r)),
                "post_adapt_reward": float(np.mean(post_r)),
                "adaptation_gain": float(np.mean(post_r)
                                         - np.mean(pre_r))}

    # -- checkpointing ----------------------------------------------------

    def save_checkpoint(self, checkpoint_dir: str) -> Optional[Dict]:
        return {"params": jax.tree_util.tree_map(np.asarray, self.params),
                "env_steps_total": self._env_steps_total}

    def load_checkpoint(self, checkpoint: Dict) -> None:
        self.params = jax.tree_util.tree_map(jnp.asarray,
                                             checkpoint["params"])
        self._env_steps_total = checkpoint.get("env_steps_total", 0)
