"""ARS: Augmented Random Search (Mania et al. 2018).

Reference analog: ``rllib/algorithms/ars/ars.py`` — like ES, a fleet of
workers evaluates antithetic parameter perturbations for whole episodes,
but with the three ARS augmentations: (V2) observations are normalized by
a running mean/std filter shared across the fleet, (b) only the top-b
directions by max(r+, r-) contribute to the update, and the step is scaled
by the standard deviation of the selected returns. Noise travels as
integer seeds (the SharedNoiseTable trick), never parameter vectors; the
running obs filter syncs by merging per-worker (count, sum, sumsq) deltas
on the driver — the same delta-merge pattern as the connector
MeanStdFilter (rl/connectors.py).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import numpy as np

import ray_tpu
from ray_tpu.rl import models
from ray_tpu.rl.algorithm import Algorithm
from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.algorithms.es import (
    _centered_ranks,  # noqa: F401  (kept for API symmetry with ES)
    _flatten,
    _noise,
    _unflatten,
)


class ARSConfig(AlgorithmConfig):
    def __init__(self, **kwargs):
        super().__init__(algo_class=ARS, **kwargs)
        self.episodes_per_perturbation = 1
        self.noise_std = 0.05
        self.num_perturbations = 16   # antithetic direction pairs / iter
        self.top_directions = 8       # b: directions kept for the update
        self.lr = 0.02
        self.max_episode_len = 500
        self.normalize_obs = True


@ray_tpu.remote
class _ARSWorker:
    """Evaluates perturbed deterministic policies with a running obs
    filter (ARS-V2). Filter deltas are popped by the driver and the merged
    global filter pushed back, so every worker normalizes with fleet-wide
    statistics."""

    def __init__(self, env_name: str, env_config: Dict, seed: int,
                 hidden, noise_std: float, max_len: int,
                 normalize_obs: bool):
        import jax
        import jax.numpy as jnp

        from ray_tpu.rl.env import make_env

        self._env = make_env(env_name, 1, env_config, seed=seed)
        self.spec = self._env.spec
        self._std = noise_std
        self._max_len = max_len
        self._normalize = normalize_obs
        base = models.init_policy(jax.random.key(0), self.spec, hidden)
        _, self._meta = _flatten(base)
        d = self.spec.obs_dim
        # global filter (mean/var used for normalization) + local delta
        self._mean = np.zeros(d, dtype=np.float64)
        self._var = np.ones(d, dtype=np.float64)
        self._delta = np.zeros((3, d), dtype=np.float64)  # count,sum,sumsq

        spec = self.spec

        @jax.jit
        def act(params, obs):
            logits = models.policy_logits(params, obs)
            if spec.discrete:
                return jnp.argmax(logits, axis=-1)
            return logits

        self._act = act

    def set_filter(self, mean: np.ndarray, var: np.ndarray) -> None:
        self._mean = np.asarray(mean, dtype=np.float64)
        self._var = np.asarray(var, dtype=np.float64)

    def pop_filter_delta(self) -> np.ndarray:
        out, self._delta = self._delta, np.zeros_like(self._delta)
        return out

    def _norm(self, obs: np.ndarray) -> np.ndarray:
        if not self._normalize:
            return obs
        self._delta[0] += 1.0
        self._delta[1] += obs[0]
        self._delta[2] += obs[0] ** 2
        return ((obs - self._mean)
                / np.sqrt(self._var + 1e-8)).astype(np.float32)

    def _episode_return(self, params) -> Tuple[float, int]:
        obs = self._env.reset()
        total, steps = 0.0, 0
        for _ in range(self._max_len):
            a = np.asarray(self._act(params, self._norm(obs)))
            if not self.spec.discrete:
                a = np.clip(a, self.spec.action_low, self.spec.action_high)
            obs, r, d = self._env.step(a)
            total += float(r[0])
            steps += 1
            if d[0]:
                break
        return total, steps

    def episode_return(self, flat: np.ndarray) -> Tuple[float, int]:
        """One episode at exactly these (unperturbed) parameters."""
        return self._episode_return(
            _unflatten(np.asarray(flat), self._meta))

    def evaluate(self, flat_center: np.ndarray, noise_seed: int,
                 episodes: int) -> Tuple[float, float, int]:
        center = np.asarray(flat_center)
        eps = _noise(noise_seed, len(center), self._std)
        steps = 0
        pos_r, neg_r = [], []
        for _ in range(episodes):
            r, n = self._episode_return(
                _unflatten(center + eps, self._meta))
            pos_r.append(r)
            steps += n
            r, n = self._episode_return(
                _unflatten(center - eps, self._meta))
            neg_r.append(r)
            steps += n
        return float(np.mean(pos_r)), float(np.mean(neg_r)), steps


class ARS(Algorithm):
    need_env_runners = False  # whole-episode eval fleet instead

    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        return ARSConfig()

    def build_learner(self) -> None:
        import jax

        cfg = self.config
        params = models.init_policy(jax.random.key(cfg.seed), self.spec,
                                    cfg.hidden)
        self._center, self._meta = _flatten(params)
        n_workers = max(1, cfg.num_env_runners)
        self._workers = [
            _ARSWorker.options(num_cpus=cfg.num_cpus_per_runner).remote(
                cfg.env, cfg.env_config, cfg.seed + 7919 * i, cfg.hidden,
                cfg.noise_std, cfg.max_episode_len, cfg.normalize_obs)
            for i in range(n_workers)
        ]
        self._rng = np.random.default_rng(cfg.seed)
        d = self.spec.obs_dim
        self._f_count = 1e-4
        self._f_sum = np.zeros(d, dtype=np.float64)
        self._f_sumsq = np.ones(d, dtype=np.float64) * 1e-4
        self.learner = self

    def get_params(self):
        return _unflatten(self._center, self._meta)

    def set_params(self, params) -> None:
        self._center, self._meta = _flatten(params)

    def get_extra_state(self):
        return {"count": self._f_count, "sum": self._f_sum,
                "sumsq": self._f_sumsq}

    def set_extra_state(self, state) -> None:
        if state:
            self._f_count = state["count"]
            self._f_sum = np.asarray(state["sum"])
            self._f_sumsq = np.asarray(state["sumsq"])
            self._broadcast_filter()

    def _broadcast_filter(self) -> None:
        mean = self._f_sum / self._f_count
        var = np.maximum(self._f_sumsq / self._f_count - mean ** 2, 1e-8)
        ray_tpu.get([w.set_filter.remote(mean, var)
                     for w in self._workers])

    def evaluate(self, num_episodes: int = 10) -> Dict[str, Any]:
        """Whole episodes at the unperturbed center parameters."""
        refs = [self._workers[i % len(self._workers)]
                .episode_return.remote(self._center)
                for i in range(num_episodes)]
        rets = [r[0] for r in ray_tpu.get(refs)]
        return {"episodes": num_episodes,
                "episode_return_mean": float(np.mean(rets))}

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        seeds = [int(s) for s in
                 self._rng.integers(0, 2 ** 31 - 1,
                                    size=cfg.num_perturbations)]
        pending = [
            self._workers[i % len(self._workers)].evaluate.remote(
                self._center, seeds[i], cfg.episodes_per_perturbation)
            for i in range(len(seeds))
        ]
        results = ray_tpu.get(pending)
        pos = np.array([r[0] for r in results])
        neg = np.array([r[1] for r in results])
        # top-b directions by max(r+, r-)
        b = min(cfg.top_directions, len(seeds))
        order = np.argsort(-np.maximum(pos, neg))[:b]
        sel = np.concatenate([pos[order], neg[order]])
        sigma_r = float(np.std(sel)) or 1.0
        grad = np.zeros_like(self._center)
        for i in order:
            grad += (pos[i] - neg[i]) * _noise(seeds[i],
                                               len(self._center),
                                               cfg.noise_std)
        # noise above is std-scaled; divide it out so the step is in
        # unit-direction space as in the paper
        self._center = self._center \
            + cfg.lr / (b * sigma_r * cfg.noise_std) * grad
        # merge + re-broadcast the fleet's obs-filter deltas
        if cfg.normalize_obs:
            deltas = ray_tpu.get([w.pop_filter_delta.remote()
                                  for w in self._workers])
            for dlt in deltas:
                self._f_count += float(dlt[0][0])
                self._f_sum += dlt[1]
                self._f_sumsq += dlt[2]
            self._broadcast_filter()
        self._env_steps_total += int(sum(r[2] for r in results))
        all_r = np.concatenate([pos, neg])
        return {
            "mean_return": float(np.mean(all_r)),
            "best_return": float(np.max(all_r)),
            "selected_return_std": sigma_r,
        }
