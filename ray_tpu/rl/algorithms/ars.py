"""ARS: Augmented Random Search (Mania et al. 2018).

Reference analog: ``rllib/algorithms/ars/ars.py`` — ES's antithetic
whole-episode evaluation fleet (shared here by subclassing :class:`ES`;
noise travels as integer seeds, the SharedNoiseTable trick) with the
three ARS augmentations: (V2) observations are normalized by a running
mean/std filter shared across the fleet (the ``normalize_obs`` flag on
the shared ``_ESWorker``), (b) only the top-b directions by
max(r+, r-) contribute to the update, and the step is scaled by the
standard deviation of the selected returns.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

import ray_tpu
from ray_tpu.rl.algorithms.es import ES, ESConfig, _noise
from ray_tpu.rl.config import AlgorithmConfig


class ARSConfig(ESConfig):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self.algo_class = ARS
        self.episodes_per_perturbation = 1
        self.top_directions = 8       # b: directions kept for the update
        self.normalize_obs = True


class ARS(ES):
    """ES fleet + top-direction selection + fleet-synced obs filter."""

    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        return ARSConfig()

    def build_learner(self) -> None:
        super().build_learner()
        d = self.spec.obs_dim
        self._f_count = 1e-4
        self._f_sum = np.zeros(d, dtype=np.float64)
        self._f_sumsq = np.ones(d, dtype=np.float64) * 1e-4

    # -- obs-filter state (checkpointed; reference: ARS's shared
    # MeanStdFilter snapshot) --------------------------------------------

    def get_extra_state(self):
        return {"count": self._f_count, "sum": self._f_sum,
                "sumsq": self._f_sumsq}

    def set_extra_state(self, state) -> None:
        if state:
            self._f_count = state["count"]
            self._f_sum = np.asarray(state["sum"])
            self._f_sumsq = np.asarray(state["sumsq"])
            self._broadcast_filter()

    def _broadcast_filter(self) -> None:
        mean = self._f_sum / self._f_count
        var = np.maximum(self._f_sumsq / self._f_count - mean ** 2, 1e-8)
        ray_tpu.get([w.set_filter.remote(mean, var)
                     for w in self._workers])

    def _merge_filter_deltas(self) -> None:
        deltas = ray_tpu.get([w.pop_filter_delta.remote()
                              for w in self._workers])
        for dlt in deltas:
            self._f_count += float(dlt[0][0])
            self._f_sum += dlt[1]
            self._f_sumsq += dlt[2]
        self._broadcast_filter()

    # -- the ARS update ---------------------------------------------------

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        seeds = [int(s) for s in
                 self._rng.integers(0, 2 ** 31 - 1,
                                    size=cfg.num_perturbations)]
        pending = [
            self._workers[i % len(self._workers)].evaluate.remote(
                self._center, seeds[i], cfg.episodes_per_perturbation)
            for i in range(len(seeds))
        ]
        results = ray_tpu.get(pending)
        pos = np.array([r[0] for r in results])
        neg = np.array([r[1] for r in results])
        # top-b directions by max(r+, r-)
        b = min(cfg.top_directions, len(seeds))
        order = np.argsort(-np.maximum(pos, neg))[:b]
        sel = np.concatenate([pos[order], neg[order]])
        sigma_r = float(np.std(sel)) or 1.0
        grad = np.zeros_like(self._center)
        for i in order:
            grad += (pos[i] - neg[i]) * _noise(seeds[i],
                                               len(self._center),
                                               cfg.noise_std)
        # noise above is std-scaled; divide it out so the step is in
        # unit-direction space as in the paper
        self._center = self._center \
            + cfg.lr / (b * sigma_r * cfg.noise_std) * grad
        if cfg.normalize_obs:
            self._merge_filter_deltas()
        self._env_steps_total += int(sum(r[2] for r in results))
        all_r = np.concatenate([pos, neg])
        return {
            "mean_return": float(np.mean(all_r)),
            "best_return": float(np.max(all_r)),
            "selected_return_std": sigma_r,
        }
