"""APPO: asynchronous PPO — IMPALA's async pipeline + the clipped surrogate.

Reference analog: ``rllib/algorithms/appo/appo.py:66`` (APPO extends
IMPALA's execution with a PPO-style clip loss over V-trace-corrected
advantages, plus a periodically-updated target policy whose logp anchors
the ratio when fragments are very stale).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from ray_tpu.rl import models
from ray_tpu.rl.algorithms.impala import IMPALA, vtrace
from ray_tpu.rl.config import AlgorithmConfig


class APPO(IMPALA):
    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        cfg = AlgorithmConfig(algo_class=cls)
        cfg.num_env_runners = 2
        cfg.clip_param = 0.3
        return cfg

    def build_learner(self) -> None:
        cfg, spec = self.config, self.spec
        T = cfg.rollout_fragment_length
        gamma, clip = cfg.gamma, cfg.clip_param
        vf_coeff, ent_coeff = cfg.vf_coeff, cfg.entropy_coeff
        clip_rho, clip_pg = cfg.vtrace_clip_rho, cfg.vtrace_clip_pg_rho

        def loss_fn(params, batch, key):
            N = batch["rewards"].shape[0] // T
            sh = lambda a: a.reshape((T, N) + a.shape[1:])  # noqa: E731
            obs = sh(batch["obs"])
            actions = sh(batch["actions"])
            behavior_logp = sh(batch["logp"])
            logits = models.policy_logits(params, obs)
            if spec.discrete:
                target_logp = models.categorical_logp(logits, actions)
                entropy = models.categorical_entropy(logits).mean()
            else:
                target_logp = models.gaussian_logp(
                    logits, params["log_std"], actions)
                entropy = models.gaussian_entropy(params["log_std"])
            values = models.value(params, obs)
            vs, pg_adv = vtrace(
                behavior_logp, target_logp, sh(batch["rewards"]),
                values, batch["last_values"], sh(batch["dones"]), gamma,
                clip_rho, clip_pg)
            adv = (pg_adv - pg_adv.mean()) / (pg_adv.std() + 1e-8)
            # PPO clip on the behavior ratio (APPO: surrogate over v-trace
            # advantages instead of IMPALA's plain pg loss)
            ratio = jnp.exp(target_logp - behavior_logp)
            surr = jnp.minimum(
                ratio * adv, jnp.clip(ratio, 1 - clip, 1 + clip) * adv)
            pi_loss = -surr.mean()
            vf_loss = jnp.mean((values - vs) ** 2)
            total = pi_loss + vf_coeff * vf_loss - ent_coeff * entropy
            return total, {"pi_loss": pi_loss, "vf_loss": vf_loss,
                           "entropy": entropy,
                           "ratio_mean": ratio.mean()}

        from ray_tpu.rl.learner import Learner

        params = self.init_policy_params()
        self.learner = Learner(params, loss_fn, cfg.lr,
                               grad_clip=cfg.grad_clip, seed=cfg.seed)
        self._inflight: Dict[Any, Any] = {}
        self._runner_failures: Dict[Any, int] = {}  # IMPALA fleet FT state


class APPOConfig(AlgorithmConfig):
    def __init__(self, **kwargs):
        super().__init__(algo_class=APPO, **kwargs)
        self.num_env_runners = 2
        self.clip_param = 0.3
