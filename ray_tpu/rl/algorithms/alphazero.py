"""AlphaZero: self-play MCTS + policy/value network for two-player games.

Reference analog: ``rllib/algorithms/alpha_zero/`` (Silver et al. 2017).
Components: a pluggable perfect-information ``Game`` protocol, PUCT MCTS
guided by network priors with Dirichlet root noise, self-play data
generation (MCTS visit counts become policy targets; the game outcome
becomes the value target), and a jitted policy+value training step through
the shared ``Learner``.

The bundled game is TicTacToe — small enough that the convergence test
runs on CPU in seconds, while the MCTS/self-play machinery is exactly the
scaled game's. States are hashable; search trees are per-move dicts (the
tree is discarded between moves, as in the reference's single-player MCTS).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl import models
from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.learner import Learner
from ray_tpu.tune.trainable import Trainable


class Game:
    """Two-player zero-sum perfect-information game protocol. States are
    hashable values seen from an absolute perspective; ``encode`` renders
    the state from the side-to-move's perspective."""

    num_actions: int
    obs_dim: int

    def initial_state(self):
        raise NotImplementedError

    def legal_actions(self, state) -> np.ndarray:  # bool [num_actions]
        raise NotImplementedError

    def next_state(self, state, action: int):
        raise NotImplementedError

    def terminal_value(self, state) -> Optional[float]:
        """Value for the player to move (+1 win, -1 loss, 0 draw), or
        None if the game continues."""
        raise NotImplementedError

    def encode(self, state) -> np.ndarray:
        raise NotImplementedError


_WIN_LINES = ((0, 1, 2), (3, 4, 5), (6, 7, 8),
              (0, 3, 6), (1, 4, 7), (2, 5, 8),
              (0, 4, 8), (2, 4, 6))


class TicTacToe(Game):
    """State: (board 9-tuple of {0, +1, -1}, player {+1, -1})."""

    num_actions = 9
    obs_dim = 18  # own-pieces plane ++ opponent plane

    def initial_state(self):
        return ((0,) * 9, 1)

    def legal_actions(self, state) -> np.ndarray:
        board, _ = state
        return np.array([c == 0 for c in board], dtype=bool)

    def next_state(self, state, action: int):
        board, player = state
        assert board[action] == 0
        nb = list(board)
        nb[action] = player
        return (tuple(nb), -player)

    def terminal_value(self, state) -> Optional[float]:
        board, player = state
        for a, b, c in _WIN_LINES:
            s = board[a] + board[b] + board[c]
            if s == 3 or s == -3:
                # the winner just moved; the player to move has lost
                return -1.0
            # (winner's sign is irrelevant: a full line belongs to the
            # player who completed it, who is never the one to move)
        if all(c != 0 for c in board):
            return 0.0
        return None

    def encode(self, state) -> np.ndarray:
        board, player = state
        arr = np.asarray(board, dtype=np.float32)
        own = (arr == player).astype(np.float32)
        opp = (arr == -player).astype(np.float32)
        return np.concatenate([own, opp])


class MCTS:
    """PUCT search over one root. Q/N/P tables are keyed by state; values
    are always from the perspective of the player to move at that state."""

    def __init__(self, game: Game, predict, c_puct: float = 1.5,
                 dirichlet_alpha: float = 0.5, noise_eps: float = 0.25,
                 rng: Optional[np.random.Generator] = None):
        self.game = game
        self.predict = predict  # encoded obs -> (priors [A], value)
        self.c_puct = c_puct
        self.alpha = dirichlet_alpha
        self.eps = noise_eps
        self.rng = rng or np.random.default_rng(0)
        self._P: Dict[Any, np.ndarray] = {}
        self._N: Dict[Any, np.ndarray] = {}
        self._W: Dict[Any, np.ndarray] = {}

    def _expand(self, state) -> float:
        priors, value = self.predict(self.game.encode(state))
        legal = self.game.legal_actions(state)
        priors = np.where(legal, priors, 0.0)
        total = priors.sum()
        priors = (priors / total if total > 0
                  else legal / max(1, legal.sum()))
        self._P[state] = priors
        self._N[state] = np.zeros(self.game.num_actions)
        self._W[state] = np.zeros(self.game.num_actions)
        return float(value)

    def _simulate(self, state) -> float:
        """Returns the value of `state` for its player to move."""
        tv = self.game.terminal_value(state)
        if tv is not None:
            return tv
        if state not in self._P:
            return self._expand(state)
        n, w, p = self._N[state], self._W[state], self._P[state]
        legal = self.game.legal_actions(state)
        q = np.divide(w, n, out=np.zeros_like(w), where=n > 0)
        u = self.c_puct * p * math.sqrt(max(1.0, n.sum())) / (1.0 + n)
        score = np.where(legal, q + u, -np.inf)
        a = int(np.argmax(score))
        child = self.game.next_state(state, a)
        # child value is for the opponent; negate for our perspective
        v = -self._simulate(child)
        n[a] += 1
        w[a] += v
        return v

    def search(self, state, num_simulations: int,
               root_noise: bool = True) -> np.ndarray:
        if state not in self._P:
            self._expand(state)
        if root_noise and self.eps > 0:
            legal = self.game.legal_actions(state)
            k = int(legal.sum())
            noise = np.zeros(self.game.num_actions)
            noise[legal] = self.rng.dirichlet([self.alpha] * k)
            self._P[state] = ((1 - self.eps) * self._P[state]
                              + self.eps * noise)
        for _ in range(num_simulations):
            self._simulate(state)
        return self._N[state].copy()


def play_selfplay_game(game: Game, predict, *, num_simulations: int,
                       c_puct: float, dirichlet_alpha: float,
                       root_noise_eps: float, temperature_moves: int,
                       rng: np.random.Generator
                       ) -> Tuple[List[Tuple[np.ndarray, np.ndarray, float]],
                                  int]:
    """One self-play game -> ([(obs, pi, z)], moves). Shared by the local
    AlphaZero loop and LeelaChessZero's remote self-play workers."""
    state = game.initial_state()
    history: List[Tuple[np.ndarray, np.ndarray]] = []
    move = 0
    while True:
        tv = game.terminal_value(state)
        if tv is not None:
            # tv is for the player to move at the terminal state; walk
            # back alternating signs
            examples = []
            z = tv
            for obs, pi in reversed(history):
                z = -z
                examples.append((obs, pi, z))
            return examples, move
        # fresh tree per move: visit counts from earlier searches ran
        # under that root's Dirichlet noise and must not leak into this
        # move's policy target
        mcts = MCTS(game, predict, c_puct, dirichlet_alpha,
                    root_noise_eps, rng)
        visits = mcts.search(state, num_simulations)
        pi = visits / visits.sum()
        if move < temperature_moves:
            a = int(rng.choice(len(pi), p=pi))
        else:
            a = int(np.argmax(visits))
        history.append((game.encode(state), pi))
        state = game.next_state(state, a)
        move += 1


class AlphaZeroConfig(AlgorithmConfig):
    def __init__(self, **kwargs):
        super().__init__(algo_class=AlphaZero, **kwargs)
        self.env = "tictactoe"
        self.lr = 5e-3
        self.num_simulations = 32
        self.games_per_iter = 16
        self.c_puct = 1.5
        self.dirichlet_alpha = 0.5
        self.root_noise_eps = 0.25
        self.temperature_moves = 2   # sample ~ N^(1/T) for the first moves
        self.buffer_size = 4_096
        self.minibatch_size = 128
        self.num_epochs = 2
        self.vf_coeff = 1.0


GAMES = {"tictactoe": TicTacToe}  # lc0.py registers connect4


def make_game(name_or_game) -> Game:
    if isinstance(name_or_game, Game):
        return name_or_game
    if name_or_game in GAMES:
        return GAMES[name_or_game]()
    raise ValueError(f"unknown game {name_or_game!r}")


class AlphaZero(Trainable):
    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        return AlphaZeroConfig()

    def setup(self, config: Dict[str, Any]) -> None:
        if "__algo_config" in config:
            self.config: AlgorithmConfig = config["__algo_config"]
        else:
            self.config = AlphaZeroConfig().update_from_dict(config)
        cfg = self.config
        self.game = make_game(cfg.env)
        A, D = self.game.num_actions, self.game.obs_dim
        k_pi, k_v = jax.random.split(jax.random.key(cfg.seed))
        params = {
            "pi": models.init_mlp(k_pi, (D,) + tuple(cfg.hidden) + (A,)),
            "v": models.init_mlp(k_v, (D,) + tuple(cfg.hidden) + (1,),
                                 out_scale=0.1),
        }
        vf_coeff = cfg.vf_coeff

        def loss_fn(p, batch, key):
            del key
            logits = models.mlp_forward(p["pi"], batch["obs"])
            logp = jax.nn.log_softmax(logits, axis=-1)
            pi_loss = -jnp.mean(jnp.sum(batch["pi"] * logp, axis=-1))
            v = jnp.tanh(models.mlp_forward(p["v"], batch["obs"])[..., 0])
            v_loss = jnp.mean((v - batch["z"]) ** 2)
            return pi_loss + vf_coeff * v_loss, \
                {"pi_loss": pi_loss, "v_loss": v_loss}

        self.learner = Learner(params, loss_fn, cfg.lr,
                               grad_clip=cfg.grad_clip, seed=cfg.seed)

        @jax.jit
        def _predict(p, obs):
            logits = models.mlp_forward(p["pi"], obs)
            value = jnp.tanh(models.mlp_forward(p["v"], obs)[..., 0])
            return jax.nn.softmax(logits, axis=-1), value

        self._jit_predict = _predict
        self._rng = np.random.default_rng(cfg.seed)
        self._buf: List[Tuple[np.ndarray, np.ndarray, float]] = []
        self._env_steps_total = 0

    # -- inference helpers ------------------------------------------------

    def _predict_fn(self):
        params = self.learner.get_params()

        def predict(obs: np.ndarray):
            pri, val = self._jit_predict(params, jnp.asarray(obs[None]))
            return np.asarray(pri)[0], float(np.asarray(val)[0])

        return predict

    def policy_action(self, state, num_simulations: Optional[int] = None,
                      greedy: bool = True) -> int:
        """Act with the current net + MCTS (no root noise) — the
        evaluation/serving entry."""
        cfg = self.config
        mcts = MCTS(self.game, self._predict_fn(), cfg.c_puct,
                    noise_eps=0.0, rng=self._rng)
        visits = mcts.search(state, num_simulations or cfg.num_simulations,
                             root_noise=False)
        if greedy:
            return int(np.argmax(visits))
        probs = visits / visits.sum()
        return int(self._rng.choice(len(probs), p=probs))

    # -- self-play --------------------------------------------------------

    def _self_play_game(self) -> Tuple[List, int]:
        cfg = self.config
        return play_selfplay_game(
            self.game, self._predict_fn(),
            num_simulations=cfg.num_simulations, c_puct=cfg.c_puct,
            dirichlet_alpha=cfg.dirichlet_alpha,
            root_noise_eps=cfg.root_noise_eps,
            temperature_moves=cfg.temperature_moves, rng=self._rng)

    # -- Trainable API ----------------------------------------------------

    def step(self) -> Dict[str, Any]:
        cfg = self.config
        outcomes = []
        for _ in range(cfg.games_per_iter):
            examples, moves = self._self_play_game()
            self._buf.extend(examples)
            self._env_steps_total += moves
            # examples[-1] is the first position: z from player-1's view
            outcomes.append(examples[-1][2])
        self._buf = self._buf[-cfg.buffer_size:]
        obs = np.stack([e[0] for e in self._buf])
        pis = np.stack([e[1] for e in self._buf])
        zs = np.asarray([e[2] for e in self._buf], dtype=np.float32)
        metrics = self.learner.update(
            {"obs": obs, "pi": pis, "z": zs},
            num_epochs=cfg.num_epochs,
            minibatch_size=min(cfg.minibatch_size, len(zs)),
            seed=cfg.seed + self._iteration)
        metrics["buffer_size"] = len(self._buf)
        metrics["draw_rate"] = float(np.mean(np.asarray(outcomes) == 0.0))
        metrics["env_steps_total"] = self._env_steps_total
        return metrics

    def evaluate(self, num_episodes: int = 10) -> Dict[str, Any]:
        """Score against a uniform-random opponent, alternating first
        move; win=1, draw=0.5, loss=0 (a competent player scores ~1)."""
        rng = np.random.default_rng(self.config.seed + 4242)
        score = 0.0
        for g in range(num_episodes):
            state = self.game.initial_state()
            az_turn = g % 2 == 0
            while True:
                tv = self.game.terminal_value(state)
                if tv is not None:
                    val = -tv if not az_turn else tv
                    score += {1.0: 1.0, 0.0: 0.5, -1.0: 0.0}[val]
                    break
                if az_turn:
                    a = self.policy_action(state, greedy=True)
                else:
                    legal = np.nonzero(self.game.legal_actions(state))[0]
                    a = int(rng.choice(legal))
                state = self.game.next_state(state, a)
                az_turn = not az_turn
        return {"episodes": num_episodes,
                "episode_return_mean": score / max(1, num_episodes)}

    # -- checkpointing ----------------------------------------------------

    def save_checkpoint(self, checkpoint_dir: str) -> Optional[Dict]:
        return {"params": jax.tree_util.tree_map(
            np.asarray, self.learner.get_params()),
            "env_steps_total": self._env_steps_total}

    def load_checkpoint(self, checkpoint: Dict) -> None:
        self.learner.set_params(checkpoint["params"])
        self._env_steps_total = checkpoint.get("env_steps_total", 0)
