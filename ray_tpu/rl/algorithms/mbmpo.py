"""MBMPO: model-based meta-policy optimization (Clavera et al. 2018).

Reference analog: ``rllib/algorithms/mbmpo/`` (``mbmpo.py``,
``model_ensemble.py``): learn an ensemble of K transition-dynamics models
from real rollouts, then treat each model as a MAML "task" — meta-learn a
policy that adapts to any member in one inner PG step, making it robust to
model error. TPU-first redesign:

- the K models are ONE weight-stacked MLP trained by a single jitted
  ``vmap``-over-members update (batched matmuls on the MXU) instead of the
  reference's K torch nets stepped in Python loops
  (``model_ensemble.py:TDModel`` + per-model fit loops).
- each model predicts (normalized delta-obs, reward). Learning the reward
  head removes the reference's requirement that envs expose a hand-coded
  ``reward()`` (``mbmpo.py`` hard-restricts to specially wrapped envs).
- imagination, inner adaptation, and the second-order meta-gradient run
  inside ONE compiled program: imagined rollouts are ``lax.scan`` over the
  model, tasks (= ensemble members) are ``vmap``-ed, the meta-gradient is
  ``jax.grad`` through the inner update (the same estimator as
  ``maml.py`` — sampling dependence ignored, batches stop-gradiented).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl import models
from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.env import make_env
from ray_tpu.tune.trainable import Trainable


class MBMPOConfig(AlgorithmConfig):
    def __init__(self, **kwargs):
        super().__init__(algo_class=MBMPO, **kwargs)
        self.env = "Pendulum-v1"
        self.num_envs_per_runner = 16   # real-env vector width
        self.real_steps_per_iter = 400  # real transitions collected / iter
        self.buffer_size = 100_000
        # dynamics ensemble
        self.ensemble_size = 5
        self.model_hidden = (256, 256)
        self.model_lr = 1e-3
        self.model_epochs = 5
        self.model_batch = 256
        self.val_frac = 0.1
        # imagination + MAML
        self.imag_horizon = 16
        self.imag_envs = 32
        self.inner_lr = 0.1
        self.inner_steps = 1
        self.meta_steps_per_iter = 8    # MAML outer steps per fitted
        # ensemble (reference: maml_optimizer_steps)
        self.lr = 3e-4                  # meta (outer) learning rate
        self.hidden = (64, 64)
        self.exploration_noise = 0.5    # std of the gaussian policy at init


class MBMPO(Trainable):
    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        return MBMPOConfig()

    def setup(self, config: Dict[str, Any]) -> None:
        if "__algo_config" in config:
            self.config: AlgorithmConfig = config["__algo_config"]
        else:
            self.config = MBMPOConfig().update_from_dict(config)
        cfg = self.config
        self.env = make_env(cfg.env, cfg.num_envs_per_runner,
                            cfg.env_config, seed=cfg.seed)
        spec = self.env.spec
        if spec.action_dim == 0:
            raise ValueError("MBMPO needs a continuous-action env "
                             f"({cfg.env!r} is discrete)")
        D, A, K = spec.obs_dim, spec.action_dim, cfg.ensemble_size
        self._D, self._A = D, A
        self._low = np.asarray(spec.action_low, dtype=np.float32)
        self._high = np.asarray(spec.action_high, dtype=np.float32)
        self._rng = np.random.default_rng(cfg.seed)
        self._key = jax.random.key(cfg.seed + 1)
        self._env_steps_total = 0

        # -- policy (gaussian MLP, as maml.py) ----------------------------
        k_pi = jax.random.key(cfg.seed)
        self.params = {
            "pi": models.init_mlp(k_pi, (D, *cfg.hidden, A), out_scale=0.01),
            "log_std": jnp.full((A,), float(np.log(cfg.exploration_noise))),
        }
        import optax

        self._opt = optax.adam(cfg.lr)
        self._opt_state = self._opt.init(self.params)

        # -- dynamics ensemble: weight-stacked [K, ...] MLPs --------------
        def init_model(key):
            return models.init_mlp(key, (D + A, *cfg.model_hidden, D + 1),
                                   out_scale=0.1)

        mkeys = jax.random.split(jax.random.key(cfg.seed + 7), K)
        self.model_params = jax.vmap(init_model)(mkeys)
        self._model_opt = optax.adam(cfg.model_lr)
        self._model_opt_state = self._model_opt.init(self.model_params)

        # identity normalizers until real data arrives
        self._norm = {
            "x_mean": np.zeros(D + A, np.float32),
            "x_std": np.ones(D + A, np.float32),
            "y_mean": np.zeros(D + 1, np.float32),
            "y_std": np.ones(D + 1, np.float32),
        }
        self._buf: Dict[str, list] = {k: [] for k in
                                      ("obs", "act", "delta", "rew")}

        gamma = cfg.gamma
        inner_lr, inner_steps = cfg.inner_lr, cfg.inner_steps
        H, N = cfg.imag_horizon, cfg.imag_envs
        low = jnp.asarray(self._low)
        high = jnp.asarray(self._high)

        def model_fwd(mp, x_norm):
            return models.mlp_forward(mp, x_norm)

        def model_loss(mp, x, y):
            pred = model_fwd(mp, x)
            return jnp.mean((pred - y) ** 2)

        @jax.jit
        def model_update(mparams, mopt, xs, ys):
            """One SGD step for ALL K members at once; xs/ys are
            per-member bootstrap minibatches [K, B, ...]."""
            def per_member(mp, x, y):
                return jax.value_and_grad(model_loss)(mp, x, y)

            losses, grads = jax.vmap(per_member)(mparams, xs, ys)
            updates, mopt = self._model_opt.update(grads, mopt, mparams)
            import optax as _optax

            mparams = _optax.apply_updates(mparams, updates)
            return mparams, mopt, jnp.mean(losses)

        self._model_update = model_update
        self._model_val_loss = jax.jit(
            jax.vmap(model_loss, in_axes=(0, None, None)))

        def act_mean_noise(p, obs, key):
            mean = models.mlp_forward(p["pi"], obs)
            a = mean + jnp.exp(p["log_std"]) \
                * jax.random.normal(key, mean.shape)
            return jnp.clip(a, low, high)

        self._act = jax.jit(act_mean_noise)

        def imagine(p, mp, norm, start_obs, key):
            """H-step rollout inside model ``mp`` from real start states.
            Policy params are stop-gradiented — sampling dependence is
            not differentiated (the maml.py estimator)."""
            p = jax.lax.stop_gradient(p)

            def step(carry, key_t):
                obs = carry
                a = act_mean_noise(p, obs, key_t)
                x = (jnp.concatenate([obs, a], -1) - norm["x_mean"]) \
                    / norm["x_std"]
                y = model_fwd(mp, x) * norm["y_std"] + norm["y_mean"]
                nobs = obs + y[..., :-1]
                rew = y[..., -1]
                return nobs, (obs, a, rew)

            keys = jax.random.split(key, H)
            _, (obs_t, act_t, rew_t) = jax.lax.scan(step, start_obs, keys)

            def disc(acc, r):
                acc = r + gamma * acc
                return acc, acc

            _, rets = jax.lax.scan(disc, jnp.zeros(N), rew_t, reverse=True)
            batch = {"obs": obs_t.reshape(H * N, -1),
                     "acts": act_t.reshape(H * N, -1),
                     "returns": rets.reshape(H * N)}
            return jax.lax.stop_gradient(batch), jnp.mean(rew_t)

        def pg_loss(p, batch):
            mean = models.mlp_forward(p["pi"], batch["obs"])
            logp = models.gaussian_logp(mean, p["log_std"], batch["acts"])
            ret = batch["returns"]
            ret = (ret - ret.mean()) / (ret.std() + 1e-8)
            return -jnp.mean(logp * ret)

        def adapt(p, batch):
            for _ in range(inner_steps):
                g = jax.grad(pg_loss)(p, batch)
                p = jax.tree_util.tree_map(
                    lambda w, gw: w - inner_lr * gw, p, g)
            return p

        def task_loss(p, mp, norm, start_obs, key):
            k1, k2 = jax.random.split(key)
            pre, pre_rew = imagine(p, mp, norm, start_obs, k1)
            p_ad = adapt(p, pre)
            post, post_rew = imagine(p_ad, mp, norm, start_obs, k2)
            return pg_loss(p_ad, post), (pre_rew, post_rew)

        def meta_loss(p, mparams, norm, start_obs, keys):
            losses, (pre, post) = jax.vmap(
                task_loss, in_axes=(None, 0, None, None, 0))(
                p, mparams, norm, start_obs, keys)
            return jnp.mean(losses), (jnp.mean(pre), jnp.mean(post))

        self._meta_grad = jax.jit(
            jax.value_and_grad(meta_loss, has_aux=True))

        @jax.jit
        def apply_meta(p, opt_state, grads):
            import optax as _optax

            updates, opt_state = self._opt.update(grads, opt_state, p)
            return _optax.apply_updates(p, updates), opt_state

        self._apply_meta = apply_meta
        self._adapt = jax.jit(adapt)

    # -- real-env interaction ---------------------------------------------

    def _collect_real(self, n_steps: int) -> float:
        cfg = self.config
        obs = self.env.reset() if not self._buf["obs"] else self._last_obs
        rew_sum, count = 0.0, 0
        steps = max(1, n_steps // self.env.num_envs)
        for _ in range(steps):
            self._key, sub = jax.random.split(self._key)
            acts = np.asarray(self._act(self.params, jnp.asarray(obs), sub))
            nobs, rew, dones = self.env.step(acts)
            # a done row's next_obs is the RESET obs — its delta is not a
            # dynamics transition; drop those rows from the model dataset
            keep = ~dones
            self._buf["obs"].append(obs[keep])
            self._buf["act"].append(
                acts[keep].reshape(int(keep.sum()), self._A))
            self._buf["delta"].append((nobs - obs)[keep])
            self._buf["rew"].append(rew[keep])
            rew_sum += float(rew.sum())
            count += rew.size
            obs = nobs
        self._last_obs = obs
        self._env_steps_total += count
        # trim ring
        total = sum(len(a) for a in self._buf["obs"])
        while total > cfg.buffer_size and len(self._buf["obs"]) > 1:
            total -= len(self._buf["obs"][0])
            for k in self._buf:
                self._buf[k].pop(0)
        return rew_sum / max(1, count)

    def _dataset(self) -> Tuple[np.ndarray, np.ndarray]:
        obs = np.concatenate(self._buf["obs"])
        act = np.concatenate(self._buf["act"])
        delta = np.concatenate(self._buf["delta"])
        rew = np.concatenate(self._buf["rew"])[:, None]
        x = np.concatenate([obs, act], -1).astype(np.float32)
        y = np.concatenate([delta, rew], -1).astype(np.float32)
        return x, y

    def _fit_ensemble(self) -> Dict[str, float]:
        cfg = self.config
        x, y = self._dataset()
        self._norm = {
            "x_mean": x.mean(0), "x_std": x.std(0) + 1e-6,
            "y_mean": y.mean(0), "y_std": y.std(0) + 1e-6,
        }
        xn = (x - self._norm["x_mean"]) / self._norm["x_std"]
        yn = (y - self._norm["y_mean"]) / self._norm["y_std"]
        n = len(xn)
        n_val = max(1, int(n * cfg.val_frac))
        perm = self._rng.permutation(n)
        val_idx, train_idx = perm[:n_val], perm[n_val:]
        B = min(cfg.model_batch, len(train_idx))
        K = cfg.ensemble_size
        steps = max(1, len(train_idx) // B) * cfg.model_epochs
        loss = 0.0
        for _ in range(steps):
            # per-member bootstrap minibatches decorrelate the ensemble
            idx = self._rng.choice(train_idx, size=(K, B))
            self.model_params, self._model_opt_state, ls = \
                self._model_update(self.model_params,
                                   self._model_opt_state,
                                   jnp.asarray(xn[idx]),
                                   jnp.asarray(yn[idx]))
            loss = float(ls)
        val = self._model_val_loss(self.model_params,
                                   jnp.asarray(xn[val_idx]),
                                   jnp.asarray(yn[val_idx]))
        return {"model_train_loss": loss,
                "model_val_loss": float(jnp.mean(val)),
                "model_val_worst": float(jnp.max(val)),
                "dataset_size": n}

    # -- Trainable API ----------------------------------------------------

    def step(self) -> Dict[str, Any]:
        cfg = self.config
        mean_rew = self._collect_real(cfg.real_steps_per_iter)
        model_metrics = self._fit_ensemble()
        # meta-updates from real start states (several MAML outer steps
        # per fitted ensemble, reference: maml_optimizer_steps)
        obs_pool = np.concatenate(self._buf["obs"])[-4096:]
        norm = {k: jnp.asarray(v) for k, v in self._norm.items()}
        for _ in range(max(1, cfg.meta_steps_per_iter)):
            start = obs_pool[self._rng.integers(0, len(obs_pool),
                                                size=cfg.imag_envs)]
            self._key, sub = jax.random.split(self._key)
            keys = jax.random.split(sub, cfg.ensemble_size)
            (loss, (pre, post)), grads = self._meta_grad(
                self.params, self.model_params, norm,
                jnp.asarray(start), keys)
            self.params, self._opt_state = self._apply_meta(
                self.params, self._opt_state, grads)
        ep_len = getattr(self.env, "_max_t", 200)
        return {"meta_loss": float(loss),
                "imag_pre_adapt_reward": float(pre),
                "imag_post_adapt_reward": float(post),
                "imag_adaptation_gain": float(post) - float(pre),
                "real_reward_per_step": mean_rew,
                "mean_return": mean_rew * ep_len,
                "env_steps_total": self._env_steps_total,
                **model_metrics}

    def evaluate(self, num_episodes: int = 4) -> Dict[str, float]:
        """Real-env return of the CURRENT meta-policy (fresh env, training
        stream untouched)."""
        cfg = self.config
        env = make_env(cfg.env, cfg.num_envs_per_runner, cfg.env_config,
                       seed=cfg.seed + 999)
        horizon = getattr(env, "_max_t", 200)
        key = jax.random.key(cfg.seed + 31337)
        obs = env.reset()
        total = 0.0
        for _ in range(horizon):
            key, sub = jax.random.split(key)
            acts = np.asarray(self._act(self.params, jnp.asarray(obs), sub))
            obs, rew, _ = env.step(acts)
            total += float(rew.mean())
        return {"episode_return_mean": total,
                "episodes": env.num_envs}

    # -- checkpointing ----------------------------------------------------

    def save_checkpoint(self, checkpoint_dir: str) -> Optional[Dict]:
        return {"params": jax.tree_util.tree_map(np.asarray, self.params),
                "model_params": jax.tree_util.tree_map(
                    np.asarray, self.model_params),
                "norm": self._norm,
                "env_steps_total": self._env_steps_total}

    def load_checkpoint(self, checkpoint: Dict) -> None:
        self.params = jax.tree_util.tree_map(jnp.asarray,
                                             checkpoint["params"])
        self.model_params = jax.tree_util.tree_map(
            jnp.asarray, checkpoint["model_params"])
        self._norm = checkpoint["norm"]
        self._env_steps_total = checkpoint.get("env_steps_total", 0)

    def cleanup(self) -> None:
        pass

    def stop(self) -> None:
        self.cleanup()
