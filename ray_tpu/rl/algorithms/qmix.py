"""QMIX: cooperative multi-agent Q-learning with monotonic value mixing.

Reference analog: ``rllib/algorithms/qmix/`` (Rashid et al. 2018). Each
agent has a utility network Q_a(obs_a, ·) (one weight-shared MLP with an
agent-id one-hot appended to the observation — the standard parameter
sharing); a mixing network combines the chosen utilities into Q_tot under
a monotonicity constraint: the mixer's weights are produced by
hypernetworks of the global state and passed through ``abs``, so
dQ_tot/dQ_a >= 0 and the per-agent argmax equals the joint argmax.

Runs in-process on the ``MultiAgentEnv`` protocol (rl/multi_agent.py),
with transition replay, epsilon-greedy exploration, and periodically
synced target networks. The global state is the concatenation of all
agents' observations (the usual choice when the env exposes no separate
state).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl import models
from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.learner import Learner
from ray_tpu.rl.multi_agent import _MA_ENVS, MultiAgentEnv
from ray_tpu.tune.trainable import Trainable


class QMIXConfig(AlgorithmConfig):
    def __init__(self, **kwargs):
        super().__init__(algo_class=QMIX, **kwargs)
        self.env = "coordination"
        self.lr = 5e-4
        self.minibatch_size = 64
        self.buffer_size = 50_000
        self.learning_starts = 500
        self.target_update_freq = 200    # in gradient updates
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_decay_steps = 5_000
        self.mixing_embed_dim = 32
        self.updates_per_iter = 32


def _init_mixer(key, n_agents: int, state_dim: int, embed: int) -> Dict:
    """Hypernetworks state -> mixer weights (abs applied in the forward
    pass, not here): w1 [state,n*embed], b1 [state,embed], w2 [state,embed],
    and a 2-layer value head for the final bias."""
    k1, k2, k3, k4 = jax.random.split(key, 4)
    return {
        "hw1": models.init_mlp(k1, (state_dim, n_agents * embed),
                               out_scale=0.1),
        "hb1": models.init_mlp(k2, (state_dim, embed), out_scale=0.1),
        "hw2": models.init_mlp(k3, (state_dim, embed), out_scale=0.1),
        "hb2": models.init_mlp(k4, (state_dim, embed, 1), out_scale=0.1),
    }


def _mix(mixer: Dict, qs: jnp.ndarray, state: jnp.ndarray) -> jnp.ndarray:
    """qs [B, n_agents], state [B, state_dim] -> Q_tot [B]."""
    b, n = qs.shape
    w1 = jnp.abs(models.mlp_forward(mixer["hw1"], state))   # [B, n*e]
    e = w1.shape[-1] // n
    w1 = w1.reshape(b, n, e)
    b1 = models.mlp_forward(mixer["hb1"], state)            # [B, e]
    hidden = jax.nn.elu(jnp.einsum("bn,bne->be", qs, w1) + b1)
    w2 = jnp.abs(models.mlp_forward(mixer["hw2"], state))   # [B, e]
    b2 = models.mlp_forward(mixer["hb2"], state)[..., 0]    # [B]
    return jnp.sum(hidden * w2, axis=-1) + b2


class QMIX(Trainable):
    """Centralized-training / decentralized-execution cooperative MARL."""

    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        return QMIXConfig()

    def setup(self, config: Dict[str, Any]) -> None:
        if "__algo_config" in config:
            self.config: AlgorithmConfig = config["__algo_config"]
        else:
            self.config = QMIXConfig().update_from_dict(config)
        cfg = self.config
        ctor = _MA_ENVS[cfg.env] if isinstance(cfg.env, str) else cfg.env
        self.env: MultiAgentEnv = ctor(num_envs=cfg.num_envs_per_runner,
                                       **(cfg.env_config or {}))
        self.agents = list(self.env.agents)
        n = len(self.agents)
        specs = [self.env.spec[a] for a in self.agents]
        if any(not s.discrete for s in specs):
            raise ValueError("QMIX requires discrete actions")
        # shared agent net over (obs ++ agent one-hot); pad obs to the max
        # dim so heterogeneous agents share one tower
        self._obs_dims = [s.obs_dim for s in specs]
        self._max_obs = max(self._obs_dims)
        self._agent_actions = [s.num_actions for s in specs]
        self._num_actions = max(self._agent_actions)
        # heterogeneous agents: rows of invalid action slots get -inf so
        # neither exploration argmax nor the TD-target max can pick them
        mask = np.zeros((n, self._num_actions), dtype=np.float32)
        for i, a_n in enumerate(self._agent_actions):
            mask[i, a_n:] = -np.inf
        self._action_mask = mask
        self._state_dim = sum(self._obs_dims)
        in_dim = self._max_obs + n
        k = jax.random.key(cfg.seed)
        k_agent, k_mix = jax.random.split(k)
        agent_net = models.init_mlp(
            k_agent, (in_dim,) + tuple(cfg.hidden) + (self._num_actions,))
        mixer = _init_mixer(k_mix, n, self._state_dim, cfg.mixing_embed_dim)
        params = {"agent": agent_net, "mixer": mixer,
                  "target_agent": jax.tree_util.tree_map(
                      jnp.array, agent_net),
                  "target_mixer": jax.tree_util.tree_map(jnp.array, mixer)}
        gamma = cfg.gamma
        eye = jnp.eye(n, dtype=jnp.float32)
        act_mask = jnp.asarray(mask)

        def agent_qs(net, obs):
            """obs [B, n, max_obs] -> per-agent Q [B, n, A]; invalid
            action slots are -inf."""
            bsz = obs.shape[0]
            ids = jnp.broadcast_to(eye, (bsz, n, n))
            x = jnp.concatenate([obs, ids], axis=-1)
            return models.mlp_forward(net, x) + act_mask

        def loss_fn(p, batch, key):
            del key
            q = agent_qs(p["agent"], batch["obs"])          # [B, n, A]
            q_taken = jnp.take_along_axis(
                q, batch["actions"][..., None].astype(jnp.int32),
                axis=-1)[..., 0]                            # [B, n]
            q_tot = _mix(p["mixer"], q_taken, batch["state"])
            q_next = agent_qs(p["target_agent"], batch["next_obs"])
            q_next_max = jnp.max(q_next, axis=-1)           # [B, n]
            q_tot_next = _mix(p["target_mixer"], q_next_max,
                              batch["next_state"])
            nonterminal = 1.0 - batch["dones"].astype(jnp.float32)
            target = batch["rewards"] + gamma * nonterminal \
                * jax.lax.stop_gradient(q_tot_next)
            td = q_tot - target
            loss = jnp.mean(td ** 2)
            return loss, {"td_abs_mean": jnp.mean(jnp.abs(td)),
                          "q_tot_mean": jnp.mean(q_tot)}

        self.learner = Learner(params, loss_fn, cfg.lr,
                               grad_clip=cfg.grad_clip, seed=cfg.seed)
        self._agent_qs = jax.jit(
            lambda net, obs: agent_qs(net, obs))
        # replay storage (flat transitions across the vector envs)
        self._buf: Dict[str, List[np.ndarray]] = \
            {k: [] for k in ("obs", "actions", "rewards", "dones",
                             "state", "next_obs", "next_state")}
        self._buf_len = 0
        self._rng = np.random.default_rng(cfg.seed)
        self._obs = self.env.reset()
        self._env_steps_total = 0
        self._grad_updates = 0
        self._return_window: List[float] = []
        self._ep_return = np.zeros(self.env.num_envs, dtype=np.float64)

    # -- rollout ----------------------------------------------------------

    def _stack_obs(self, obs: Dict[str, np.ndarray]) -> np.ndarray:
        """dict -> [N, n_agents, max_obs] (zero-padded)."""
        n_envs = self.env.num_envs
        out = np.zeros((n_envs, len(self.agents), self._max_obs),
                       dtype=np.float32)
        for i, a in enumerate(self.agents):
            out[:, i, :self._obs_dims[i]] = obs[a]
        return out

    def _state_of(self, obs: Dict[str, np.ndarray]) -> np.ndarray:
        return np.concatenate([obs[a] for a in self.agents],
                              axis=-1).astype(np.float32)

    @property
    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self._env_steps_total
                   / max(1, cfg.epsilon_decay_steps))
        return cfg.epsilon_initial \
            + frac * (cfg.epsilon_final - cfg.epsilon_initial)

    def _collect(self, steps: int) -> float:
        cfg = self.config
        n_envs = self.env.num_envs
        reward_sum = 0.0
        for _ in range(steps):
            stacked = self._stack_obs(self._obs)
            q = np.asarray(self._agent_qs(
                self.learner.get_params()["agent"], jnp.asarray(stacked)))
            greedy = np.argmax(q, axis=-1)                  # [N, n]
            eps_mask = self._rng.random(greedy.shape) < self._epsilon
            rand = np.stack([self._rng.integers(0, a_n, n_envs)
                             for a_n in self._agent_actions], axis=1)
            chosen = np.where(eps_mask, rand, greedy)
            acts = {a: chosen[:, i].astype(np.int64)
                    for i, a in enumerate(self.agents)}
            next_obs, rewards, dones = self.env.step(acts)
            team_r = np.mean([rewards[a] for a in self.agents],
                             axis=0).astype(np.float32)
            self._buf["obs"].append(stacked)
            self._buf["actions"].append(chosen.astype(np.int64))
            self._buf["rewards"].append(team_r)
            self._buf["dones"].append(dones.astype(np.float32))
            self._buf["state"].append(self._state_of(self._obs))
            self._buf["next_obs"].append(self._stack_obs(next_obs))
            self._buf["next_state"].append(self._state_of(next_obs))
            self._buf_len += n_envs
            self._env_steps_total += n_envs
            reward_sum += float(team_r.sum())
            self._ep_return += team_r
            for i in np.nonzero(dones)[0]:
                self._return_window.append(float(self._ep_return[i]))
                self._ep_return[i] = 0.0
            self._obs = next_obs
            # bound the buffer
            max_rows = max(1, cfg.buffer_size // n_envs)
            for key in self._buf:
                if len(self._buf[key]) > max_rows:
                    del self._buf[key][:len(self._buf[key]) - max_rows]
            self._buf_len = min(self._buf_len,
                                max_rows * n_envs)
        self._return_window = self._return_window[-100:]
        return reward_sum / max(1, steps * n_envs)

    def _sample_batch(self, arrays: Dict[str, np.ndarray],
                      size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, len(arrays["rewards"]), size)
        return {k: v[idx] for k, v in arrays.items()}

    # -- Trainable API ----------------------------------------------------

    def step(self) -> Dict[str, Any]:
        cfg = self.config
        mean_step_r = self._collect(cfg.rollout_fragment_length)
        metrics: Dict[str, Any] = {"reward_mean_per_step": mean_step_r,
                                   "epsilon": self._epsilon}
        if self._buf_len >= cfg.learning_starts:
            updates = cfg.updates_per_iter or 1
            mlist = []
            # one concatenation per step(), not per minibatch draw
            arrays = {k: np.concatenate(v) for k, v in self._buf.items()}
            for _ in range(updates):
                mb = self._sample_batch(arrays, cfg.minibatch_size)
                mlist.append(self.learner.update_minibatch(mb))
                self._grad_updates += 1
                if self._grad_updates % cfg.target_update_freq == 0:
                    p = self.learner.get_params()
                    p = dict(p)
                    p["target_agent"] = jax.tree_util.tree_map(
                        jnp.array, p["agent"])
                    p["target_mixer"] = jax.tree_util.tree_map(
                        jnp.array, p["mixer"])
                    self.learner.set_params(p)
            for k in mlist[0]:
                metrics[k] = float(np.mean([float(m[k]) for m in mlist]))
        metrics["env_steps_total"] = self._env_steps_total
        if self._return_window:
            metrics["episode_return_mean"] = float(
                np.mean(self._return_window))
        return metrics

    def evaluate(self, num_episodes: int = 10) -> Dict[str, Any]:
        """Greedy (epsilon=0) episodes on a fresh env instance."""
        cfg = self.config
        ctor = _MA_ENVS[cfg.env] if isinstance(cfg.env, str) else cfg.env
        env: MultiAgentEnv = ctor(num_envs=cfg.num_envs_per_runner,
                                  **(cfg.env_config or {}))
        obs = env.reset()
        done_returns: List[float] = []
        ep_ret = np.zeros(env.num_envs, dtype=np.float64)
        params = self.learner.get_params()["agent"]
        for _ in range(4096):
            stacked = self._stack_obs(obs)
            q = np.asarray(self._agent_qs(params, jnp.asarray(stacked)))
            chosen = np.argmax(q, axis=-1)
            acts = {a: chosen[:, i].astype(np.int64)
                    for i, a in enumerate(self.agents)}
            obs, rewards, dones = env.step(acts)
            ep_ret += np.mean([rewards[a] for a in self.agents], axis=0)
            for i in np.nonzero(dones)[0]:
                done_returns.append(float(ep_ret[i]))
                ep_ret[i] = 0.0
            if len(done_returns) >= num_episodes:
                break
        return {"episodes": len(done_returns),
                "episode_return_mean": float(np.mean(done_returns))
                if done_returns else float("nan")}

    # -- checkpointing ----------------------------------------------------

    def save_checkpoint(self, checkpoint_dir: str) -> Optional[Dict]:
        return {"params": jax.tree_util.tree_map(
            np.asarray, self.learner.get_params()),
            "env_steps_total": self._env_steps_total}

    def load_checkpoint(self, checkpoint: Dict) -> None:
        self.learner.set_params(checkpoint["params"])
        self._env_steps_total = checkpoint.get("env_steps_total", 0)
