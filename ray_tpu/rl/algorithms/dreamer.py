"""DreamerV3 (compact): world-model RL with imagination training.

Reference analog: ``rllib/algorithms/dreamerv3/`` (Hafner et al. 2023).
The full architecture at reduced width, faithful to the v3 recipe where
it matters:

- **RSSM world model**: GRU deterministic state ``h`` + CATEGORICAL
  stochastic latent ``z`` (K groups x C classes, straight-through
  gradients, 1% unimix), posterior ``q(z|h, emb(obs))`` vs learned prior
  ``p(z|h)``; heads decode observation, reward, and episode-continue
  from ``(h, z)``.
- **KL balancing + free bits**: ``kl(sg(post)||prior)`` (dynamics) and
  ``0.1 * kl(post||sg(prior))`` (representation), each clipped below 1
  free nat — the v3 stabilization.
- **Imagination actor-critic**: from every posterior state of the
  training batch, roll the PRIOR forward ``imag_horizon`` steps with the
  actor; the critic regresses lambda-returns on the imagined
  trajectories, the actor takes the REINFORCE gradient (discrete
  actions, as v3 does) with advantages normalized by an EMA of the
  return percentile range, plus an entropy bonus.

Vector observations only (the TPU-relevant path here is the learner
loop, not Atari conv stacks); sequences are collected as fixed-length
chunks with ``is_first`` flags, replayed uniformly.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl import models
from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.env import make_env
from ray_tpu.tune.trainable import Trainable


class DreamerV3Config(AlgorithmConfig):
    def __init__(self, **kwargs):
        super().__init__(algo_class=DreamerV3, **kwargs)
        self.env = "CartPole-v1"
        self.lr = 3e-4                 # world-model lr
        self.actor_lr = 1e-4
        self.critic_lr = 1e-4
        self.hidden = (128,)           # head widths
        self.deter_dim = 128           # GRU state
        self.stoch_groups = 8          # K
        self.stoch_classes = 8         # C
        self.embed_dim = 128
        # replay chunks are rollout_fragment_length timesteps long
        self.batch_seqs = 16           # sequences per update
        self.imag_horizon = 10
        self.buffer_size = 50_000      # in timesteps
        self.learning_starts = 1_000
        self.updates_per_iter = 8
        self.entropy_coeff = 3e-3
        self.kl_dyn_scale = 1.0
        self.kl_rep_scale = 0.1
        self.free_nats = 1.0
        self.lambda_ = 0.95
        self.num_envs_per_runner = 8
        self.rollout_fragment_length = 16


def _mlp(key, dims, out_scale=1.0):
    return models.init_mlp(key, dims, out_scale=out_scale)


def _fwd(p, x):
    return models.mlp_forward(p, x)


def _gru_init(key, in_dim: int, h_dim: int) -> Dict:
    k1, k2 = jax.random.split(key)
    s_in = 1.0 / np.sqrt(in_dim)
    s_h = 1.0 / np.sqrt(h_dim)
    return {"wi": jax.random.normal(k1, (in_dim, 3 * h_dim)) * s_in,
            "wh": jax.random.normal(k2, (h_dim, 3 * h_dim)) * s_h,
            "b": jnp.zeros(3 * h_dim)}


def _gru(p, h, x):
    gi = x @ p["wi"] + p["b"]
    gh = h @ p["wh"]
    iz, ir, ia = jnp.split(gi, 3, axis=-1)
    hz, hr, ha = jnp.split(gh, 3, axis=-1)
    z = jax.nn.sigmoid(iz + hz)
    r = jax.nn.sigmoid(ir + hr)
    a = jnp.tanh(ia + r * ha)
    return (1 - z) * a + z * h


def _unimix(logits, classes: int, mix: float = 0.01):
    probs = jax.nn.softmax(logits, axis=-1)
    probs = (1 - mix) * probs + mix / classes
    return jnp.log(probs)


def _st_sample(key, logits):
    """Straight-through categorical over [..., K, C]."""
    idx = jax.random.categorical(key, logits)
    onehot = jax.nn.one_hot(idx, logits.shape[-1], dtype=logits.dtype)
    probs = jax.nn.softmax(logits, axis=-1)
    return onehot + probs - jax.lax.stop_gradient(probs)


def _kl_cat(logits_a, logits_b):
    """KL(a || b) per group, summed over groups: [..., K, C] -> [...]."""
    pa = jax.nn.softmax(logits_a, axis=-1)
    la = jax.nn.log_softmax(logits_a, axis=-1)
    lb = jax.nn.log_softmax(logits_b, axis=-1)
    return jnp.sum(pa * (la - lb), axis=(-2, -1))


class DreamerV3(Trainable):
    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        return DreamerV3Config()

    def setup(self, config: Dict[str, Any]) -> None:
        if "__algo_config" in config:
            self.config: AlgorithmConfig = config["__algo_config"]
        else:
            self.config = DreamerV3Config().update_from_dict(config)
        cfg = self.config
        self.env = make_env(cfg.env, cfg.num_envs_per_runner,
                            cfg.env_config)
        spec = self.env.spec
        if not spec.discrete or spec.is_pixel:
            raise ValueError("this DreamerV3 targets discrete actions "
                             "over vector observations")
        self.spec = spec
        D, K, C = cfg.deter_dim, cfg.stoch_groups, cfg.stoch_classes
        A = spec.num_actions
        Z = K * C
        feat = D + Z
        obs_dim = spec.obs_dim
        E = cfg.embed_dim
        H = cfg.imag_horizon
        lam, gamma = cfg.lambda_, cfg.gamma
        ent_coeff = cfg.entropy_coeff
        free = cfg.free_nats
        dyn_s, rep_s = cfg.kl_dyn_scale, cfg.kl_rep_scale

        keys = jax.random.split(jax.random.key(cfg.seed), 10)
        self.wm = {
            "enc": _mlp(keys[0], (obs_dim, E, E), out_scale=1.0),
            "gru": _gru_init(keys[1], Z + A, D),
            "prior": _mlp(keys[2], (D, *cfg.hidden, Z), out_scale=1.0),
            "post": _mlp(keys[3], (D + E, *cfg.hidden, Z), out_scale=1.0),
            "dec": _mlp(keys[4], (feat, *cfg.hidden, obs_dim),
                        out_scale=1.0),
            # reward/continue condition on the CURRENT action too: with
            # auto-reset vector envs the post-action observation of a
            # terminal step is unobtainable (it is replaced by the next
            # episode's reset obs), so r_t and cont_t — both functions of
            # (s_t, a_t) — are predicted from (h_t, z_t, a_t) instead of
            # Hafner's post-action-state pairing; every terminal cont=0
            # row stays correctly associated
            "rew": _mlp(keys[5], (feat + A, *cfg.hidden, 1),
                        out_scale=0.01),
            "cont": _mlp(keys[6], (feat + A, *cfg.hidden, 1),
                         out_scale=0.01),
        }
        self.actor = _mlp(keys[7], (feat, *cfg.hidden, A), out_scale=0.01)
        self.critic = _mlp(keys[8], (feat, *cfg.hidden, 1), out_scale=0.01)

        import optax

        self._wm_opt = optax.chain(optax.clip_by_global_norm(100.0),
                                   optax.adam(cfg.lr))
        self._a_opt = optax.chain(optax.clip_by_global_norm(100.0),
                                  optax.adam(cfg.actor_lr))
        self._c_opt = optax.chain(optax.clip_by_global_norm(100.0),
                                  optax.adam(cfg.critic_lr))
        self._wm_state = self._wm_opt.init(self.wm)
        self._a_state = self._a_opt.init(self.actor)
        self._c_state = self._c_opt.init(self.critic)

        def obs_step(wm, h, z_flat, a_onehot, emb, is_first, key):
            """One posterior RSSM step; resets state on episode starts."""
            keep = (1.0 - is_first)[..., None]
            h = h * keep
            z_flat = z_flat * keep
            a_onehot = a_onehot * keep
            h = _gru(wm["gru"], h, jnp.concatenate([z_flat, a_onehot],
                                                   axis=-1))
            prior_logits = _unimix(
                _fwd(wm["prior"], h).reshape(h.shape[0], K, C), C)
            post_logits = _unimix(
                _fwd(wm["post"], jnp.concatenate([h, emb], axis=-1))
                .reshape(h.shape[0], K, C), C)
            z = _st_sample(key, post_logits)
            return h, z.reshape(h.shape[0], Z), prior_logits, post_logits

        def wm_loss(wm, batch, key):
            """batch: obs [B,T,o], actions [B,T] (prev action one-hot is
            built inside), rewards [B,T], conts [B,T], is_first [B,T]."""
            B, T = batch["rewards"].shape
            emb = _fwd(wm["enc"], batch["obs"])              # [B,T,E]
            a_onehot = jax.nn.one_hot(batch["actions"], A)   # [B,T,A]
            # previous action enters the transition (a_{t-1} -> z_t)
            a_prev = jnp.concatenate(
                [jnp.zeros((B, 1, A)), a_onehot[:, :-1]], axis=1)
            ks = jax.random.split(key, T)

            def scan_fn(carry, t):
                h, z = carry
                h, z, prior_l, post_l = obs_step(
                    wm, h, z, a_prev[:, t], emb[:, t],
                    batch["is_first"][:, t], ks[t])
                return (h, z), (h, z, prior_l, post_l)

            (_, _), (hs, zs, prior_l, post_l) = jax.lax.scan(
                scan_fn, (jnp.zeros((B, D)), jnp.zeros((B, Z))),
                jnp.arange(T))
            # [T,B,...] -> [B,T,...]
            hs = jnp.swapaxes(hs, 0, 1)
            zs = jnp.swapaxes(zs, 0, 1)
            prior_l = jnp.swapaxes(prior_l, 0, 1)
            post_l = jnp.swapaxes(post_l, 0, 1)
            ft = jnp.concatenate([hs, zs], axis=-1)          # [B,T,feat]
            recon = _fwd(wm["dec"], ft)
            ft_a = jnp.concatenate([ft, a_onehot], axis=-1)  # current a_t
            rew_pred = _fwd(wm["rew"], ft_a)[..., 0]
            cont_logit = _fwd(wm["cont"], ft_a)[..., 0]
            recon_loss = jnp.mean(jnp.sum(
                (recon - batch["obs"]) ** 2, axis=-1))
            rew_loss = jnp.mean((rew_pred - batch["rewards"]) ** 2)
            cont_loss = jnp.mean(
                optax.sigmoid_binary_cross_entropy(
                    cont_logit, batch["conts"]))
            kl_dyn = jnp.maximum(
                _kl_cat(jax.lax.stop_gradient(post_l), prior_l),
                free).mean()
            kl_rep = jnp.maximum(
                _kl_cat(post_l, jax.lax.stop_gradient(prior_l)),
                free).mean()
            loss = recon_loss + rew_loss + cont_loss \
                + dyn_s * kl_dyn + rep_s * kl_rep
            states = (jax.lax.stop_gradient(hs.reshape(-1, D)),
                      jax.lax.stop_gradient(zs.reshape(-1, Z)))
            return loss, {"recon_loss": recon_loss, "rew_loss": rew_loss,
                          "cont_loss": cont_loss, "kl_dyn": kl_dyn,
                          "states": states}

        def imagine(wm, actor, h0, z0, key):
            """Prior rollout with the actor: [B*T] starts, H steps."""
            ks = jax.random.split(key, H)

            def scan_fn(carry, k):
                h, z = carry
                ft = jnp.concatenate([h, z], axis=-1)
                logits = _fwd(actor, ft)
                k1, k2 = jax.random.split(k)
                a = jax.random.categorical(k1, logits)
                a_oh = jax.nn.one_hot(a, A)
                h = _gru(wm["gru"], h, jnp.concatenate([z, a_oh],
                                                       axis=-1))
                prior_l = _unimix(
                    _fwd(wm["prior"], h).reshape(h.shape[0], K, C), C)
                z = _st_sample(k2, prior_l).reshape(h.shape[0], Z)
                return (h, z), (ft, a, logits)

            (_, _), (fts, acts, logits) = jax.lax.scan(
                scan_fn, (h0, z0), ks)
            return fts, acts, logits  # [H, B*T, ...]

        def actor_loss_fn(actor, critic, wm, h0, z0, key, ret_scale):
            """ONE imagination rollout serves both losses: the actor's
            REINFORCE term here, and (via the stop-gradient aux) the
            critic regression in train_step."""
            wm = jax.lax.stop_gradient(wm)
            fts, acts, logits = imagine(wm, actor, h0, z0, key)
            fts_a = jnp.concatenate(
                [fts, jax.nn.one_hot(acts, A)], axis=-1)
            rew = _fwd(wm["rew"], fts_a)[..., 0]             # [H, N]
            cont = jax.nn.sigmoid(_fwd(wm["cont"], fts_a)[..., 0])
            disc = gamma * cont
            values = _fwd(critic, fts)[..., 0]               # [H, N]
            # lambda returns for t = 0..H-2, mixing the NEXT state's
            # value and bootstrapping from values[-1] (Hafner's
            # lambda_return: inputs = r + disc*(1-lam)*V(s_{t+1}))
            inputs = rew[:-1] + disc[:-1] * (1 - lam) * values[1:]

            def ret_scan(acc, t):
                r = inputs[t] + disc[t] * lam * acc
                return r, r

            _, rets = jax.lax.scan(ret_scan, values[-1],
                                   jnp.arange(H - 2, -1, -1))
            rets = rets[::-1]                                # [H-1, N]
            rets_sg = jax.lax.stop_gradient(rets)
            adv = (rets_sg - jax.lax.stop_gradient(values[:-1])) \
                / jnp.maximum(ret_scale, 1.0)
            logp = jax.nn.log_softmax(logits[:-1], axis=-1)
            lp_a = jnp.take_along_axis(
                logp, acts[:-1][..., None], axis=-1)[..., 0]
            entropy = -jnp.sum(jnp.exp(logp) * logp, axis=-1).mean()
            actor_loss = -jnp.mean(lp_a * adv) - ent_coeff * entropy
            aux = (jax.lax.stop_gradient(fts), rets_sg, entropy)
            return actor_loss, aux

        @jax.jit
        def train_step(wm, actor, critic, opt_states, batch, key,
                       ret_scale):
            wm_state, a_state, c_state = opt_states
            k1, k2 = jax.random.split(key)
            (wl, wm_aux), wm_grads = jax.value_and_grad(
                wm_loss, has_aux=True)(wm, batch, k1)
            upd, wm_state = self._wm_opt.update(wm_grads, wm_state, wm)
            wm = optax.apply_updates(wm, upd)
            h0, z0 = wm_aux.pop("states")

            def a_loss_fn(a):
                return actor_loss_fn(a, critic, wm, h0, z0, k2,
                                     ret_scale)

            (al, (fts_sg, rets_sg, ent)), a_grads = jax.value_and_grad(
                a_loss_fn, has_aux=True)(actor)
            upd, a_state = self._a_opt.update(a_grads, a_state, actor)
            actor = optax.apply_updates(actor, upd)

            # critic regresses on the SAME (pre-update-actor) rollout:
            # targets are the lambda returns computed with the pre-update
            # critic, stop-gradded — no second imagination pass
            def c_loss_fn(c):
                vals = _fwd(c, fts_sg)[..., 0]
                return jnp.mean((vals[:-1] - rets_sg) ** 2)

            cl, c_grads = jax.value_and_grad(c_loss_fn)(critic)
            upd, c_state = self._c_opt.update(c_grads, c_state, critic)
            critic = optax.apply_updates(critic, upd)
            lo = jnp.percentile(rets_sg, 5)
            hi = jnp.percentile(rets_sg, 95)
            metrics = dict(wm_aux, wm_loss=wl, actor_loss=al,
                           critic_loss=cl, actor_entropy=ent,
                           ret_range=hi - lo)
            return wm, actor, critic, (wm_state, a_state, c_state), \
                metrics

        self._train_step = train_step

        @jax.jit
        def act_fn(wm, actor, h, z, a_prev, obs, is_first, key):
            emb = _fwd(wm["enc"], obs)
            k1, k2 = jax.random.split(key)
            h, z, _, _ = obs_step(wm, h, z, a_prev, emb, is_first, k1)
            logits = _fwd(actor, jnp.concatenate([h, z], axis=-1))
            a = jax.random.categorical(k2, logits)
            return h, z, a

        self._act_fn = act_fn
        N = cfg.num_envs_per_runner
        self._h = jnp.zeros((N, D))
        self._z = jnp.zeros((N, Z))
        self._a_prev = jnp.zeros((N, A))
        self._is_first = np.ones(N, dtype=np.float32)
        self._key = jax.random.key(cfg.seed + 99)
        self._obs = self.env.reset()
        self._A = A
        self._ret_scale = 1.0

        self._chunks: List[Dict[str, np.ndarray]] = []
        self._buf_steps = 0
        self._rng = np.random.default_rng(cfg.seed)
        self._env_steps_total = 0
        from ray_tpu.rl.evaluation import ReturnWindow

        self._returns = ReturnWindow(N)

    # -- collection -------------------------------------------------------

    def _collect(self, steps: int) -> None:
        cfg = self.config
        N = self.env.num_envs
        rows = {k: [] for k in ("obs", "actions", "rewards", "conts",
                                "is_first")}
        for _ in range(steps):
            self._key, sub = jax.random.split(self._key)
            h, z, a = self._act_fn(
                self.wm, self.actor, self._h, self._z, self._a_prev,
                jnp.asarray(self._obs), jnp.asarray(self._is_first), sub)
            acts = np.asarray(a)
            rows["obs"].append(self._obs.copy())
            rows["is_first"].append(self._is_first.copy())
            next_obs, rew, dones = self.env.step(acts)
            rows["actions"].append(acts)
            rows["rewards"].append(rew.astype(np.float32))
            rows["conts"].append(1.0 - dones.astype(np.float32))
            self._h, self._z = h, z
            self._a_prev = jnp.asarray(np.eye(self._A,
                                              dtype=np.float32)[acts])
            self._is_first = dones.astype(np.float32)
            self._obs = next_obs
            self._env_steps_total += N
            self._returns.add(rew, dones)
        chunk = {k: np.stack(v, axis=1) for k, v in rows.items()}  # [N,T]
        self._chunks.append(chunk)
        self._buf_steps += steps * N
        max_chunks = max(1, cfg.buffer_size
                         // (cfg.rollout_fragment_length * N))
        if len(self._chunks) > max_chunks:
            drop = len(self._chunks) - max_chunks
            del self._chunks[:drop]
            self._buf_steps = sum(c["rewards"].size for c in self._chunks)

    def _sample_batch(self) -> Dict[str, np.ndarray]:
        cfg = self.config
        B = cfg.batch_seqs
        out = {k: [] for k in ("obs", "actions", "rewards", "conts",
                               "is_first")}
        for _ in range(B):
            c = self._chunks[self._rng.integers(len(self._chunks))]
            row = self._rng.integers(c["rewards"].shape[0])
            for k in out:
                out[k].append(c[k][row])
        batch = {k: np.stack(v) for k, v in out.items()}
        # The RSSM scan starts each sampled chunk from a zeroed (h, z), so the
        # first replayed step must be treated as an episode start even when the
        # chunk was cut mid-episode (the reference forces is_first=True on the
        # first replayed step for the same reason) — otherwise the world model
        # trains on zero-state transitions that never occur at collection time.
        batch["is_first"][:, 0] = 1.0  # np.stack already copied
        return batch

    # -- Trainable API ----------------------------------------------------

    def step(self) -> Dict[str, Any]:
        cfg = self.config
        self._collect(cfg.rollout_fragment_length)
        metrics: Dict[str, Any] = {"buffer_steps": self._buf_steps}
        if self._buf_steps >= cfg.learning_starts:
            mlist = []
            for _ in range(cfg.updates_per_iter or 1):
                self._key, sub = jax.random.split(self._key)
                batch = {k: jnp.asarray(v)
                         for k, v in self._sample_batch().items()}
                (self.wm, self.actor, self.critic,
                 (self._wm_state, self._a_state, self._c_state),
                 m) = self._train_step(
                    self.wm, self.actor, self.critic,
                    (self._wm_state, self._a_state, self._c_state),
                    batch, sub, self._ret_scale)
                # EMA of the imagined-return percentile range (v3's
                # advantage normalizer)
                self._ret_scale = 0.99 * self._ret_scale \
                    + 0.01 * float(m["ret_range"])
                mlist.append(m)
            for k in mlist[0]:
                metrics[k] = float(np.mean([float(x[k]) for x in mlist]))
            metrics["ret_scale"] = self._ret_scale
        metrics["env_steps_total"] = self._env_steps_total
        mean_ret = self._returns.mean()
        if mean_ret is not None:
            metrics["episode_return_mean"] = mean_ret
        return metrics

    def evaluate(self, num_episodes: int = 10) -> Dict[str, Any]:
        """Fresh env, stochastic actor through the world-model filter."""
        cfg = self.config
        env = make_env(cfg.env, cfg.num_envs_per_runner, cfg.env_config)
        N = env.num_envs
        D = cfg.deter_dim
        Z = cfg.stoch_groups * cfg.stoch_classes
        h = jnp.zeros((N, D))
        z = jnp.zeros((N, Z))
        a_prev = jnp.zeros((N, self._A))
        is_first = np.ones(N, dtype=np.float32)
        from ray_tpu.rl.evaluation import run_episodes

        state = {"h": h, "z": z, "a_prev": a_prev, "is_first": is_first,
                 "obs": env.reset(),
                 "key": jax.random.key(cfg.seed + 12345)}

        def step():
            state["key"], sub = jax.random.split(state["key"])
            state["h"], state["z"], a = self._act_fn(
                self.wm, self.actor, state["h"], state["z"],
                state["a_prev"], jnp.asarray(state["obs"]),
                jnp.asarray(state["is_first"]), sub)
            acts = np.asarray(a)
            state["obs"], rew, dones = env.step(acts)
            state["a_prev"] = jnp.asarray(
                np.eye(self._A, dtype=np.float32)[acts])
            state["is_first"] = dones.astype(np.float32)
            return rew, dones

        return run_episodes(step, num_episodes, N)

    # -- checkpointing ----------------------------------------------------

    def save_checkpoint(self, checkpoint_dir: str) -> Optional[Dict]:
        to_np = lambda t: jax.tree_util.tree_map(np.asarray, t)  # noqa
        return {"wm": to_np(self.wm), "actor": to_np(self.actor),
                "critic": to_np(self.critic),
                "ret_scale": self._ret_scale,
                "env_steps_total": self._env_steps_total}

    def load_checkpoint(self, checkpoint: Dict) -> None:
        to_j = lambda t: jax.tree_util.tree_map(jnp.asarray, t)  # noqa
        self.wm = to_j(checkpoint["wm"])
        self.actor = to_j(checkpoint["actor"])
        self.critic = to_j(checkpoint["critic"])
        self._ret_scale = checkpoint.get("ret_scale", 1.0)
        self._env_steps_total = checkpoint.get("env_steps_total", 0)
