"""CQL: conservative Q-learning for offline RL.

Reference analog: ``rllib/algorithms/cql/cql.py`` (CQL(H) on top of SAC —
the soft actor-critic update plus a conservative penalty that pushes Q
down on out-of-distribution actions and up on dataset actions, Kumar et
al. 2020). Same shape here: the SAC loss terms plus

    alpha_cql * ( logsumexp_a Q(s, a~) - Q(s, a_data) )

with a~ drawn from uniform-random and current-policy actions
(importance-corrected), all inside one jitted update over offline
minibatches — no env interaction.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl import models
from ray_tpu.rl.algorithm import Algorithm
from ray_tpu.rl.algorithms.offline import _to_arrays
from ray_tpu.rl.algorithms.sac import _squashed_sample_logp
from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.learner import Learner


class CQLConfig(AlgorithmConfig):
    def __init__(self, **kwargs):
        super().__init__(algo_class=CQL, **kwargs)
        self.env = "Pendulum-v1"
        self.minibatch_size = 256
        self.cql_alpha = 5.0
        self.cql_num_actions = 8   # sampled actions for the logsumexp
        self.updates_per_iter = 50


class CQL(Algorithm):
    need_env_runners = False  # offline: the dataset IS the experience

    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        return CQLConfig()

    def build_learner(self) -> None:
        cfg, spec = self.config, self.spec
        if cfg.offline_data is None:
            raise ValueError("CQL needs config.offline_data")
        self._data = _to_arrays(cfg.offline_data)
        for col in ("obs", "actions", "rewards", "next_obs", "dones"):
            if col not in self._data:
                raise ValueError(f"offline_data missing {col!r}")
        self._n = len(self._data["rewards"])
        self._rng = np.random.default_rng(cfg.seed)

        gamma, tau = cfg.gamma, cfg.tau
        low, high = spec.action_low, spec.action_high
        adim = spec.action_dim
        n_samp = cfg.cql_num_actions
        cql_alpha = cfg.cql_alpha

        key = jax.random.key(cfg.seed)
        k_pi, k_q1, k_q2 = jax.random.split(key, 3)
        qin = spec.obs_dim + adim
        q1 = models.init_mlp(k_q1, [qin, *cfg.hidden, 1], out_scale=1.0)
        q2 = models.init_mlp(k_q2, [qin, *cfg.hidden, 1], out_scale=1.0)
        pi = models.init_mlp(
            k_pi, [spec.obs_dim, *cfg.hidden, 2 * adim], out_scale=0.01)
        params = {
            "pi": pi, "q1": q1, "q2": q2,
            "q1_target": jax.tree_util.tree_map(jnp.copy, q1),
            "q2_target": jax.tree_util.tree_map(jnp.copy, q2),
            "log_alpha": jnp.asarray(float(np.log(cfg.initial_alpha))),
        }

        def pi_dist(pi_params, obs):
            out = models.mlp_forward(pi_params, obs)
            return jnp.split(out, 2, axis=-1)

        def q_val(q_params, obs, act):
            return models.mlp_forward(
                q_params, jnp.concatenate([obs, act], axis=-1))[..., 0]

        def _q_on_sampled(q_params, obs, acts):
            """Q over [S, B, A] sampled actions -> [S, B]."""
            rep = jnp.broadcast_to(obs, (acts.shape[0],) + obs.shape)
            return q_val(q_params, rep, acts)

        def loss_fn(params, batch, key):
            k1, k2, k3, k4 = jax.random.split(key, 4)
            obs, nobs = batch["obs"], batch["next_obs"]
            acts = batch["actions"]
            B = obs.shape[0]
            alpha = jnp.exp(params["log_alpha"])
            # --- SAC critic target (soft bellman backup) ---
            nmean, nlogstd = pi_dist(params["pi"], nobs)
            nact, nlogp = _squashed_sample_logp(nmean, nlogstd, k1,
                                                low, high)
            qt = jnp.minimum(q_val(params["q1_target"], nobs, nact),
                             q_val(params["q2_target"], nobs, nact))
            nonterminal = 1.0 - batch["dones"].astype(jnp.float32)
            target = jax.lax.stop_gradient(
                batch["rewards"] + gamma * nonterminal
                * (qt - alpha * nlogp))
            q1_pred = q_val(params["q1"], obs, acts)
            q2_pred = q_val(params["q2"], obs, acts)
            bellman = jnp.mean((q1_pred - target) ** 2) + \
                jnp.mean((q2_pred - target) ** 2)
            # --- conservative penalty (CQL(H)) ---
            rand = jax.random.uniform(k2, (n_samp, B, adim),
                                      minval=low, maxval=high)
            mean, log_std = pi_dist(params["pi"], obs)
            pol, pol_logp = _squashed_sample_logp(
                jnp.broadcast_to(mean, (n_samp,) + mean.shape),
                jnp.broadcast_to(log_std, (n_samp,) + log_std.shape),
                k3, low, high)
            span = high - low
            rand_logp = -adim * jnp.log(span)  # uniform density
            cql_cat = []
            for qp in ("q1", "q2"):
                q_rand = _q_on_sampled(params[qp], obs, rand) - rand_logp
                q_pol = _q_on_sampled(params[qp], obs, pol) \
                    - jax.lax.stop_gradient(pol_logp)
                cat = jnp.concatenate([q_rand, q_pol], axis=0)  # [2S, B]
                lse = jax.nn.logsumexp(cat, axis=0) - jnp.log(2 * n_samp)
                pred = q1_pred if qp == "q1" else q2_pred
                cql_cat.append(jnp.mean(lse - pred))
            cql_penalty = cql_cat[0] + cql_cat[1]
            # --- actor (SAC) ---
            act_new, logp = _squashed_sample_logp(mean, log_std, k4,
                                                  low, high)
            q_min = jnp.minimum(
                q_val(jax.lax.stop_gradient(params["q1"]), obs, act_new),
                q_val(jax.lax.stop_gradient(params["q2"]), obs, act_new))
            pi_loss = jnp.mean(jax.lax.stop_gradient(alpha) * logp - q_min)
            alpha_loss = -jnp.mean(
                params["log_alpha"]
                * jax.lax.stop_gradient(logp - adim))
            total = bellman + cql_alpha * cql_penalty + pi_loss + alpha_loss
            return total, {"bellman_loss": bellman,
                           "cql_penalty": cql_penalty,
                           "pi_loss": pi_loss,
                           "alpha": alpha}

        self.learner = Learner(params, loss_fn, cfg.lr,
                               grad_clip=cfg.grad_clip, seed=cfg.seed)

        @jax.jit
        def polyak(params):
            new = dict(params)
            for src, dst in (("q1", "q1_target"), ("q2", "q2_target")):
                new[dst] = jax.tree_util.tree_map(
                    lambda t, s: (1 - tau) * t + tau * s,
                    params[dst], params[src])
            return new

        self._polyak = polyak
        self._q_val = jax.jit(
            lambda p, o, a: q_val(p["q1"], o, a))

    def q_value(self, obs: np.ndarray, actions: np.ndarray) -> np.ndarray:
        """Q1 estimates — the OOD-vs-dataset probe used by tests."""
        return np.asarray(self._q_val(self.learner.get_params(),
                                      jnp.asarray(obs), jnp.asarray(actions)))

    def _minibatch(self, size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._n, size=min(size, self._n))
        return {k: v[idx] for k, v in self._data.items()}

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        m: Dict[str, Any] = {}
        for _ in range(cfg.updates_per_iter or 50):
            m = self.learner.update_minibatch(
                self._minibatch(cfg.minibatch_size))
            self.learner.params = self._polyak(self.learner.params)
        self._env_steps_total += 0  # offline: no env interaction
        return {k: float(v) for k, v in m.items()}
