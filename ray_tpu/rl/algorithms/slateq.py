"""SlateQ: Q-learning for slate recommendation.

Reference analog: ``rllib/algorithms/slateq/slateq.py`` (Ie et al. 2019,
on RecSim). The action is a SLATE of k documents from an m-document
candidate set; SlateQ makes the combinatorial action space tractable by
decomposing the slate value under a conditional user-choice model:

    Q(s, A) = sum_{i in A} P(click = i | s, A) * Q(s, i)

with per-ITEM Q-values. With multinomial-logit choice (score-proportional
clicks), the greedy slate is the top-k items by choice-weighted Q, so
both action selection and the TD target stay O(m log m).

``RecSlateEnv`` is the bundled RecSim analog: users carry an interest
vector that nudges toward clicked documents; the click model is a
softmax over ``interest . doc`` scores with a no-click option; reward is
the clicked document's engagement. Observations expose the user interest
and every candidate's features (the same flattened layout RecSim's
wrappers produce).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rl import models
from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.learner import Learner
from ray_tpu.rl.replay_buffer import ReplayBuffer
from ray_tpu.tune.trainable import Trainable


class RecSlateEnv:
    """Vectorized slate-recommendation episodes."""

    def __init__(self, num_envs: int = 8, num_docs: int = 10,
                 slate_size: int = 3, feat_dim: int = 4,
                 horizon: int = 20, no_click_bias: float = 1.0,
                 interest_lr: float = 0.2, seed: int = 0):
        self.num_envs = num_envs
        self.num_docs = num_docs
        self.slate_size = slate_size
        self.feat_dim = feat_dim
        self.horizon = horizon
        self.no_click_bias = no_click_bias
        self.interest_lr = interest_lr
        self._rng = np.random.default_rng(seed)
        self._t = np.zeros(num_envs, dtype=np.int64)
        self._user = np.zeros((num_envs, feat_dim), dtype=np.float32)
        self._docs = np.zeros((num_envs, num_docs, feat_dim),
                              dtype=np.float32)
        self._quality = np.zeros((num_envs, num_docs), dtype=np.float32)
        self._reset_envs(np.ones(num_envs, dtype=bool))

    def _reset_envs(self, mask: np.ndarray) -> None:
        n = int(mask.sum())
        if not n:
            return

        def unit(x):
            return x / (np.linalg.norm(x, axis=-1, keepdims=True) + 1e-8)

        self._user[mask] = unit(self._rng.standard_normal(
            (n, self.feat_dim)).astype(np.float32))
        self._docs[mask] = unit(self._rng.standard_normal(
            (n, self.num_docs, self.feat_dim)).astype(np.float32))
        self._quality[mask] = self._rng.uniform(
            0.2, 1.0, (n, self.num_docs)).astype(np.float32)
        self._t[mask] = 0

    def obs(self) -> np.ndarray:
        """[N, feat + docs*(feat+1)]: user interest ++ per-doc features
        and quality (the candidate set IS part of the observation)."""
        docs = np.concatenate(
            [self._docs, self._quality[..., None]], axis=-1)
        return np.concatenate(
            [self._user, docs.reshape(self.num_envs, -1)],
            axis=-1).astype(np.float32)

    @property
    def obs_dim(self) -> int:
        return self.feat_dim + self.num_docs * (self.feat_dim + 1)

    def reset(self) -> np.ndarray:
        self._reset_envs(np.ones(self.num_envs, dtype=bool))
        return self.obs()

    def choice_probs(self, slates: np.ndarray) -> np.ndarray:
        """Multinomial-logit user choice over a [N, k] slate; returns
        [N, k+1] probs, last column = no-click."""
        scores = np.take_along_axis(
            np.einsum("nf,ndf->nd", self._user, self._docs),
            slates, axis=1)                                  # [N, k]
        logits = np.concatenate(
            [scores, np.full((self.num_envs, 1), self.no_click_bias,
                             dtype=np.float32)], axis=1)
        z = np.exp(logits - logits.max(axis=1, keepdims=True))
        return z / z.sum(axis=1, keepdims=True)

    def step(self, slates: np.ndarray):
        """slates [N, k] int doc indices -> (obs, reward, done, clicked)
        where clicked is the chosen slate POSITION or -1 for no-click."""
        probs = self.choice_probs(slates)
        u = self._rng.random((self.num_envs, 1))
        choice = (probs.cumsum(axis=1) < u).sum(axis=1)      # [N] in 0..k
        clicked_pos = np.where(choice < self.slate_size, choice, -1)
        reward = np.zeros(self.num_envs, dtype=np.float32)
        hit = clicked_pos >= 0
        if hit.any():
            doc_idx = np.take_along_axis(
                slates[hit], clicked_pos[hit][:, None], axis=1)[:, 0]
            reward[hit] = self._quality[hit, doc_idx]
            # interest drifts toward consumed content
            d = self._docs[hit, doc_idx]
            self._user[hit] = (1 - self.interest_lr) * self._user[hit] \
                + self.interest_lr * d
            self._user[hit] /= (np.linalg.norm(
                self._user[hit], axis=-1, keepdims=True) + 1e-8)
        self._t += 1
        dones = self._t >= self.horizon
        self._reset_envs(dones)
        return self.obs(), reward, dones, clicked_pos


class SlateQConfig(AlgorithmConfig):
    def __init__(self, **kwargs):
        super().__init__(algo_class=SlateQ, **kwargs)
        self.lr = 1e-3
        self.minibatch_size = 128
        self.buffer_size = 50_000
        self.learning_starts = 500
        self.target_update_freq = 200
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_decay_steps = 10_000
        self.updates_per_iter = 32
        self.num_docs = 10
        self.slate_size = 3
        self.feat_dim = 4
        self.recsim_horizon = 20


class SlateQ(Trainable):
    @classmethod
    def get_default_config(cls) -> AlgorithmConfig:
        return SlateQConfig()

    def setup(self, config: Dict[str, Any]) -> None:
        if "__algo_config" in config:
            self.config: AlgorithmConfig = config["__algo_config"]
        else:
            self.config = SlateQConfig().update_from_dict(config)
        cfg = self.config
        self.env = RecSlateEnv(
            num_envs=cfg.num_envs_per_runner, num_docs=cfg.num_docs,
            slate_size=cfg.slate_size, feat_dim=cfg.feat_dim,
            horizon=cfg.recsim_horizon, seed=cfg.seed,
            **(cfg.env_config or {}))
        m, k, f = cfg.num_docs, cfg.slate_size, cfg.feat_dim
        gamma = cfg.gamma
        user_dim = f
        doc_feat = f + 1  # features + quality
        no_click = self.env.no_click_bias

        # per-item Q net: (user interest ++ doc features) -> scalar
        qnet = models.init_mlp(
            jax.random.key(cfg.seed),
            (user_dim + doc_feat, *cfg.hidden, 1), out_scale=1.0)
        params = {"q": qnet,
                  "target": jax.tree_util.tree_map(jnp.copy, qnet)}

        def split_obs(obs):
            user = obs[:, :user_dim]                         # [B, f]
            docs = obs[:, user_dim:].reshape(-1, m, doc_feat)
            return user, docs

        def item_qs(net, obs):
            """[B, m] per-item Q over the full candidate set."""
            user, docs = split_obs(obs)
            rep = jnp.broadcast_to(user[:, None], (user.shape[0], m,
                                                   user_dim))
            x = jnp.concatenate([rep, docs], axis=-1)
            return models.mlp_forward(net, x)[..., 0]

        def choice_weights(obs, slate_idx):
            """softmax(interest . doc) over slate + no-click -> [B, k]
            click probs for each slate position."""
            user, docs = split_obs(obs)
            scores = jnp.einsum("bf,bmf->bm", user, docs[..., :user_dim])
            s = jnp.take_along_axis(scores, slate_idx, axis=1)  # [B, k]
            logits = jnp.concatenate(
                [s, jnp.full((s.shape[0], 1), no_click)], axis=1)
            p = jax.nn.softmax(logits, axis=1)
            return p[:, :k]

        def greedy_slate(net, obs):
            """Top-k by choice-weighted Q — the reference's top-k heuristic.

            Maximizes the unnormalized sum(w_i * Q_i), not the true MNL slate
            value sum(w_i*Q_i)/(w_noclick + sum(w_i)); exact when Q >= 0 and
            the no-click weight dominates, otherwise a heuristic bound."""
            q = item_qs(net, obs)                            # [B, m]
            user, docs = split_obs(obs)
            scores = jnp.einsum("bf,bmf->bm", user, docs[..., :user_dim])
            w = jnp.exp(scores)  # choice propensity (unnormalized)
            _, idx = jax.lax.top_k(w * q, k)
            return idx

        def slate_value(net, obs, slate_idx):
            """Q(s, A) under the decomposition."""
            q = item_qs(net, obs)
            qs = jnp.take_along_axis(q, slate_idx, axis=1)   # [B, k]
            w = choice_weights(obs, slate_idx)
            return jnp.sum(w * qs, axis=1)

        def loss_fn(p, batch, key):
            del key
            # TD on the CLICKED item's Q (no-click transitions carry no
            # item gradient, matching the SlateQ decomposition)
            q_all = item_qs(p["q"], batch["obs"])            # [B, m]
            clicked_doc = batch["clicked_doc"]               # [B] (or -1)
            hit = (clicked_doc >= 0).astype(jnp.float32)
            safe_idx = jnp.maximum(clicked_doc, 0)
            q_clicked = jnp.take_along_axis(
                q_all, safe_idx[:, None], axis=1)[:, 0]
            next_slate = greedy_slate(p["q"], batch["next_obs"])
            v_next = slate_value(p["target"], batch["next_obs"],
                                 next_slate)
            nonterm = 1.0 - batch["dones"].astype(jnp.float32)
            target = jax.lax.stop_gradient(
                batch["rewards"] + gamma * nonterm * v_next)
            td = (q_clicked - target) * hit
            loss = jnp.sum(td ** 2) / jnp.maximum(hit.sum(), 1.0)
            return loss, {"td_abs_mean": jnp.sum(jnp.abs(td))
                          / jnp.maximum(hit.sum(), 1.0),
                          "click_rate": hit.mean(),
                          "q_clicked_mean": jnp.sum(q_clicked * hit)
                          / jnp.maximum(hit.sum(), 1.0)}

        self.learner = Learner(params, loss_fn, cfg.lr,
                               grad_clip=cfg.grad_clip, seed=cfg.seed)
        self._greedy_slate = jax.jit(
            lambda net, obs: greedy_slate(net, obs))
        self._updates = 0
        self.buffer = ReplayBuffer(cfg.buffer_size, seed=cfg.seed)
        self._rng = np.random.default_rng(cfg.seed)
        self._obs = self.env.reset()
        self._env_steps_total = 0
        from ray_tpu.rl.evaluation import ReturnWindow

        self._returns = ReturnWindow(self.env.num_envs)

    @property
    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self._env_steps_total
                   / max(1, cfg.epsilon_decay_steps))
        return cfg.epsilon_initial \
            + frac * (cfg.epsilon_final - cfg.epsilon_initial)

    def _pick_slates(self, obs: np.ndarray, epsilon: float) -> np.ndarray:
        cfg = self.config
        n = self.env.num_envs
        slates = np.array(self._greedy_slate(
            self.learner.get_params()["q"], jnp.asarray(obs)))
        explore = self._rng.random(n) < epsilon
        for i in np.nonzero(explore)[0]:
            slates[i] = self._rng.choice(cfg.num_docs, cfg.slate_size,
                                         replace=False)
        return slates

    def _collect(self, steps: int) -> None:
        n_envs = self.env.num_envs
        for _ in range(steps):
            obs = self._obs
            slates = self._pick_slates(obs, self._epsilon)
            next_obs, rewards, dones, clicked_pos = self.env.step(slates)
            clicked_doc = np.where(
                clicked_pos >= 0,
                np.take_along_axis(
                    slates, np.maximum(clicked_pos, 0)[:, None],
                    axis=1)[:, 0],
                -1)
            self.buffer.add_batch(
                {"obs": obs, "clicked_doc": clicked_doc.astype(np.int32),
                 "rewards": rewards, "dones": dones.astype(np.float32),
                 "next_obs": next_obs})
            self._env_steps_total += n_envs
            self._returns.add(rewards, dones)
            self._obs = next_obs

    def step(self) -> Dict[str, Any]:
        cfg = self.config
        self._collect(cfg.rollout_fragment_length)
        metrics: Dict[str, Any] = {"epsilon": self._epsilon,
                                   "buffer_size": len(self.buffer)}
        if len(self.buffer) >= cfg.learning_starts:
            mlist = []
            for _ in range(cfg.updates_per_iter or 1):
                mb = self.buffer.sample(cfg.minibatch_size)
                target_before = self.learner.params["target"]
                mlist.append(self.learner.update_minibatch(mb))
                self.learner.params = dict(self.learner.params,
                                           target=target_before)
                self._updates += 1
                if self._updates % cfg.target_update_freq == 0:
                    self.learner.params = dict(
                        self.learner.params,
                        target=jax.tree_util.tree_map(
                            jnp.copy, self.learner.params["q"]))
            for k in mlist[0]:
                metrics[k] = float(np.mean([float(m[k]) for m in mlist]))
        metrics["env_steps_total"] = self._env_steps_total
        mean_ret = self._returns.mean()
        if mean_ret is not None:
            metrics["episode_return_mean"] = mean_ret
        return metrics

    def evaluate(self, num_episodes: int = 10) -> Dict[str, Any]:
        """Greedy slates on a fresh env."""
        from ray_tpu.rl.evaluation import run_episodes

        cfg = self.config
        env = RecSlateEnv(
            num_envs=cfg.num_envs_per_runner, num_docs=cfg.num_docs,
            slate_size=cfg.slate_size, feat_dim=cfg.feat_dim,
            horizon=cfg.recsim_horizon, seed=cfg.seed + 777,
            **(cfg.env_config or {}))
        state = {"obs": env.reset()}
        qnet = self.learner.get_params()["q"]

        def step():
            slates = np.asarray(self._greedy_slate(
                qnet, jnp.asarray(state["obs"])))
            state["obs"], rewards, dones, _ = env.step(slates)
            return rewards, dones

        return run_episodes(step, num_episodes, env.num_envs)

    def save_checkpoint(self, checkpoint_dir: str) -> Optional[Dict]:
        return {"params": jax.tree_util.tree_map(
            np.asarray, self.learner.get_params()),
            "env_steps_total": self._env_steps_total,
            "updates": self._updates}

    def load_checkpoint(self, checkpoint: Dict) -> None:
        self.learner.set_params(checkpoint["params"])
        self._env_steps_total = checkpoint.get("env_steps_total", 0)
        self._updates = checkpoint.get("updates", 0)
